"""Pallas ragged paged attention kernel for TPU.

TPU-native replacement for the reference's paged-attention CUDA kernels
(csrc/attention/paged_attention_v{1,2}.cu) and the torch_xla
ragged_paged_attention op its TPU backend calls
(vllm/v1/attention/backends/pallas.py:232). Re-designed for Pallas rather
than translated:

* Grid ``(seq, q_tile)``; each program runs the whole flash-attention
  loop over that sequence's KV pages as a dynamic-trip-count
  ``fori_loop`` (decode cost is O(kv_len), not O(max_model_len)), with
  online-softmax accumulators as loop carries.
* Per-sequence metadata (q_start, q_len, kv_len, batch row) is
  scalar-prefetched into SMEM; KV pages are gathered from HBM by manual
  async DMA using page ids read from the prefetched block table (the
  paging side of csrc/attention is pure DMA here).
* Mixed prefill/decode in one call: each sequence brings q_len query rows
  (1 for decode, up to max_q for a chunked-prefill step).
* Mosaic-friendly compute: the KV cache page layout is head-major
  [page, kv_head, page_size, head_dim] so each page DMAs into a
  contiguous [kv_head, block, head_dim] VMEM block; scores are 2-D
  matmuls per kv head (GQA queries of a group fold into rows), avoiding
  batched dots and sub-tile DMA slices entirely.

Layout contract with the model runner:

* Token arrays are the flat ragged batch; each sequence's q rows are
  contiguous, sequence runs are back-to-back in run order r = 0..num_seqs.
* ``q`` and the returned output have at least ``q_tile`` padding rows at
  the end: a sequence's final tile may spill past its q_len; spilled rows
  of sequence r are garbage but are rewritten by sequence r+1's own tile
  flush (the TPU grid executes sequentially in order), and the last
  sequence spills into the padding rows.
* ``seq_info[r] = (q_start, q_len, kv_len, batch_row)``; ``kv_len``
  includes tokens written this step. ``block_tables[batch_row]`` holds the
  page ids (rows are input-batch rows, indirected through batch_row).
* ``page_size`` must be a multiple of 8 (sublane tiling of the DMA
  destination slices).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_distributed_tpu import envs

_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)

# ---------------------------------------------------------------------------
# Mega-kernel partition descriptor
#
# One Pallas call consumes an arbitrary mixed prefill+decode batch: the
# grid is a flat program list and a host-built descriptor row tells each
# program what it is. Programs execute in order (the TPU grid is
# sequential), which is what lets KV-write programs land the step's new
# K/V pages before any attention program reads them.
#
#   desc[p] = (kind, a, b) int32
#     kind 0 (noop)     — padding row, program does nothing.
#     kind 1 (prefill)  — a = seq_info row r, b = tile start within the
#                         sequence's q run; runs the flash loop over a
#                         fixed ``bq`` q tile (independent of the token
#                         bucket). Writeback is EXACT (8-row chunks + a
#                         per-row tail), so tiles never spill into
#                         neighbouring rows and program order between
#                         attention programs does not matter.
#     kind 2 (decode)   — a = start index into ``decode_list``, b =
#                         number of active slots (<= sb); stacks sb
#                         single-token sequences as virtual heads so one
#                         MXU dot scores every sequence at once (the
#                         _decode_kernel trick), even when prefill tiles
#                         share the wave.
#     kind 3 (kv write) — a = row into ``kv_runs``; the in-place paged
#                         RMW of ops/pallas_kv_write.py, compiled into
#                         the kernel only for the fused write+attend
#                         variant (attention-only calls treat kind 3 as
#                         a noop).
#
# ``decode_list`` holds the seq_info row indices of every q_len == 1
# sequence; any single-token run (a decode step OR a one-token chunked-
# prefill tail — the attention math is identical) lands there.
#
# The compile-lattice math: descriptor length and q padding are
# deterministic functions of the token bucket, and no kernel static
# depends on the batch composition — the forward graph count collapses
# from O(|T| x compositions) kernel variants to one kernel x |T| input
# shapes.
# ---------------------------------------------------------------------------

KIND_NOOP = 0
KIND_PREFILL = 1
KIND_DECODE = 2
KIND_KV_WRITE = 3

# Token arrays carry this many padding rows past the token bucket: a
# prefill tile's final 8-row read chunk may start at the last valid row
# (q reads are 8-row-aligned; writes are exact and never need it).
Q_TILE_PAD = 8


def prefill_tile_size(num_q_heads: int, head_dim: int) -> int:
    """Static prefill q-tile rows. Fixed (never a function of the token
    bucket) so the kernel has no per-composition statics; 32 rows fold to
    32*group score rows per kv head — MXU-filling for GQA groups >= 4.
    Shrinks (staying a multiple of 8, the IO chunk) for wide-head models
    so per-program staging stays inside the VMEM budget."""
    bq = 32
    while bq > 8 and bq * num_q_heads * head_dim * 32 > 12 * 1024**2:
        bq //= 2
    return bq


def decode_group_size(num_q_heads: int, num_kv_heads: int) -> int:
    """Static decode-group width (sequences stacked as virtual heads per
    program). Independent of the runtime batch size — inactive slots are
    masked — and sized against the worst-case 128-position kv block so
    the same sb is valid for every caller (the cascade suffix call sees
    a shorter block table than the main call)."""
    sb = max(1, min(8, 128 // max(1, num_q_heads // 4)))
    while sb > 1 and (sb * num_q_heads) * (sb * num_kv_heads * 128) * 8 \
            > 8 * 1024**2:
        sb //= 2
    return sb


def num_partition_programs(t_bucket: int, max_num_reqs: int, *, bq: int,
                           sb: int, num_kv_writes: int = 0) -> int:
    """Descriptor length bound as a deterministic function of the token
    bucket: worst-case prefill tiles (every sequence pays one partial
    tile) + decode groups + kv-write rows. Adds no lattice dimension."""
    return (num_kv_writes + -(-t_bucket // bq) + max_num_reqs +
            -(-max_num_reqs // sb))


def build_partition_descriptor(
    seq_info: np.ndarray,  # [R, 4] int32 host copy
    num_seqs: int,
    *,
    bq: int,
    sb: int,
    num_programs: int,
    num_kv_writes: int = 0,
    decode_rows: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side partition of a step into mega-kernel programs.

    Returns ``(desc [num_programs, 3], decode_list [R])``. Pass
    ``decode_rows`` (row indices into seq_info) to skip the q_len scan —
    the runner's pure-decode fast path feeds its row vector directly."""
    R = seq_info.shape[0]
    desc = np.zeros((num_programs, 3), np.int32)
    dl = np.zeros((R, ), np.int32)
    p = num_kv_writes
    if num_kv_writes:
        desc[:p, 0] = KIND_KV_WRITE
        desc[:p, 1] = np.arange(num_kv_writes, dtype=np.int32)
    if decode_rows is None:
        q_lens = seq_info[:num_seqs, 1]
        decode_rows = np.nonzero(q_lens == 1)[0]
        for r in np.nonzero(q_lens > 1)[0]:
            nt = -(-int(q_lens[r]) // bq)
            desc[p:p + nt, 0] = KIND_PREFILL
            desc[p:p + nt, 1] = r
            desc[p:p + nt, 2] = np.arange(nt, dtype=np.int32) * bq
            p += nt
    n_dec = len(decode_rows)
    dl[:n_dec] = decode_rows
    starts = np.arange(0, n_dec, sb, dtype=np.int32)
    ng = len(starts)
    assert p + ng <= num_programs, "partition descriptor overflow"
    desc[p:p + ng, 0] = KIND_DECODE
    desc[p:p + ng, 1] = starts
    desc[p:p + ng, 2] = np.minimum(sb, n_dec - starts)
    return desc, dl


def _kernel(
    # scalar prefetch
    seq_info_ref,  # [R, 4] int32: q_start, q_len, kv_len, batch_row
    num_seqs_ref,  # [1] int32
    layer_ref,  # [1] int32
    block_tables_ref,  # [max_reqs, pages_per_req] int32
    # tensor inputs (HBM)
    q_hbm,  # [T_pad, QH, D]
    k_hbm,  # [L, num_pages, KVH, PS, D] (full stacked cache)
    v_hbm,
    # outputs (HBM): out_hbm, then state_hbm when emit_state
    *refs,
    sm_scale: float,
    bq: int,
    ppb: int,
    page_size: int,
    group: int,
    emit_state: bool,
):
    if emit_state:
        (out_hbm, state_hbm, q_vmem, k_vmem, v_vmem, out_stage,
         state_stage, q_sem, kv_sems, out_sem, state_sem) = refs
    else:
        (out_hbm, q_vmem, k_vmem, v_vmem, out_stage, q_sem, kv_sems,
         out_sem) = refs
        state_hbm = state_stage = state_sem = None
    r = pl.program_id(0)
    qt = pl.program_id(1)

    q_start = seq_info_ref[r, 0]
    q_len = seq_info_ref[r, 1]
    kv_len = seq_info_ref[r, 2]
    row = seq_info_ref[r, 3]
    num_seqs = num_seqs_ref[0]
    layer = layer_ref[0]
    num_q_heads = q_vmem.shape[1]
    num_kv_heads = k_vmem.shape[1]  # [slot, KVH, blk, D]
    head_dim = q_vmem.shape[2]

    blk = ppb * page_size
    tile_start = qt * bq
    # Absolute position of the last query row in this tile; kv blocks past
    # it are causally invisible and never fetched.
    q_pos_max = kv_len - q_len + jnp.minimum(tile_start + bq, q_len) - 1
    active = jnp.logical_and(
        r < num_seqs,
        jnp.logical_and(tile_start < q_len, kv_len > 0))

    @pl.when(active)
    def _run():
        # Whole q tile in one leading-dim DMA (token rows are the major
        # axis; head/lane dims stay intact — Mosaic constrains sub-tile
        # slicing of the minor two dims).
        q_dma = pltpu.make_async_copy(
            q_hbm.at[pl.ds(q_start + tile_start, bq)], q_vmem, q_sem)
        q_dma.start()
        num_blocks = q_pos_max // blk + 1

        # Double-buffered KV pipeline: block b+1's pages stream from HBM
        # while block b computes, so the MXU never idles on a fetch
        # (the reference's paged_attention_v2.cu overlaps its gathers
        # the same way via cp.async).
        def fetch(b, slot):
            for i in range(ppb):
                page_id = block_tables_ref[row, b * ppb + i]
                pltpu.make_async_copy(
                    k_hbm.at[layer, page_id],
                    k_vmem.at[slot, :, pl.ds(i * page_size, page_size)],
                    kv_sems.at[slot, 0, i]).start()
                pltpu.make_async_copy(
                    v_hbm.at[layer, page_id],
                    v_vmem.at[slot, :, pl.ds(i * page_size, page_size)],
                    kv_sems.at[slot, 1, i]).start()

        fetch(0, 0)  # warm-up overlaps the q DMA in flight
        q_dma.wait()

        q_tile = q_vmem[...].astype(jnp.float32) * sm_scale  # [BQ, QH, D]
        if bq == 1:
            # Decode: rows are heads; group slices are leading-dim slices.
            q_flat = q_tile.reshape(num_q_heads, head_dim)
            q_heads = [
                q_flat[h * group:(h + 1) * group]
                for h in range(num_kv_heads)
            ]
        else:
            q_heads = [
                q_tile[:, h * group:(h + 1) * group, :].reshape(
                    bq * group, head_dim) for h in range(num_kv_heads)
            ]
        rows = bq * group

        row_pos = (kv_len - q_len + tile_start +
                   jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 0) //
                   group)
        col_base = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)
        row_valid = (jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 0) //
                     group + tile_start) < q_len

        def body(b, carry):
            ms, ls, accs = carry
            kv_start = b * blk
            slot = jax.lax.rem(b, 2)

            @pl.when(b + 1 < num_blocks)
            def _prefetch():
                fetch(b + 1, jax.lax.rem(b + 1, 2))

            for i in range(ppb):
                pltpu.make_async_copy(
                    k_hbm.at[0, 0],
                    k_vmem.at[slot, :, pl.ds(i * page_size, page_size)],
                    kv_sems.at[slot, 0, i]).wait()
                pltpu.make_async_copy(
                    v_hbm.at[0, 0],
                    v_vmem.at[slot, :, pl.ds(i * page_size, page_size)],
                    kv_sems.at[slot, 1, i]).wait()
            k_blk = k_vmem[slot]  # [KVH, BLK, D]
            v_blk = v_vmem[slot]

            kv_pos = kv_start + col_base
            mask = jnp.logical_and(kv_pos <= row_pos, row_valid)

            new_ms, new_ls, new_accs = [], [], []
            for h in range(num_kv_heads):
                k_h = k_blk[h]  # [BLK, D]
                v_h = v_blk[h]
                s = jax.lax.dot_general(
                    q_heads[h], k_h.astype(jnp.float32),
                    dimension_numbers=(((1, ), (1, )), ((), ())),
                    preferred_element_type=jnp.float32)  # [rows, BLK]
                s = jnp.where(mask, s, _MASK_VALUE)
                m_prev, l_prev, acc_prev = ms[h], ls[h], accs[h]
                m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
                p = jnp.exp(s - m_new)
                alpha = jnp.exp(m_prev - m_new)
                l_new = l_prev * alpha + p.sum(axis=-1, keepdims=True)
                pv = jax.lax.dot_general(
                    p.astype(v_h.dtype), v_h,
                    dimension_numbers=(((1, ), (0, )), ((), ())),
                    preferred_element_type=jnp.float32)  # [rows, D]
                acc_new = acc_prev * alpha + pv
                new_ms.append(m_new)
                new_ls.append(l_new)
                new_accs.append(acc_new)
            return tuple(new_ms), tuple(new_ls), tuple(new_accs)

        init = (
            tuple(
                jnp.full((rows, 1), _MASK_VALUE, jnp.float32)
                for _ in range(num_kv_heads)),
            tuple(
                jnp.zeros((rows, 1), jnp.float32)
                for _ in range(num_kv_heads)),
            tuple(
                jnp.zeros((rows, head_dim), jnp.float32)
                for _ in range(num_kv_heads)),
        )
        ms, ls, accs = jax.lax.fori_loop(0, num_blocks, body, init)

        half = head_dim // 2
        for h in range(num_kv_heads):
            o_h = accs[h] / jnp.maximum(ls[h], 1e-20)  # [rows, D]
            if bq == 1:
                out_stage[0, h * group:(h + 1) * group, :] = (
                    o_h.astype(out_stage.dtype))
            else:
                out_stage[:, h * group:(h + 1) * group, :] = (
                    o_h.reshape(bq, group, head_dim).astype(
                        out_stage.dtype))
            if emit_state:
                # Online-softmax partial state for exact merging with
                # another KV range (cascade): m broadcast over the low
                # lanes, l over the high — lane-sliced out by the
                # caller. Full-D staging keeps the DMA tile-aligned.
                st = jnp.concatenate([
                    jnp.broadcast_to(ms[h], (rows, half)),
                    jnp.broadcast_to(ls[h], (rows, head_dim - half)),
                ], axis=-1)
                if bq == 1:
                    state_stage[0, h * group:(h + 1) * group, :] = st
                else:
                    state_stage[:, h * group:(h + 1) * group, :] = (
                        st.reshape(bq, group, head_dim))
        out_dma = pltpu.make_async_copy(
            out_stage, out_hbm.at[pl.ds(q_start + tile_start, bq)],
            out_sem)
        out_dma.start()
        if emit_state:
            st_dma = pltpu.make_async_copy(
                state_stage,
                state_hbm.at[pl.ds(q_start + tile_start, bq)], state_sem)
            st_dma.start()
            st_dma.wait()
        out_dma.wait()


def _decode_kernel(
    # scalar prefetch
    seq_info_ref,  # [R, 4] int32: q_start, q_len, kv_len, batch_row
    num_seqs_ref,  # [1] int32
    layer_ref,  # [1] int32
    block_tables_ref,  # [max_reqs, pages_per_req] int32
    # tensor inputs (HBM)
    q_hbm,  # [T_pad, QH, D]
    k_hbm,  # [L, num_pages, KVH, PS, D]
    v_hbm,
    out_hbm,
    # scratch
    q_vmem,  # [SB, QH, D]
    k_vmem,  # [2, SB, KVH, blk, D] double-buffered
    v_vmem,
    out_stage,  # [SB, QH, D]
    q_sem,
    kv_sems,  # [2, 2, SB, ppb]
    out_sem,
    *,
    sm_scale: float,
    sb: int,
    ppb: int,
    page_size: int,
    group: int,
):
    """Decode-specialized attention: SB sequences per grid program.

    Decode starves the MXU when each sequence's score dot is only
    ``group`` rows (VERDICT r4: 4–8 rows on a 128x128 array). Here the
    SB sequences x KVH kv-heads of a program are stacked as SB*KVH
    "virtual heads": ONE [SB*QH, D] x [D, SB*KVH*blk] dot scores every
    sequence at once, with a block-diagonal mask (virtual head of query
    row == virtual head of kv column) recovering per-sequence/per-head
    attention. Cross-terms cost flops the DMA-bound loop has to spare;
    rows go from `group` to SB*QH. KV pages double-buffer across the
    block loop exactly like the general kernel.

    Layout contract (decode steps only): every scheduled sequence has
    q_len == 1; its query row is read through seq_info's q_start, so
    compacted/scattered layouts (token parallelism's per-rank lists)
    work unchanged.
    """
    p = pl.program_id(0)
    num_seqs = num_seqs_ref[0]
    layer = layer_ref[0]
    QH = q_vmem.shape[1]
    KVH = k_vmem.shape[2]
    D = q_vmem.shape[2]
    blk = ppb * page_size
    base = p * sb
    ROWS = sb * QH
    C = sb * KVH * blk

    # Per-sequence scalars (static unroll over the SB slots). Inactive
    # slots read row 0's metadata but mask everything via kv_len = 0.
    idx = [jnp.minimum(base + i, seq_info_ref.shape[0] - 1)
           for i in range(sb)]
    kv_lens = [
        jnp.where(base + i < num_seqs, seq_info_ref[idx[i], 2], 0)
        for i in range(sb)
    ]
    rows_ = [seq_info_ref[idx[i], 3] for i in range(sb)]
    q_starts = [seq_info_ref[idx[i], 0] for i in range(sb)]

    max_kv = kv_lens[0]
    for i in range(1, sb):
        max_kv = jnp.maximum(max_kv, kv_lens[i])
    num_blocks = jax.lax.div(max_kv - 1, blk) + 1  # 0 when all inactive

    @pl.when(base < num_seqs)
    def _run():
        for i in range(sb):
            pltpu.make_async_copy(
                q_hbm.at[pl.ds(q_starts[i], 1)],
                q_vmem.at[pl.ds(i, 1)], q_sem.at[i]).start()

        def fetch(b, slot):
            for i in range(sb):
                # Clamp past-the-end blocks of shorter sequences to
                # their last valid block: the DMA stays in-bounds and
                # the mask discards the stale columns.
                bi = jnp.clip(b, 0,
                              jnp.maximum(
                                  jax.lax.div(kv_lens[i] - 1, blk), 0))
                for j in range(ppb):
                    page_id = block_tables_ref[rows_[i], bi * ppb + j]
                    pltpu.make_async_copy(
                        k_hbm.at[layer, page_id],
                        k_vmem.at[slot, i, :,
                                  pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 0, i, j]).start()
                    pltpu.make_async_copy(
                        v_hbm.at[layer, page_id],
                        v_vmem.at[slot, i, :,
                                  pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 1, i, j]).start()

        fetch(0, 0)
        for i in range(sb):
            pltpu.make_async_copy(
                q_hbm.at[pl.ds(0, 1)], q_vmem.at[pl.ds(i, 1)],
                q_sem.at[i]).wait()
        q_all = (q_vmem[...].astype(jnp.float32) * sm_scale).reshape(
            ROWS, D)

        # Block-diagonal structure: query row r belongs to virtual head
        # r // group (rows are seq-major then head-major, QH = KVH *
        # group); kv column c belongs to virtual head c // blk.
        vh_r = jax.lax.broadcasted_iota(jnp.int32, (ROWS, C), 0) // group
        vh_c = jax.lax.broadcasted_iota(jnp.int32, (ROWS, C), 1) // blk
        diag = vh_r == vh_c
        col_off = jax.lax.broadcasted_iota(jnp.int32, (ROWS, C), 1) % blk
        kvlen_rows = jnp.concatenate(
            [jnp.full((QH, ), kv_lens[i], jnp.int32) for i in range(sb)])

        def body(b, carry):
            m_prev, l_prev, acc_prev = carry
            slot = jax.lax.rem(b, 2)

            @pl.when(b + 1 < num_blocks)
            def _prefetch():
                fetch(b + 1, jax.lax.rem(b + 1, 2))

            for i in range(sb):
                for j in range(ppb):
                    pltpu.make_async_copy(
                        k_hbm.at[0, 0],
                        k_vmem.at[slot, i, :,
                                  pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 0, i, j]).wait()
                    pltpu.make_async_copy(
                        v_hbm.at[0, 0],
                        v_vmem.at[slot, i, :,
                                  pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 1, i, j]).wait()
            k_all = k_vmem[slot].reshape(C, D)  # [SB*KVH*blk, D]
            v_all = v_vmem[slot].reshape(C, D)

            s = jax.lax.dot_general(
                q_all, k_all.astype(jnp.float32),
                dimension_numbers=(((1, ), (1, )), ((), ())),
                preferred_element_type=jnp.float32)  # [ROWS, C]
            mask = jnp.logical_and(
                diag, b * blk + col_off < kvlen_rows[:, None])
            s = jnp.where(mask, s, _MASK_VALUE)

            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            pr = jnp.exp(s - m_new)
            # Zero the off-diagonal terms so the PV dot sums only each
            # row's own block (exp(_MASK_VALUE - m) underflows to 0
            # already; the where guards m == _MASK_VALUE rows).
            pr = jnp.where(mask, pr, 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + pr.sum(axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                pr.astype(v_all.dtype), v_all,
                dimension_numbers=(((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)  # [ROWS, D]
            return m_new, l_new, acc_prev * alpha + pv

        init = (
            jnp.full((ROWS, 1), _MASK_VALUE, jnp.float32),
            jnp.zeros((ROWS, 1), jnp.float32),
            jnp.zeros((ROWS, D), jnp.float32),
        )
        _m, l_fin, acc = jax.lax.fori_loop(0, num_blocks, body, init)
        out = acc / jnp.maximum(l_fin, 1e-20)
        out_stage[...] = out.reshape(sb, QH, D).astype(out_stage.dtype)
        # Per-sequence writeback through q_start; inactive slots MUST
        # NOT write (their q_start aliases row 0 — a real token).
        for i in range(sb):
            @pl.when(base + i < num_seqs)
            def _wb(i=i):
                pltpu.make_async_copy(
                    out_stage.at[pl.ds(i, 1)],
                    out_hbm.at[pl.ds(q_starts[i], 1)],
                    out_sem.at[i]).start()
        for i in range(sb):
            @pl.when(base + i < num_seqs)
            def _wb_wait(i=i):
                pltpu.make_async_copy(
                    out_stage.at[pl.ds(i, 1)],
                    out_hbm.at[pl.ds(0, 1)], out_sem.at[i]).wait()


def _decode_call(q, k_pages, v_pages, seq_info, num_seqs, block_tables,
                 layer, *, sm_scale, interpret):
    """Launch the SB-batched decode kernel (max_q == 1, no state)."""
    T_pad, num_q_heads, head_dim = q.shape
    _, _, num_kv_heads, page_size, _ = k_pages.shape
    group = num_q_heads // num_kv_heads
    R = seq_info.shape[0]
    pages_per_req = block_tables.shape[1]
    ppb = max(1, min(128 // page_size, pages_per_req))
    while pages_per_req % ppb:
        ppb -= 1
    blk = ppb * page_size

    sb = max(1, min(8, R, 128 // max(1, num_q_heads // 4)))
    # Score tile [sb*QH, sb*KVH*blk] f32 (+ exp copy) dominates VMEM.
    while sb > 1 and (sb * num_q_heads) * (sb * num_kv_heads * blk) * 8 \
            > 8 * 1024**2:
        sb //= 2
    assert T_pad >= R, "decode q must cover one row per sequence"
    # The last program reads/writes rows [base, base+sb); keep that
    # inside the q padding when R is not a multiple of sb.
    while sb > 1 and pl.cdiv(R, sb) * sb > T_pad:
        sb //= 2

    grid = (pl.cdiv(R, sb), )
    kernel = functools.partial(
        _decode_kernel, sm_scale=sm_scale, sb=sb, ppb=ppb,
        page_size=page_size, group=group)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # q
            pl.BlockSpec(memory_space=pltpu.ANY),  # k_pages
            pl.BlockSpec(memory_space=pltpu.ANY),  # v_pages
        ],
        out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        scratch_shapes=[
            pltpu.VMEM((sb, num_q_heads, head_dim), q.dtype),
            pltpu.VMEM((2, sb, num_kv_heads, blk, head_dim),
                       k_pages.dtype),
            pltpu.VMEM((2, sb, num_kv_heads, blk, head_dim),
                       v_pages.dtype),
            pltpu.VMEM((sb, num_q_heads, head_dim), q.dtype),
            pltpu.SemaphoreType.DMA((sb, )),
            pltpu.SemaphoreType.DMA((2, 2, sb, ppb)),
            pltpu.SemaphoreType.DMA((sb, )),
        ],
    )
    (out, ) = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        interpret=interpret,
    )(seq_info, num_seqs, layer, block_tables, q, k_pages, v_pages)
    return out


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "max_q", "interpret", "emit_state"))
def ragged_paged_attention_pallas(
    q: jax.Array,  # [T_pad, QH, D]; T_pad >= T + q_tile padding
    k_pages: jax.Array,  # [L, num_pages, KVH, PS, D] full stacked cache
    v_pages: jax.Array,
    seq_info: jax.Array,  # [R, 4] int32 (q_start, q_len, kv_len, row)
    num_seqs: jax.Array,  # [1] int32
    block_tables: jax.Array,  # [max_reqs, pages_per_req] int32
    layer: jax.Array | None = None,  # [1] int32
    *,
    sm_scale: float,
    max_q: int,
    interpret: bool | None = None,
    emit_state: bool = False,
):
    """Unified prefill/decode attention over the paged KV cache.

    ``max_q`` is the static per-sequence query bucket (1 for pure decode).
    The cache keeps its stacked layer dim; ``layer`` selects the slice to
    read (pages are DMA'd as [layer, page] — no layer copy materializes).
    Returns [T_pad, QH, D]; rows past each sequence's q_len are garbage.

    ``emit_state=True`` additionally returns the online-softmax partial
    state as an f32 [T_pad, QH, D] array with the row max broadcast over
    lanes [0, D/2) and the exp-sum over [D/2, D) — what cascade needs to
    merge this call's KV range with a shared-prefix phase exactly
    (reference: csrc/attention/merge_attn_states.cu exports the same
    (max, sumexp) pair).
    """
    if interpret is None:
        interpret = envs.VDT_PALLAS_INTERPRET
    if k_pages.ndim == 4:
        # Single-layer convenience form (tests).
        k_pages = k_pages[None]
        v_pages = v_pages[None]
    if layer is None:
        layer = jnp.zeros((1, ), jnp.int32)
    T_pad, num_q_heads, head_dim = q.shape
    _, num_pages, num_kv_heads, page_size, _ = k_pages.shape
    assert num_q_heads % num_kv_heads == 0
    group = num_q_heads // num_kv_heads
    R = seq_info.shape[0]
    pages_per_req = block_tables.shape[1]

    if max_q == 1 and not emit_state:
        # Pure decode: the SB-batched kernel fills the MXU (see
        # _decode_kernel). Cascade's emit_state decode stays on the
        # general kernel (it exports per-row softmax state).
        return _decode_call(q, k_pages, v_pages, seq_info, num_seqs,
                            block_tables, layer, sm_scale=sm_scale,
                            interpret=interpret)

    bq = min(max_q, 128)
    # Keep the per-program footprint (q/out staging, f32 accumulators and
    # their loop-carry double buffers, per-head score tiles) inside the
    # ~16MB VMEM budget: shrink the q tile for wide-head models.
    while bq > 8 and bq * num_q_heads * head_dim * 32 > 12 * 1024**2:
        bq //= 2
    num_q_tiles = pl.cdiv(max_q, bq)
    assert T_pad >= bq, "q must be padded to at least one tile"
    # ~128 kv positions per block, at least one page.
    ppb = max(1, min(128 // page_size, pages_per_req))
    while pages_per_req % ppb:
        ppb -= 1
    blk = ppb * page_size

    grid = (R, num_q_tiles)
    kernel = functools.partial(
        _kernel, sm_scale=sm_scale, bq=bq, ppb=ppb, page_size=page_size,
        group=group, emit_state=emit_state)

    scratch = [
        pltpu.VMEM((bq, num_q_heads, head_dim), q.dtype),
        pltpu.VMEM((2, num_kv_heads, blk, head_dim), k_pages.dtype),
        pltpu.VMEM((2, num_kv_heads, blk, head_dim), v_pages.dtype),
        pltpu.VMEM((bq, num_q_heads, head_dim), q.dtype),
    ]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    out_specs = [pl.BlockSpec(memory_space=pltpu.ANY)]
    if emit_state:
        scratch.append(
            pltpu.VMEM((bq, num_q_heads, head_dim), jnp.float32))
        out_shape.append(
            jax.ShapeDtypeStruct(q.shape, jnp.float32))
        out_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
    scratch += [
        pltpu.SemaphoreType.DMA(()),
        pltpu.SemaphoreType.DMA((2, 2, ppb)),  # [slot, k/v, page]
        pltpu.SemaphoreType.DMA(()),
    ]
    if emit_state:
        scratch.append(pltpu.SemaphoreType.DMA(()))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # q
            pl.BlockSpec(memory_space=pltpu.ANY),  # k_pages
            pl.BlockSpec(memory_space=pltpu.ANY),  # v_pages
        ],
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    result = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(seq_info, num_seqs, layer, block_tables, q, k_pages, v_pages)
    if emit_state:
        return tuple(result)
    return result[0]


# ---------------------------------------------------------------------------
# Mixed-batch attention mega-kernel
# ---------------------------------------------------------------------------


def _mega_kernel(
    # scalar prefetch
    desc_ref,  # [P, 3] int32: (kind, a, b) — see module header
    seq_info_ref,  # [R, 4] int32: q_start, q_len, kv_len, batch_row
    dl_ref,  # [R] int32: seq_info rows of q_len == 1 sequences
    kv_runs_ref,  # [G, 4] int32 page-write runs (fuse_write only)
    layer_ref,  # [1] int32
    block_tables_ref,  # [max_reqs, pages_per_req] int32
    *refs,
    sm_scale: float,
    bq: int,
    sb: int,
    ppb: int,
    page_size: int,
    group: int,
    emit_state: bool,
    fuse_write: bool,
    window: int,
    logit_cap: float,
    has_alibi: bool,
    has_sinks: bool,
):
    """One program list, three program types (see the partition
    descriptor contract in the module docstring). Prefill tiles run the
    general flash loop at a FIXED bq; decode groups keep the SB
    virtual-head batching even when prefill tiles share the wave; kv
    writes (fused variant) land first so attention reads this step's
    pages.

    Per-model attention features ride along so windowed / soft-capped /
    ALiBi / sink models reach this kernel instead of the XLA fallback:
    ``window`` (sliding-window bound) and ``logit_cap`` (tanh
    soft-capping) are per-layer STATICS like the model's scan-segment
    plan; ALiBi slopes and sink logits arrive in the tiny ``feat``
    input ([2, QH] f32: row 0 slopes, row 1 sinks) so learned sinks and
    TP-sharded head slices stay dynamic. Masking is feature-complete
    but the page loop still walks the full block table — window layers
    discard out-of-window blocks by mask, not by loop bounds (loop
    trimming is a profiled follow-up)."""
    if fuse_write:
        (q_hbm, k_new, v_new, _k_in, _v_in, feat_ref,
         out_hbm, k_cache, v_cache,
         q_vmem, k_vmem, v_vmem, out_stage,
         k_page, v_page, k_win, v_win,
         q_sems, kv_sems, out_sems, w_sems) = refs
        state_hbm = state_stage = state_sems = None
    elif emit_state:
        (q_hbm, k_cache, v_cache, feat_ref, out_hbm, state_hbm,
         q_vmem, k_vmem, v_vmem, out_stage, state_stage,
         q_sems, kv_sems, out_sems, state_sems) = refs
    else:
        (q_hbm, k_cache, v_cache, feat_ref, out_hbm,
         q_vmem, k_vmem, v_vmem, out_stage,
         q_sems, kv_sems, out_sems) = refs
        state_hbm = state_stage = state_sems = None

    p = pl.program_id(0)
    kind = desc_ref[p, 0]
    a = desc_ref[p, 1]
    b = desc_ref[p, 2]
    layer = layer_ref[0]
    QH = q_vmem.shape[1]
    KVH = k_vmem.shape[2]
    D = q_vmem.shape[2]
    blk = ppb * page_size
    half = D // 2
    nck = bq // 8  # 8-row IO chunks per prefill tile

    if fuse_write:

        @pl.when(kind == KIND_KV_WRITE)
        def _kv_write():
            # The page-RMW body shared with ops/pallas_kv_write.py
            # (page-aligned 2*PS window + one-hot shift matmul). Runs
            # precede every attention program in the descriptor, and the
            # grid executes in order, so attention below reads the
            # freshly written pages — through the aliased OUTPUT refs.
            from vllm_distributed_tpu.ops.pallas_kv_write import page_rmw
            run_len = kv_runs_ref[a, 3]

            @pl.when(run_len > 0)
            def _run():
                page_rmw(kv_runs_ref[a, 0], kv_runs_ref[a, 1],
                         kv_runs_ref[a, 2], run_len, layer, k_new,
                         v_new, k_cache, v_cache, k_page, v_page, k_win,
                         v_win, w_sems, page_size=page_size)

    @pl.when(kind == KIND_PREFILL)
    def _prefill():
        r = a
        tile_start = b
        q_start = seq_info_ref[r, 0]
        q_len = seq_info_ref[r, 1]
        kv_len = seq_info_ref[r, 2]
        row = seq_info_ref[r, 3]
        n_valid = jnp.minimum(q_len - tile_start, bq)
        q_pos_max = kv_len - q_len + tile_start + n_valid - 1
        num_blocks = q_pos_max // blk + 1

        # q tile read in 8-row chunks: chunks starting past q_len are
        # skipped (their stale VMEM rows are masked out of the scores),
        # so reads never pass q_start + q_len + 7 — inside the token
        # array's Q_TILE_PAD padding even for the layout's last tile.
        for c in range(nck):
            @pl.when(tile_start + 8 * c < q_len)
            def _rd(c=c):
                pltpu.make_async_copy(
                    q_hbm.at[pl.ds(q_start + tile_start + 8 * c, 8)],
                    q_vmem.at[pl.ds(8 * c, 8)], q_sems.at[c]).start()

        def fetch(bi, slot):
            for i in range(ppb):
                page_id = block_tables_ref[row, bi * ppb + i]
                pltpu.make_async_copy(
                    k_cache.at[layer, page_id],
                    k_vmem.at[slot, 0, :, pl.ds(i * page_size, page_size)],
                    kv_sems.at[slot, 0, 0, i]).start()
                pltpu.make_async_copy(
                    v_cache.at[layer, page_id],
                    v_vmem.at[slot, 0, :, pl.ds(i * page_size, page_size)],
                    kv_sems.at[slot, 1, 0, i]).start()

        fetch(0, 0)  # overlaps the q chunk DMAs in flight
        for c in range(nck):
            @pl.when(tile_start + 8 * c < q_len)
            def _rdw(c=c):
                pltpu.make_async_copy(
                    q_hbm.at[pl.ds(0, 8)], q_vmem.at[pl.ds(8 * c, 8)],
                    q_sems.at[c]).wait()

        q_tile = q_vmem[...][:bq].astype(jnp.float32) * sm_scale
        q_heads = [
            q_tile[:, h * group:(h + 1) * group, :].reshape(
                bq * group, D) for h in range(KVH)
        ]
        rows = bq * group
        row_pos = (kv_len - q_len + tile_start +
                   jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 0) //
                   group)
        col_base = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)
        row_valid = (jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 0) //
                     group + tile_start) < q_len
        feat_val = (feat_ref[...].astype(jnp.float32)
                    if (has_alibi or has_sinks) else None)
        # Per-row head feature vectors: tile rows are q-row-major then
        # group (row r belongs to q head h*group + r % group).
        if has_alibi:
            slopes_rows = [
                jnp.tile(feat_val[0, h * group:(h + 1) * group],
                         (bq, ))[:, None] for h in range(KVH)
            ]

        def body(bi, carry):
            ms, ls, accs = carry
            kv_start = bi * blk
            slot = jax.lax.rem(bi, 2)

            @pl.when(bi + 1 < num_blocks)
            def _prefetch():
                fetch(bi + 1, jax.lax.rem(bi + 1, 2))

            for i in range(ppb):
                pltpu.make_async_copy(
                    k_cache.at[0, 0],
                    k_vmem.at[slot, 0, :, pl.ds(i * page_size, page_size)],
                    kv_sems.at[slot, 0, 0, i]).wait()
                pltpu.make_async_copy(
                    v_cache.at[0, 0],
                    v_vmem.at[slot, 0, :, pl.ds(i * page_size, page_size)],
                    kv_sems.at[slot, 1, 0, i]).wait()
            k_blk = k_vmem[slot, 0]  # [KVH, BLK, D]
            v_blk = v_vmem[slot, 0]
            kv_pos = kv_start + col_base
            mask = jnp.logical_and(kv_pos <= row_pos, row_valid)
            if window > 0:
                mask = jnp.logical_and(mask, kv_pos > row_pos - window)
            new_ms, new_ls, new_accs = [], [], []
            for h in range(KVH):
                s = jax.lax.dot_general(
                    q_heads[h], k_blk[h].astype(jnp.float32),
                    dimension_numbers=(((1, ), (1, )), ((), ())),
                    preferred_element_type=jnp.float32)
                if logit_cap > 0:
                    s = logit_cap * jnp.tanh(s / logit_cap)
                if has_alibi:
                    s = s + slopes_rows[h] * (
                        kv_pos - row_pos).astype(jnp.float32)
                s = jnp.where(mask, s, _MASK_VALUE)
                m_prev, l_prev, acc_prev = ms[h], ls[h], accs[h]
                m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
                pr = jnp.exp(s - m_new)
                alpha = jnp.exp(m_prev - m_new)
                l_new = l_prev * alpha + pr.sum(axis=-1, keepdims=True)
                pv = jax.lax.dot_general(
                    pr.astype(v_blk.dtype), v_blk[h],
                    dimension_numbers=(((1, ), (0, )), ((), ())),
                    preferred_element_type=jnp.float32)
                new_ms.append(m_new)
                new_ls.append(l_new)
                new_accs.append(acc_prev * alpha + pv)
            return tuple(new_ms), tuple(new_ls), tuple(new_accs)

        init = (
            tuple(jnp.full((rows, 1), _MASK_VALUE, jnp.float32)
                  for _ in range(KVH)),
            tuple(jnp.zeros((rows, 1), jnp.float32) for _ in range(KVH)),
            tuple(jnp.zeros((rows, D), jnp.float32) for _ in range(KVH)),
        )
        ms, ls, accs = jax.lax.fori_loop(0, num_blocks, body, init)

        if has_sinks:
            # Learned per-head virtual key joining only the softmax
            # denominator (softmax shift-invariance makes the running
            # max of the REAL scores a valid reference point).
            ls = tuple(
                ls[h] + jnp.exp(
                    jnp.tile(feat_val[1, h * group:(h + 1) * group],
                             (bq, ))[:, None] - ms[h])
                for h in range(KVH))
        for h in range(KVH):
            o_h = accs[h] / jnp.maximum(ls[h], 1e-20)
            out_stage[0:bq, h * group:(h + 1) * group, :] = (
                o_h.reshape(bq, group, D).astype(out_stage.dtype))
            if emit_state:
                st = jnp.concatenate([
                    jnp.broadcast_to(ms[h], (rows, half)),
                    jnp.broadcast_to(ls[h], (rows, D - half)),
                ], axis=-1)
                state_stage[0:bq, h * group:(h + 1) * group, :] = (
                    st.reshape(bq, group, D))

        # EXACT writeback: full 8-row chunks, then a per-row tail for
        # the partial chunk — a tile never writes a row it does not own,
        # so program order between attention programs is irrelevant and
        # the token array needs no bq-sized spill padding.
        def flush(stage, hbm, sems):
            for c in range(nck):
                @pl.when(8 * (c + 1) <= n_valid)
                def _wc(c=c):
                    pltpu.make_async_copy(
                        stage.at[pl.ds(8 * c, 8)],
                        hbm.at[pl.ds(q_start + tile_start + 8 * c, 8)],
                        sems.at[c]).start()
            for rr in range(bq):
                @pl.when(jnp.logical_and(rr // 8 == n_valid // 8,
                                         rr < n_valid))
                def _wr(rr=rr):
                    pltpu.make_async_copy(
                        stage.at[pl.ds(rr, 1)],
                        hbm.at[pl.ds(q_start + tile_start + rr, 1)],
                        sems.at[rr]).start()
            for c in range(nck):
                @pl.when(8 * (c + 1) <= n_valid)
                def _wcw(c=c):
                    pltpu.make_async_copy(
                        stage.at[pl.ds(8 * c, 8)],
                        hbm.at[pl.ds(0, 8)], sems.at[c]).wait()
            for rr in range(bq):
                @pl.when(jnp.logical_and(rr // 8 == n_valid // 8,
                                         rr < n_valid))
                def _wrw(rr=rr):
                    pltpu.make_async_copy(
                        stage.at[pl.ds(rr, 1)],
                        hbm.at[pl.ds(0, 1)], sems.at[rr]).wait()

        flush(out_stage, out_hbm, out_sems)
        if emit_state:
            flush(state_stage, state_hbm, state_sems)

    @pl.when(kind == KIND_DECODE)
    def _decode():
        # SB-batched decode (see _decode_kernel): the group's sequences
        # x kv heads stack as virtual heads; ONE dot scores every
        # sequence, a block-diagonal mask recovers per-sequence
        # attention. Slots address sequences through decode_list, so
        # decode rows keep MXU-filling batching in mixed waves.
        cnt = b
        R_dl = dl_ref.shape[0]
        idx = [dl_ref[jnp.minimum(a + i, R_dl - 1)] for i in range(sb)]
        kv_lens = [
            jnp.where(jnp.asarray(i) < cnt, seq_info_ref[idx[i], 2], 0)
            for i in range(sb)
        ]
        rows_ = [seq_info_ref[idx[i], 3] for i in range(sb)]
        q_starts = [seq_info_ref[idx[i], 0] for i in range(sb)]
        max_kv = kv_lens[0]
        for i in range(1, sb):
            max_kv = jnp.maximum(max_kv, kv_lens[i])
        num_blocks = jax.lax.div(max_kv - 1, blk) + 1
        ROWS = sb * QH
        C = sb * KVH * blk

        for i in range(sb):
            pltpu.make_async_copy(
                q_hbm.at[pl.ds(q_starts[i], 1)],
                q_vmem.at[pl.ds(i, 1)], q_sems.at[i]).start()

        def fetch(bi, slot):
            for i in range(sb):
                ci = jnp.clip(bi, 0,
                              jnp.maximum(
                                  jax.lax.div(kv_lens[i] - 1, blk), 0))
                for j in range(ppb):
                    page_id = block_tables_ref[rows_[i], ci * ppb + j]
                    pltpu.make_async_copy(
                        k_cache.at[layer, page_id],
                        k_vmem.at[slot, i, :,
                                  pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 0, i, j]).start()
                    pltpu.make_async_copy(
                        v_cache.at[layer, page_id],
                        v_vmem.at[slot, i, :,
                                  pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 1, i, j]).start()

        fetch(0, 0)
        for i in range(sb):
            pltpu.make_async_copy(
                q_hbm.at[pl.ds(0, 1)], q_vmem.at[pl.ds(i, 1)],
                q_sems.at[i]).wait()
        q_all = (q_vmem[...][:sb].astype(jnp.float32) *
                 sm_scale).reshape(ROWS, D)

        vh_r = jax.lax.broadcasted_iota(jnp.int32, (ROWS, C), 0) // group
        vh_c = jax.lax.broadcasted_iota(jnp.int32, (ROWS, C), 1) // blk
        diag = vh_r == vh_c
        col_off = jax.lax.broadcasted_iota(jnp.int32, (ROWS, C), 1) % blk
        kvlen_rows = jnp.concatenate(
            [jnp.full((QH, ), kv_lens[i], jnp.int32) for i in range(sb)])
        feat_val = (feat_ref[...].astype(jnp.float32)
                    if (has_alibi or has_sinks) else None)
        if has_alibi:
            # Decode rows are seq-major then q-head-major: row i*QH + qh.
            slope_rows = jnp.tile(feat_val[0], (sb, ))[:, None]

        def body(bi, carry):
            m_prev, l_prev, acc_prev = carry
            slot = jax.lax.rem(bi, 2)

            @pl.when(bi + 1 < num_blocks)
            def _prefetch():
                fetch(bi + 1, jax.lax.rem(bi + 1, 2))

            for i in range(sb):
                for j in range(ppb):
                    pltpu.make_async_copy(
                        k_cache.at[0, 0],
                        k_vmem.at[slot, i, :,
                                  pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 0, i, j]).wait()
                    pltpu.make_async_copy(
                        v_cache.at[0, 0],
                        v_vmem.at[slot, i, :,
                                  pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 1, i, j]).wait()
            k_all = k_vmem[slot].reshape(C, D)
            v_all = v_vmem[slot].reshape(C, D)
            s = jax.lax.dot_general(
                q_all, k_all.astype(jnp.float32),
                dimension_numbers=(((1, ), (1, )), ((), ())),
                preferred_element_type=jnp.float32)
            if logit_cap > 0:
                s = logit_cap * jnp.tanh(s / logit_cap)
            if has_alibi:
                # Decode q position is kv_len - 1 per sequence.
                s = s + slope_rows * (
                    bi * blk + col_off -
                    (kvlen_rows[:, None] - 1)).astype(jnp.float32)
            mask = jnp.logical_and(
                diag, bi * blk + col_off < kvlen_rows[:, None])
            if window > 0:
                mask = jnp.logical_and(
                    mask,
                    bi * blk + col_off > kvlen_rows[:, None] - 1 - window)
            s = jnp.where(mask, s, _MASK_VALUE)
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            pr = jnp.exp(s - m_new)
            pr = jnp.where(mask, pr, 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + pr.sum(axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                pr.astype(v_all.dtype), v_all,
                dimension_numbers=(((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_prev * alpha + pv

        init = (
            jnp.full((ROWS, 1), _MASK_VALUE, jnp.float32),
            jnp.zeros((ROWS, 1), jnp.float32),
            jnp.zeros((ROWS, D), jnp.float32),
        )
        m_fin, l_fin, acc = jax.lax.fori_loop(0, num_blocks, body, init)
        if has_sinks:
            l_fin = l_fin + jnp.exp(
                jnp.tile(feat_val[1], (sb, ))[:, None] - m_fin)
        out = acc / jnp.maximum(l_fin, 1e-20)
        out_stage[0:sb, :, :] = out.reshape(sb, QH, D).astype(
            out_stage.dtype)
        if emit_state:
            st = jnp.concatenate([
                jnp.broadcast_to(m_fin, (ROWS, half)),
                jnp.broadcast_to(l_fin, (ROWS, D - half)),
            ], axis=-1)
            state_stage[0:sb, :, :] = st.reshape(sb, QH, D)
        # Per-sequence writeback through q_start; inactive slots MUST
        # NOT write (their q_start aliases a real token's row).
        for i in range(sb):
            @pl.when(jnp.asarray(i) < cnt)
            def _wb(i=i):
                pltpu.make_async_copy(
                    out_stage.at[pl.ds(i, 1)],
                    out_hbm.at[pl.ds(q_starts[i], 1)],
                    out_sems.at[i]).start()
                if emit_state:
                    pltpu.make_async_copy(
                        state_stage.at[pl.ds(i, 1)],
                        state_hbm.at[pl.ds(q_starts[i], 1)],
                        state_sems.at[i]).start()
        for i in range(sb):
            @pl.when(jnp.asarray(i) < cnt)
            def _wbw(i=i):
                pltpu.make_async_copy(
                    out_stage.at[pl.ds(i, 1)],
                    out_hbm.at[pl.ds(0, 1)], out_sems.at[i]).wait()
                if emit_state:
                    pltpu.make_async_copy(
                        state_stage.at[pl.ds(i, 1)],
                        state_hbm.at[pl.ds(0, 1)], state_sems.at[i]).wait()


def _mega_call(q, k_pages, v_pages, desc, seq_info, decode_list, kv_runs,
               block_tables, layer, k_new_hl, v_new_hl, *, sm_scale, bq,
               sb, interpret, emit_state, fuse_write, feat=None,
               window=0, logit_cap=0.0, has_alibi=False,
               has_sinks=False):
    """Shared launcher for the attention-only and fused write+attend
    variants of the mega-kernel."""
    T_pad, num_q_heads, head_dim = q.shape
    _, _, num_kv_heads, page_size, _ = k_pages.shape
    assert num_q_heads % num_kv_heads == 0
    assert bq % 8 == 0 and bq >= 8
    group = num_q_heads // num_kv_heads
    pages_per_req = block_tables.shape[1]
    ppb = max(1, min(128 // page_size, pages_per_req))
    while pages_per_req % ppb:
        ppb -= 1
    blk = ppb * page_size
    stage_rows = max(bq, sb)
    if feat is None:
        feat = jnp.zeros((2, num_q_heads), jnp.float32)

    kernel = functools.partial(
        _mega_kernel, sm_scale=sm_scale, bq=bq, sb=sb, ppb=ppb,
        page_size=page_size, group=group, emit_state=emit_state,
        fuse_write=fuse_write, window=window, logit_cap=logit_cap,
        has_alibi=has_alibi, has_sinks=has_sinks)

    in_specs = [pl.BlockSpec(memory_space=pltpu.ANY)]  # q
    operands = [q]
    if fuse_write:
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
        operands += [k_new_hl, v_new_hl]
    in_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
    operands += [k_pages, v_pages]
    # Head-feature sidecar (ALiBi slopes / sink logits): whole-array
    # VMEM block, read as a value by the attention bodies.
    in_specs += [pl.BlockSpec(memory_space=pltpu.VMEM)]
    operands += [feat]

    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    out_specs = [pl.BlockSpec(memory_space=pltpu.ANY)]
    aliases = {}
    if fuse_write:
        out_shape += [
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ]
        out_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
        # Flat operand indices: 6 scalar-prefetch args, then q, k_new,
        # v_new, k_pages (9), v_pages (10) alias outputs 1 and 2.
        aliases = {9: 1, 10: 2}
    if emit_state:
        out_shape.append(jax.ShapeDtypeStruct(q.shape, jnp.float32))
        out_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))

    scratch = [
        pltpu.VMEM((stage_rows, num_q_heads, head_dim), q.dtype),
        pltpu.VMEM((2, sb, num_kv_heads, blk, head_dim), k_pages.dtype),
        pltpu.VMEM((2, sb, num_kv_heads, blk, head_dim), v_pages.dtype),
        pltpu.VMEM((stage_rows, num_q_heads, head_dim), q.dtype),
    ]
    if emit_state:
        scratch.append(
            pltpu.VMEM((stage_rows, num_q_heads, head_dim), jnp.float32))
    if fuse_write:
        scratch += [
            pltpu.VMEM((num_kv_heads, page_size, head_dim),
                       k_pages.dtype),
            pltpu.VMEM((num_kv_heads, page_size, head_dim),
                       v_pages.dtype),
            pltpu.VMEM((num_kv_heads, 2 * page_size, head_dim),
                       k_pages.dtype),
            pltpu.VMEM((num_kv_heads, 2 * page_size, head_dim),
                       v_pages.dtype),
        ]
    scratch += [
        pltpu.SemaphoreType.DMA((max(sb, bq // 8), )),  # q reads
        pltpu.SemaphoreType.DMA((2, 2, sb, ppb)),  # kv double buffer
        pltpu.SemaphoreType.DMA((stage_rows, )),  # out flush
    ]
    if emit_state:
        scratch.append(pltpu.SemaphoreType.DMA((stage_rows, )))
    if fuse_write:
        scratch.append(pltpu.SemaphoreType.DMA((4, )))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(desc.shape[0], ),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    result = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )(desc, seq_info, decode_list, kv_runs, layer, block_tables,
      *operands)
    return tuple(result)


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "bq", "sb", "interpret", "emit_state",
                     "window", "logit_cap", "has_alibi", "has_sinks"))
def unified_ragged_paged_attention_pallas(
    q: jax.Array,  # [T_pad, QH, D]; T_pad >= T + Q_TILE_PAD
    k_pages: jax.Array,  # [L, num_pages, KVH, PS, D] stacked cache
    v_pages: jax.Array,
    desc: jax.Array,  # [P, 3] int32 partition descriptor
    seq_info: jax.Array,  # [R, 4] int32 (q_start, q_len, kv_len, row)
    decode_list: jax.Array,  # [R] int32
    block_tables: jax.Array,  # [max_reqs, pages_per_req] int32
    layer: jax.Array | None = None,  # [1] int32
    feat: jax.Array | None = None,  # [2, QH] f32 (slopes, sinks)
    *,
    sm_scale: float,
    bq: int,
    sb: int,
    interpret: bool | None = None,
    emit_state: bool = False,
    window: int = 0,
    logit_cap: float = 0.0,
    has_alibi: bool = False,
    has_sinks: bool = False,
):
    """Mixed-batch attention in ONE kernel call, partitioned by ``desc``
    (see the module docstring for the descriptor contract). No static
    depends on the batch composition: ``bq``/``sb`` are fixed per model
    (prefill_tile_size / decode_group_size), so the compile lattice is
    one kernel x |T| input shapes. Rows the descriptor does not cover
    (padding tokens) are left unwritten — callers mask them.

    ``emit_state=True`` additionally returns the online-softmax partial
    state as an f32 [T_pad, QH, D] array (row max broadcast over lanes
    [0, D/2), exp-sum over [D/2, D)) for exact cascade merging, from
    BOTH prefill tiles and decode groups."""
    if interpret is None:
        interpret = envs.VDT_PALLAS_INTERPRET
    if k_pages.ndim == 4:
        k_pages = k_pages[None]
        v_pages = v_pages[None]
    if layer is None:
        layer = jnp.zeros((1, ), jnp.int32)
    result = _mega_call(
        q, k_pages, v_pages, desc, seq_info, decode_list,
        jnp.zeros((1, 4), jnp.int32), block_tables, layer, None, None,
        sm_scale=sm_scale, bq=bq, sb=sb, interpret=interpret,
        emit_state=emit_state, fuse_write=False, feat=feat,
        window=window, logit_cap=logit_cap, has_alibi=has_alibi,
        has_sinks=has_sinks)
    if emit_state:
        return result  # (out, state)
    return result[0]


@functools.partial(
    jax.jit, static_argnames=("sm_scale", "bq", "sb", "interpret",
                              "window", "logit_cap", "has_alibi",
                              "has_sinks"))
def unified_write_attend_pallas(
    q: jax.Array,  # [T_pad, QH, D]
    k_pages: jax.Array,  # [L, num_pages, KVH, PS, D] (aliased in place)
    v_pages: jax.Array,
    k_new_hl: jax.Array,  # [KVH, T_pad + 3*PS, D] head-leading, padded
    v_new_hl: jax.Array,
    desc: jax.Array,  # [P, 3] with kind-3 kv-write rows FIRST
    seq_info: jax.Array,
    decode_list: jax.Array,
    kv_runs: jax.Array,  # [G, 4] int32 (page, off, window_start, len)
    block_tables: jax.Array,
    layer: jax.Array,  # [1] int32
    feat: jax.Array | None = None,  # [2, QH] f32 (slopes, sinks)
    *,
    sm_scale: float,
    bq: int,
    sb: int,
    interpret: bool | None = None,
    window: int = 0,
    logit_cap: float = 0.0,
    has_alibi: bool = False,
    has_sinks: bool = False,
):
    """Fused KV-page write + mixed-batch attention: ONE pass over the
    cache per layer. The descriptor's kind-3 programs land the step's
    new K/V pages in place (input/output aliasing), and because the TPU
    grid executes programs in order, every attention program reads the
    freshly written pages. Returns (out, k_pages, v_pages)."""
    if interpret is None:
        interpret = envs.VDT_PALLAS_INTERPRET
    if layer is None:
        layer = jnp.zeros((1, ), jnp.int32)
    out, k2, v2 = _mega_call(
        q, k_pages, v_pages, desc, seq_info, decode_list, kv_runs,
        block_tables, layer, k_new_hl, v_new_hl, sm_scale=sm_scale,
        bq=bq, sb=sb, interpret=interpret, emit_state=False,
        fuse_write=True, feat=feat, window=window, logit_cap=logit_cap,
        has_alibi=has_alibi, has_sinks=has_sinks)
    return out, k2, v2
