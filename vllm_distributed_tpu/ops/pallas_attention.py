"""Pallas ragged paged attention kernel for TPU.

TPU-native replacement for the reference's paged-attention CUDA kernels
(csrc/attention/paged_attention_v{1,2}.cu) and the torch_xla
ragged_paged_attention op its TPU backend calls
(vllm/v1/attention/backends/pallas.py:232). Re-designed for Pallas rather
than translated:

* Grid ``(seq, q_tile)``; each program runs the whole flash-attention
  loop over that sequence's KV pages as a dynamic-trip-count
  ``fori_loop`` (decode cost is O(kv_len), not O(max_model_len)), with
  online-softmax accumulators as loop carries.
* Per-sequence metadata (q_start, q_len, kv_len, batch row) is
  scalar-prefetched into SMEM; KV pages are gathered from HBM by manual
  async DMA using page ids read from the prefetched block table (the
  paging side of csrc/attention is pure DMA here).
* Mixed prefill/decode in one call: each sequence brings q_len query rows
  (1 for decode, up to max_q for a chunked-prefill step).
* Mosaic-friendly compute: the KV cache page layout is head-major
  [page, kv_head, page_size, head_dim] so each page DMAs into a
  contiguous [kv_head, block, head_dim] VMEM block; scores are 2-D
  matmuls per kv head (GQA queries of a group fold into rows), avoiding
  batched dots and sub-tile DMA slices entirely.

Layout contract with the model runner:

* Token arrays are the flat ragged batch; each sequence's q rows are
  contiguous, sequence runs are back-to-back in run order r = 0..num_seqs.
* ``q`` and the returned output have at least ``q_tile`` padding rows at
  the end: a sequence's final tile may spill past its q_len; spilled rows
  of sequence r are garbage but are rewritten by sequence r+1's own tile
  flush (the TPU grid executes sequentially in order), and the last
  sequence spills into the padding rows.
* ``seq_info[r] = (q_start, q_len, kv_len, batch_row)``; ``kv_len``
  includes tokens written this step. ``block_tables[batch_row]`` holds the
  page ids (rows are input-batch rows, indirected through batch_row).
* ``page_size`` must be a multiple of 8 (sublane tiling of the DMA
  destination slices).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_distributed_tpu import envs

_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _kernel(
    # scalar prefetch
    seq_info_ref,  # [R, 4] int32: q_start, q_len, kv_len, batch_row
    num_seqs_ref,  # [1] int32
    layer_ref,  # [1] int32
    block_tables_ref,  # [max_reqs, pages_per_req] int32
    # tensor inputs (HBM)
    q_hbm,  # [T_pad, QH, D]
    k_hbm,  # [L, num_pages, KVH, PS, D] (full stacked cache)
    v_hbm,
    # outputs (HBM): out_hbm, then state_hbm when emit_state
    *refs,
    sm_scale: float,
    bq: int,
    ppb: int,
    page_size: int,
    group: int,
    emit_state: bool,
):
    if emit_state:
        (out_hbm, state_hbm, q_vmem, k_vmem, v_vmem, out_stage,
         state_stage, q_sem, kv_sems, out_sem, state_sem) = refs
    else:
        (out_hbm, q_vmem, k_vmem, v_vmem, out_stage, q_sem, kv_sems,
         out_sem) = refs
        state_hbm = state_stage = state_sem = None
    r = pl.program_id(0)
    qt = pl.program_id(1)

    q_start = seq_info_ref[r, 0]
    q_len = seq_info_ref[r, 1]
    kv_len = seq_info_ref[r, 2]
    row = seq_info_ref[r, 3]
    num_seqs = num_seqs_ref[0]
    layer = layer_ref[0]
    num_q_heads = q_vmem.shape[1]
    num_kv_heads = k_vmem.shape[1]  # [slot, KVH, blk, D]
    head_dim = q_vmem.shape[2]

    blk = ppb * page_size
    tile_start = qt * bq
    # Absolute position of the last query row in this tile; kv blocks past
    # it are causally invisible and never fetched.
    q_pos_max = kv_len - q_len + jnp.minimum(tile_start + bq, q_len) - 1
    active = jnp.logical_and(
        r < num_seqs,
        jnp.logical_and(tile_start < q_len, kv_len > 0))

    @pl.when(active)
    def _run():
        # Whole q tile in one leading-dim DMA (token rows are the major
        # axis; head/lane dims stay intact — Mosaic constrains sub-tile
        # slicing of the minor two dims).
        q_dma = pltpu.make_async_copy(
            q_hbm.at[pl.ds(q_start + tile_start, bq)], q_vmem, q_sem)
        q_dma.start()
        num_blocks = q_pos_max // blk + 1

        # Double-buffered KV pipeline: block b+1's pages stream from HBM
        # while block b computes, so the MXU never idles on a fetch
        # (the reference's paged_attention_v2.cu overlaps its gathers
        # the same way via cp.async).
        def fetch(b, slot):
            for i in range(ppb):
                page_id = block_tables_ref[row, b * ppb + i]
                pltpu.make_async_copy(
                    k_hbm.at[layer, page_id],
                    k_vmem.at[slot, :, pl.ds(i * page_size, page_size)],
                    kv_sems.at[slot, 0, i]).start()
                pltpu.make_async_copy(
                    v_hbm.at[layer, page_id],
                    v_vmem.at[slot, :, pl.ds(i * page_size, page_size)],
                    kv_sems.at[slot, 1, i]).start()

        fetch(0, 0)  # warm-up overlaps the q DMA in flight
        q_dma.wait()

        q_tile = q_vmem[...].astype(jnp.float32) * sm_scale  # [BQ, QH, D]
        if bq == 1:
            # Decode: rows are heads; group slices are leading-dim slices.
            q_flat = q_tile.reshape(num_q_heads, head_dim)
            q_heads = [
                q_flat[h * group:(h + 1) * group]
                for h in range(num_kv_heads)
            ]
        else:
            q_heads = [
                q_tile[:, h * group:(h + 1) * group, :].reshape(
                    bq * group, head_dim) for h in range(num_kv_heads)
            ]
        rows = bq * group

        row_pos = (kv_len - q_len + tile_start +
                   jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 0) //
                   group)
        col_base = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)
        row_valid = (jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 0) //
                     group + tile_start) < q_len

        def body(b, carry):
            ms, ls, accs = carry
            kv_start = b * blk
            slot = jax.lax.rem(b, 2)

            @pl.when(b + 1 < num_blocks)
            def _prefetch():
                fetch(b + 1, jax.lax.rem(b + 1, 2))

            for i in range(ppb):
                pltpu.make_async_copy(
                    k_hbm.at[0, 0],
                    k_vmem.at[slot, :, pl.ds(i * page_size, page_size)],
                    kv_sems.at[slot, 0, i]).wait()
                pltpu.make_async_copy(
                    v_hbm.at[0, 0],
                    v_vmem.at[slot, :, pl.ds(i * page_size, page_size)],
                    kv_sems.at[slot, 1, i]).wait()
            k_blk = k_vmem[slot]  # [KVH, BLK, D]
            v_blk = v_vmem[slot]

            kv_pos = kv_start + col_base
            mask = jnp.logical_and(kv_pos <= row_pos, row_valid)

            new_ms, new_ls, new_accs = [], [], []
            for h in range(num_kv_heads):
                k_h = k_blk[h]  # [BLK, D]
                v_h = v_blk[h]
                s = jax.lax.dot_general(
                    q_heads[h], k_h.astype(jnp.float32),
                    dimension_numbers=(((1, ), (1, )), ((), ())),
                    preferred_element_type=jnp.float32)  # [rows, BLK]
                s = jnp.where(mask, s, _MASK_VALUE)
                m_prev, l_prev, acc_prev = ms[h], ls[h], accs[h]
                m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
                p = jnp.exp(s - m_new)
                alpha = jnp.exp(m_prev - m_new)
                l_new = l_prev * alpha + p.sum(axis=-1, keepdims=True)
                pv = jax.lax.dot_general(
                    p.astype(v_h.dtype), v_h,
                    dimension_numbers=(((1, ), (0, )), ((), ())),
                    preferred_element_type=jnp.float32)  # [rows, D]
                acc_new = acc_prev * alpha + pv
                new_ms.append(m_new)
                new_ls.append(l_new)
                new_accs.append(acc_new)
            return tuple(new_ms), tuple(new_ls), tuple(new_accs)

        init = (
            tuple(
                jnp.full((rows, 1), _MASK_VALUE, jnp.float32)
                for _ in range(num_kv_heads)),
            tuple(
                jnp.zeros((rows, 1), jnp.float32)
                for _ in range(num_kv_heads)),
            tuple(
                jnp.zeros((rows, head_dim), jnp.float32)
                for _ in range(num_kv_heads)),
        )
        ms, ls, accs = jax.lax.fori_loop(0, num_blocks, body, init)

        half = head_dim // 2
        for h in range(num_kv_heads):
            o_h = accs[h] / jnp.maximum(ls[h], 1e-20)  # [rows, D]
            if bq == 1:
                out_stage[0, h * group:(h + 1) * group, :] = (
                    o_h.astype(out_stage.dtype))
            else:
                out_stage[:, h * group:(h + 1) * group, :] = (
                    o_h.reshape(bq, group, head_dim).astype(
                        out_stage.dtype))
            if emit_state:
                # Online-softmax partial state for exact merging with
                # another KV range (cascade): m broadcast over the low
                # lanes, l over the high — lane-sliced out by the
                # caller. Full-D staging keeps the DMA tile-aligned.
                st = jnp.concatenate([
                    jnp.broadcast_to(ms[h], (rows, half)),
                    jnp.broadcast_to(ls[h], (rows, head_dim - half)),
                ], axis=-1)
                if bq == 1:
                    state_stage[0, h * group:(h + 1) * group, :] = st
                else:
                    state_stage[:, h * group:(h + 1) * group, :] = (
                        st.reshape(bq, group, head_dim))
        out_dma = pltpu.make_async_copy(
            out_stage, out_hbm.at[pl.ds(q_start + tile_start, bq)],
            out_sem)
        out_dma.start()
        if emit_state:
            st_dma = pltpu.make_async_copy(
                state_stage,
                state_hbm.at[pl.ds(q_start + tile_start, bq)], state_sem)
            st_dma.start()
            st_dma.wait()
        out_dma.wait()


def _decode_kernel(
    # scalar prefetch
    seq_info_ref,  # [R, 4] int32: q_start, q_len, kv_len, batch_row
    num_seqs_ref,  # [1] int32
    layer_ref,  # [1] int32
    block_tables_ref,  # [max_reqs, pages_per_req] int32
    # tensor inputs (HBM)
    q_hbm,  # [T_pad, QH, D]
    k_hbm,  # [L, num_pages, KVH, PS, D]
    v_hbm,
    out_hbm,
    # scratch
    q_vmem,  # [SB, QH, D]
    k_vmem,  # [2, SB, KVH, blk, D] double-buffered
    v_vmem,
    out_stage,  # [SB, QH, D]
    q_sem,
    kv_sems,  # [2, 2, SB, ppb]
    out_sem,
    *,
    sm_scale: float,
    sb: int,
    ppb: int,
    page_size: int,
    group: int,
):
    """Decode-specialized attention: SB sequences per grid program.

    Decode starves the MXU when each sequence's score dot is only
    ``group`` rows (VERDICT r4: 4–8 rows on a 128x128 array). Here the
    SB sequences x KVH kv-heads of a program are stacked as SB*KVH
    "virtual heads": ONE [SB*QH, D] x [D, SB*KVH*blk] dot scores every
    sequence at once, with a block-diagonal mask (virtual head of query
    row == virtual head of kv column) recovering per-sequence/per-head
    attention. Cross-terms cost flops the DMA-bound loop has to spare;
    rows go from `group` to SB*QH. KV pages double-buffer across the
    block loop exactly like the general kernel.

    Layout contract (decode steps only): every scheduled sequence has
    q_len == 1; its query row is read through seq_info's q_start, so
    compacted/scattered layouts (token parallelism's per-rank lists)
    work unchanged.
    """
    p = pl.program_id(0)
    num_seqs = num_seqs_ref[0]
    layer = layer_ref[0]
    QH = q_vmem.shape[1]
    KVH = k_vmem.shape[2]
    D = q_vmem.shape[2]
    blk = ppb * page_size
    base = p * sb
    ROWS = sb * QH
    C = sb * KVH * blk

    # Per-sequence scalars (static unroll over the SB slots). Inactive
    # slots read row 0's metadata but mask everything via kv_len = 0.
    idx = [jnp.minimum(base + i, seq_info_ref.shape[0] - 1)
           for i in range(sb)]
    kv_lens = [
        jnp.where(base + i < num_seqs, seq_info_ref[idx[i], 2], 0)
        for i in range(sb)
    ]
    rows_ = [seq_info_ref[idx[i], 3] for i in range(sb)]
    q_starts = [seq_info_ref[idx[i], 0] for i in range(sb)]

    max_kv = kv_lens[0]
    for i in range(1, sb):
        max_kv = jnp.maximum(max_kv, kv_lens[i])
    num_blocks = jax.lax.div(max_kv - 1, blk) + 1  # 0 when all inactive

    @pl.when(base < num_seqs)
    def _run():
        for i in range(sb):
            pltpu.make_async_copy(
                q_hbm.at[pl.ds(q_starts[i], 1)],
                q_vmem.at[pl.ds(i, 1)], q_sem.at[i]).start()

        def fetch(b, slot):
            for i in range(sb):
                # Clamp past-the-end blocks of shorter sequences to
                # their last valid block: the DMA stays in-bounds and
                # the mask discards the stale columns.
                bi = jnp.clip(b, 0,
                              jnp.maximum(
                                  jax.lax.div(kv_lens[i] - 1, blk), 0))
                for j in range(ppb):
                    page_id = block_tables_ref[rows_[i], bi * ppb + j]
                    pltpu.make_async_copy(
                        k_hbm.at[layer, page_id],
                        k_vmem.at[slot, i, :,
                                  pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 0, i, j]).start()
                    pltpu.make_async_copy(
                        v_hbm.at[layer, page_id],
                        v_vmem.at[slot, i, :,
                                  pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 1, i, j]).start()

        fetch(0, 0)
        for i in range(sb):
            pltpu.make_async_copy(
                q_hbm.at[pl.ds(0, 1)], q_vmem.at[pl.ds(i, 1)],
                q_sem.at[i]).wait()
        q_all = (q_vmem[...].astype(jnp.float32) * sm_scale).reshape(
            ROWS, D)

        # Block-diagonal structure: query row r belongs to virtual head
        # r // group (rows are seq-major then head-major, QH = KVH *
        # group); kv column c belongs to virtual head c // blk.
        vh_r = jax.lax.broadcasted_iota(jnp.int32, (ROWS, C), 0) // group
        vh_c = jax.lax.broadcasted_iota(jnp.int32, (ROWS, C), 1) // blk
        diag = vh_r == vh_c
        col_off = jax.lax.broadcasted_iota(jnp.int32, (ROWS, C), 1) % blk
        kvlen_rows = jnp.concatenate(
            [jnp.full((QH, ), kv_lens[i], jnp.int32) for i in range(sb)])

        def body(b, carry):
            m_prev, l_prev, acc_prev = carry
            slot = jax.lax.rem(b, 2)

            @pl.when(b + 1 < num_blocks)
            def _prefetch():
                fetch(b + 1, jax.lax.rem(b + 1, 2))

            for i in range(sb):
                for j in range(ppb):
                    pltpu.make_async_copy(
                        k_hbm.at[0, 0],
                        k_vmem.at[slot, i, :,
                                  pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 0, i, j]).wait()
                    pltpu.make_async_copy(
                        v_hbm.at[0, 0],
                        v_vmem.at[slot, i, :,
                                  pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 1, i, j]).wait()
            k_all = k_vmem[slot].reshape(C, D)  # [SB*KVH*blk, D]
            v_all = v_vmem[slot].reshape(C, D)

            s = jax.lax.dot_general(
                q_all, k_all.astype(jnp.float32),
                dimension_numbers=(((1, ), (1, )), ((), ())),
                preferred_element_type=jnp.float32)  # [ROWS, C]
            mask = jnp.logical_and(
                diag, b * blk + col_off < kvlen_rows[:, None])
            s = jnp.where(mask, s, _MASK_VALUE)

            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            pr = jnp.exp(s - m_new)
            # Zero the off-diagonal terms so the PV dot sums only each
            # row's own block (exp(_MASK_VALUE - m) underflows to 0
            # already; the where guards m == _MASK_VALUE rows).
            pr = jnp.where(mask, pr, 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + pr.sum(axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                pr.astype(v_all.dtype), v_all,
                dimension_numbers=(((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)  # [ROWS, D]
            return m_new, l_new, acc_prev * alpha + pv

        init = (
            jnp.full((ROWS, 1), _MASK_VALUE, jnp.float32),
            jnp.zeros((ROWS, 1), jnp.float32),
            jnp.zeros((ROWS, D), jnp.float32),
        )
        _m, l_fin, acc = jax.lax.fori_loop(0, num_blocks, body, init)
        out = acc / jnp.maximum(l_fin, 1e-20)
        out_stage[...] = out.reshape(sb, QH, D).astype(out_stage.dtype)
        # Per-sequence writeback through q_start; inactive slots MUST
        # NOT write (their q_start aliases row 0 — a real token).
        for i in range(sb):
            @pl.when(base + i < num_seqs)
            def _wb(i=i):
                pltpu.make_async_copy(
                    out_stage.at[pl.ds(i, 1)],
                    out_hbm.at[pl.ds(q_starts[i], 1)],
                    out_sem.at[i]).start()
        for i in range(sb):
            @pl.when(base + i < num_seqs)
            def _wb_wait(i=i):
                pltpu.make_async_copy(
                    out_stage.at[pl.ds(i, 1)],
                    out_hbm.at[pl.ds(0, 1)], out_sem.at[i]).wait()


def _decode_call(q, k_pages, v_pages, seq_info, num_seqs, block_tables,
                 layer, *, sm_scale, interpret):
    """Launch the SB-batched decode kernel (max_q == 1, no state)."""
    T_pad, num_q_heads, head_dim = q.shape
    _, _, num_kv_heads, page_size, _ = k_pages.shape
    group = num_q_heads // num_kv_heads
    R = seq_info.shape[0]
    pages_per_req = block_tables.shape[1]
    ppb = max(1, min(128 // page_size, pages_per_req))
    while pages_per_req % ppb:
        ppb -= 1
    blk = ppb * page_size

    sb = max(1, min(8, R, 128 // max(1, num_q_heads // 4)))
    # Score tile [sb*QH, sb*KVH*blk] f32 (+ exp copy) dominates VMEM.
    while sb > 1 and (sb * num_q_heads) * (sb * num_kv_heads * blk) * 8 \
            > 8 * 1024**2:
        sb //= 2
    assert T_pad >= R, "decode q must cover one row per sequence"
    # The last program reads/writes rows [base, base+sb); keep that
    # inside the q padding when R is not a multiple of sb.
    while sb > 1 and pl.cdiv(R, sb) * sb > T_pad:
        sb //= 2

    grid = (pl.cdiv(R, sb), )
    kernel = functools.partial(
        _decode_kernel, sm_scale=sm_scale, sb=sb, ppb=ppb,
        page_size=page_size, group=group)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # q
            pl.BlockSpec(memory_space=pltpu.ANY),  # k_pages
            pl.BlockSpec(memory_space=pltpu.ANY),  # v_pages
        ],
        out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        scratch_shapes=[
            pltpu.VMEM((sb, num_q_heads, head_dim), q.dtype),
            pltpu.VMEM((2, sb, num_kv_heads, blk, head_dim),
                       k_pages.dtype),
            pltpu.VMEM((2, sb, num_kv_heads, blk, head_dim),
                       v_pages.dtype),
            pltpu.VMEM((sb, num_q_heads, head_dim), q.dtype),
            pltpu.SemaphoreType.DMA((sb, )),
            pltpu.SemaphoreType.DMA((2, 2, sb, ppb)),
            pltpu.SemaphoreType.DMA((sb, )),
        ],
    )
    (out, ) = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        interpret=interpret,
    )(seq_info, num_seqs, layer, block_tables, q, k_pages, v_pages)
    return out


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "max_q", "interpret", "emit_state"))
def ragged_paged_attention_pallas(
    q: jax.Array,  # [T_pad, QH, D]; T_pad >= T + q_tile padding
    k_pages: jax.Array,  # [L, num_pages, KVH, PS, D] full stacked cache
    v_pages: jax.Array,
    seq_info: jax.Array,  # [R, 4] int32 (q_start, q_len, kv_len, row)
    num_seqs: jax.Array,  # [1] int32
    block_tables: jax.Array,  # [max_reqs, pages_per_req] int32
    layer: jax.Array | None = None,  # [1] int32
    *,
    sm_scale: float,
    max_q: int,
    interpret: bool | None = None,
    emit_state: bool = False,
):
    """Unified prefill/decode attention over the paged KV cache.

    ``max_q`` is the static per-sequence query bucket (1 for pure decode).
    The cache keeps its stacked layer dim; ``layer`` selects the slice to
    read (pages are DMA'd as [layer, page] — no layer copy materializes).
    Returns [T_pad, QH, D]; rows past each sequence's q_len are garbage.

    ``emit_state=True`` additionally returns the online-softmax partial
    state as an f32 [T_pad, QH, D] array with the row max broadcast over
    lanes [0, D/2) and the exp-sum over [D/2, D) — what cascade needs to
    merge this call's KV range with a shared-prefix phase exactly
    (reference: csrc/attention/merge_attn_states.cu exports the same
    (max, sumexp) pair).
    """
    if interpret is None:
        interpret = envs.VDT_PALLAS_INTERPRET
    if k_pages.ndim == 4:
        # Single-layer convenience form (tests).
        k_pages = k_pages[None]
        v_pages = v_pages[None]
    if layer is None:
        layer = jnp.zeros((1, ), jnp.int32)
    T_pad, num_q_heads, head_dim = q.shape
    _, num_pages, num_kv_heads, page_size, _ = k_pages.shape
    assert num_q_heads % num_kv_heads == 0
    group = num_q_heads // num_kv_heads
    R = seq_info.shape[0]
    pages_per_req = block_tables.shape[1]

    if max_q == 1 and not emit_state:
        # Pure decode: the SB-batched kernel fills the MXU (see
        # _decode_kernel). Cascade's emit_state decode stays on the
        # general kernel (it exports per-row softmax state).
        return _decode_call(q, k_pages, v_pages, seq_info, num_seqs,
                            block_tables, layer, sm_scale=sm_scale,
                            interpret=interpret)

    bq = min(max_q, 128)
    # Keep the per-program footprint (q/out staging, f32 accumulators and
    # their loop-carry double buffers, per-head score tiles) inside the
    # ~16MB VMEM budget: shrink the q tile for wide-head models.
    while bq > 8 and bq * num_q_heads * head_dim * 32 > 12 * 1024**2:
        bq //= 2
    num_q_tiles = pl.cdiv(max_q, bq)
    assert T_pad >= bq, "q must be padded to at least one tile"
    # ~128 kv positions per block, at least one page.
    ppb = max(1, min(128 // page_size, pages_per_req))
    while pages_per_req % ppb:
        ppb -= 1
    blk = ppb * page_size

    grid = (R, num_q_tiles)
    kernel = functools.partial(
        _kernel, sm_scale=sm_scale, bq=bq, ppb=ppb, page_size=page_size,
        group=group, emit_state=emit_state)

    scratch = [
        pltpu.VMEM((bq, num_q_heads, head_dim), q.dtype),
        pltpu.VMEM((2, num_kv_heads, blk, head_dim), k_pages.dtype),
        pltpu.VMEM((2, num_kv_heads, blk, head_dim), v_pages.dtype),
        pltpu.VMEM((bq, num_q_heads, head_dim), q.dtype),
    ]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    out_specs = [pl.BlockSpec(memory_space=pltpu.ANY)]
    if emit_state:
        scratch.append(
            pltpu.VMEM((bq, num_q_heads, head_dim), jnp.float32))
        out_shape.append(
            jax.ShapeDtypeStruct(q.shape, jnp.float32))
        out_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
    scratch += [
        pltpu.SemaphoreType.DMA(()),
        pltpu.SemaphoreType.DMA((2, 2, ppb)),  # [slot, k/v, page]
        pltpu.SemaphoreType.DMA(()),
    ]
    if emit_state:
        scratch.append(pltpu.SemaphoreType.DMA(()))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # q
            pl.BlockSpec(memory_space=pltpu.ANY),  # k_pages
            pl.BlockSpec(memory_space=pltpu.ANY),  # v_pages
        ],
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    result = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(seq_info, num_seqs, layer, block_tables, q, k_pages, v_pages)
    if emit_state:
        return tuple(result)
    return result[0]
