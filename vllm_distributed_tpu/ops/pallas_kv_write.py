"""Pallas KV-cache write kernel (in-place, aliased).

TPU-native equivalent of the reference's reshape_and_cache CUDA kernel
(csrc/cache_kernels.cu:211) and the kv_cache_update Pallas op its TPU
backend uses (vllm/attention/ops/pallas_kv_cache_update.py, wired with
input/output aliasing at v1/attention/backends/pallas.py:282). Key design
points:

* Operates on the FULL stacked cache [L, N, KVH, PS, D] with the layer as
  a scalar operand, so the per-layer loop never materializes a layer
  slice — XLA would otherwise copy the whole cache through every
  ``lax.scan`` iteration (the original cause of decode steps costing
  ~cache-size in HBM traffic).
* ``input_output_aliases`` make the op update the cache buffer in place;
  only the touched pages move.
* Writes are grouped into page *runs* (maximal consecutive-slot spans
  within one page; a decode token is a run of length 1, a full prefill
  page a run of length PS). Each run is a read-modify-write of one page:
  DMA the page to VMEM, blend the new rows in with a vector select, DMA
  it back. Runs in one step always touch distinct pages, and the TPU grid
  executes programs in order, so RMW is race-free.
* New K/V arrive head-leading [KVH, T + 3*PS, D] with PS padding rows at
  the front and 2*PS at the back, so each run can fetch a page-aligned
  2*PS window around its rows: target window row p corresponds to flat
  token (window_start - PS) + p.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_distributed_tpu import envs


def page_rmw(page, off_start, window_start, run_len, layer, k_new, v_new,
             k_dst, v_dst, k_page, v_page, k_win, v_win, sems, *,
             page_size: int):
    """Read-modify-write ONE cache page with a run of new K/V rows —
    the body shared by the standalone write kernel below and the
    attention mega-kernel's fused kind-3 programs
    (ops/pallas_attention.py). Traced scalars + refs in, DMAs out; the
    caller guards activity (run_len > 0) with pl.when."""
    full = run_len == page_size
    # Mosaic requires provably tile-aligned starts when slicing the
    # sublane dim of an HBM ref: fetch a page-aligned 2*PS window and
    # shift to the exact rows in-register below.
    aligned = pl.multiple_of(
        (window_start // page_size) * page_size, page_size)
    shift = window_start - aligned
    kw = pltpu.make_async_copy(
        k_new.at[:, pl.ds(aligned, 2 * page_size)], k_win, sems.at[0])
    vw = pltpu.make_async_copy(
        v_new.at[:, pl.ds(aligned, 2 * page_size)], v_win, sems.at[1])
    kw.start()
    vw.start()

    @pl.when(jnp.logical_not(full))
    def _read_page():
        kp = pltpu.make_async_copy(k_dst.at[layer, page], k_page,
                                   sems.at[2])
        vp = pltpu.make_async_copy(v_dst.at[layer, page], v_page,
                                   sems.at[3])
        kp.start()
        vp.start()
        kp.wait()
        vp.wait()

    kw.wait()
    vw.wait()

    # Shift the 2*PS window down by `shift` rows via a one-hot
    # selection matmul (Mosaic has no dynamic_slice on values; the
    # 0/1 matrix keeps the selection exact in any dtype).
    num_kv_heads = k_page.shape[0]
    w_ids = jax.lax.broadcasted_iota(jnp.int32,
                                     (page_size, 2 * page_size), 1)
    p_ids = jax.lax.broadcasted_iota(jnp.int32,
                                     (page_size, 2 * page_size), 0)
    sel = (w_ids == p_ids + shift).astype(jnp.float32)

    # Window rows outside the run hold neighbouring flat-batch tokens
    # (or padding garbage, possibly NaN/Inf): zero them before the
    # selection matmul — 0 * NaN = NaN would otherwise poison every
    # selected row of the page.
    w_row = jax.lax.broadcasted_iota(jnp.int32, (2 * page_size, 1), 0)
    w_valid = jnp.logical_and(w_row >= shift + off_start,
                              w_row < shift + off_start + run_len)

    def shifted(win_ref):
        return jnp.stack([
            jax.lax.dot(sel,
                        jnp.where(w_valid,
                                  win_ref[h].astype(jnp.float32), 0.0),
                        preferred_element_type=jnp.float32)
            for h in range(num_kv_heads)
        ]).astype(k_page.dtype)

    k_rows = shifted(k_win)
    v_rows = shifted(v_win)
    row = jax.lax.broadcasted_iota(jnp.int32,
                                   (1, page_size, 1), 1)
    mask = jnp.logical_and(row >= off_start,
                           row < off_start + run_len)
    mask = jnp.logical_or(full, mask)
    k_page[...] = jnp.where(mask, k_rows, k_page[...])
    v_page[...] = jnp.where(mask, v_rows, v_page[...])

    kb = pltpu.make_async_copy(k_page, k_dst.at[layer, page],
                               sems.at[2])
    vb = pltpu.make_async_copy(v_page, v_dst.at[layer, page],
                               sems.at[3])
    kb.start()
    vb.start()
    kb.wait()
    vb.wait()


def _kernel(
    # scalar prefetch
    runs_ref,  # [G, 4] int32: page, off_start, window_start, run_len
    num_runs_ref,  # [1] int32
    layer_ref,  # [1] int32
    # tensors (HBM)
    k_new,  # [KVH, T + 2*PS, D]
    v_new,
    k_all,  # [L, N, KVH, PS, D] (aliased input)
    v_all,
    # outputs (aliased to k_all, v_all)
    k_out,
    v_out,
    # scratch
    k_page,  # [KVH, PS, D]
    v_page,
    k_win,  # [KVH, PS, D]
    v_win,
    sems,  # DMA [4]
    *,
    page_size: int,
):
    g = pl.program_id(0)
    page = runs_ref[g, 0]
    off_start = runs_ref[g, 1]
    window_start = runs_ref[g, 2]
    run_len = runs_ref[g, 3]
    layer = layer_ref[0]
    active = jnp.logical_and(g < num_runs_ref[0], run_len > 0)

    @pl.when(active)
    def _run():
        page_rmw(page, off_start, window_start, run_len, layer, k_new,
                 v_new, k_out, v_out, k_page, v_page, k_win, v_win,
                 sems, page_size=page_size)


@functools.partial(jax.jit, static_argnames=("interpret", ))
def write_kv_pages_pallas(
    k_all: jax.Array,  # [L, N, KVH, PS, D]
    v_all: jax.Array,
    k_new_hl: jax.Array,  # [KVH, T + 2*PS, D] head-leading, padded
    v_new_hl: jax.Array,
    runs: jax.Array,  # [G, 4] int32 (page, off_start, window_start, len)
    num_runs: jax.Array,  # [1] int32
    layer: jax.Array,  # [1] int32
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Blend the step's new K/V rows into their cache pages in place."""
    if interpret is None:
        interpret = envs.VDT_PALLAS_INTERPRET
    L, N, KVH, PS, D = k_all.shape
    G = runs.shape[0]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(G, ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # k_new
            pl.BlockSpec(memory_space=pltpu.ANY),  # v_new
            pl.BlockSpec(memory_space=pltpu.ANY),  # k_all
            pl.BlockSpec(memory_space=pltpu.ANY),  # v_all
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((KVH, PS, D), k_all.dtype),
            pltpu.VMEM((KVH, PS, D), v_all.dtype),
            pltpu.VMEM((KVH, 2 * PS, D), k_all.dtype),
            pltpu.VMEM((KVH, 2 * PS, D), v_all.dtype),
            pltpu.SemaphoreType.DMA((4, )),
        ],
    )
    kernel = functools.partial(_kernel, page_size=PS)
    # Operand order: 3 scalar-prefetch args, then tensor inputs; the cache
    # arrays (flat input indices 5 and 6) alias the two outputs.
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(k_all.shape, k_all.dtype),
            jax.ShapeDtypeStruct(v_all.shape, v_all.dtype),
        ),
        input_output_aliases={5: 0, 6: 1},
        interpret=interpret,
    )(runs, num_runs, layer, k_new_hl, v_new_hl, k_all, v_all)
