"""Per-request tracing spans.

Reference: vllm/tracing.py:52 — ``init_tracer`` builds an OTLP exporter
and the engine emits one span per finished request with SpanAttributes
(:98) covering queue/prefill/e2e latencies and token counts, enabled by
ObservabilityConfig.otlp_traces_endpoint.

This environment ships only the opentelemetry API shim (no SDK), so the
tracer degrades gracefully: an ``http(s)://``/``grpc://`` endpoint uses
the OTel SDK when importable, and a ``file://`` (or bare path) endpoint
appends one JSON line per span — same attribute names, no dependency.
"""

import json
import threading
import time
from typing import Optional

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


class SpanAttributes:
    """Attribute names (reference: tracing.py:98 SpanAttributes)."""

    GEN_AI_REQUEST_ID = "gen_ai.request.id"
    GEN_AI_REQUEST_MAX_TOKENS = "gen_ai.request.max_tokens"
    GEN_AI_REQUEST_TEMPERATURE = "gen_ai.request.temperature"
    GEN_AI_USAGE_PROMPT_TOKENS = "gen_ai.usage.prompt_tokens"
    GEN_AI_USAGE_COMPLETION_TOKENS = "gen_ai.usage.completion_tokens"
    GEN_AI_LATENCY_TIME_TO_FIRST_TOKEN = \
        "gen_ai.latency.time_to_first_token"
    GEN_AI_LATENCY_E2E = "gen_ai.latency.e2e"
    GEN_AI_RESPONSE_FINISH_REASON = "gen_ai.response.finish_reason"


class RequestTracer:
    """Emits one span per finished request."""

    def emit(self, attributes: dict) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class JsonlTracer(RequestTracer):
    """Dependency-free exporter: one JSON object per span, appended to a
    file (endpoint "file:///path" or a bare path)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        logger.info("request tracing -> %s (jsonl)", path)

    def emit(self, attributes: dict) -> None:
        record = {"name": "llm_request", "ts": time.time(),  # wallclock-ok
                  "attributes": attributes}
        with self._lock, open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")


class OtelTracer(RequestTracer):
    def __init__(self, endpoint: str) -> None:
        from opentelemetry import trace
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter \
            import OTLPSpanExporter
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
        provider = TracerProvider()
        provider.add_span_processor(
            BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint)))
        self._provider = provider
        self._tracer = trace.get_tracer("vllm_distributed_tpu",
                                        tracer_provider=provider)
        logger.info("request tracing -> %s (otlp)", endpoint)

    def emit(self, attributes: dict) -> None:
        with self._tracer.start_as_current_span("llm_request") as span:
            for key, value in attributes.items():
                span.set_attribute(key, value)

    def shutdown(self) -> None:
        self._provider.shutdown()


def init_tracer(endpoint: Optional[str]) -> Optional[RequestTracer]:
    """None endpoint disables tracing (reference: is_otel_available +
    init_tracer gating)."""
    if not endpoint:
        return None
    if endpoint.startswith(("http://", "https://", "grpc://")):
        try:
            return OtelTracer(endpoint)
        except Exception as e:  # noqa: BLE001 - degrade, don't die
            logger.warning(
                "OTLP exporter unavailable (%s); tracing disabled "
                "(use a file:// endpoint for the built-in exporter)", e)
            return None
    path = endpoint[len("file://"):] if endpoint.startswith("file://") \
        else endpoint
    return JsonlTracer(path)
