"""Per-request tracing spans.

Reference: vllm/tracing.py:52 — ``init_tracer`` builds an OTLP exporter
and the engine emits one span per finished request with SpanAttributes
(:98) covering queue/prefill/e2e latencies and token counts, enabled by
ObservabilityConfig.otlp_traces_endpoint.

Beyond the reference's single flat span, ``emit`` takes the request's
phase intervals (computed by ``metrics/events.phases_from_timeline``
from the lifecycle timeline) and renders them as CHILD spans — queue,
kv_pull, prefill, decode, stalls — under one parent span per request,
so "where did this request's 4 seconds go" is answerable per request.
A replayed continuation (crash recovery) keeps the original request id,
so its trace survives the engine restart as one parent span whose
timeline carries the journal/replay events.

This environment ships only the opentelemetry API shim (no SDK), so the
tracer degrades gracefully: an ``http(s)://``/``grpc://`` endpoint uses
the OTel SDK when importable, and a ``file://`` (or bare path) endpoint
appends one JSON line per span — same attribute names, no dependency.
"""

import json
import os
import threading
import time
from typing import Optional

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


class SpanAttributes:
    """Attribute names (reference: tracing.py:98 SpanAttributes)."""

    GEN_AI_REQUEST_ID = "gen_ai.request.id"
    GEN_AI_REQUEST_MAX_TOKENS = "gen_ai.request.max_tokens"
    GEN_AI_REQUEST_TEMPERATURE = "gen_ai.request.temperature"
    GEN_AI_USAGE_PROMPT_TOKENS = "gen_ai.usage.prompt_tokens"
    GEN_AI_USAGE_COMPLETION_TOKENS = "gen_ai.usage.completion_tokens"
    GEN_AI_LATENCY_TIME_TO_FIRST_TOKEN = \
        "gen_ai.latency.time_to_first_token"
    GEN_AI_LATENCY_E2E = "gen_ai.latency.e2e"
    GEN_AI_RESPONSE_FINISH_REASON = "gen_ai.response.finish_reason"
    # Distributed trace plane (VDT_TRACE_PLANE): the fleet-wide trace
    # id minted at admission — join key against the /debug/trace
    # assembler and any foreign replica's spans.
    GEN_AI_TRACE_ID = "gen_ai.request.trace_id"


# Component lanes rendered as their own child spans when the request's
# timeline carries matching events (disagg handoffs, fleet actuations,
# KV-tier moves, router placement) — the cross-subsystem legs the flat
# per-request span never showed.
_COMPONENT_SPAN_LANES = ("router", "disagg", "kv_transfer", "kv_tier",
                         "fleet")


def component_events(events: Optional[list]) -> dict[str, list]:
    """Group a request's relative-timestamp event list by component
    lane, keeping only the cross-subsystem lanes worth their own child
    spans. ``events`` rows are ``[rel_ts, event, detail]``."""
    if not events:
        return {}
    from vllm_distributed_tpu.trace_plane import component_of
    lanes: dict[str, list] = {}
    for row in events:
        try:
            lane = component_of(row[1])
        except (IndexError, TypeError):
            continue
        if lane in _COMPONENT_SPAN_LANES:
            lanes.setdefault(lane, []).append(row)
    return lanes


class RequestTracer:
    """Emits one parent span (with optional phase child spans) per
    finished request."""

    def emit(self, attributes: dict,
             phases: Optional[list[dict]] = None,
             events: Optional[list] = None) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class JsonlTracer(RequestTracer):
    """Dependency-free exporter: one JSON object per span, appended to a
    file (endpoint "file:///path" or a bare path). Keeps a persistent
    file handle (reopening per span is wasteful under load) but follows
    log rotation: each emit compares the path's (dev, inode) against
    the open handle (one stat, logging.WatchedFileHandler's trick —
    writes to a renamed/unlinked file still SUCCEED, so failure-driven
    reopening alone would strand spans on the rotated inode). Never
    raises out of ``emit`` — a full disk or bad path degrades tracing
    instead of killing the output processor."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._file = None
        self._broken = False
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        logger.info("request tracing -> %s (jsonl)", path)

    def emit(self, attributes: dict,
             phases: Optional[list[dict]] = None,
             events: Optional[list] = None) -> None:
        record = {"name": "llm_request", "ts": time.time(),  # wallclock-ok
                  "attributes": attributes}
        if phases:
            # Child phase spans, start/duration relative to the parent
            # span's start (the earliest phase start).
            t0 = min(p["start"] for p in phases)
            record["phases"] = [{
                "phase": p["phase"],
                "start_s": round(p["start"] - t0, 6),
                "duration_s": round(p["end"] - p["start"], 6),
            } for p in phases]
        if events:
            record["events"] = events
            lanes = component_events(events)
            if lanes:
                # Cross-subsystem legs as explicit child records: one
                # per component lane spanning its first->last event.
                record["components"] = [{
                    "component": lane,
                    "start_s": rows[0][0],
                    "duration_s": round(rows[-1][0] - rows[0][0], 6),
                    "events": [r[1] for r in rows],
                } for lane, rows in sorted(lanes.items())]
        try:
            with self._lock:
                self._ensure_file_locked()
                self._file.write(json.dumps(record) + "\n")
                self._file.flush()
        except Exception as e:  # noqa: BLE001 - degrade, don't die
            # Drop the handle so the next emit reopens the path — a
            # transiently bad handle (ENOSPC recovery, closed fd) must
            # not divert spans forever.
            with self._lock:
                if self._file is not None:
                    try:
                        self._file.close()
                    except Exception:  # noqa: BLE001 - already broken
                        pass
                    self._file = None
            if not self._broken:
                self._broken = True
                logger.warning("trace emit to %s failed (%s); further "
                               "failures logged at debug", self.path, e)
            else:
                logger.debug("trace emit failed: %s", e)

    def _ensure_file_locked(self) -> None:
        """Open (or re-open after rotation) the span file. Caller holds
        the lock. Rotation check: the handle's inode no longer matches
        the path's (renamed) or the path is gone (unlinked)."""
        if self._file is not None:
            try:
                st = os.stat(self.path)
                fst = os.fstat(self._file.fileno())
                if (st.st_dev, st.st_ino) == (fst.st_dev, fst.st_ino):
                    return
            except OSError:
                pass  # path missing/unstattable: reopen below
            try:
                self._file.close()
            except Exception:  # noqa: BLE001 - stale handle
                pass
            self._file = None
        self._file = open(self.path, "a")

    def shutdown(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except Exception:  # noqa: BLE001 - teardown
                    pass
                self._file = None


class OtelTracer(RequestTracer):
    def __init__(self, endpoint: str) -> None:
        from opentelemetry import trace
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter \
            import OTLPSpanExporter
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
        provider = TracerProvider()
        provider.add_span_processor(
            BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint)))
        self._provider = provider
        self._tracer = trace.get_tracer("vllm_distributed_tpu",
                                        tracer_provider=provider)
        logger.info("request tracing -> %s (otlp)", endpoint)

    def emit(self, attributes: dict,
             phases: Optional[list[dict]] = None,
             events: Optional[list] = None) -> None:
        try:
            with self._tracer.start_as_current_span("llm_request") as span:
                for key, value in attributes.items():
                    span.set_attribute(key, value)
                for p in (phases or ()):
                    # Child span per phase under the active parent; the
                    # monotonic interval is carried as attributes (OTLP
                    # span times are wall-clock epoch ns).
                    with self._tracer.start_as_current_span(
                            f"phase.{p['phase']}") as child:
                        child.set_attribute("phase", p["phase"])
                        child.set_attribute("duration_s",
                                            p["end"] - p["start"])
                for lane, rows in sorted(
                        component_events(events).items()):
                    # Cross-subsystem legs (router pick, disagg
                    # handoff, KV-tier moves, fleet actuations) as
                    # component child spans.
                    with self._tracer.start_as_current_span(
                            f"component.{lane}") as child:
                        child.set_attribute("component", lane)
                        child.set_attribute(
                            "duration_s", rows[-1][0] - rows[0][0])
                        child.set_attribute(
                            "events", ",".join(r[1] for r in rows))
        except Exception as e:  # noqa: BLE001 - degrade, don't die
            logger.debug("otel trace emit failed: %s", e)

    def shutdown(self) -> None:
        self._provider.shutdown()


def init_tracer(endpoint: Optional[str]) -> Optional[RequestTracer]:
    """None endpoint disables tracing (reference: is_otel_available +
    init_tracer gating)."""
    if not endpoint:
        return None
    if endpoint.startswith(("http://", "https://", "grpc://")):
        try:
            return OtelTracer(endpoint)
        except Exception as e:  # noqa: BLE001 - degrade, don't die
            logger.warning(
                "OTLP exporter unavailable (%s); tracing disabled "
                "(use a file:// endpoint for the built-in exporter)", e)
            return None
    path = endpoint[len("file://"):] if endpoint.startswith("file://") \
        else endpoint
    try:
        return JsonlTracer(path)
    except Exception as e:  # noqa: BLE001 - bad path degrades tracing
        logger.warning("jsonl tracer at %s unavailable (%s); tracing "
                       "disabled", path, e)
        return None
