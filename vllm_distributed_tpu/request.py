"""Engine-core request state (reference: vllm/v1/request.py).

A ``Request`` is the scheduler-side record of one in-flight generation: its
token ids, how many tokens have KV computed, its lifecycle status, and the
bookkeeping the KV-cache manager needs (block hashes are kept separately in
the manager).
"""

import copy
import enum
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from vllm_distributed_tpu.sampling_params import SamplingParams


class RequestStatus(enum.IntEnum):
    """Lifecycle of a request (reference: v1/request.py RequestStatus)."""

    WAITING = 0
    RUNNING = 1
    PREEMPTED = 2
    # Held out of the waiting queue until an async KV pull lands
    # (reference: v1/request.py WAITING_FOR_REMOTE_KVS).
    WAITING_FOR_REMOTE_KVS = 3
    # Terminal states below.
    FINISHED_STOPPED = 4
    FINISHED_LENGTH_CAPPED = 5
    FINISHED_ABORTED = 6
    FINISHED_IGNORED = 7

    @staticmethod
    def is_finished(status: "RequestStatus") -> bool:
        return status >= RequestStatus.FINISHED_STOPPED

    @staticmethod
    def get_finished_reason(status: "RequestStatus") -> Optional[str]:
        return _FINISHED_REASONS.get(status)


_FINISHED_REASONS: dict[RequestStatus, str] = {
    RequestStatus.FINISHED_STOPPED: "stop",
    RequestStatus.FINISHED_LENGTH_CAPPED: "length",
    RequestStatus.FINISHED_ABORTED: "abort",
    RequestStatus.FINISHED_IGNORED: "length",
}


@dataclass
class EngineCoreRequest:
    """Wire format between the engine front-end and the core
    (reference: v1/engine/__init__.py EngineCoreRequest)."""

    request_id: str
    prompt_token_ids: list[int]
    sampling_params: SamplingParams
    eos_token_id: Optional[int] = None
    # Epoch timestamp (user-facing stats), never deadline arithmetic.
    arrival_time: float = field(default_factory=time.time)  # wallclock-ok
    # Priority class (lower = more important, matching the scheduler's
    # priority policy): <= 0 is interactive, > 0 is best-effort — the
    # admission gate sheds best-effort traffic first under overload.
    priority: int = 0
    # Tenant identity (the OpenAI body's "tenant"/"user" field): labels
    # per-class shedding and debug introspection; never trusted for
    # isolation.
    tenant: Optional[str] = None
    # Disaggregated prefill routing (reference: kv_transfer_params on the
    # request, nixl_connector.py:205).
    kv_transfer_params: Optional[dict[str, Any]] = None
    # Multi-LoRA: {"name": ..., "path": ...} selecting the adapter
    # (reference: LoRARequest on add_request, vllm/lora/request.py).
    lora_request: Optional[dict[str, str]] = None
    # Embedding/pooling request: {"type": "last"} (reference:
    # vllm/pooling_params.py; pooled hidden state instead of sampling).
    pooling_params: Optional[dict[str, Any]] = None
    # Multimodal: positioned pre-computed encoder outputs, one per image
    # (multimodal/__init__.py MultiModalInput; reference: the mm_inputs
    # of v1/engine/__init__.py EngineCoreRequest).
    mm_inputs: Optional[list] = None
    # Distributed trace plane (VDT_TRACE_PLANE): {"trace_id": hex,
    # "span_id": hex} minted at admission. Deep-copied by
    # continuation_request and re-admitted verbatim by the disagg
    # handoff, so every hop of one request stamps the SAME trace id —
    # that is the cross-replica causal link. None when the plane is off
    # (serial.py then omits the key: old-wire byte-identical).
    trace_ctx: Optional[dict[str, Any]] = None


def continuation_request(orig: EngineCoreRequest,
                         generated: list[int]) -> EngineCoreRequest:
    """Continuation prefill for a crash-recovery replay: the journaled
    request's prompt absorbs the tokens already delivered downstream and
    the sampling budget shrinks by the same amount, so a respawned core
    (or a failover replica) resumes exactly where the dead one stopped —
    with greedy sampling the resumed stream is token-identical to an
    uninterrupted run."""
    req = copy.deepcopy(orig)
    # Never replay a remote-KV pull: by replay time the producer's
    # deferred-free registration is consumed or expired, so re-entering
    # WAITING_FOR_REMOTE_KVS would only burn the watchdog ladder before
    # degrading anyway — go straight to local (re)compute.
    req.kv_transfer_params = None
    if not generated:
        return req
    req.prompt_token_ids = list(orig.prompt_token_ids) + list(generated)
    sp = req.sampling_params
    if sp.max_tokens is not None:
        sp.max_tokens = max(1, sp.max_tokens - len(generated))
    if getattr(sp, "min_tokens", 0):
        sp.min_tokens = max(0, sp.min_tokens - len(generated))
    return req


class Request:
    """Scheduler-side mutable request state."""

    def __init__(
        self,
        request_id: str,
        prompt_token_ids: list[int],
        sampling_params: SamplingParams,
        eos_token_id: Optional[int] = None,
        arrival_time: Optional[float] = None,
        priority: int = 0,
        kv_transfer_params: Optional[dict[str, Any]] = None,
        lora_request: Optional[dict[str, str]] = None,
        pooling_params: Optional[dict[str, Any]] = None,
        mm_inputs: Optional[list] = None,
        tenant: Optional[str] = None,
        trace_ctx: Optional[dict[str, Any]] = None,
    ) -> None:
        self.request_id = request_id
        self.prompt_token_ids = prompt_token_ids
        # Deep-copy: the engine mutates stop sets / max_tokens below, and
        # callers routinely share one SamplingParams across a batch.
        self.sampling_params = copy.deepcopy(sampling_params)
        sampling_params = self.sampling_params
        self.eos_token_id = eos_token_id
        self.arrival_time = (time.time()  # wallclock-ok: epoch stat
                             if arrival_time is None else arrival_time)
        self.priority = priority
        self.tenant = tenant
        self.kv_transfer_params = kv_transfer_params
        self.lora_request = lora_request
        self.pooling_params = pooling_params
        self.mm_inputs = mm_inputs
        self.trace_ctx = trace_ctx
        # Content hash of the images, salted into the block hashes so
        # identical placeholder token ids with different images never
        # share prefix-cache pages (kv_cache_utils.hash_request_tokens).
        self.mm_hash: Optional[bytes] = None
        if mm_inputs:
            from vllm_distributed_tpu.multimodal import mm_content_hash
            self.mm_hash = mm_content_hash(mm_inputs)

        self.status = RequestStatus.WAITING
        self.stop_reason: Optional[int | str] = None

        # Lifecycle timeline (metrics/events.py): (monotonic_ts, event,
        # detail) tuples recorded by the scheduler at every transition,
        # drained onto the next EngineCoreOutput for this request so
        # the front-end can stitch phase spans. Appended only at
        # lifecycle TRANSITIONS, never per token — which is why the
        # async run-ahead grant is recorded once (first grant), not per
        # speculative step.
        self.events: list[tuple] = []
        self.async_spec_granted = False

        # All token ids: prompt + generated. The scheduler appends sampled
        # tokens in update_from_output.
        self._all_token_ids: list[int] = list(prompt_token_ids)
        self.output_token_ids: list[int] = []
        self.spec_token_ids: list[int] = []

        # Prompt-logprob entries scored so far (entry index ->
        # {token: lp}); assembled into the first emitted output once
        # the prompt completes. Dict-keyed so a preemption re-run
        # overwrites rather than duplicates.
        self.prompt_lp_entries: dict[int, dict] = {}
        self.prompt_lp_delivered = False

        # Tokens whose KV is present on device. Grows by num_scheduled
        # each step (speculative: adjusted down on rejection).
        self.num_computed_tokens = 0
        # Prefix-cache hits recorded at first schedule, for stats.
        self.num_cached_tokens = -1
        # Tokens an async KV pull will make computed once it lands
        # (WAITING_FOR_REMOTE_KVS bookkeeping; applied by the scheduler
        # when the worker reports finished_recving).
        self.num_external_computed_tokens = 0
        # Watchdog bookkeeping for the WAITING_FOR_REMOTE_KVS hold:
        # sweep deadline (unix seconds; set at hold entry) and how many
        # times the pull was retried before degrading to local prefill.
        self.remote_kv_deadline: Optional[float] = None
        self.num_kv_pull_retries = 0
        # Number of preemptions experienced (stats).
        self.num_preemptions = 0
        # Token-parallel rank owning this request's KV (assigned by the
        # scheduler at admission when token_parallel_size > 1; sticky
        # across preemption so resume refills the same shard's pool).
        self.tknp_rank: Optional[int] = None

        sampling_params.update_from_tokenizer(eos_token_id)

        if sampling_params.max_tokens is None:
            sampling_params.max_tokens = 2**31

    @classmethod
    def from_engine_core_request(cls, req: EngineCoreRequest) -> "Request":
        return cls(
            request_id=req.request_id,
            prompt_token_ids=req.prompt_token_ids,
            sampling_params=req.sampling_params,
            eos_token_id=req.eos_token_id,
            arrival_time=req.arrival_time,
            priority=req.priority,
            kv_transfer_params=req.kv_transfer_params,
            lora_request=req.lora_request,
            pooling_params=req.pooling_params,
            mm_inputs=req.mm_inputs,
            tenant=req.tenant,
            trace_ctx=req.trace_ctx,
        )

    # ------------------------------------------------------------------
    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_token_ids)

    @property
    def num_output_tokens(self) -> int:
        return len(self.output_token_ids)

    @property
    def num_tokens(self) -> int:
        return len(self._all_token_ids)

    @property
    def num_tokens_with_spec(self) -> int:
        return len(self._all_token_ids) + len(self.spec_token_ids)

    @property
    def all_token_ids(self) -> list[int]:
        return self._all_token_ids

    def append_output_token_ids(self, token_ids: int | list[int]) -> None:
        if isinstance(token_ids, int):
            token_ids = [token_ids]
        self.output_token_ids.extend(token_ids)
        self._all_token_ids.extend(token_ids)

    @property
    def is_finished(self) -> bool:
        return RequestStatus.is_finished(self.status)

    def get_finished_reason(self) -> Optional[str]:
        return RequestStatus.get_finished_reason(self.status)

    def __repr__(self) -> str:
        return (f"Request(id={self.request_id}, status={self.status.name}, "
                f"prompt={self.num_prompt_tokens}t, "
                f"out={self.num_output_tokens}t, "
                f"computed={self.num_computed_tokens}t)")
