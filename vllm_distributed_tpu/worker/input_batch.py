"""Persistent host-side batch state for in-flight requests.

Reference: vllm/v1/worker/gpu_input_batch.py (persistent token/block-table/
sampling arrays updated incrementally from SchedulerOutput) and
tpu_input_batch.py. Rows are slotted (free-list), not compacted: padding
discipline lives in the per-step flat arrays the runner builds, so row
stability is worth more than density.
"""

from typing import Optional

import numpy as np

from vllm_distributed_tpu.core.sched.output import (CachedRequestData,
                                                    NewRequestData,
                                                    SchedulerOutput)
from vllm_distributed_tpu.sampling_params import SamplingParams


class InputBatch:

    def __init__(self, max_num_reqs: int, max_model_len: int,
                 max_pages_per_req: int, page_size: int) -> None:
        self.max_num_reqs = max_num_reqs
        self.max_model_len = max_model_len
        self.max_pages_per_req = max_pages_per_req
        self.page_size = page_size

        R, L, P = max_num_reqs, max_model_len, max_pages_per_req
        self.token_ids = np.zeros((R, L), np.int32)
        self.num_tokens = np.zeros((R, ), np.int32)
        self.num_computed = np.zeros((R, ), np.int32)
        self.block_table = np.zeros((R, P), np.int32)
        self.num_blocks = np.zeros((R, ), np.int32)

        self.temperature = np.zeros((R, ), np.float32)
        self.top_k = np.zeros((R, ), np.int32)
        self.top_p = np.ones((R, ), np.float32)
        self.min_p = np.zeros((R, ), np.float32)
        self.seed = np.full((R, ), -1, np.int64)

        # Extended sampling (penalties / bias / logprobs / min-tokens).
        self.presence_penalty = np.zeros((R, ), np.float32)
        self.frequency_penalty = np.zeros((R, ), np.float32)
        self.repetition_penalty = np.ones((R, ), np.float32)
        self.min_tokens = np.zeros((R, ), np.int32)
        self.num_logprobs = np.zeros((R, ), np.int32)  # 0 = sampled only
        # prompt_logprobs top-k per row; -1 = not requested (reference:
        # SamplingParams.prompt_logprobs).
        self.prompt_logprobs = np.full((R, ), -1, np.int32)
        self.prompt_len = np.zeros((R, ), np.int32)
        # Lifetime (static) extended-graph need; min-tokens activity is
        # checked dynamically via extended_active().
        self.needs_extended = np.zeros((R, ), np.bool_)
        # Multi-LoRA adapter slot per row (0 = no adapter).
        self.lora_slot = np.zeros((R, ), np.int32)
        # Pooling type per row (None = generation request).
        self.pooling: list = [None] * R
        # Multimodal inputs per row (list[MultiModalInput] | None).
        self.mm: list = [None] * R
        # Sparse per-row python state (lowered to fixed [R, B] arrays in
        # the runner only when a batch contains extended rows).
        self.logit_bias: list[Optional[dict[int, float]]] = [None] * R
        self.allowed_token_ids: list[Optional[list[int]]] = [None] * R
        self.stop_token_ids: list[tuple[int, ...]] = [()] * R

        # Bumped whenever a row's token content is REWRITTEN (not
        # appended): admission, preemption resume. The runner's
        # device-resident history mirror re-uploads such rows in full
        # and follows appends with small deltas (model_runner.
        # _hist_rows_device).
        self.row_version = np.zeros((R, ), np.int64)

        self.req_id_to_index: dict[str, int] = {}
        self.index_to_req_id: dict[int, str] = {}
        self._free_rows = list(range(R - 1, -1, -1))

    @property
    def num_reqs(self) -> int:
        return len(self.req_id_to_index)

    # ------------------------------------------------------------------
    def add_request(self, data: NewRequestData) -> int:
        assert data.req_id not in self.req_id_to_index
        assert self._free_rows, "input batch overflow"
        row = self._free_rows.pop()
        self.req_id_to_index[data.req_id] = row
        self.index_to_req_id[row] = data.req_id

        tokens = data.prompt_token_ids
        n = len(tokens)
        self.token_ids[row, :n] = tokens
        self.token_ids[row, n:] = 0
        self.num_tokens[row] = n
        self.row_version[row] += 1
        self.num_computed[row] = data.num_computed_tokens
        nb = len(data.block_ids)
        self.block_table[row, :nb] = data.block_ids
        self.block_table[row, nb:] = 0
        self.num_blocks[row] = nb

        sp: SamplingParams = data.sampling_params
        self.temperature[row] = sp.temperature
        self.top_k[row] = sp.top_k
        self.top_p[row] = sp.top_p
        self.min_p[row] = sp.min_p
        self.seed[row] = -1 if sp.seed is None else sp.seed

        self.presence_penalty[row] = sp.presence_penalty
        self.frequency_penalty[row] = sp.frequency_penalty
        self.repetition_penalty[row] = sp.repetition_penalty
        self.min_tokens[row] = sp.min_tokens
        self.num_logprobs[row] = sp.logprobs or 0
        self.prompt_logprobs[row] = (-1 if sp.prompt_logprobs is None
                                     else sp.prompt_logprobs)
        self.prompt_len[row] = n
        self.needs_extended[row] = sp.needs_extended_static
        self.lora_slot[row] = 0  # runner sets after adapter resolution
        self.pooling[row] = (data.pooling_params or {}).get("type") \
            if data.pooling_params else None
        self.logit_bias[row] = sp.logit_bias
        self.allowed_token_ids[row] = sp.allowed_token_ids
        self.stop_token_ids[row] = tuple(sp.all_stop_token_ids)
        self.mm[row] = data.mm_inputs
        return row

    def update_cached(self, data: CachedRequestData) -> None:
        for i, req_id in enumerate(data.req_ids):
            row = self.req_id_to_index[req_id]
            if data.resumed_from_preemption[i]:
                # Full state replacement: block table was re-allocated.
                tokens = data.new_token_ids[i]
                self.token_ids[row, :len(tokens)] = tokens
                self.num_tokens[row] = len(tokens)
                self.row_version[row] += 1
                nb = len(data.new_block_ids[i])
                self.block_table[row, :nb] = data.new_block_ids[i]
                self.block_table[row, nb:] = 0
                self.num_blocks[row] = nb
            else:
                new_blocks = data.new_block_ids[i]
                if new_blocks:
                    nb = self.num_blocks[row]
                    self.block_table[row, nb:nb + len(new_blocks)] = \
                        new_blocks
                    self.num_blocks[row] = nb + len(new_blocks)
            self.num_computed[row] = data.num_computed_tokens[i]

    def extended_active(self, row: int) -> bool:
        """Does this row need the extended sampling graph RIGHT NOW?
        (static features, or min-tokens stop suppression still in its
        window)."""
        return bool(self.needs_extended[row]
                    or (self.num_tokens[row] - self.prompt_len[row]
                        < self.min_tokens[row]))

    def append_token(self, req_id: str, token_id: int) -> None:
        """Record a token sampled this step (so the next step's input
        includes it). A request already removed (its finish raced a
        trailing async batch's retirement) is a no-op."""
        row = self.req_id_to_index.get(req_id)
        if row is None:
            return
        n = self.num_tokens[row]
        if n < self.max_model_len:
            self.token_ids[row, n] = token_id
            self.num_tokens[row] = n + 1

    def remove_request(self, req_id: str) -> Optional[int]:
        row = self.req_id_to_index.pop(req_id, None)
        if row is None:
            return None
        del self.index_to_req_id[row]
        self._free_rows.append(row)
        self.num_tokens[row] = 0
        self.num_computed[row] = 0
        self.num_blocks[row] = 0
        self.block_table[row, :] = 0
        self.needs_extended[row] = False
        self.lora_slot[row] = 0
        self.pooling[row] = None
        self.num_logprobs[row] = 0
        self.prompt_logprobs[row] = -1
        self.min_tokens[row] = 0
        self.presence_penalty[row] = 0.0
        self.frequency_penalty[row] = 0.0
        self.repetition_penalty[row] = 1.0
        self.logit_bias[row] = None
        self.allowed_token_ids[row] = None
        self.stop_token_ids[row] = ()
        self.mm[row] = None
        return row
