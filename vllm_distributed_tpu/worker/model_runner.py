"""TPU model runner: flat ragged batches, bucketed static shapes, one
jitted step.

Reference: vllm/v1/worker/gpu_model_runner.py:101 (``GPUModelRunner``:
_prepare_inputs :892, execute_model :1614, CUDA-graph capture :2683) and
the TPU variant tpu_model_runner.py:98 (bucketed precompilation
:1248-1443). TPU-native re-design:

* The whole forward + logits + sampling step is ONE jitted function; KV
  caches are donated so XLA updates them in place.
* Dynamic quantities (num tokens T, num sampling reqs R) are padded to a
  bucket lattice; each (T, R) pair compiles once. There is no CUDA-graph
  equivalent to manage — jit caching plays that role.
* Sharding: params/caches carry NamedShardings over the engine mesh; the
  same runner code is TP=1 and TP=N (GSPMD inserts the collectives).
"""

import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.output import (ModelRunnerOutput,
                                                    SchedulerOutput)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.models.common import AttentionBatch
from vllm_distributed_tpu.sample.metadata import SamplingMetadata
from vllm_distributed_tpu.sample.sampler import sample_tokens
from vllm_distributed_tpu.utils import cdiv, make_buckets, pad_to_bucket
from vllm_distributed_tpu.worker.input_batch import InputBatch

logger = init_logger(__name__)


class TPUModelRunner:

    def __init__(self, config: EngineConfig, mesh,
                 model=None, params=None) -> None:
        self.config = config
        self.mesh = mesh
        sched_cfg = config.scheduler_config
        self.page_size = config.cache_config.block_size
        self.max_num_reqs = sched_cfg.max_num_seqs
        self.max_model_len = sched_cfg.max_model_len
        self.max_pages_per_req = cdiv(self.max_model_len, self.page_size)

        self.model = model
        self.params = params
        self.kv_caches: Optional[dict] = None

        self.input_batch = InputBatch(
            max_num_reqs=self.max_num_reqs,
            max_model_len=self.max_model_len,
            max_pages_per_req=self.max_pages_per_req,
            page_size=self.page_size,
        )

        self.token_buckets = make_buckets(
            16, sched_cfg.max_num_batched_tokens)
        self.req_buckets = make_buckets(8, self.max_num_reqs)

        self._step_fn = None
        self._rng = np.random.default_rng(config.model_config.seed)
        self._compiled_shapes: set[tuple[int, int]] = set()

    # ------------------------------------------------------------------
    def load_model(self) -> None:
        """Build the model and load weights per LoadConfig."""
        from vllm_distributed_tpu.models.loader import get_model
        self.model, self.params = get_model(self.config, self.mesh)

    def initialize_kv_cache(self, num_pages: int) -> None:
        from jax.sharding import NamedSharding
        assert self.model is not None
        self.num_pages = num_pages
        with self.mesh:
            caches = self.model.make_kv_caches(num_pages, self.page_size)
            specs = self.model.kv_cache_specs()
            self.kv_caches = jax.tree.map(
                lambda x, s: jax.device_put(
                    x, NamedSharding(self.mesh, s)), caches, specs,
                is_leaf=lambda x: isinstance(x, jax.Array))
        self._build_step_fn()

    def kv_cache_bytes_per_page(self) -> int:
        c = self.model.cfg
        itemsize = jnp.dtype(c.dtype).itemsize
        return (2 * c.num_layers * self.page_size * c.num_kv_heads *
                c.head_dim * itemsize)

    def _build_step_fn(self) -> None:
        model = self.model

        def step(params, kv_caches, token_ids, batch: AttentionBatch,
                 logits_indices, sampling_md: SamplingMetadata):
            hidden, kv_caches = model.forward(params, kv_caches, token_ids,
                                              batch)
            sel = hidden[logits_indices]
            logits = model.compute_logits(params, sel)
            tokens, logprobs = sample_tokens(logits, sampling_md)
            return kv_caches, tokens, logprobs

        # Donate the caches: XLA aliases them in place of a copy.
        self._step_fn = jax.jit(step, donate_argnums=(1, ))

    # ------------------------------------------------------------------
    def _update_states(self, scheduler_output: SchedulerOutput) -> None:
        for req_id in scheduler_output.finished_req_ids:
            self.input_batch.remove_request(req_id)
        for new_req in scheduler_output.scheduled_new_reqs:
            self.input_batch.add_request(new_req)
        self.input_batch.update_cached(scheduler_output.scheduled_cached_reqs)

    def _prepare_inputs(self, scheduler_output: SchedulerOutput):
        """Flatten the scheduled requests into padded per-token arrays."""
        ib = self.input_batch
        num_sched = scheduler_output.num_scheduled_tokens
        total_tokens = scheduler_output.total_num_scheduled_tokens
        T = pad_to_bucket(total_tokens, self.token_buckets)

        token_ids = np.zeros((T, ), np.int32)
        positions = np.zeros((T, ), np.int32)
        req_idx = np.zeros((T, ), np.int32)
        slot_mapping = np.full((T, ), -1, np.int32)

        sampling_rows: list[int] = []
        sampling_req_ids: list[str] = []
        logits_idx: list[int] = []

        t = 0
        for req_id, n in num_sched.items():
            row = ib.req_id_to_index[req_id]
            start = ib.num_computed[row]
            end = start + n
            token_ids[t:t + n] = ib.token_ids[row, start:end]
            positions[t:t + n] = np.arange(start, end, dtype=np.int32)
            req_idx[t:t + n] = row
            pos = np.arange(start, end)
            slot_mapping[t:t + n] = (
                ib.block_table[row, pos // self.page_size] *
                self.page_size + pos % self.page_size)
            if end >= ib.num_tokens[row]:
                # This step finishes all known tokens: sample.
                sampling_rows.append(row)
                sampling_req_ids.append(req_id)
                logits_idx.append(t + n - 1)
            t += n

        R = pad_to_bucket(max(len(sampling_rows), 1), self.req_buckets)
        rows = np.asarray(sampling_rows +
                          [0] * (R - len(sampling_rows)), np.int32)
        logits_indices = np.asarray(logits_idx + [0] *
                                    (R - len(logits_idx)), np.int32)

        # Seeds: seeded requests fold (user_seed, step-in-request) so runs
        # reproduce; unseeded draw from the engine rng.
        user_seed = ib.seed[rows]
        step_in_req = ib.num_tokens[rows].astype(np.int64)
        random_part = self._rng.integers(0, 2**31 - 1, size=R)
        seeds = np.where(user_seed >= 0,
                         user_seed * 1000003 + step_in_req, random_part)

        sampling_md = SamplingMetadata(
            temperature=jnp.asarray(ib.temperature[rows]),
            top_k=jnp.asarray(ib.top_k[rows]),
            top_p=jnp.asarray(ib.top_p[rows]),
            min_p=jnp.asarray(ib.min_p[rows]),
            seeds=jnp.asarray(seeds),
        )
        batch = AttentionBatch(
            req_idx=jnp.asarray(req_idx),
            positions=jnp.asarray(positions),
            slot_mapping=jnp.asarray(slot_mapping),
            block_tables=jnp.asarray(ib.block_table),
            seq_lens=jnp.asarray(ib.num_computed),
        )
        return (jnp.asarray(token_ids), batch,
                jnp.asarray(logits_indices), sampling_md,
                sampling_req_ids, (T, R))

    # ------------------------------------------------------------------
    def execute_model(self,
                      scheduler_output: SchedulerOutput) -> ModelRunnerOutput:
        self._update_states(scheduler_output)
        if scheduler_output.total_num_scheduled_tokens == 0:
            return ModelRunnerOutput()

        (token_ids, batch, logits_indices, sampling_md, sampling_req_ids,
         shape) = self._prepare_inputs(scheduler_output)

        if shape not in self._compiled_shapes:
            logger.info("compiling step for shape (tokens=%d, reqs=%d)",
                        *shape)
            start = time.perf_counter()
        with self.mesh:
            self.kv_caches, tokens, logprobs = self._step_fn(
                self.params, self.kv_caches, token_ids, batch,
                logits_indices, sampling_md)
        if shape not in self._compiled_shapes:
            self._compiled_shapes.add(shape)
            logger.info("compiled in %.1fs", time.perf_counter() - start)

        tokens_np = np.asarray(jax.device_get(tokens))
        logprobs_np = np.asarray(jax.device_get(logprobs))

        # Record sampled tokens so next step's decode inputs include them.
        req_ids, sampled, lps = [], [], []
        for i, req_id in enumerate(sampling_req_ids):
            token = int(tokens_np[i])
            self.input_batch.append_token(req_id, token)
            req_ids.append(req_id)
            sampled.append([token])
            lps.append([{token: float(logprobs_np[i])}])
        # Partial-prefill requests report no samples.
        sampling_set = set(sampling_req_ids)
        for req_id in scheduler_output.num_scheduled_tokens:
            if req_id not in sampling_set:
                req_ids.append(req_id)
                sampled.append([])
                lps.append([])
        return ModelRunnerOutput(req_ids=req_ids,
                                 sampled_token_ids=sampled,
                                 logprobs=lps)

    # ------------------------------------------------------------------
    def precompile(self) -> None:
        """Warm the (T, R) lattice ahead of serving (reference:
        tpu_model_runner.py:1248 precompilation suite). Compiles the
        smallest and largest shapes; the rest compile on demand."""
        pass

    def profile_memory_bytes(self) -> int:
        """Bytes of HBM available for KV pages after weights."""
        try:
            stats = jax.local_devices()[0].memory_stats()
            limit = stats.get("bytes_limit")
            in_use = stats.get("bytes_in_use")
            if limit:
                util = self.config.cache_config.gpu_memory_utilization
                return max(int(limit * util) - int(in_use or 0), 0)
        except Exception:  # pragma: no cover - platform specific
            pass
        return 0
