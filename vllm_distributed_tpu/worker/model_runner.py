"""TPU model runner: flat ragged batches, bucketed static shapes, one
jitted step.

Reference: vllm/v1/worker/gpu_model_runner.py:101 (``GPUModelRunner``:
_prepare_inputs :892, execute_model :1614, CUDA-graph capture :2683) and
the TPU variant tpu_model_runner.py:98 (bucketed precompilation
:1248-1443). TPU-native re-design:

* The whole forward + logits + sampling step is ONE jitted function; KV
  caches are donated so XLA updates them in place.
* Dynamic quantities (num tokens T, num sampling reqs R) are padded to a
  bucket lattice; each (T, R) pair compiles once. There is no CUDA-graph
  equivalent to manage — jit caching plays that role.
* Sharding: params/caches carry NamedShardings over the engine mesh; the
  same runner code is TP=1 and TP=N (GSPMD inserts the collectives).
"""

import functools
import os
import time
from contextlib import contextmanager
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.output import (ModelRunnerOutput,
                                                    SchedulerOutput)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.metrics.stats import (STEP_PHASE_BUCKETS,
                                                Histogram)
from vllm_distributed_tpu.models.common import (AttentionBatch,
                                                TknpAttentionBatch)
from vllm_distributed_tpu.ops.attention import resolve_attention_backend
from vllm_distributed_tpu.sample.metadata import (ExtendedSamplingMetadata,
                                                  SamplingMetadata)
from vllm_distributed_tpu.sample.sampler import (MAX_LOGPROBS, sample_tokens,
                                                 sample_tokens_extended)
from vllm_distributed_tpu.utils import cdiv, make_buckets, pad_to_bucket
from vllm_distributed_tpu.worker.input_batch import InputBatch

logger = init_logger(__name__)


class TPUModelRunner:

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0, ))
    def _hist_apply_full(dev, rows, vals):
        """Overwrite whole history rows (admission/resume); padding rows
        carry an out-of-range index and drop."""
        return dev.at[rows].set(vals, mode="drop")

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0, ))
    def _hist_apply_delta(dev, d_rows, d_start, d_toks, d_len):
        """Append newly committed tokens per row (width = the
        runner's _hist_delta)."""
        D = d_toks.shape[1]
        pos = d_start[:, None] + jnp.arange(D, dtype=jnp.int32)[None, :]
        valid = jnp.arange(D, dtype=jnp.int32)[None, :] < d_len[:, None]
        rowm = jnp.broadcast_to(d_rows[:, None], pos.shape)
        pos = jnp.where(valid, pos, dev.shape[1])
        return dev.at[rowm, pos].set(d_toks, mode="drop")

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0, ))
    def _state_row_to_pool(pool, cache, row, slot):
        """SSM state snapshot: copy one request's state rows (axis 1 of
        every layer) into a snapshot-pool slot. Dispatched AFTER the
        step's forward, so program order guarantees the copied state is
        exactly the post-step (boundary) state."""
        return pool.at[:, slot].set(cache[:, row])

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0, ))
    def _state_pool_to_row(cache, pool, row, slot):
        """SSM state restore: fill a request's state rows from a pool
        slot. Dispatched BEFORE the forward — the segmented scan then
        re-enters mid-sequence through its has_init carry path
        (ops/mamba.build_segment_info)."""
        return cache.at[:, row].set(pool[:, slot])

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0, ))
    def _state_put_row(cache, value, row):
        """SSM state restore from a host checkpoint (crash recovery):
        upload the journaled state directly into the request's rows."""
        return cache.at[:, row].set(value)

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0, ))
    def _chain_record(last, rows, tokens):
        """Async scheduling: scatter this step's sampled tokens (still
        on device) into the per-row last-sampled mirror at DISPATCH
        time; padding rows carry an out-of-range index and drop."""
        return last.at[rows].set(tokens, mode="drop")

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0, ))
    def _chain_apply(token_ids, pos, last, rows):
        """Async scheduling: overwrite the flat input positions whose
        token the host does not know yet with the previous step's
        on-device sample — step N+1's input rows read step N's output
        without a host round-trip (the same device-to-device chaining
        the multi-step lax.scan burst does within a burst). Padding
        entries point one past the array and drop."""
        return token_ids.at[pos].set(last[rows], mode="drop")

    def __init__(self, config: EngineConfig, mesh,
                 model=None, params=None) -> None:
        self.config = config
        self.mesh = mesh
        sched_cfg = config.scheduler_config
        self.page_size = config.cache_config.block_size
        self.max_num_reqs = sched_cfg.max_num_seqs
        self.max_model_len = sched_cfg.max_model_len
        self.max_pages_per_req = cdiv(self.max_model_len, self.page_size)

        self.model = model
        self.params = params
        self.kv_caches: Optional[dict] = None
        # Async scheduling: device-resident [max_num_reqs] mirror of
        # each row's most recently sampled token, written at dispatch
        # time (_chain_record) and read by the next dispatch's input
        # chain (_chain_apply) — the host never round-trips decode
        # tokens on the hot path.
        self._async_chain = config.scheduler_config.async_scheduling
        self._last_sampled_dev: Optional[jax.Array] = None
        # Device-resident sampling-history mirror (see _hist_rows_device).
        self._hist_dev: Optional[jax.Array] = None
        self._hist_len = np.zeros((self.max_num_reqs, ), np.int32)
        self._hist_ver = np.full((self.max_num_reqs, ), -1, np.int64)
        # Token parallelism: requests' pages live on one token-axis rank;
        # per-rank metadata is built each step (reference:
        # gpu_model_runner.py:334 _build_token_parallel_metadata).
        self.tknp_size = config.parallel_config.token_parallel_size

        # Worker-side KV connector (disaggregated prefill; reference:
        # gpu_model_runner.py maybe_setup_kv_connector :2047).
        # Multi-LoRA adapter slots (set up in load_model, which knows
        # the arch config).
        self.lora_manager = None
        from vllm_distributed_tpu.distributed.kv_transfer import (
            KVConnectorRole, create_kv_connector)
        self.kv_connector = create_kv_connector(config,
                                                KVConnectorRole.WORKER)
        # KV transfer composes with token parallelism: connectors
        # address pages by GLOBAL page id, and the eager gather/scatter
        # in kv_transfer/page_io works on the token-axis-sharded cache
        # (XLA moves the touched shards; validated by
        # tests/kv_transfer/test_shared_storage.py tknp case).

        self.input_batch = InputBatch(
            max_num_reqs=self.max_num_reqs,
            max_model_len=self.max_model_len,
            max_pages_per_req=self.max_pages_per_req,
            page_size=self.page_size,
        )

        self.token_buckets = make_buckets(
            16, sched_cfg.max_num_batched_tokens)
        self.req_buckets = make_buckets(8, self.max_num_reqs)
        # Disagg pool role ("prefill" | "decode" | None): prunes the
        # precompile lattice per role — a prefill replica skips the
        # fused-block/multi-step decode variants, a decode replica
        # skips the prompt-logprob graphs (plp requests are exempt from
        # handoff and serve on the prefill pool); the decode pool's
        # token ladder itself is already capped by its pool config.
        self.pool_role = config.kv_transfer_config.pool_role

        # Step-phase profiler share: host-side input prep per dispatch
        # (merged into vdt:step_phase_seconds{phase="prepare_inputs"} by
        # the engine core's get_stats).
        self.prepare_inputs_hist = Histogram(STEP_PHASE_BUCKETS)
        # Device/compilation telemetry (metrics/telemetry.py): the
        # blocking device-fetch wait per step and the recompile counter
        # behind vdt:recompiles_total — a steady-state recompile is the
        # classic silent TPU perf killer, so it must be a counter an
        # alert can watch, not only a log line. The enable flag and the
        # engine core's transport recorder are captured ONCE at
        # construction (the envs registry re-reads os.environ per
        # access; the recorder install window only spans construction).
        from vllm_distributed_tpu.metrics import telemetry
        self._device_telemetry = telemetry.device_telemetry_enabled()
        self._telemetry = telemetry.current_recorder()
        self.device_wait_hist = Histogram(STEP_PHASE_BUCKETS)
        self.num_recompiles = 0

        # Speculative decoding (ngram drafts verified in-step; reference:
        # v1/spec_decode/ngram_proposer.py + rejection_sampler.py). The
        # sampler runs on S+1 positions per sampling request; acceptance
        # is a host-side prefix match of the per-position target samples
        # against the drafts — unbiased (the emitted token at each
        # position IS the target sample) and zero extra device code.
        spec = config.speculative_config
        self.spec_k = (spec.num_speculative_tokens
                       if spec and spec.method in ("ngram", "draft_model",
                                                   "eagle") else 0)
        self.proposer = None
        self._draft_spec = None
        self._eagle_spec = None
        self._eagle = None
        # Per-request truncated draft-support metadata ([S, K] ids and
        # probs) written at proposal time, read by next step's
        # rejection verifier (see sample/sampler.py
        # spec_verify_rejection).
        self._draft_meta: dict[str, tuple] = {}
        if self.spec_k and spec.method == "ngram":
            from vllm_distributed_tpu.spec_decode.ngram_proposer import \
                NgramProposer
            self.proposer = NgramProposer(spec)
        elif self.spec_k and spec.method == "eagle":
            # EAGLE drafter builds with the target model (load_model
            # knows the geometry); its KV layers stack onto the
            # target's cache.
            self._eagle_spec = spec
        elif self.spec_k:
            # Draft model loads with the target model (load_model knows
            # the dtype); until then proposals are empty.
            self._draft_spec = spec
        # Max per-step append a history row can absorb without a full
        # re-upload: a step commits up to spec_k + 1 tokens per row
        # (accepted drafts + the target sample).
        self._hist_delta = max(8, self.spec_k + 1)
        # KV-write runs: worst case one partial page per request plus the
        # full pages the step writes. Padded as a deterministic function of
        # T (see _batch_shape) so it adds no lattice dimension.
        max_runs = (cdiv(sched_cfg.max_num_batched_tokens + 128,
                         self.page_size) + self.max_num_reqs)
        self.kv_run_buckets = make_buckets(8, max_runs)

        self._forward_fn = None
        self._sample_fn = None
        # Correctness-sentinel numerics watch (correctness_plane.py):
        # a tiny jitted logits reduction dispatched every
        # NUMERICS_TAP_STRIDE sample launches (it re-derives logits, so
        # per-step would double the lm-head cost), harvested one step
        # behind. Off (None) by default — VDT_CORRECTNESS=0 must keep
        # this path byte-identical. The countdown starts at 1 so the
        # first sample of a fresh runner is tapped (deterministic for
        # drills) and the stride paces steady state.
        self._numerics = None
        self._numerics_fn = None
        self._numerics_countdown = 1
        from vllm_distributed_tpu import envs
        if envs.VDT_CORRECTNESS:
            from vllm_distributed_tpu.correctness_plane import NumericsTap
            self._numerics = NumericsTap()
        # M-RoPE (Qwen2-VL): per-row ([prompt_len, 3] id table, decode
        # delta); active when the model declares mrope_section.
        self._mrope: dict[int, tuple] = {}
        self._mrope_on = False
        self._rng = np.random.default_rng(config.model_config.seed)
        # Spec-decode acceptance counters (reference:
        # v1/metrics SpecDecodingStats).
        self.spec_num_drafts = 0
        self.spec_num_draft_tokens = 0
        self.spec_num_accepted_tokens = 0
        # Steps that took the cascade (shared-prefix) attention path.
        self.cascade_steps = 0
        # Memoized "model uses the standard K/V page layout" (see
        # _detect_cascade); None until the model is loaded.
        self._cascade_layout_ok: Optional[bool] = None
        # Shapes warmed by precompile(); execute-time compiles outside this
        # set are recompile-guard violations (reference:
        # tpu_model_runner.py:318 _update_num_xla_graphs).
        self._compiled_shapes: set[tuple] = set()
        self._precompiled = False
        # Mega-kernel partition parameters: resolved once the model is
        # loaded (None until then). _unified gates the collapsed compile
        # lattice + descriptor batches; (bq, sb) are the fixed
        # prefill-tile / decode-group sizes shared by the host
        # descriptor builder and the kernel.
        self._unified: Optional[bool] = None
        self._tile_params_memo: Optional[tuple[int, int]] = None
        self._xla_route_memo: Optional[bool] = None
        # Kernel-dispatch observability: one count per step per kernel
        # family (fused_block|unified|decode|general|cascade|naive)
        # behind vdt:attn_kernel_calls_total, plus the warmed-graph
        # count behind vdt:precompile_graphs_total.
        self.attn_kernel_calls: dict[str, int] = {}
        self.precompile_graphs = 0
        # Fused decode-block dispatch (ops/pallas_block.py): steps that
        # ran the fused path vs steps that fell back (by reason) while
        # fusion was enabled+eligible — vdt:block_fusion_calls_total /
        # vdt:block_fusion_fallbacks_total{reason}. Eligibility is the
        # loader's once-per-load decision; None until the model exists.
        self._block_fusion_memo: Optional[bool] = None
        self.block_fusion_calls = 0
        self.block_fusion_fallbacks: dict[str, int] = {}
        # Performance-attribution plane (metrics/costmodel.py): the
        # loader priced the model once (arch.cost_model; None when
        # VDT_PERF_ATTRIB=0). Every dispatch is charged analytic FLOPs
        # and HBM bytes keyed by (kernel family, phase, token bucket)
        # and reconciled against the measured device wait in
        # wait_model — the numerators behind vdt:mfu / vdt:mbu /
        # vdt:hbm_bytes_total{kind} / vdt:roofline_bound{phase} and the
        # GET /debug/perf table. All dict-bump accounting on the
        # single engine-core thread; get_stats snapshots read
        # GIL-atomically like the other runner counters.
        self._perf_memo: Optional[bool] = None
        self._perf_attrib: dict[str, dict] = {}
        self._perf_phases: dict[str, dict] = {}
        self._perf_bytes = {"weights": 0.0, "kv_read": 0.0,
                            "kv_write": 0.0, "activations": 0.0}
        self._perf_flops = 0.0
        self._perf_device_s = 0.0
        self._perf_dispatches = 0
        # SSM state-snapshot pool (core/state_cache.py): per-state-array
        # device buffers of `resolve_state_slots` slots, written/read by
        # the scheduler's state_saves/state_restores directives. Built
        # in initialize_kv_cache once the model (and its state
        # geometry) exists; None for stateless models or with the cache
        # disabled.
        self._state_pool: Optional[dict] = None
        self._state_keys: list[str] = []
        self.num_state_checkpoints = 0
        self.num_state_restores = 0
        # Hierarchical KV tiering (core/kv_tier.py): the scheduler's
        # tier manager, shared in-proc (wired by the engine core after
        # construction). The runner executes the device legs — the
        # pre-forward demotion gather / promotion scatter directives
        # riding SchedulerOutput. None = untiered.
        self.kv_tier = None

    # ------------------------------------------------------------------
    def load_model(self) -> None:
        """Build the model and load weights per LoadConfig."""
        from vllm_distributed_tpu.models.loader import get_model
        self.model, self.params = get_model(self.config, self.mesh)
        self._mrope_on = bool(
            getattr(self.model.cfg, "mrope_section", None))
        if getattr(self.model, "CROSS_ATTENTION", False):
            # install_cross_states projects through the loaded cross
            # weights at admission time.
            self.model.params_ref = self.params
        self._init_lora_manager()
        if self._draft_spec is not None:
            from vllm_distributed_tpu.spec_decode.draft_model import \
                DraftModelProposer
            self.proposer = DraftModelProposer(
                self._draft_spec, self.model.cfg.dtype,
                max_num_reqs=self.max_num_reqs)
        if self._eagle_spec is not None:
            from jax.sharding import NamedSharding

            from vllm_distributed_tpu.spec_decode.eagle import EagleDrafter
            self._eagle = EagleDrafter(self._eagle_spec, self.model,
                                       self.max_num_reqs, self.page_size)
            host = self._eagle.load_params(self.params)
            specs = self._eagle.param_specs()
            with self.mesh:
                def place(p, key_specs):
                    if isinstance(p, dict):
                        return {k: place(v, key_specs[k])
                                for k, v in p.items()}
                    return jax.device_put(
                        p, NamedSharding(self.mesh, key_specs))

                placed = place(host, specs)
            self.params["eagle"] = placed
            self._eagle.eparams = placed

    def _init_lora_manager(self) -> None:
        if self.config.lora_config.enable_lora:
            from vllm_distributed_tpu.models.lora import LoRASlotManager
            self.lora_manager = LoRASlotManager(
                self.model.cfg, self.config.lora_config.max_loras)

    def lora_buffer_trees(self):
        """(param-dict, (layer_start, layer_end)) pairs holding the
        stacked LoRA buffers — one pair for the single-program runner,
        one per stage under pipeline parallelism."""
        return [(self.params["layers"], (0, self.model.cfg.num_layers))]

    def _make_sharded_caches(self, num_pages: int) -> dict:
        from jax.sharding import NamedSharding
        with self.mesh:
            depth = None
            if self._eagle is not None:
                # EAGLE's draft KV layers stack onto the target's cache
                # (same pages/block tables; see spec_decode/eagle.py).
                depth = (self.model.cfg.num_layers +
                         self._eagle.num_layers)
            caches = self.model.make_kv_caches(num_pages, self.page_size,
                                               num_layers=depth)
            specs = self.model.kv_cache_specs()
            return jax.tree.map(
                lambda x, s: jax.device_put(
                    x, NamedSharding(self.mesh, s)), caches, specs,
                is_leaf=lambda x: isinstance(x, jax.Array))

    def initialize_kv_cache(self, num_pages: int) -> None:
        assert self.model is not None
        self.num_pages = num_pages
        self.kv_caches = self._make_sharded_caches(num_pages)
        self._init_state_pool()
        if self._forward_fn is None:
            self._build_step_fn()

    # ------------------------------------------------------------------
    # SSM state-snapshot pool (core/state_cache.py device half)
    # ------------------------------------------------------------------
    def _state_cache_active(self) -> bool:
        # Snapshotable state only: Whisper/BART are STATEFUL (fixed
        # cross-attention rows) but expose no state_shapes() — the
        # snapshot pool must not activate for them (same gate the
        # scheduler applies via loader.resolve_state_snapshotable).
        if (self.model is None
                or not getattr(self.model, "STATEFUL", False)
                or not hasattr(self.model, "state_shapes")):
            return False
        from vllm_distributed_tpu.core.state_cache import \
            state_cache_enabled
        return state_cache_enabled(self.config, True)

    def _init_state_pool(self) -> None:
        if not self._state_cache_active():
            return
        from jax.sharding import NamedSharding

        from vllm_distributed_tpu.core.state_cache import \
            resolve_state_slots
        n_slots = resolve_state_slots(self.config)
        shapes = self.model.state_shapes()
        specs = self.model.kv_cache_specs()
        self._state_keys = sorted(shapes)
        with self.mesh:
            self._state_pool = {
                name: jax.device_put(
                    jnp.zeros((shape[0], n_slots) + shape[2:], dtype),
                    NamedSharding(self.mesh, specs[name]))
                for name, (shape, dtype) in shapes.items()
            }
        logger.info("SSM state pool: %d slots, %.2f MiB",
                    n_slots, self.state_pool_bytes() / 2**20)

    def _state_fingerprint(self) -> bytes:
        """Journal geometry fingerprint (core/state_cache.py): stamped
        into every checkpoint file and checked at lookup so a shared
        VDT_SSM_CKPT_DIR never serves a shape-foreign snapshot."""
        from vllm_distributed_tpu.core.state_cache import \
            state_fingerprint
        return state_fingerprint(self.model.state_shapes())

    def state_pool_slot_bytes(self) -> int:
        """Device bytes of ONE snapshot (all state arrays, all layers)."""
        if not self._state_cache_active():
            return 0
        return sum(
            int(np.prod((shape[0], ) + shape[2:]))
            * jnp.dtype(dtype).itemsize
            for shape, dtype in self.model.state_shapes().values())

    def state_pool_bytes(self) -> int:
        """Total pool footprint, charged against the fixed-state HBM
        budget by worker.determine_num_available_blocks."""
        if not self._state_cache_active():
            return 0
        from vllm_distributed_tpu.core.state_cache import \
            resolve_state_slots
        return resolve_state_slots(self.config) * \
            self.state_pool_slot_bytes()

    def _apply_state_restores(self, scheduler_output) -> None:
        """Execute state_restores BEFORE the forward: the restored rows
        are the carry the segmented scan re-enters with."""
        restores = getattr(scheduler_output, "state_restores", None)
        if not restores or self._state_pool is None:
            return
        from vllm_distributed_tpu.core.state_cache import read_journal
        with self.mesh:
            self._run_state_restores(restores, read_journal)

    def _run_state_restores(self, restores, read_journal) -> None:
        for d in restores:
            row = self.input_batch.req_id_to_index.get(d.req_id)
            if row is None:
                logger.warning("state restore for unknown request %s",
                               d.req_id)
                continue
            if d.slot >= 0:
                for name in self._state_keys:
                    with self._compile_watch(("ssm_restore", name)):
                        self.kv_caches[name] = self._state_pool_to_row(
                            self.kv_caches[name], self._state_pool[name],
                            row, d.slot)
            else:
                # Crash-recovery journal hit: the scheduler verified the
                # checksum at lookup and carried the payload on the
                # (in-proc) directive. A re-read that fails must fail
                # loudly — uploading nothing would silently resume from
                # another request's state.
                arrays = d.arrays or read_journal(d.journal)
                if arrays is None:
                    raise RuntimeError(
                        f"SSM checkpoint {d.journal} became unreadable "
                        f"between scheduler lookup and restore")
                for name in self._state_keys:
                    with self._compile_watch(("ssm_put", name)):
                        self.kv_caches[name] = self._state_put_row(
                            self.kv_caches[name],
                            jnp.asarray(arrays[name]), row)
            self.num_state_restores += 1

    # ------------------------------------------------------------------
    # Hierarchical KV tiering (core/kv_tier.py device legs)
    # ------------------------------------------------------------------
    def _apply_kv_tier_pre(self, scheduler_output):
        """Pre-forward KV-tier device legs. The demotion gather
        dispatches FIRST — device program order pins the evicted
        pages' pre-overwrite contents while the actual device->host
        DMA overlaps the forward (the host fetch happens in
        ``_apply_kv_tier_post``). Promote scatters follow: staged
        wire-layout arrays land in their freshly allocated pages via
        the existing page_io staging + chunked donated scatter, all
        before the forward reads them."""
        tier = self.kv_tier
        if tier is None:
            return None
        demote = getattr(scheduler_output, "kv_demotes", None)
        promotes = getattr(scheduler_output, "kv_promotes", None)
        if demote is None and not promotes:
            return None
        from vllm_distributed_tpu import envs
        from vllm_distributed_tpu.distributed.kv_transfer import page_io
        handle = None
        if demote is not None:
            handle = page_io.gather_pages_start(self, demote.page_ids)
        for d in promotes or ():
            t0 = time.perf_counter()
            k_np = np.stack([kv[0] for kv in d.arrays], axis=1)
            v_np = np.stack([kv[1] for kv in d.arrays], axis=1)
            k_dev, v_dev = page_io.stage_pages(self, k_np, v_np)
            chunk = max(1, int(envs.VDT_KV_APPLY_CHUNK_PAGES))
            for lo in range(0, len(d.page_ids), chunk):
                page_io.scatter_pages_chunk(self, d.page_ids, k_dev,
                                            v_dev, lo, chunk)
            # Histogram records the host-side dispatch cost (the
            # scatter itself overlaps the forward; correctness rides
            # program order, not completion).
            tier.record_promotion(d, time.perf_counter() - t0)
        return (demote, handle) if handle is not None else None

    def _apply_kv_tier_post(self, pending) -> None:
        """Post-dispatch half of a demotion: complete the (already
        in-flight) device->host copies and land each page in the host
        tier — the fetch, and any host->disk spill it triggers, run
        while the forward executes on device."""
        if pending is None:
            return
        demote, handle = pending
        from vllm_distributed_tpu.distributed.kv_transfer import page_io
        k_np, v_np = page_io.gather_pages_finish(self, handle)
        for i, key in enumerate(demote.keys):
            self.kv_tier.insert_host(key, k_np[:, i], v_np[:, i])

    def _apply_state_saves(self, scheduler_output) -> None:
        """Execute state_saves AFTER the forward dispatch: program order
        on the cache arrays guarantees the copy sees the post-step
        (exact-boundary) state. Journal-tagged saves additionally
        serialize the slot to the host checkpoint journal (a blocking
        device fetch — only taken when VDT_SSM_CKPT_DIR is set)."""
        saves = getattr(scheduler_output, "state_saves", None)
        if not saves or self._state_pool is None:
            return
        from vllm_distributed_tpu.core.state_cache import write_journal
        with self.mesh:
            self._run_state_saves(saves, write_journal)

    def _run_state_saves(self, saves, write_journal) -> None:
        for d in saves:
            if not getattr(d, "persist_only", False):
                row = self.input_batch.req_id_to_index.get(d.req_id)
                if row is None:
                    logger.warning("state save for unknown request %s",
                                   d.req_id)
                    continue
                for name in self._state_keys:
                    with self._compile_watch(("ssm_save", name)):
                        self._state_pool[name] = self._state_row_to_pool(
                            self._state_pool[name], self.kv_caches[name],
                            row, d.slot)
                self.num_state_checkpoints += 1
            if d.journal:
                # persist_only: journal an already-committed slot whose
                # key (async save) only resolved at commit time.
                arrays = {
                    name: np.asarray(
                        jax.device_get(self._state_pool[name][:, d.slot]))
                    for name in self._state_keys
                }
                write_journal(d.journal, arrays, d.num_tokens,
                              fingerprint=self._state_fingerprint())

    # ------------------------------------------------------------------
    # Sharded-state checkpoints (reference: model_loader/
    # sharded_state_loader.py + Worker.save_sharded_state — pre-sharded
    # per-rank checkpoints for fast reload; here orbax writes each
    # array's shards in parallel from wherever they live on the mesh)
    # ------------------------------------------------------------------
    def save_sharded_state(self, path: str) -> None:
        import orbax.checkpoint as ocp
        ckpt = ocp.StandardCheckpointer()
        ckpt.save(os.path.abspath(path), self.params)
        ckpt.wait_until_finished()
        logger.info("saved sharded state to %s", path)

    # ------------------------------------------------------------------
    # Sleep / wake (RLHF colocation; reference: CuMemAllocator tag-based
    # discard/offload, device_allocator/cumem.py:106, driven by the
    # EngineCore.sleep/wake_up RPCs, core.py:312-319)
    # ------------------------------------------------------------------
    def sleep(self, level: int = 1) -> int:
        """Release device HBM. Level 1 offloads weights to host and
        frees the KV cache; level 2 also drops the host copy (wake
        reloads from the checkpoint). Returns bytes released (approx:
        weights + KV)."""
        assert self.kv_caches is not None, "engine not initialized"
        freed = sum(x.nbytes
                    for x in jax.tree_util.tree_leaves(self.params))
        freed += sum(x.nbytes
                     for x in jax.tree_util.tree_leaves(self.kv_caches))
        if level == 1:
            self._host_params = jax.tree.map(
                lambda x: np.asarray(jax.device_get(x)), self.params)
        else:
            self._host_params = None
        if self._hist_dev is not None:
            self._hist_dev.delete()
            self._hist_dev = None
            self._hist_ver[:] = -1
        if self._last_sampled_dev is not None:
            self._last_sampled_dev.delete()
            self._last_sampled_dev = None
        if self._state_pool is not None:
            # Snapshots die with the HBM; the engine core resets the
            # scheduler-side index so no stale slot is ever restored.
            freed += sum(x.nbytes for x in self._state_pool.values())
            for leaf in self._state_pool.values():
                leaf.delete()
            self._state_pool = None
        for leaf in jax.tree_util.tree_leaves(self.params):
            leaf.delete()
        for leaf in jax.tree_util.tree_leaves(self.kv_caches):
            leaf.delete()
        self.params = None
        self.kv_caches = None
        self._sleeping = True
        logger.info("sleeping: released ~%.2f GiB HBM (level %d)",
                    freed / 2**30, level)
        return freed

    def wake_up(self) -> None:
        """Restore weights + a fresh (empty) KV cache. Compiled step
        functions persist — shapes are unchanged, so no recompiles."""
        assert getattr(self, "_sleeping", False), "not sleeping"
        from jax.sharding import NamedSharding
        if self._host_params is not None:
            # Walk the saved tree generically — families carry extra
            # top-level keys (embed_pos, embed_ln, encoder heads) and
            # some drop final_ln (post-norm BART).
            specs = self.model.param_specs()
            if self._eagle is not None and "eagle" in self._host_params:
                specs["eagle"] = self._eagle.param_specs()

            def place(p, s):
                if isinstance(p, dict):
                    return {k: place(v, s[k]) for k, v in p.items()}
                return jax.device_put(p, NamedSharding(self.mesh, s))

            self.params = place(self._host_params, specs)
            self._host_params = None
        else:
            from vllm_distributed_tpu.models.loader import get_model
            self.model, self.params = get_model(self.config, self.mesh)
            if self.lora_manager is not None:
                # The reload came with fresh zero adapter buffers; the
                # slot map must forget its names or old adapters would
                # "resolve" to zeroed slots and silently serve the base
                # model. Safe: sleep requires an idle engine.
                self._init_lora_manager()
        if getattr(self.model, "CROSS_ATTENTION", False):
            self.model.params_ref = self.params  # old arrays deleted
        if self._eagle is not None and "eagle" in (self.params or {}):
            self._eagle.eparams = self.params["eagle"]
        self.kv_caches = self._make_sharded_caches(self.num_pages)
        self._init_state_pool()
        self._sleeping = False
        logger.info("awake: weights restored, KV cache reset")

    def kv_cache_bytes_per_page(self) -> int:
        # The model owns its cache layout (MLA stores one latent row per
        # token instead of per-head K/V).
        bytes_ = self.model.kv_cache_page_bytes(self.page_size)
        if self._eagle is not None:
            L = self.model.cfg.num_layers
            bytes_ = bytes_ * (L + self._eagle.num_layers) // L
        return bytes_

    def model_fixed_cache_bytes(self) -> int:
        """Per-request fixed state bytes (SSM rows); 0 for paged-KV-only
        models."""
        fn = getattr(self.model, "fixed_cache_bytes", None)
        return fn() if fn is not None else 0

    def _build_step_fn(self) -> None:
        """Two jits instead of one: forward (shapes keyed by the token
        bucket T) and logits+sample (keyed by the sampling-rows bucket R).
        The split makes the precompile lattice ADDITIVE (|T| + |R| graphs)
        instead of multiplicative (|T| x |R|) — the TPU answer to the
        reference's per-shape warm-up suite (tpu_model_runner.py:1248).
        The [R]-row gather between them runs op-by-op (one XLA gather)."""
        model = self.model
        eagle = self._eagle

        def forward(params, kv_caches, token_ids, batch: AttentionBatch):
            hidden, kv_caches = model.forward(params, kv_caches, token_ids,
                                              batch)
            if eagle is not None:
                # The drafter advances its KV in the SAME program: every
                # scheduled token's (embedding, target hidden) runs the
                # eagle layers, writing cache rows past the target's
                # depth (reference: eagle.py:120 advances per step).
                kv_caches = eagle.advance(params["eagle"], kv_caches,
                                          token_ids, hidden, batch)
            return kv_caches, hidden

        def sample(params, hidden_sel, sampling_md: SamplingMetadata):
            logits = model.compute_logits(params, hidden_sel)
            tokens, logprobs = sample_tokens(logits, sampling_md)
            return tokens, logprobs

        def sample_ext(params, hidden_sel, sampling_md: SamplingMetadata,
                       ext: ExtendedSamplingMetadata, want_topk: bool,
                       vocab_mask=None):
            logits = model.compute_logits(params, hidden_sel)
            return sample_tokens_extended(logits, sampling_md, ext,
                                          want_topk, vocab_mask)

        def prompt_lp(params, sel, targets):
            """Score prompt positions: log-softmax over the LM head at
            the pre-gathered rows [P, H], returning the target (= actual
            next prompt token) logprob plus the top-k alternatives
            (reference: the prompt_logprobs gather of
            gpu_model_runner._get_prompt_logprobs_dict). The row gather
            runs op-by-op outside so the graph keys only on the P
            bucket — ADDITIVE with the forward lattice, like the
            forward/sample split."""
            logits = model.compute_logits(params, sel)
            lp = jax.nn.log_softmax(logits, axis=-1)
            tgt = jnp.take_along_axis(lp, targets[:, None], axis=1)[:, 0]
            topv, topi = jax.lax.top_k(
                lp, min(MAX_LOGPROBS, lp.shape[-1]))
            return tgt, topv, topi

        def spec_verify(params, hidden_sel, drafts, q_ids, q_probs,
                        sampling_md: SamplingMetadata,
                        truncate: bool = False):
            """Logits + true rejection-sampling verification in one
            graph (reference: v1/sample/rejection_sampler.py:23); keyed
            by the R bucket like the plain sampler."""
            import dataclasses as _dc

            from vllm_distributed_tpu.sample.sampler import \
                spec_verify_rejection
            logits = model.compute_logits(params, hidden_sel)
            R = drafts.shape[0]
            S1 = hidden_sel.shape[0] // R
            # The dispatch path builds [R*S1]-expanded metadata (the
            # plain sampler's layout); the verifier wants per-row fields
            # and the per-position seeds.
            md_r = _dc.replace(
                sampling_md,
                temperature=sampling_md.temperature.reshape(R, S1)[:, 0],
                top_k=sampling_md.top_k.reshape(R, S1)[:, 0],
                top_p=sampling_md.top_p.reshape(R, S1)[:, 0],
                min_p=sampling_md.min_p.reshape(R, S1)[:, 0])
            return spec_verify_rejection(
                logits.reshape(R, S1, logits.shape[-1]), drafts, q_ids,
                q_probs, md_r, truncate=truncate)

        def numerics(params, hidden_sel):
            """Correctness-sentinel reduction over the SAME rows the
            sampler consumes: [nonfinite logits, mean entropy, mean
            top-1/top-2 margin]. One extra LM-head matmul per step —
            the sentinel's documented device cost."""
            logits = model.compute_logits(params, hidden_sel)
            bad = jnp.sum(~jnp.isfinite(logits)).astype(jnp.float32)
            lp = jax.nn.log_softmax(logits, axis=-1)
            ent = -jnp.sum(jnp.exp(lp) * lp, axis=-1)
            top2 = jax.lax.top_k(logits, 2)[0]
            return jnp.stack([
                bad, jnp.mean(ent), jnp.mean(top2[:, 0] - top2[:, 1])
            ])

        # Donate the caches: XLA aliases them in place of a copy.
        self._forward_fn = jax.jit(forward, donate_argnums=(1, ))
        if self._numerics is not None:
            self._numerics_fn = jax.jit(numerics)
        self._plp_fn = jax.jit(prompt_lp)
        self._sample_fn = jax.jit(sample)
        self._sample_ext_fn = jax.jit(sample_ext,
                                      static_argnames=("want_topk", ))
        self._spec_verify_fn = jax.jit(spec_verify,
                                       static_argnames=("truncate", ))
        self._build_multi_step_fn()

    def _build_multi_step_fn(self) -> None:
        """N fused decode steps in one jitted lax.scan: the host pays one
        dispatch+sync per burst instead of per token (TPU answer to the
        reference's multi-step scheduling + advance_step.cu in-place input
        update; sampled tokens feed the next step on-device)."""
        import dataclasses

        model = self.model
        page_size = self.page_size
        mrope_on = self._mrope_on

        def multi_step(params, kv_caches, tok0, pos0, block_tables,
                       sampling_md: SamplingMetadata, seeds, num_active,
                       mrope_deltas):
            R = tok0.shape[0]
            rows = jnp.arange(R, dtype=jnp.int32)
            ones = jnp.ones((R, ), jnp.int32)

            def one(carry, seeds_t):
                kv, tok, pos = carry
                active = rows < num_active[0]
                page = block_tables[rows, pos // page_size]
                off = pos % page_size
                slot = jnp.where(active, page * page_size + off, -1)
                seq_info = jnp.stack([rows, ones, pos + 1, rows], axis=1)
                # One single-token page-write run per active request.
                kv_runs = jnp.stack(
                    [page, off, rows - off + page_size,
                     jnp.where(active, 1, 0)], axis=1)
                mrope = None
                if mrope_on:
                    # Decode ids continue at position + delta on all
                    # three rotary dims (qwen2_vl get_rope_index).
                    mrope = jnp.broadcast_to(
                        (pos + mrope_deltas)[:, None], (R, 3))
                batch = AttentionBatch(
                    req_idx=rows, positions=pos, slot_mapping=slot,
                    block_tables=block_tables, seq_lens=pos + 1,
                    seq_info=seq_info, num_seqs=num_active,
                    kv_runs=kv_runs, num_kv_runs=num_active,
                    mrope_positions=mrope, max_q=1)
                hidden, kv = model.forward(params, kv, tok, batch)
                logits = model.compute_logits(params, hidden)
                md_t = dataclasses.replace(sampling_md, seeds=seeds_t)
                tok_next, logprobs = sample_tokens(logits, md_t)
                return (kv, tok_next, pos + 1), (tok_next, logprobs)

            (kv, _, _), (toks, lps) = jax.lax.scan(
                one, (kv_caches, tok0, pos0), seeds)
            return kv, toks, lps

        self._multi_step_fn = jax.jit(multi_step, donate_argnums=(1, ))

    # ------------------------------------------------------------------
    def _update_states(self, scheduler_output: SchedulerOutput) -> None:
        for req_id in scheduler_output.finished_req_ids:
            if self.lora_manager is not None:
                row = self.input_batch.req_id_to_index.get(req_id)
                if row is not None and self.input_batch.lora_slot[row]:
                    self.lora_manager.release(
                        int(self.input_batch.lora_slot[row]))
            self._draft_meta.pop(req_id, None)
            self.input_batch.remove_request(req_id)
        for new_req in scheduler_output.scheduled_new_reqs:
            row = self.input_batch.add_request(new_req)
            if self._mrope_on:
                from vllm_distributed_tpu.multimodal import \
                    compute_mrope_positions
                if new_req.mm_inputs:
                    self._mrope[row] = compute_mrope_positions(
                        len(new_req.prompt_token_ids),
                        new_req.mm_inputs)
                else:
                    self._mrope[row] = (None, 0)
            if getattr(self.model, "CROSS_ATTENTION", False):
                # Encoder-decoder (whisper): project the audio
                # encoder's hidden states into this request's
                # cross-KV state row (offset=-1 payloads; reference:
                # the cross-attn KV fill of models/whisper.py). A row
                # claimed WITHOUT a payload must have its stale state
                # masked — the previous occupant's audio/document would
                # otherwise leak into this request's cross-attention.
                installed = False
                for inp in (new_req.mm_inputs or ()):
                    if inp.offset < 0:
                        self.kv_caches = self.model.install_cross_states(
                            self.kv_caches, row, inp.embeds)
                        installed = True
                if not installed:
                    self.kv_caches = self.model.clear_cross_states(
                        self.kv_caches, row)
            if new_req.lora_request is not None:
                if self.lora_manager is None:
                    raise ValueError(
                        "request carries a LoRA adapter but the engine "
                        "was built without enable_lora")
                self.input_batch.lora_slot[row] = \
                    self.lora_manager.acquire(
                        new_req.lora_request["name"],
                        new_req.lora_request["path"], self)
        self.input_batch.update_cached(scheduler_output.scheduled_cached_reqs)

    def _use_unified(self) -> bool:
        """Mega-kernel (partition-descriptor) batches: on for every
        model with the standard K/V page layout. MLA models (latent
        cache, own kernel keyed by max_q) keep the legacy composition-
        split shapes."""
        if self._unified is None:
            if self.model is None:
                return False  # don't memoize before the model exists
            self._unified = "k" in self.model.kv_cache_specs()
        return self._unified

    def _block_fusion_active(self) -> bool:
        """Can this engine dispatch the fused decode-block path at all?
        The loader decided arch eligibility once (cfg.block_fusion,
        VDT_BLOCK_FUSION-gated); the runner adds the dispatch-side
        requirements: the unified (descriptor) batch layout, no token
        parallelism, and the Pallas backend (the XLA-composed reference
        exists for tests, not serving — on the XLA backend the per-op
        path IS the reference)."""
        if self._block_fusion_memo is None:
            if self.model is None:
                return False  # don't memoize before the model exists
            # Disagg prefill-pool replicas never see a pure-decode wave
            # (their requests finish at the first sampled token), so
            # fusion neither warms its graph variants nor dispatches —
            # the per-role precompile-lattice prune (engine/disagg.py).
            self._block_fusion_memo = bool(
                getattr(self.model.cfg, "block_fusion", False)
                and self.pool_role != "prefill"
                and self._use_unified()
                and self.tknp_size == 1
                and resolve_attention_backend() == "pallas")
        return self._block_fusion_memo

    def _count_block_fusion(self, batch=None, reason: str = None) -> None:
        """Per-step fused-dispatch accounting, only while fusion is
        enabled+eligible so the families stay silent otherwise."""
        if not self._block_fusion_active():
            return
        if reason is None and batch is not None:
            if getattr(batch, "block_fused", False):
                self.block_fusion_calls += 1
                return
            reason = ("cascade"
                      if getattr(batch, "cascade_shared_ids", None)
                      is not None else "mixed_wave")
        self.block_fusion_fallbacks[reason] = (
            self.block_fusion_fallbacks.get(reason, 0) + 1)

    def _tile_params(self) -> tuple[int, int]:
        """The fixed (prefill tile rows, decode group width) of the
        mega-kernel, computed from LOCAL head counts (the kernel runs
        per-shard under tensor parallelism) and the storage head dim.
        The same values ride the batch as statics so the host-built
        descriptor and the kernel can never disagree."""
        if self._tile_params_memo is None:
            from vllm_distributed_tpu.ops.attention import \
                storage_head_dim
            from vllm_distributed_tpu.ops.pallas_attention import (
                decode_group_size, prefill_tile_size)
            cfg = self.model.cfg
            tp = max(1, self.config.parallel_config.tensor_parallel_size)
            qh = max(1, cfg.num_q_heads // tp)
            kvh = max(1, getattr(cfg, "total_kv_heads",
                                 cfg.num_kv_heads) // tp)
            hd = storage_head_dim(cfg.head_dim)
            self._tile_params_memo = (prefill_tile_size(qh, hd),
                                      decode_group_size(qh, kvh))
        return self._tile_params_memo

    def _batch_shape(self, total_tokens: int,
                     max_sched: int) -> tuple[int, int, int]:
        """Static (T, max_q, G) for a step.

        Unified (mega-kernel) models: the batch composition is carried
        by the partition descriptor, NOT by any static — ``max_q`` is
        pinned to 1 and T = t_bucket + Q_TILE_PAD for every mix of
        prefill and decode, so the forward lattice is exactly one graph
        per token bucket (decode buckets coincide with small token
        buckets and dedupe away).

        Legacy (MLA) models: ``max_q`` is 1 for pure decode, else the
        token bucket (the kernel grid skips tiles past each sequence's
        q_len), splitting each bucket into a decode and a prefill
        variant. G (KV-write run bucket) is a deterministic function of
        T in both modes."""
        t_bucket = pad_to_bucket(total_tokens, self.token_buckets)
        if self._use_unified():
            from vllm_distributed_tpu.ops.pallas_attention import \
                Q_TILE_PAD
            max_q = 1
            T = t_bucket + Q_TILE_PAD
        else:
            max_q = 1 if max_sched <= 1 else t_bucket
            T = t_bucket + min(max_q, 128)
        G = pad_to_bucket(cdiv(T, self.page_size) + self.max_num_reqs,
                          self.kv_run_buckets)
        return T, max_q, G

    def _fast_decode_rows(self, scheduler_output: SchedulerOutput):
        """Vectorized-prep eligibility: a pure single-token decode batch
        with no new/resumed rows and none of the per-token features the
        general loop handles (spec drafts, M-RoPE tables, LoRA slot
        grouping, token-parallel rank views, prompt-logprob scoring, mm
        placeholder substitution). Returns (rows, req_ids) when every
        scheduled request is one decode token past its prompt, else
        None — _prepare_inputs then fills the flat arrays with numpy
        gathers instead of the per-request python loop (delta-style
        prep for the decode steady state, where host time is the
        throughput ceiling)."""
        num_sched = scheduler_output.num_scheduled_tokens
        if (self.spec_k or self.tknp_size > 1 or self._mrope_on
                or self.lora_manager is not None
                or scheduler_output.scheduled_new_reqs
                or scheduler_output.total_num_scheduled_tokens
                != len(num_sched)):
            return None
        ib = self.input_batch
        req_ids = list(num_sched)
        rows = np.fromiter((ib.req_id_to_index[r] for r in req_ids),
                           np.int32, count=len(req_ids))
        starts = ib.num_computed[rows]
        # Every row past its prompt (no plp entries, no mm windows) and
        # sampling this step (start+1 reaches all committed tokens —
        # continuation prefills with backlog take the general loop).
        if not (np.all(starts >= ib.prompt_len[rows])
                and np.all(starts + 1 >= ib.num_tokens[rows])):
            return None
        return rows, req_ids

    def _ensure_last_sampled(self) -> jax.Array:
        if self._last_sampled_dev is None:
            self._last_sampled_dev = jnp.zeros((self.max_num_reqs, ),
                                               jnp.int32)
        return self._last_sampled_dev

    def _prepare_inputs(self, scheduler_output: SchedulerOutput):
        """Flatten the scheduled requests into padded per-token arrays."""
        ib = self.input_batch
        num_sched = scheduler_output.num_scheduled_tokens
        total_tokens = scheduler_output.total_num_scheduled_tokens
        # Static shape bucket; token arrays carry one extra q tile of
        # padding so a sequence's final tile may spill past its q_len
        # (see ops/pallas_attention.py).
        T, max_q, G = self._batch_shape(total_tokens,
                                        max(num_sched.values()))

        token_ids = np.zeros((T, ), np.int32)
        positions = np.zeros((T, ), np.int32)
        req_idx = np.zeros((T, ), np.int32)
        slot_mapping = np.full((T, ), -1, np.int32)
        mrope_np = (np.zeros((T, 3), np.int32) if self._mrope_on
                    else None)
        seq_info = np.zeros((self.max_num_reqs, 4), np.int32)
        kv_runs: list[tuple[int, int, int, int]] = []
        ps = self.page_size

        K = self.tknp_size
        if K > 1:
            # Per-rank views: a request's owner rank is implied by its
            # page range (the scheduler allocates each request's pages
            # from one rank's pool partition).
            Nl = self.num_pages // K
            tk_slot = np.full((K, T), -1, np.int32)
            tk_bt = np.zeros(
                (K, self.max_num_reqs, self.max_pages_per_req), np.int32)
            tk_seq_info = np.zeros((K, self.max_num_reqs, 4), np.int32)
            tk_num_seqs = np.zeros((K, 1), np.int32)
            tk_kv_runs = np.zeros((K, G, 4), np.int32)
            tk_num_kv_runs = np.zeros((K, 1), np.int32)

        sampling_rows: list[int] = []
        sampling_req_ids: list[str] = []
        logits_idx: list[int] = []
        spec_drafts: list[list[int]] = []
        # Prompt-logprob rows: flat row index + next-prompt-token target
        # per scored position (reference: the prompt_logprobs path of
        # gpu_model_runner._get_prompt_logprobs_dict).
        plp_rows: list[int] = []
        plp_targets: list[int] = []
        # (req_id, entry_index, k, target_token) per scored position.
        plp_meta: list[tuple[str, int, int, int]] = []
        # Async scheduling: flat positions whose input token is still on
        # device (step N's sample, not yet landed on the host) and the
        # batch row to chain it from (_chain_apply).
        chain_pos: list[int] = []
        chain_rows: list[int] = []

        fast = self._fast_decode_rows(scheduler_output)
        if fast is not None:
            # Pure single-token decode: fill the flat arrays with
            # vectorized gathers against the persistent batch instead
            # of the per-request python loop — the decode steady state
            # is where per-step host time matters most.
            rows_np, fast_req_ids = fast
            N = len(rows_np)
            starts = ib.num_computed[rows_np].astype(np.int32)
            idx = np.arange(N, dtype=np.int32)
            token_ids[:N] = ib.token_ids[rows_np, starts]
            positions[:N] = starts
            req_idx[:N] = rows_np
            pages = ib.block_table[rows_np, starts // ps]
            offs = starts % ps
            slot_mapping[:N] = pages * ps + offs
            seq_info[:N] = np.stack(
                [idx, np.ones(N, np.int32), starts + 1, rows_np], axis=1)
            num_runs = N
            kv_runs_arr = np.zeros((G, 4), np.int32)
            kv_runs_arr[:N] = np.stack(
                [pages, offs, idx - offs + ps,
                 np.ones(N, np.int32)], axis=1)
            n_kv_runs = N
            sampling_rows = [int(r) for r in rows_np]
            sampling_req_ids = fast_req_ids
            logits_idx = [int(i) for i in idx]
            if self._async_chain:
                chained = starts >= ib.num_tokens[rows_np]
                chain_pos = [int(i) for i in idx[chained]]
                chain_rows = [int(r) for r in rows_np[chained]]
            t = N
            loop_items = ()
        else:
            loop_items = num_sched.items()
            t = 0
            num_runs = 0
        for req_id, n in loop_items:
            row = ib.req_id_to_index[req_id]
            start = ib.num_computed[row]
            end = start + n
            if self._async_chain:
                # Positions past the host's committed tokens take the
                # previous step's on-device sample (async run-ahead).
                known = int(ib.num_tokens[row])
                for p in range(max(start, known), end):
                    chain_pos.append(t + (p - start))
                    chain_rows.append(row)
            drafts = (scheduler_output.scheduled_spec_decode_tokens.get(
                req_id, []) if self.spec_k else [])
            if drafts:
                # Draft tokens are not committed history: stage them into
                # the row's scratch tail so the flat slice below sees them
                # (they sit exactly at positions [end-D, end)).
                ib.token_ids[row, end - len(drafts):end] = drafts
            token_ids[t:t + n] = ib.token_ids[row, start:end]
            positions[t:t + n] = np.arange(start, end, dtype=np.int32)
            req_idx[t:t + n] = row
            if mrope_np is not None:
                # Prompt positions read the request's 3D id table;
                # generated positions continue at position + delta on
                # all three dims (reference: qwen2_vl get_rope_index).
                table, delta = self._mrope.get(row, (None, 0))
                seg = np.arange(start, end)
                vals = np.repeat((seg + delta)[:, None], 3, axis=1)
                if table is not None:
                    in_prompt = seg < table.shape[0]
                    vals[in_prompt] = table[seg[in_prompt]]
                mrope_np[t:t + n] = vals
            pos = np.arange(start, end)
            slot_mapping[t:t + n] = (
                ib.block_table[row, pos // ps] * ps + pos % ps)
            seq_info[num_runs] = (t, n, end, row)
            num_runs += 1
            k_plp = int(ib.prompt_logprobs[row])
            if k_plp >= 0 and start < int(ib.prompt_len[row]):
                # Row at position p predicts prompt token p+1; the row
                # at prompt_len-1 predicts the first OUTPUT token and is
                # the sampling row, not a prompt entry.
                for p in range(start,
                               min(end, int(ib.prompt_len[row]) - 1)):
                    tgt = int(ib.token_ids[row, p + 1])
                    plp_rows.append(t + (p - start))
                    plp_targets.append(tgt)
                    plp_meta.append((req_id, p + 1, k_plp, tgt))
                if end >= int(ib.prompt_len[row]):
                    # Final chunk scored: stop re-scoring on a
                    # preempt-resume re-run of an already-delivered
                    # prompt (the row persists across preemption).
                    ib.prompt_logprobs[row] = -1
            if K > 1:
                owner = int(ib.block_table[row, 0]) // Nl
                tk_slot[owner, t:t + n] = \
                    slot_mapping[t:t + n] - owner * Nl * ps
                tk_bt[owner, row] = np.maximum(
                    ib.block_table[row] - owner * Nl, 0)
                i_r = tk_num_seqs[owner, 0]
                tk_seq_info[owner, i_r] = (t, n, end, row)
                tk_num_seqs[owner, 0] = i_r + 1
            # Page-write runs for the Pallas KV-write kernel: maximal
            # consecutive-slot spans within one page.
            consumed = 0
            while consumed < n:
                p = start + consumed
                off = p % ps
                run_len = min(ps - off, n - consumed)
                src = t + consumed
                page_id = int(ib.block_table[row, p // ps])
                kv_runs.append((page_id, off, src - off + ps, run_len))
                if K > 1:
                    g = tk_num_kv_runs[owner, 0]
                    tk_kv_runs[owner, g] = (page_id - owner * Nl, off,
                                            src - off + ps, run_len)
                    tk_num_kv_runs[owner, 0] = g + 1
                consumed += run_len
            if end >= ib.num_tokens[row]:
                # This step finishes all known tokens: sample.
                sampling_rows.append(row)
                sampling_req_ids.append(req_id)
                logits_idx.append(t + n - 1)
                spec_drafts.append(drafts)
            t += n

        if fast is None:
            kv_runs_arr = np.zeros((G, 4), np.int32)
            if kv_runs:
                kv_runs_arr[:len(kv_runs)] = kv_runs
            n_kv_runs = len(kv_runs)

        # Mega-kernel partition descriptor: kv-write rows first (the
        # fused write+attend pass needs them to precede every attention
        # program), then prefill q-tiles and SB decode groups. The fast
        # decode path feeds its row vector directly (no q_len scan).
        attn_desc = decode_list_arr = None
        bq = sb = 0
        if self._use_unified():
            from vllm_distributed_tpu.ops.pallas_attention import (
                Q_TILE_PAD, build_partition_descriptor,
                num_partition_programs)
            bq, sb = self._tile_params()
            P_desc = num_partition_programs(
                T - Q_TILE_PAD, self.max_num_reqs, bq=bq, sb=sb,
                num_kv_writes=G)
            desc_np, dl_np = build_partition_descriptor(
                seq_info, num_runs, bq=bq, sb=sb,
                num_programs=P_desc, num_kv_writes=n_kv_runs,
                decode_rows=(np.arange(num_runs, dtype=np.int32)
                             if fast is not None else None))
            attn_desc = jnp.asarray(desc_np)
            decode_list_arr = jnp.asarray(dl_np)
            if K > 1:
                tk_desc = np.zeros((K, P_desc, 3), np.int32)
                tk_dl = np.zeros((K, self.max_num_reqs), np.int32)
                for kk in range(K):
                    tk_desc[kk], tk_dl[kk] = build_partition_descriptor(
                        tk_seq_info[kk], int(tk_num_seqs[kk, 0]),
                        bq=bq, sb=sb, num_programs=P_desc)

        S1 = self.spec_k + 1  # sampled positions per sampling request
        R = pad_to_bucket(max(len(sampling_rows), 1), self.req_buckets)
        rows = np.asarray(sampling_rows +
                          [0] * (R - len(sampling_rows)), np.int32)
        if self.spec_k:
            # Each sampling request samples at its last D+1 positions
            # (the committed token + its drafts), padded to S+1 rows by
            # repeating the last index; drafts pad with -1 (never equal a
            # sampled token, so padding positions reject).
            from vllm_distributed_tpu.spec_decode.draft_model import \
                SUPPORT_K
            verify_idx = np.zeros((R, S1), np.int32)
            drafts_arr = np.full((R, self.spec_k), -1, np.int32)
            # Draft-support metadata for rejection-sampling verification:
            # proposers that sampled stochastically recorded their
            # truncated support; deterministic proposals (ngram, greedy
            # drafts) are a delta at the draft token — min(1, p/q) with
            # q = 1 accepts with exactly prob p(d), the same rate the
            # old prefix match achieved, so one verifier serves all.
            q_ids = np.zeros((R, self.spec_k, SUPPORT_K), np.int32)
            q_probs = np.zeros((R, self.spec_k, SUPPORT_K), np.float32)
            for i, li in enumerate(logits_idx):
                D = len(spec_drafts[i])
                verify_idx[i] = li  # default: repeat the last position
                verify_idx[i, :D + 1] = np.arange(li - D, li + 1)
                if D:
                    drafts_arr[i, :D] = spec_drafts[i]
                    meta = self._draft_meta.get(sampling_req_ids[i])
                    if meta is not None and meta[0].shape[1] == SUPPORT_K:
                        m_ids, m_probs = meta
                        q_ids[i, :D] = m_ids[:D]
                        q_probs[i, :D] = m_probs[:D]
                    else:
                        q_ids[i, :D, 0] = spec_drafts[i]
                        q_probs[i, :D, 0] = 1.0
            logits_indices = verify_idx.reshape(-1)
        else:
            drafts_arr = None
            q_ids = q_probs = None
            logits_indices = np.asarray(logits_idx + [0] *
                                        (R - len(logits_idx)), np.int32)

        # Seeds: seeded requests fold (user_seed, step-in-request) so runs
        # reproduce; unseeded draw from the engine rng.
        user_seed = ib.seed[rows]
        step_in_req = ib.num_tokens[rows].astype(np.int64)
        random_part = self._rng.integers(0, 2**31 - 1, size=R)
        seeds = np.where(user_seed >= 0,
                         user_seed * 1000003 + step_in_req, random_part)

        def expand(x):
            return np.repeat(x, S1, axis=0) if self.spec_k else x

        # Per-position seed offsets keep sampled positions independent.
        seeds_e = expand(seeds)
        if self.spec_k:
            seeds_e = seeds_e + 7919 * np.tile(np.arange(S1), R)
        sampling_md = SamplingMetadata(
            temperature=jnp.asarray(expand(ib.temperature[rows])),
            top_k=jnp.asarray(expand(ib.top_k[rows])),
            top_p=jnp.asarray(expand(ib.top_p[rows])),
            min_p=jnp.asarray(expand(ib.min_p[rows])),
            seeds=jnp.asarray(seeds_e),
        )
        ext_md = None
        want_topk = False
        if any(ib.extended_active(r) for r in sampling_rows):
            ext_md = self._build_extended_md(rows, expand)
            want_topk = bool(any(ib.num_logprobs[r] > 0
                                 for r in sampling_rows))
        # Structured-output grammar masks (reference: grammar bitmask on
        # the scheduler output, applied at gpu_model_runner.py:1433).
        # Dense [R, V] bool, padding/unconstrained rows all-True; only
        # built when a scheduled sampling request has a grammar.
        vocab_mask = None
        struct_masks = getattr(scheduler_output, "structured_masks",
                               None) or {}
        if struct_masks and any(rid in struct_masks
                                for rid in sampling_req_ids):
            V = self.model.cfg.vocab_size
            mask_np = np.ones((R, V), bool)
            for i, rid in enumerate(sampling_req_ids):
                m = struct_masks.get(rid)
                if m is not None:
                    # Tokenizer and model vocab sizes can differ (padded
                    # embeddings / unused ids): ids beyond the grammar
                    # table are never valid grammar bytes -> disallowed.
                    n = min(len(m), V)
                    mask_np[i, :n] = m[:n]
                    mask_np[i, n:] = False
            if self.spec_k:
                # Structured rows never carry drafts (the extended path
                # disables proposals), so only position 0 of each S1
                # group is ever emitted — repeating the pre-advance mask
                # across the group masks real samples correctly and the
                # discarded padding positions don't matter.
                mask_np = np.repeat(mask_np, S1, axis=0)
            vocab_mask = jnp.asarray(mask_np)
        tknp = None
        if K > 1:
            tknp = TknpAttentionBatch(
                slot_mapping=jnp.asarray(tk_slot),
                block_tables=jnp.asarray(tk_bt),
                seq_info=jnp.asarray(tk_seq_info),
                num_seqs=jnp.asarray(tk_num_seqs),
                kv_runs=jnp.asarray(tk_kv_runs),
                num_kv_runs=jnp.asarray(tk_num_kv_runs),
                desc=(jnp.asarray(tk_desc) if attn_desc is not None
                      else None),
                decode_list=(jnp.asarray(tk_dl)
                             if attn_desc is not None else None),
            )
        cascade_ids = self._detect_cascade(scheduler_output)
        lora_ctx = None
        if self.lora_manager is not None:
            # Token -> adapter-slot grouping, shared by every LoRA
            # matmul this step (padding tokens inherit row 0's slot —
            # their outputs are never read).
            from vllm_distributed_tpu.models.common import LoraBatch
            slots = ib.lora_slot[req_idx]
            order = np.argsort(slots, kind="stable")
            S = self.config.lora_config.max_loras + 1
            lora_ctx = LoraBatch(
                order=jnp.asarray(order.astype(np.int32)),
                inv=jnp.asarray(np.argsort(order).astype(np.int32)),
                group_sizes=jnp.asarray(
                    np.bincount(slots, minlength=S)[:S].astype(np.int32)),
                scaling=jnp.asarray(
                    self.lora_manager.scaling[slots[order]]),
            )
        # Multimodal: placeholder positions scheduled this step take
        # their pre-computed encoder rows (reference: the scheduled
        # encoder inputs of v1/core/sched/output.py + the embedding
        # merge in gpu_model_runner._execute_mm_encoder). Host loop over
        # real tokens only, and only on steps with an image request.
        mm_embeds = mm_mask = None
        def _mm_scheduled():
            # Cheap gate: a row needs substitution only while scheduled
            # positions can still fall inside a placeholder span (never
            # on decode steps; the row's first position this step is its
            # pre-step num_computed).
            for r in num_sched:
                row = ib.req_id_to_index[r]
                # offset < 0 marks cross-attention payloads (whisper
                # audio), consumed at admission, never substituted.
                mm_list = [inp for inp in (ib.mm[row] or ())
                           if inp.offset >= 0]
                if mm_list and ib.num_computed[row] < max(
                        inp.offset + inp.num_tokens for inp in mm_list):
                    return True
            return False
        if _mm_scheduled():
            Hd = self.model.cfg.hidden_size
            ov = np.zeros((T, Hd), np.float32)
            mk = np.zeros((T, ), bool)
            for ti in range(total_tokens):
                mm_list = ib.mm[req_idx[ti]]
                if not mm_list:
                    continue
                p = int(positions[ti])
                for inp in mm_list:
                    if 0 <= inp.offset <= p < inp.offset + inp.num_tokens:
                        ov[ti] = inp.embeds[p - inp.offset]
                        mk[ti] = True
                        break
            if mk.any():
                mm_embeds = jnp.asarray(ov)
                mm_mask = jnp.asarray(mk)
            # else: pure-decode step of an image request — no placeholder
            # positions scheduled; take the text-only graph (no [T, H]
            # upload, no mm-variant compile).

        batch = AttentionBatch(
            req_idx=jnp.asarray(req_idx),
            positions=jnp.asarray(positions),
            slot_mapping=jnp.asarray(slot_mapping),
            block_tables=jnp.asarray(ib.block_table),
            seq_lens=jnp.asarray(ib.num_computed),
            seq_info=jnp.asarray(seq_info),
            num_seqs=jnp.asarray([num_runs], np.int32),
            kv_runs=jnp.asarray(kv_runs_arr),
            num_kv_runs=jnp.asarray([n_kv_runs], np.int32),
            tknp=tknp,
            lora=lora_ctx,
            cascade_shared_ids=cascade_ids,
            mm_embeds=mm_embeds,
            mm_mask=mm_mask,
            mrope_positions=(jnp.asarray(mrope_np)
                             if mrope_np is not None else None),
            attn_desc=attn_desc,
            decode_list=decode_list_arr,
            max_q=max_q,
            attn_bq=bq,
            attn_sb=sb,
            # Fused decode-block dispatch: the vectorized-prep fast path
            # already proves this wave is pure single-token decode with
            # none of the per-token features (spec drafts / M-RoPE /
            # LoRA / tknp / plp / mm) the fused kernel would miss.
            block_fused=bool(self._block_fusion_active()
                             and fast is not None
                             and cascade_ids is None),
        )
        plp = None
        if plp_rows:
            Pb = pad_to_bucket(len(plp_rows), self.token_buckets)
            rows_np = np.zeros((Pb, ), np.int32)
            tgt_np = np.zeros((Pb, ), np.int32)
            rows_np[:len(plp_rows)] = plp_rows
            tgt_np[:len(plp_targets)] = plp_targets
            plp = (jnp.asarray(rows_np), jnp.asarray(tgt_np), plp_meta)
        # Verifier truncation only when some batch row needs it (static
        # jit arg: the default-sampling serving case keeps the cheaper
        # untruncated verify graph; padding rows sit at the no-op
        # defaults so they never flip it).
        spec_truncate = bool(self.spec_k) and bool(
            (ib.top_k[rows] > 0).any() or (ib.top_p[rows] < 1.0).any()
            or (ib.min_p[rows] > 0.0).any())
        chain = None
        if chain_pos:
            # Padded to the request bucket; pad positions point one past
            # the token array so _chain_apply drops them.
            C = pad_to_bucket(len(chain_pos), self.req_buckets)
            cp = np.full((C, ), T, np.int32)
            cr = np.zeros((C, ), np.int32)
            cp[:len(chain_pos)] = chain_pos
            cr[:len(chain_rows)] = chain_rows
            chain = (jnp.asarray(cp), jnp.asarray(cr))
        return (jnp.asarray(token_ids), batch,
                jnp.asarray(logits_indices), sampling_md,
                sampling_req_ids, (T, max_q, G), R,
                (drafts_arr, q_ids, q_probs, spec_truncate), ext_md,
                want_topk, vocab_mask, plp, chain)

    # Fixed sparse-bias width; keeps the graph keyed by R. Admission-time
    # validation in SamplingParams guarantees every request fits.
    from vllm_distributed_tpu.sampling_params import \
        BIAS_BUF_WIDTH as _BIAS_BUF

    def _hist_rows_device(self, rows: np.ndarray, expand) -> jax.Array:
        """[R(*S1), max_model_len] token history for the penalty kernels,
        gathered from a DEVICE-RESIDENT mirror of the input batch's
        token table. Per-step host->device traffic is O(R * _hist_delta)
        (the newly committed tokens), independent of max_model_len —
        round-2/3 ADVICE flagged the previous full [R, max_model_len]
        upload every penalty step. Rows re-upload in full only when
        their content was rewritten (admission, preemption resume) or
        drifted more than _hist_delta tokens while off the extended
        path."""
        ib = self.input_batch
        L = self.max_model_len
        max_reqs = ib.token_ids.shape[0]
        if self._hist_dev is None:
            self._hist_dev = jnp.zeros((max_reqs, L), jnp.int32)
        D = self._hist_delta
        R = len(rows)
        uniq = np.unique(rows)
        full_rows: list[int] = []
        d_rows = np.full((R, ), max_reqs, np.int32)  # pad -> dropped
        d_start = np.zeros((R, ), np.int32)
        d_toks = np.zeros((R, D), np.int32)
        d_len = np.zeros((R, ), np.int32)
        nd = 0
        for r in uniq:
            r = int(r)
            n = int(ib.num_tokens[r])
            behind = n - int(self._hist_len[r])
            if (self._hist_ver[r] != ib.row_version[r]
                    or not 0 <= behind <= D):
                full_rows.append(r)
                self._hist_ver[r] = ib.row_version[r]
            elif behind:
                s = n - behind
                d_rows[nd] = r
                d_start[nd] = s
                d_toks[nd, :behind] = ib.token_ids[r, s:n]
                d_len[nd] = behind
                nd += 1
            self._hist_len[r] = n
        if full_rows:
            fr = np.full((R, ), max_reqs, np.int32)
            fr[:len(full_rows)] = full_rows
            vals = np.zeros((R, L), np.int32)
            vals[:len(full_rows)] = ib.token_ids[full_rows]
            self._hist_dev = self._hist_apply_full(self._hist_dev,
                                              jnp.asarray(fr),
                                              jnp.asarray(vals))
        if nd:
            self._hist_dev = self._hist_apply_delta(
                self._hist_dev, jnp.asarray(d_rows),
                jnp.asarray(d_start), jnp.asarray(d_toks),
                jnp.asarray(d_len))
        rows_pad = np.asarray(expand(rows), np.int32)
        return self._hist_dev[jnp.asarray(rows_pad)]

    def _build_extended_md(self, rows: np.ndarray,
                           expand) -> ExtendedSamplingMetadata:
        """Lower per-row python sampling extras to the fixed-shape
        ExtendedSamplingMetadata (see sample/metadata.py). ``rows`` is the
        padded [R] array of input-batch row indices."""
        ib = self.input_batch
        R = len(rows)
        B = self._BIAS_BUF
        pad_id = self.model.cfg.vocab_size  # out of vocab -> scatter drops
        bias_ids = np.full((R, B), pad_id, np.int32)
        bias_vals = np.zeros((R, B), np.float32)
        base_fill = np.zeros((R, ), np.float32)
        for i, row in enumerate(rows):
            allowed = ib.allowed_token_ids[row]
            bias = ib.logit_bias[row]
            entries: dict[int, float] = {}
            if allowed is not None:
                base_fill[i] = float("-inf")
                entries = {t: (bias or {}).get(t, 0.0) for t in allowed}
            elif bias:
                entries = dict(bias)
            n_out = int(ib.num_tokens[row] - ib.prompt_len[row])
            if n_out < ib.min_tokens[row]:
                for s in ib.stop_token_ids[row]:
                    entries[s] = float("-inf")
            if len(entries) > B:
                raise ValueError(
                    f"request needs {len(entries)} logit-bias/mask entries; "
                    f"the static buffer holds {B}")
            for j, (t, v) in enumerate(entries.items()):
                bias_ids[i, j] = t
                bias_vals[i, j] = v
        return ExtendedSamplingMetadata(
            hist_tokens=self._hist_rows_device(rows, expand),
            prompt_len=jnp.asarray(expand(ib.prompt_len[rows])),
            total_len=jnp.asarray(expand(ib.num_tokens[rows])),
            presence_penalty=jnp.asarray(expand(ib.presence_penalty[rows])),
            frequency_penalty=jnp.asarray(expand(
                ib.frequency_penalty[rows])),
            repetition_penalty=jnp.asarray(expand(
                ib.repetition_penalty[rows])),
            bias_ids=jnp.asarray(expand(bias_ids)),
            bias_vals=jnp.asarray(expand(bias_vals)),
            base_fill=jnp.asarray(expand(base_fill)),
        )

    # ------------------------------------------------------------------
    def execute_model(self,
                      scheduler_output: SchedulerOutput) -> ModelRunnerOutput:
        return self.wait_model(self.dispatch_model(scheduler_output))

    def dispatch_model(self, scheduler_output: SchedulerOutput) -> dict:
        """Non-blocking half of a step: sync batch state, enqueue the
        device work, return a handle for wait_model(). The engine core's
        pipeline-parallel batch queue dispatches several of these before
        waiting on the oldest (reference: core.py:242
        step_with_batch_queue); requests in a dispatched batch are
        excluded from scheduling until their batch retires."""
        self._update_states(scheduler_output)
        # State restores BEFORE the forward (the scan's re-entry carry);
        # zero-token outputs never carry them (scheduler invariant: the
        # zero-token path does no device work).
        self._apply_state_restores(scheduler_output)
        # KV-tier demotion gather + promotion scatter, also pre-forward
        # (and, like state ops, never on zero-token outputs).
        tier_pending = self._apply_kv_tier_pre(scheduler_output)
        if scheduler_output.total_num_scheduled_tokens == 0:
            # Nothing to run, but async KV transfers may need servicing:
            # hand queued peer reads / completed pulls to the connector
            # and report completion notifications (reference:
            # gpu_model_runner.py kv_connector_no_forward path).
            # CONTRACT: no device dispatch on this path — the PP batch
            # queue's sync fallback (engine/core.py) runs zero-token
            # batches while async batches are in flight and relies on it.
            out = ModelRunnerOutput()
            self._poll_kv_connector(scheduler_output, out)
            return {"ready": out}
        if scheduler_output.multi_step > 1:
            # Perf attribution: the burst blocks for its device results
            # inside _execute_multi_step, so the elapsed wall here IS
            # the dispatch's device time as this worker sees it (the
            # same approximation vdt:device_wait_seconds makes).
            pending = self._perf_charge(
                scheduler_output, self._multi_step_label(),
                pad_to_bucket(len(scheduler_output.num_scheduled_tokens),
                              self.req_buckets),
                n_steps=scheduler_output.multi_step)
            t_burst = time.perf_counter() if pending is not None else 0.0
            out = self._execute_multi_step(scheduler_output)
            if pending is not None:
                self._perf_commit(pending,
                                  time.perf_counter() - t_burst)
            self._apply_kv_tier_post(tier_pending)
            return {"ready": out}

        t_prep = time.perf_counter()
        (token_ids, batch, logits_indices, sampling_md, sampling_req_ids,
         fwd_shape, R, spec_pack, ext_md, want_topk, vocab_mask,
         plp, chain) = self._prepare_inputs(scheduler_output)
        prep_s = time.perf_counter() - t_prep
        self.prepare_inputs_hist.observe(prep_s)
        attn_label = self._attn_kernel_label(batch)
        self._count_attn_dispatch(attn_label)
        self._count_block_fusion(batch)
        perf = self._perf_charge(scheduler_output, attn_label,
                                 fwd_shape[0])
        drafts_arr, q_ids, q_probs, spec_truncate = spec_pack
        if chain is not None:
            # Async run-ahead rows: substitute the previous dispatch's
            # on-device samples for the host-unknown input tokens. JAX
            # program order serializes this gather after the previous
            # step's _chain_record scatter, so the value is always the
            # real sampled token by the time the forward reads it.
            with self.mesh:
                token_ids = self._chain_apply(
                    token_ids, chain[0], self._ensure_last_sampled(),
                    chain[1])

        kv_meta = scheduler_output.kv_connector_metadata
        if self.kv_connector is not None and kv_meta is not None:
            # External KV lands in the paged cache BEFORE the forward
            # (reference: maybe_setup_kv_connector/start_load_kv).
            self.kv_connector.start_load_kv(kv_meta, self)

        # Rejection-sampling verification handles every spec batch
        # except extended/structured ones (those rows never carry
        # drafts; the plain expanded sampler + host prefix match stays
        # exact for them).
        spec_q = None
        if (self.spec_k and ext_md is None and vocab_mask is None):
            spec_q = (jnp.asarray(drafts_arr), jnp.asarray(q_ids),
                      jnp.asarray(q_probs), spec_truncate)
        dev = self._launch_device_step(token_ids, batch, logits_indices,
                                       sampling_md, fwd_shape, ext_md,
                                       want_topk, vocab_mask, plp=plp,
                                       spec_q=spec_q)
        if self._async_chain and spec_q is None:
            # Record this step's samples for the next dispatch's chain
            # (device-to-device; no host sync). Padding rows scatter out
            # of range and drop.
            rows_pad = np.full((R, ), self.max_num_reqs, np.int32)
            rows_pad[:len(sampling_req_ids)] = [
                self.input_batch.req_id_to_index[r]
                for r in sampling_req_ids]
            with self.mesh:
                self._last_sampled_dev = self._chain_record(
                    self._ensure_last_sampled(), jnp.asarray(rows_pad),
                    dev[0])
        # State snapshots AFTER the forward dispatch: program order on
        # the (donated) cache arrays makes the copy read post-step rows.
        self._apply_state_saves(scheduler_output)
        # Demotion host fetch AFTER the forward dispatch: the copies
        # were started pre-forward, so they complete while the device
        # runs the step.
        self._apply_kv_tier_post(tier_pending)
        return {"so": scheduler_output, "dev": dev, "kv_meta": kv_meta,
                "sampling_req_ids": sampling_req_ids,
                "drafts_arr": drafts_arr, "R": R,
                "specv": spec_q is not None,
                "plp_meta": plp[2] if plp else None,
                "perf": perf, "perf_prep_s": prep_s}

    def wait_model(self, handle: dict) -> ModelRunnerOutput:
        """Blocking half: fetch the sampled tokens, fold them into the
        persistent batch, build the runner output."""
        if "ready" in handle:
            return handle["ready"]
        scheduler_output = handle["so"]
        kv_meta = handle["kv_meta"]
        sampling_req_ids = handle["sampling_req_ids"]
        drafts_arr = handle["drafts_arr"]
        R = handle["R"]

        # Device-vs-host attribution: this fetch is where the host
        # blocks on the device (everything since dispatch ran async), so
        # its duration IS the step's device wait as seen by this worker.
        # The perf-attribution plane rides the same timing pair to
        # charge the dispatch's analytic FLOPs/bytes against it.
        perf = handle.get("perf")
        timing = self._device_telemetry or perf is not None
        t_wait = time.perf_counter() if timing else 0.0
        if handle.get("specv"):
            verify = handle["dev"][0]
            (accept_np, residual_np, bonus_np, lp_cand_np,
             lp_bonus_np) = (np.asarray(jax.device_get(x))
                             for x in verify)
            tokens_np = logprobs_np = topk_np = None
        else:
            tokens_np, logprobs_np, topk_np = self._fetch_sample(
                handle["dev"])
        if timing:
            wait_s = time.perf_counter() - t_wait
            if self._device_telemetry:
                self.device_wait_hist.observe(wait_s)
            if perf is not None:
                self._perf_commit(perf, wait_s,
                                  handle.get("perf_prep_s", 0.0))

        # Embedding requests: the pooled hidden state of the sampled row
        # is the result; no token is emitted (reference: pooling path of
        # the runner, v1/pool/). "last" pooling = the final prompt
        # position's hidden state, exact under chunked prefill too.
        pooled: dict[str, list[float]] = {}
        # .get: under async scheduling a trailing speculative batch can
        # retire after its request finished and left the input batch.
        pool_rows = [
            (i, rid)
            for i, rid in enumerate(handle["sampling_req_ids"])
            if (row := self.input_batch.req_id_to_index.get(rid))
            is not None and self.input_batch.pooling[row] is not None
        ]
        if pool_rows:
            S1 = self.spec_k + 1
            hidden_sel = handle["dev"][3]
            # Final-norm the pooled vectors so they match HF
            # last_hidden_state semantics (the model applies model.norm
            # before returning hidden states). One host transfer for
            # the weight (cached) and one for all pooled rows.
            if not hasattr(self, "_final_ln_np"):
                self._final_ln_np = np.asarray(
                    jax.device_get(self.params["final_ln"]), np.float32)
            w = self._final_ln_np
            eps = self.model.cfg.rms_norm_eps
            idx = np.asarray([i * S1 for i, _ in pool_rows], np.int32)
            vecs = np.asarray(jax.device_get(hidden_sel[idx]), np.float32)
            norms = np.sqrt(np.mean(vecs * vecs, axis=-1,
                                    keepdims=True) + eps)
            normed = vecs / norms * w
            for (_, rid), vec in zip(pool_rows, normed):
                pooled[rid] = [float(x) for x in vec]

        if self.kv_connector is not None and kv_meta is not None:
            # The forward wrote this step's KV; persist producer pages
            # (reference: save_kv_layer/wait_for_save, collapsed to one
            # post-step call — XLA ran the whole forward already).
            self.kv_connector.save_kv(kv_meta, self)

        req_ids, sampled, lps = [], [], []
        spec_out: Optional[list[list[int]]] = [] if self.spec_k else None
        if self.spec_k and handle.get("specv"):
            # Rejection-sampling verification (reference:
            # v1/sample/rejection_sampler.py): the longest accepted
            # draft prefix, then either the exact-residual resample at
            # the first rejection or the bonus sample after a clean
            # sweep. Emitted tokens are distributed exactly as the
            # target regardless of draft quality.
            S = self.spec_k
            n_acc = np.cumprod(accept_np.astype(np.int64),
                               axis=1).sum(axis=1)
            emitted_map: dict[str, list[int]] = {}
            for i, req_id in enumerate(sampling_req_ids):
                n_draft = int((drafts_arr[i] >= 0).sum())
                if n_draft:
                    self.spec_num_drafts += 1
                    self.spec_num_draft_tokens += n_draft
                    self.spec_num_accepted_tokens += int(n_acc[i])
                if req_id in pooled:
                    req_ids.append(req_id)
                    sampled.append([])
                    lps.append([])
                    continue
                na = int(n_acc[i])
                emitted = [int(t) for t in drafts_arr[i, :na]]
                elps = [float(x) for x in lp_cand_np[i, :na, 0]]
                if na == S:
                    emitted.append(int(bonus_np[i]))
                    elps.append(float(lp_bonus_np[i]))
                else:
                    emitted.append(int(residual_np[i, na]))
                    elps.append(float(lp_cand_np[i, na, 1]))
                for tok in emitted:
                    self.input_batch.append_token(req_id, tok)
                emitted_map[req_id] = emitted
                req_ids.append(req_id)
                sampled.append(emitted)
                lps.append([{tok: lp}
                            for tok, lp in zip(emitted, elps)])
            if self._eagle is not None:
                draft_map = self._propose_drafts_eagle(
                    sampling_req_ids, emitted_map, handle)
            else:
                draft_map = self._propose_drafts_all(
                    [r for r in sampling_req_ids if r not in pooled])
            spec_out.extend(draft_map.get(r, []) if r not in pooled
                            else [] for r in sampling_req_ids)
        elif self.spec_k:
            S1 = self.spec_k + 1
            toks = tokens_np.reshape(R, S1)
            lp2 = logprobs_np.reshape(R, S1)
            # Extended/structured batches: accept the longest draft
            # prefix the per-position target samples agree with;
            # position i's sample IS the emitted token, so the output
            # distribution equals non-spec sampling (the deterministic
            # limit of rejection sampling).
            match = toks[:, :self.spec_k] == drafts_arr
            accepted = np.cumprod(match.astype(np.int64), axis=1)
            num_emitted = 1 + accepted.sum(axis=1)
            for i in range(len(sampling_req_ids)):
                n_draft = int((drafts_arr[i] >= 0).sum())
                if n_draft:
                    self.spec_num_drafts += 1
                    self.spec_num_draft_tokens += n_draft
                    self.spec_num_accepted_tokens += int(num_emitted[i] - 1)
            for i, req_id in enumerate(sampling_req_ids):
                if req_id in pooled:
                    req_ids.append(req_id)
                    sampled.append([])
                    lps.append([])
                    continue
                emitted = [int(t) for t in toks[i, :num_emitted[i]]]
                for tok in emitted:
                    self.input_batch.append_token(req_id, tok)
                req_ids.append(req_id)
                sampled.append(emitted)
                lps.append([
                    self._lp_dict(req_id, i * S1 + p, tok,
                                  lp2[i, p], topk_np)
                    for p, tok in enumerate(emitted)
                ])
            # Next-step drafts AFTER every row committed its tokens —
            # one batched call for draft-model proposers.
            draft_map = self._propose_drafts_all(
                [r for r in sampling_req_ids if r not in pooled])
            spec_out.extend(draft_map.get(r, []) if r not in pooled
                            else [] for r in sampling_req_ids)
        else:
            # Record sampled tokens so next step's inputs include them.
            for i, req_id in enumerate(sampling_req_ids):
                if req_id in pooled:
                    req_ids.append(req_id)
                    sampled.append([])
                    lps.append([])
                    continue
                token = int(tokens_np[i])
                self.input_batch.append_token(req_id, token)
                req_ids.append(req_id)
                sampled.append([token])
                lps.append([self._lp_dict(req_id, i, token,
                                          logprobs_np[i], topk_np)])
        # Partial-prefill requests report no samples.
        sampling_set = set(sampling_req_ids)
        for req_id in scheduler_output.num_scheduled_tokens:
            if req_id not in sampling_set:
                req_ids.append(req_id)
                sampled.append([])
                lps.append([])
                if spec_out is not None:
                    spec_out.append([])
        out = ModelRunnerOutput(req_ids=req_ids,
                                sampled_token_ids=sampled,
                                logprobs=lps,
                                spec_token_ids=spec_out,
                                pooled=pooled or None,
                                prompt_logprobs=self._fetch_plp(handle))
        self._poll_kv_connector(scheduler_output, out)
        return out

    @staticmethod
    def _fetch_plp(handle) -> Optional[dict[str, list]]:
        """Assemble this step's prompt-logprob chunk: per scored prompt
        position, {actual_token: lp} plus the request's top-k."""
        meta = handle.get("plp_meta")
        if not meta:
            return None
        tgt, topv, topi = (np.asarray(jax.device_get(x))
                           for x in handle["dev"][4])
        chunks: dict[str, list] = {}
        for i, (req_id, entry, k, target) in enumerate(meta):
            d = {int(topi[i, j]): float(topv[i, j])
                 for j in range(min(k, topi.shape[1]))}
            # The actual prompt token's logprob is always present.
            d[int(target)] = float(tgt[i])
            chunks.setdefault(req_id, []).append((entry, d))
        return chunks

    def _detect_cascade(self, scheduler_output: SchedulerOutput):
        """Batch-wide shared-prefix detection for cascade attention
        (reference: use_cascade_attention, gpu_model_runner.py:1111):
        fires when EVERY scheduled request's first S page-table slots
        hold identical page ids (prefix-cache hits make them literally
        the same pages). Opt-in via VDT_CASCADE_ATTENTION."""
        from vllm_distributed_tpu import envs
        if self._cascade_layout_ok is None:
            # Cascade rides the standard K/V page layout (MLA's latent
            # cache has its own attention path); both backends (XLA scan
            # and the Pallas kernel via its emit_state merge) support it.
            self._cascade_layout_ok = "k" in self.model.kv_cache_specs()
        if (not envs.VDT_CASCADE_ATTENTION or self.tknp_size > 1
                or self.config.parallel_config.pipeline_parallel_size > 1
                or getattr(self.model.cfg, "sliding_window", None)
                or getattr(self.model.cfg, "alibi", False)
                or not self._cascade_layout_ok):
            return None
        S = envs.VDT_CASCADE_SHARED_PAGES
        rows = [self.input_batch.req_id_to_index[r]
                for r in scheduler_output.num_scheduled_tokens]
        if len(rows) < 2:
            return None
        ib = self.input_batch
        # Strictly more than S blocks: the suffix phase needs at least
        # one per-request page past the shared prefix.
        if any(ib.num_blocks[r] <= S for r in rows):
            return None
        first = ib.block_table[rows[0], :S]
        for r in rows[1:]:
            if not np.array_equal(ib.block_table[r, :S], first):
                return None
        self.cascade_steps += 1
        return jnp.asarray(first)

    def _poll_kv_connector(self, scheduler_output: SchedulerOutput,
                           out: ModelRunnerOutput) -> None:
        """Give the connector its per-step main-thread slot: service
        queued async work against the live ``kv_caches`` reference and
        collect (finished_sending, finished_recving) notifications
        (reference: gpu_model_runner.py get_finished_kv_transfers)."""
        if self.kv_connector is None:
            return
        meta = scheduler_output.kv_connector_metadata
        if meta is not None and scheduler_output.total_num_scheduled_tokens == 0:
            # The pre-forward start_load_kv site didn't run this step
            # (nothing scheduled); async pull kickoffs still must.
            self.kv_connector.start_load_kv(meta, self)
        sending, recving, failed = self.kv_connector.get_finished(self)
        if sending or recving or failed:
            out.finished_sending = sending
            out.finished_recving = recving
            out.failed_recving = failed

    def _launch_device_step(self, token_ids, batch, logits_indices,
                            sampling_md, fwd_shape, ext_md, want_topk,
                            vocab_mask=None, plp=None, spec_q=None):
        """Enqueue one step's device work WITHOUT blocking: JAX dispatch
        is asynchronous, so the host returns as soon as the programs are
        queued. The pipeline-parallel engine core exploits this to keep
        several microbatches in flight (its batch queue blocks only on
        the oldest, reference core.py:242 step_with_batch_queue); the
        pipeline-parallel runner overrides only the forward half."""
        with self.mesh:
            cascade = batch.cascade_shared_ids is not None
            fused = bool(getattr(batch, "block_fused", False))
            with self._compile_watch(("fwd", ) + fwd_shape +
                                     (cascade, fused)):
                self.kv_caches, hidden = self._forward_fn(
                    self.params, self.kv_caches, token_ids, batch)
            return self._launch_sample(hidden, logits_indices, sampling_md,
                                       ext_md, want_topk, self.mesh,
                                       vocab_mask, plp=plp, spec_q=spec_q)

    def _launch_sample(self, hidden, logits_indices, sampling_md, ext_md,
                       want_topk, mesh, vocab_mask=None, plp=None,
                       spec_q=None):
        """Row gather + (extended) sampling on ``mesh``, dispatch only;
        shared by the single-program and pipeline-parallel step paths.
        Returns device arrays (tokens, logprobs, (topv, topi) | None);
        with ``spec_q`` the first slot instead carries the rejection
        verifier's output tuple."""
        n_rows = logits_indices.shape[0]  # R or R*(S+1) with spec
        topk_dev = None
        plp_dev = None
        if plp is not None:
            rows, targets, _meta = plp
            sel = self._gather_sample_rows(hidden, rows, mesh=mesh)
            with self._compile_watch(("plp", rows.shape[0])):
                plp_dev = self._plp_fn(self.params, sel, targets)
        hidden_sel = self._gather_sample_rows(hidden, logits_indices,
                                              mesh=mesh)
        if self._numerics_fn is not None:
            # Dispatch-only like the sampler; the tap harvests the
            # PREVIOUS step's reduction, so this never blocks the step.
            # Strided (the reduction re-derives logits — an extra
            # lm-head pass — so tapping every step would be a ~2x
            # logits cost). Fused multi-step bursts bypass
            # _launch_sample and are not tapped (documented sentinel
            # limitation).
            self._numerics_countdown -= 1
            if self._numerics_countdown <= 0:
                from vllm_distributed_tpu.correctness_plane import \
                    NUMERICS_TAP_STRIDE
                self._numerics_countdown = NUMERICS_TAP_STRIDE
                with self._compile_watch(("numerics", n_rows)):
                    self._numerics.dispatch(
                        self._numerics_fn(self.params, hidden_sel))
        if spec_q is not None:
            drafts_d, q_ids_d, q_probs_d, truncate = spec_q
            with self._compile_watch(("specv", n_rows, truncate)):
                verify = self._spec_verify_fn(
                    self.params, hidden_sel, drafts_d, q_ids_d,
                    q_probs_d, sampling_md, truncate=truncate)
            return verify, None, None, hidden_sel, plp_dev
        if ext_md is not None:
            with self._compile_watch(("sampleX", n_rows, want_topk,
                                      vocab_mask is not None)):
                tokens, logprobs, topv, topi = self._sample_ext_fn(
                    self.params, hidden_sel, sampling_md, ext_md,
                    want_topk, vocab_mask)
            if want_topk:
                topk_dev = (topv, topi)
        else:
            with self._compile_watch(("sample", n_rows)):
                tokens, logprobs = self._sample_fn(
                    self.params, hidden_sel, sampling_md)
        # hidden_sel rides along for pooling requests (fetched lazily).
        return tokens, logprobs, topk_dev, hidden_sel, plp_dev

    @staticmethod
    def _fetch_sample(dev):
        """Blocking half: device arrays -> host numpy."""
        tokens, logprobs, topk_dev, _hidden_sel, _plp_dev = dev
        topk_np = None
        if topk_dev is not None:
            topk_np = (np.asarray(jax.device_get(topk_dev[0])),
                       np.asarray(jax.device_get(topk_dev[1])))
        return (np.asarray(jax.device_get(tokens)),
                np.asarray(jax.device_get(logprobs)), topk_np)

    def _lp_dict(self, req_id: str, flat_row: int, token: int,
                 chosen_lp: float, topk_np) -> dict[int, float]:
        """Per-token logprob dict: the sampled token first (the output
        processor's cumulative-logprob reads the first value), then the
        request's `logprobs=k` top entries when requested."""
        d = {int(token): float(chosen_lp)}
        # Row may be gone when a trailing async batch retires after its
        # request finished; the scheduler drops the output anyway.
        row = self.input_batch.req_id_to_index.get(req_id)
        k = 0 if row is None else int(self.input_batch.num_logprobs[row])
        if topk_np is not None and k > 0:
            vals, ids = topk_np
            for v, t in zip(vals[flat_row, :k], ids[flat_row, :k]):
                d.setdefault(int(t), float(v))
        return d

    def _draft_eligible(self, req_id: str) -> Optional[np.ndarray]:
        """The request's committed token history, or None when it must
        not receive drafts. Extended-sampling rows get none: penalties
        change the target distribution position-by-position, so draft
        verification there would be biased."""
        ib = self.input_batch
        row = ib.req_id_to_index[req_id]
        if ib.extended_active(row):
            return None
        n = int(ib.num_tokens[row])
        if n >= self.max_model_len:
            return None
        return ib.token_ids[row, :n]

    def _propose_drafts_all(self,
                            req_ids: list[str]) -> dict[str, list[int]]:
        """Next-step drafts for every eligible request (reference:
        gpu_model_runner.py:1925 propose_draft_token_ids). Ngram runs
        per-request on the host; the draft model proposes the whole
        batch in one jitted call."""
        if self.proposer is None:
            return {}
        eligible: list[tuple[str, np.ndarray]] = []
        for req_id in req_ids:
            hist = self._draft_eligible(req_id)
            if hist is not None:
                eligible.append((req_id, hist))
        if not eligible:
            return {}
        if hasattr(self.proposer, "propose_batch"):
            ib = self.input_batch
            rows = [ib.req_id_to_index[rid] for rid, _ in eligible]
            # Stochastic proposals sample with each request's own
            # temperature; the support metadata feeds next step's
            # rejection verifier (seed stream distinct from the
            # verifier's so draft and accept randomness stay
            # independent for seeded requests).
            temps = ib.temperature[rows].astype(np.float32)
            user_seed = ib.seed[rows]
            seeds = np.where(
                user_seed >= 0,
                user_seed * 999983 + ib.num_tokens[rows],
                self._rng.integers(0, 2**31 - 1, size=len(rows)))
            drafts, meta = self.proposer.propose_batch(
                [h for _, h in eligible], temps, seeds)
            for (rid, _), m in zip(eligible, meta):
                self._draft_meta[rid] = m
            return {rid: d for (rid, _), d in zip(eligible, drafts)}
        return {rid: self.proposer.propose(h) for rid, h in eligible}

    def _propose_drafts_eagle(self, sampling_req_ids: list[str],
                              emitted_map: dict[str, list[int]],
                              handle: dict) -> dict[str, list[int]]:
        """EAGLE proposals for next step: one batched jit consuming the
        target hidden states already on device (handle's hidden_sel
        rows) — the draft KV advanced in-step during the forward, so
        proposing is k tiny decode steps over the eagle layers
        (reference: eagle.py propose per verified step)."""
        ib = self.input_batch
        S1 = self.spec_k + 1
        entries, rows_l = [], []
        for i, req_id in enumerate(sampling_req_ids):
            emitted = emitted_map.get(req_id)
            if not emitted or self._draft_eligible(req_id) is None:
                continue
            row = ib.req_id_to_index[req_id]
            flat = i * S1 + (len(emitted) - 1)
            pos_last = int(ib.num_tokens[row]) - 1
            entries.append((req_id, flat, emitted[-1], pos_last))
            rows_l.append(row)
        if not entries:
            return {}
        rows_a = np.asarray(rows_l)
        temps = ib.temperature[rows_a].astype(np.float32)
        user_seed = ib.seed[rows_a]
        seeds = np.where(
            user_seed >= 0,
            user_seed * 999983 + ib.num_tokens[rows_a],
            self._rng.integers(0, 2**31 - 1, size=len(rows_a)))
        hidden_sel = handle["dev"][3]
        with self.mesh:
            self.kv_caches, drafts, meta = self._eagle.propose_batch(
                self.kv_caches, entries, hidden_sel, temps, seeds,
                ib.block_table[rows_a], ib.num_blocks[rows_a])
        for (rid, *_), m in zip(entries, meta):
            self._draft_meta[rid] = m
        return {rid: d for (rid, *_), d in zip(entries, drafts)}

    # ------------------------------------------------------------------
    def _execute_multi_step(
            self, scheduler_output: SchedulerOutput) -> ModelRunnerOutput:
        """Run scheduler_output.multi_step fused decode steps (pure-decode
        batch; one host roundtrip for the whole burst)."""
        self._count_attn_dispatch(self._multi_step_label())
        self._count_block_fusion(reason="multi_step")
        ib = self.input_batch
        n_steps = scheduler_output.multi_step
        req_ids = list(scheduler_output.num_scheduled_tokens)
        num_active = len(req_ids)
        R = pad_to_bucket(num_active, self.req_buckets)
        rows = np.zeros((R, ), np.int32)
        rows[:num_active] = [ib.req_id_to_index[r] for r in req_ids]

        pos0 = ib.num_computed[rows].astype(np.int32)
        tok0 = ib.token_ids[rows, pos0].astype(np.int32)
        block_tables = ib.block_table[rows]

        user_seed = ib.seed[rows]
        step_in_req = ib.num_tokens[rows].astype(np.int64)
        seeds = np.empty((n_steps, R), np.int64)
        for t in range(n_steps):
            random_part = self._rng.integers(0, 2**31 - 1, size=R)
            seeds[t] = np.where(user_seed >= 0,
                                user_seed * 1000003 + step_in_req + t,
                                random_part)
        sampling_md = SamplingMetadata(
            temperature=jnp.asarray(ib.temperature[rows]),
            top_k=jnp.asarray(ib.top_k[rows]),
            top_p=jnp.asarray(ib.top_p[rows]),
            min_p=jnp.asarray(ib.min_p[rows]),
            seeds=jnp.asarray(seeds[0]),
        )

        deltas = np.zeros((R, ), np.int32)
        if self._mrope_on:
            for i, r in enumerate(rows):
                deltas[i] = self._mrope.get(int(r), (None, 0))[1]
        with self.mesh:
            with self._compile_watch(("multi", n_steps, R)):
                self.kv_caches, toks, lps = self._multi_step_fn(
                    self.params, self.kv_caches, jnp.asarray(tok0),
                    jnp.asarray(pos0), jnp.asarray(block_tables),
                    sampling_md, jnp.asarray(seeds),
                    jnp.asarray([num_active], np.int32),
                    jnp.asarray(deltas))

        toks_np = np.asarray(jax.device_get(toks))  # [n_steps, R]
        lps_np = np.asarray(jax.device_get(lps))

        out_req_ids, sampled, out_lps = [], [], []
        for i, req_id in enumerate(req_ids):
            tokens = [int(t) for t in toks_np[:, i]]
            for tok in tokens:
                self.input_batch.append_token(req_id, tok)
            out_req_ids.append(req_id)
            sampled.append(tokens)
            out_lps.append([{tok: float(lp)}
                            for tok, lp in zip(tokens, lps_np[:, i])])
        out = ModelRunnerOutput(req_ids=out_req_ids,
                                sampled_token_ids=sampled,
                                logprobs=out_lps)
        # Config normalization forces num_scheduler_steps=1 whenever a
        # KV connector is configured, so this is a no-op today — kept so
        # the invariant lives here, not in a distant config rule.
        self._poll_kv_connector(scheduler_output, out)
        return out

    # ------------------------------------------------------------------
    def _model_routes_xla(self) -> bool:
        """True when the model carries a feature the Pallas kernels do
        not: since sliding window / softcap / ALiBi / sinks folded into
        the mega-kernel's per-layer statics + head-feature sidecar, the
        only remaining model-level XLA forcer is an fp8 KV cache (the
        kernels' fp8 dequant is a follow-up)."""
        if getattr(self, "_xla_route_memo", None) is None:
            cfg = self.model.cfg if self.model is not None else None
            if cfg is None:
                return False  # don't memoize before the model exists
            self._xla_route_memo = bool(
                "fp8" in str(
                    self.config.cache_config.cache_dtype).lower())
        return self._xla_route_memo

    def _model_has_attn_features(self) -> bool:
        """Sliding window / softcap / ALiBi / sinks anywhere in the
        model: these reach the Pallas path only through the mega-kernel
        descriptor, so descriptor-less batches still fall back."""
        cfg = self.model.cfg if self.model is not None else None
        if cfg is None:
            return False
        return bool(
            getattr(cfg, "sliding_window", None)
            or getattr(cfg, "window_pattern", None)
            or getattr(cfg, "attn_logit_softcap", 0)
            or getattr(cfg, "alibi", False)
            or getattr(cfg, "attn_sinks", False))

    def _attn_kernel_label(self, batch) -> str:
        """Which attention kernel family this step's batch dispatches to
        (mirrors the ops/attention.py routing, including the feature
        gates that force the XLA path): the vdt:attn_kernel_calls_total
        {kernel} observability for the dispatch layer."""
        from vllm_distributed_tpu.ops.attention import \
            resolve_attention_backend
        if (resolve_attention_backend() != "pallas"
                or self._model_routes_xla()):
            return "naive"
        if getattr(batch, "block_fused", False):
            return "fused_block"
        if getattr(batch, "cascade_shared_ids", None) is not None:
            return ("naive" if self._model_has_attn_features()
                    else "cascade")
        if getattr(batch, "attn_desc", None) is not None:
            return "unified"
        if self._model_has_attn_features():
            return "naive"  # descriptor-less legacy path keeps XLA
        return "decode" if batch.max_q == 1 else "general"

    def _count_attn_dispatch(self, label: str) -> None:
        self.attn_kernel_calls[label] = (
            self.attn_kernel_calls.get(label, 0) + 1)

    # ------------------------------------------------------------------
    # Performance-attribution plane (metrics/costmodel.py)
    # ------------------------------------------------------------------
    def _cost_model(self):
        """The loader-attached analytic cost model; None = plane off
        (VDT_PERF_ATTRIB=0) and every per-step perf hook is this one
        memoized check."""
        memo = self._perf_memo
        if memo is None:
            if self.model is None:
                return None
            self._perf_cm = getattr(self.model.cfg, "cost_model", None)
            self._perf_memo = memo = self._perf_cm is not None
        return self._perf_cm if memo else None

    def _multi_step_label(self) -> str:
        """Kernel family the fused multi-step burst dispatches: the
        in-jit batches carry no partition descriptor, so they ride the
        legacy SB decode kernel on the Pallas backend (and
        window/softcap/ALiBi/sink models the XLA path)."""
        from vllm_distributed_tpu.ops.attention import \
            resolve_attention_backend
        return ("decode" if (resolve_attention_backend() == "pallas"
                             and not self._model_routes_xla()
                             and not self._model_has_attn_features())
                else "naive")

    def _perf_charge(self, scheduler_output, label: str, bucket: int,
                     n_steps: int = 1):
        """Analytic price of one dispatch, from the scheduler grant +
        the input batch's pre-step context lengths: (attribution key,
        phase, WaveCost) — or None with the plane off / nothing
        scheduled. FLOPs count real (unpadded) tokens; attention pairs
        clamp to a uniform sliding window; a multi-step burst charges
        n_steps in-graph decode steps with the KV span growing per
        step."""
        cm = self._cost_model()
        if cm is None:
            return None
        ib = self.input_batch
        prefill_toks = 0
        decode_toks = 0
        kv_terms = 0.0
        for rid, n in scheduler_output.num_scheduled_tokens.items():
            row = ib.req_id_to_index.get(rid)
            if row is not None:
                ctx = float(ib.num_computed[row])
                # Phase by the PROMPT boundary, not the grant width: a
                # spec-decode verify wave grants 1+k tokens but is
                # decode, and a chunked prefill's final 1-token chunk
                # is still prefill — the grant-width heuristic would
                # mislabel both and corrupt the roofline buckets.
                generating = ctx >= float(ib.prompt_len[row])
            else:
                ctx, generating = 0.0, False
            if n_steps > 1:
                n = n_steps
            kv_terms += cm.span_sum(ctx, n)
            if generating:
                decode_toks += n
            else:
                prefill_toks += n
        total = prefill_toks + decode_toks
        if total == 0:
            return None
        rows = len(scheduler_output.num_scheduled_tokens) * n_steps
        cost = cm.wave_cost(total, kv_terms, rows, passes=n_steps)
        phase = ("decode" if prefill_toks == 0
                 else "prefill" if decode_toks == 0 else "mixed")
        return (f"{label}/{phase}/b{bucket}", phase, cost)

    def _perf_commit(self, pending, device_s: float,
                     host_s: float = 0.0) -> None:
        """Reconcile one priced dispatch against its measured device
        wait. Single engine-core thread; stats polls snapshot with
        GIL-atomic dict copies."""
        key, phase, cost = pending
        e = self._perf_attrib.get(key)
        if e is None:
            e = self._perf_attrib[key] = {
                "device_seconds": 0.0, "flops": 0.0, "bytes": 0.0,
                "dispatches": 0}
        e["device_seconds"] += device_s
        e["flops"] += cost.flops
        e["bytes"] += cost.total_bytes
        e["dispatches"] += 1
        p = self._perf_phases.get(phase)
        if p is None:
            p = self._perf_phases[phase] = {
                "device_seconds": 0.0, "host_seconds": 0.0,
                "flops": 0.0, "bytes": 0.0}
        p["device_seconds"] += device_s
        p["host_seconds"] += host_s
        p["flops"] += cost.flops
        p["bytes"] += cost.total_bytes
        self._perf_bytes["weights"] += cost.weight_bytes
        self._perf_bytes["kv_read"] += cost.kv_read_bytes
        self._perf_bytes["kv_write"] += cost.kv_write_bytes
        self._perf_bytes["activations"] += cost.act_bytes
        self._perf_flops += cost.flops
        self._perf_device_s += device_s
        self._perf_dispatches += 1

    # ------------------------------------------------------------------
    @contextmanager
    def _compile_watch(self, key: tuple):
        """Track/log compilations; after precompile() has run, any new
        shape is a recompile-guard violation (reference:
        tpu_model_runner.py:318 _update_num_xla_graphs /
        _verify_num_xla_graphs)."""
        new = key not in self._compiled_shapes
        if new:
            if self._precompiled:
                from vllm_distributed_tpu import envs
                # Counted BEFORE the assert gate so vdt:recompiles_total
                # reflects the violation either way (the raise is a test
                # harness mode; production watches the counter).
                self.num_recompiles += 1
                msg = (f"compiling shape {key} AFTER precompile warm-up - "
                       "the shape lattice is leaking")
                if envs.VDT_ASSERT_NO_RECOMPILE:
                    raise RuntimeError(msg)
                logger.warning(msg)
            else:
                logger.info("compiling shape %s", key)
            start = time.perf_counter()
        yield
        if new:
            self._compiled_shapes.add(key)
            logger.info("compiled %s in %.1fs", key,
                        time.perf_counter() - start)

    def _gather_sample_rows(self, hidden, logits_indices, mesh=None):
        """[R]-row gather between the forward and sample jits, committed to
        a REPLICATED sharding: jax.jit keys its cache on input sharding, so
        the sampler must see the same sharding at warm-up and serving or
        every ('sample', R) shape would recompile on a >1-device mesh."""
        from jax.sharding import NamedSharding, PartitionSpec
        sel = hidden[logits_indices]
        return jax.device_put(sel, NamedSharding(mesh or self.mesh,
                                                 PartitionSpec()))

    def _dummy_step_inputs(self, T: int, max_q: int, G: int):
        """Inert inputs for one forward at shape (T, max_q, G): padding
        slots (-1) and zero run/seq counts make every write a no-op (an
        all-noop partition descriptor likewise runs zero programs)."""
        K = self.tknp_size
        attn_desc = decode_list = None
        bq = sb = 0
        P_desc = 0
        if self._use_unified():
            from vllm_distributed_tpu.ops.pallas_attention import (
                Q_TILE_PAD, num_partition_programs)
            bq, sb = self._tile_params()
            P_desc = num_partition_programs(
                T - Q_TILE_PAD, self.max_num_reqs, bq=bq, sb=sb,
                num_kv_writes=G)
            attn_desc = jnp.zeros((P_desc, 3), jnp.int32)
            decode_list = jnp.zeros((self.max_num_reqs, ), jnp.int32)
        tknp = None
        if K > 1:
            tknp = TknpAttentionBatch(
                slot_mapping=jnp.full((K, T), -1, jnp.int32),
                block_tables=jnp.zeros(
                    (K, self.max_num_reqs, self.max_pages_per_req),
                    jnp.int32),
                seq_info=jnp.zeros((K, self.max_num_reqs, 4), jnp.int32),
                num_seqs=jnp.zeros((K, 1), jnp.int32),
                kv_runs=jnp.zeros((K, G, 4), jnp.int32),
                num_kv_runs=jnp.zeros((K, 1), jnp.int32),
                desc=(jnp.zeros((K, P_desc, 3), jnp.int32)
                      if attn_desc is not None else None),
                decode_list=(jnp.zeros((K, self.max_num_reqs),
                                       jnp.int32)
                             if attn_desc is not None else None),
            )
        batch = AttentionBatch(
            req_idx=jnp.zeros((T, ), jnp.int32),
            positions=jnp.zeros((T, ), jnp.int32),
            slot_mapping=jnp.full((T, ), -1, jnp.int32),
            block_tables=jnp.zeros(
                (self.max_num_reqs, self.max_pages_per_req), jnp.int32),
            seq_lens=jnp.zeros((self.max_num_reqs, ), jnp.int32),
            seq_info=jnp.zeros((self.max_num_reqs, 4), jnp.int32),
            num_seqs=jnp.zeros((1, ), jnp.int32),
            kv_runs=jnp.zeros((G, 4), jnp.int32),
            num_kv_runs=jnp.zeros((1, ), jnp.int32),
            tknp=tknp,
            lora=self._dummy_lora_batch(T),
            mrope_positions=(jnp.zeros((T, 3), jnp.int32)
                             if self._mrope_on else None),
            attn_desc=attn_desc,
            decode_list=decode_list,
            max_q=max_q,
            attn_bq=bq,
            attn_sb=sb,
        )
        return jnp.zeros((T, ), jnp.int32), batch

    def _dummy_lora_batch(self, T: int):
        """Inert LoRA routing for warm-up (all tokens in slot 0): the
        compiled graph's pytree must match real steps' when LoRA is on."""
        if self.lora_manager is None:
            return None
        from vllm_distributed_tpu.models.common import LoraBatch
        S = self.config.lora_config.max_loras + 1
        gs = np.zeros((S, ), np.int32)
        gs[0] = T
        return LoraBatch(
            order=jnp.arange(T, dtype=jnp.int32),
            inv=jnp.arange(T, dtype=jnp.int32),
            group_sizes=jnp.asarray(gs),
            scaling=jnp.zeros((T, ), jnp.float32),
        )

    def forward_shapes(self) -> set[tuple[int, int, int]]:
        """Every (T, max_q, G) the runner can present. Unified
        (mega-kernel) models: composition is descriptor-carried, so
        decode shapes coincide with the small token buckets and the set
        collapses to one shape per token bucket — strictly fewer warmed
        graphs than the legacy decode+prefill split at the same bucket
        config. Legacy (MLA) models keep both variants."""
        shapes = set()
        for r in self.req_buckets:
            shapes.add(self._batch_shape(r, 1))
        for t in self.token_buckets:
            shapes.add(self._batch_shape(t, 2))
        return shapes

    def precompile(self) -> None:
        """Warm every step graph before serving (reference:
        tpu_model_runner.py:1248-1443 precompilation suite): all forward
        shapes, all sampler shapes, and the fused multi-step graph. After
        this, a compile during serving is a bug (_compile_watch)."""
        assert self.kv_caches is not None, "initialize_kv_cache first"
        start = time.perf_counter()
        n = 0
        with self.mesh:
            # Pure-decode waves can present any token bucket up to the
            # request ceiling; those buckets additionally warm the
            # fused-block variant when fusion is on.
            fusion_t_max = (pad_to_bucket(self.max_num_reqs,
                                          self.token_buckets)
                            if self._block_fusion_active() else -1)
            for T, max_q, G in sorted(self.forward_shapes()):
                token_ids, batch = self._dummy_step_inputs(T, max_q, G)
                with self._compile_watch(("fwd", T, max_q, G, False,
                                          False)):
                    self.kv_caches, hidden = self._forward_fn(
                        self.params, self.kv_caches, token_ids, batch)
                jax.block_until_ready(hidden)
                n += 1
                import dataclasses as _dc

                from vllm_distributed_tpu import envs as _envs
                from vllm_distributed_tpu.ops.pallas_attention import \
                    Q_TILE_PAD
                if (max_q == 1 and batch.attn_desc is not None
                        and 0 <= T - Q_TILE_PAD <= fusion_t_max):
                    fbatch = _dc.replace(batch, block_fused=True)
                    with self._compile_watch(("fwd", T, max_q, G, False,
                                              True)):
                        self.kv_caches, hidden = self._forward_fn(
                            self.params, self.kv_caches, token_ids,
                            fbatch)
                    jax.block_until_ready(hidden)
                    n += 1
                if _envs.VDT_CASCADE_ATTENTION:
                    S = _envs.VDT_CASCADE_SHARED_PAGES
                    cbatch = _dc.replace(
                        batch,
                        cascade_shared_ids=jnp.zeros((S, ), jnp.int32))
                    with self._compile_watch(("fwd", T, max_q, G, True,
                                              False)):
                        self.kv_caches, hidden = self._forward_fn(
                            self.params, self.kv_caches, token_ids,
                            cbatch)
                    jax.block_until_ready(hidden)
                    n += 1
            n += self._precompile_samplers(self.mesh)
            n += self._precompile_plp(self.mesh)
            n += self._precompile_state_cache()
            n_steps = self.config.scheduler_config.num_scheduler_steps
            # The scheduler forces multi-step to 1 for stateful models
            # with the state cache on (fused bursts would cross
            # snapshot boundaries mid-burst): don't warm burst graphs
            # that can never dispatch.
            if self._state_cache_active():
                n_steps = 1
            if n_steps > 1:
                for R in self.req_buckets:
                    self._precompile_multi_step(n_steps, R)
                    n += 1
            if self.proposer is not None and hasattr(
                    self.proposer, "precompile"):
                n += self.proposer.precompile()
            if self._eagle is not None:
                self.kv_caches, ne = self._eagle.precompile(
                    self.kv_caches, self.model.cfg.hidden_size,
                    self.model.cfg.dtype, self.max_pages_per_req)
                n += ne
        self._precompiled = True
        self.precompile_graphs = n
        logger.info("precompiled %d graphs in %.1fs", n,
                    time.perf_counter() - start)

    def _precompile_state_cache(self) -> int:
        """Warm the SSM snapshot/restore copies (one graph per state
        array per direction) so a serving-time checkpoint is never a
        recompile-guard violation. Copies between slot 0 and row 0 of
        the zero-initialized arrays are inert."""
        if self._state_pool is None:
            return 0
        n = 0
        shapes = self.model.state_shapes()
        for name in self._state_keys:
            with self._compile_watch(("ssm_save", name)):
                self._state_pool[name] = self._state_row_to_pool(
                    self._state_pool[name], self.kv_caches[name], 0, 0)
            with self._compile_watch(("ssm_restore", name)):
                self.kv_caches[name] = self._state_pool_to_row(
                    self.kv_caches[name], self._state_pool[name], 0, 0)
            shape, dtype = shapes[name]
            value = jnp.asarray(
                np.zeros((shape[0], ) + shape[2:], jnp.dtype(dtype)))
            with self._compile_watch(("ssm_put", name)):
                self.kv_caches[name] = self._state_put_row(
                    self.kv_caches[name], value, 0)
            jax.block_until_ready(self.kv_caches[name])
            n += 3
        return n

    def _precompile_plp(self, mesh) -> int:
        """Warm the prompt-logprob graphs — one per P bucket (the row
        gather runs outside the jit, so the lattice is additive with
        the forward shapes). Disagg decode-pool replicas skip the
        family: prompt_logprobs requests are exempt from handoff and
        serve monolithically on the prefill pool (a pool_down degraded
        placement compiles lazily with a recompile warning)."""
        if self.pool_role == "decode":
            return 0
        n = 0
        for P_ in self.token_buckets:
            sel = self._gather_sample_rows(
                jnp.zeros((P_, self.model.cfg.hidden_size),
                          self.model.cfg.dtype),
                jnp.arange(P_, dtype=jnp.int32), mesh=mesh)
            with self._compile_watch(("plp", P_)):
                tgt, _, _ = self._plp_fn(
                    self.params, sel, jnp.zeros((P_, ), jnp.int32))
            jax.block_until_ready(tgt)
            n += 1
        return n

    def _precompile_samplers(self, mesh) -> int:
        """Warm the plain + extended sampler graphs for every row bucket
        on ``mesh`` (the last stage's sub-mesh under PP). Returns the
        number of graphs compiled."""
        n = 0
        S1 = self.spec_k + 1
        for R in self.req_buckets:
            rows = R * S1  # sampler sees S+1 rows/request with spec
            md = SamplingMetadata(
                temperature=jnp.zeros((rows, ), jnp.float32),
                top_k=jnp.zeros((rows, ), jnp.int32),
                top_p=jnp.ones((rows, ), jnp.float32),
                min_p=jnp.zeros((rows, ), jnp.float32),
                seeds=jnp.zeros((rows, ), jnp.int64),
            )
            hidden_sel = self._gather_sample_rows(
                jnp.zeros((rows, self.model.cfg.hidden_size),
                          self.model.cfg.dtype),
                jnp.arange(rows, dtype=jnp.int32), mesh=mesh)
            with self._compile_watch(("sample", rows)):
                tokens, _ = self._sample_fn(self.params, hidden_sel, md)
            jax.block_until_ready(tokens)
            n += 1
            if self._numerics_fn is not None:
                # Warm the sentinel reduction on the sampler's own row
                # lattice (discarded — warm-up must not pollute the
                # tap's histograms/window).
                with self._compile_watch(("numerics", rows)):
                    nm = self._numerics_fn(self.params, hidden_sel)
                jax.block_until_ready(nm)
                n += 1
            if self.spec_k:
                from vllm_distributed_tpu.spec_decode.draft_model import \
                    SUPPORT_K
                with self._compile_watch(("specv", rows)):
                    verify = self._spec_verify_fn(
                        self.params, hidden_sel,
                        jnp.full((R, self.spec_k), -1, jnp.int32),
                        jnp.zeros((R, self.spec_k, SUPPORT_K),
                                  jnp.int32),
                        jnp.zeros((R, self.spec_k, SUPPORT_K),
                                  jnp.float32), md)
                jax.block_until_ready(verify[0])
                n += 1
            ext = ExtendedSamplingMetadata(
                hist_tokens=jnp.zeros((rows, self.max_model_len),
                                      jnp.int32),
                prompt_len=jnp.zeros((rows, ), jnp.int32),
                total_len=jnp.zeros((rows, ), jnp.int32),
                presence_penalty=jnp.zeros((rows, ), jnp.float32),
                frequency_penalty=jnp.zeros((rows, ), jnp.float32),
                repetition_penalty=jnp.ones((rows, ), jnp.float32),
                bias_ids=jnp.zeros((rows, self._BIAS_BUF), jnp.int32),
                bias_vals=jnp.zeros((rows, self._BIAS_BUF), jnp.float32),
                base_fill=jnp.zeros((rows, ), jnp.float32),
            )
            mask = jnp.ones((rows, self.model.cfg.vocab_size), jnp.bool_)
            for want_topk in (False, True):
                for vocab_mask in (None, mask):
                    with self._compile_watch(("sampleX", rows, want_topk,
                                              vocab_mask is not None)):
                        tokens, _, _, _ = self._sample_ext_fn(
                            self.params, hidden_sel, md, ext, want_topk,
                            vocab_mask)
                    jax.block_until_ready(tokens)
                    n += 1
        return n

    def _precompile_multi_step(self, n_steps: int, R: int) -> None:
        md = SamplingMetadata(
            temperature=jnp.zeros((R, ), jnp.float32),
            top_k=jnp.zeros((R, ), jnp.int32),
            top_p=jnp.ones((R, ), jnp.float32),
            min_p=jnp.zeros((R, ), jnp.float32),
            seeds=jnp.zeros((R, ), jnp.int64),
        )
        with self._compile_watch(("multi", n_steps, R)):
            self.kv_caches, toks, _ = self._multi_step_fn(
                self.params, self.kv_caches, jnp.zeros((R, ), jnp.int32),
                jnp.zeros((R, ), jnp.int32),
                jnp.zeros((R, self.max_pages_per_req), jnp.int32), md,
                jnp.zeros((n_steps, R), jnp.int64),
                jnp.zeros((1, ), jnp.int32),
                jnp.zeros((R, ), jnp.int32))
        jax.block_until_ready(toks)

    def get_stats(self) -> dict[str, float]:
        """Runner-side stats (spec-decode acceptance; reference:
        v1/metrics/stats.py SpecDecodingStats) plus the input-prep share
        of the step-phase profiler and the device/compilation telemetry
        (recompiles, device wait, HBM high-water mark)."""
        stats: dict = {
            "prepare_inputs_seconds": self.prepare_inputs_hist.to_dict(),
            "num_recompiles": self.num_recompiles,
            # Kernel-dispatch + lattice observability (vdt:attn_kernel_
            # calls_total{kernel} / vdt:precompile_graphs_total).
            "attn_kernel_calls": dict(self.attn_kernel_calls),
            "precompile_graphs": self.precompile_graphs,
        }
        if self._numerics is not None:
            # Correctness-sentinel numerics (per replica; the DP merge
            # keys this by replica index, never numeric-summed).
            stats["numerics"] = self._numerics.stats()
        if self.model is not None and getattr(self.model.cfg,
                                              "block_fusion", False):
            # Fused decode-block dispatch (vdt:block_fusion_calls_total
            # / vdt:block_fusion_fallbacks_total{reason}): rendered only
            # while the loader enabled fusion, so the families are a
            # positive signal that the flag is live.
            stats["block_fusion_calls"] = self.block_fusion_calls
            stats["block_fusion_fallbacks"] = dict(
                self.block_fusion_fallbacks)
        if self.model is not None and getattr(self.model.cfg, "mla",
                                              False):
            # MLA latent-pool geometry (vdt:tpla_latent_shards /
            # vdt:mla_latent_page_bytes{worker}): shards > 1 proves the
            # TPLA layout is live; page bytes is the PER-RANK cost one
            # latent page charges against this worker's HBM — together
            # with vdt:kv_blocks they quantify the ~TP x capacity win.
            stats["tpla_latent_shards"] = int(
                getattr(self.model.cfg, "tpla_shards", 1) or 1)
            stats["mla_latent_page_bytes"] = int(
                self.model.kv_cache_page_bytes(self.page_size))
        cm = self._cost_model()
        if cm is not None and self._perf_dispatches:
            # Performance-attribution plane: analytic totals + the
            # per-(kernel, phase, bucket) attribution table and phase
            # accumulators (roofline classification happens at render
            # time from the DP-merged accumulators, never by merging
            # classifications). mfu/mbu move into workers[label] at the
            # worker layer — the DP numeric-sum must not add ratios.
            dev_s = self._perf_device_s
            stats["model_flops"] = self._perf_flops
            stats["hbm_bytes"] = dict(self._perf_bytes)
            stats["perf_attrib"] = {k: dict(v)
                                    for k, v in self._perf_attrib.items()}
            stats["perf_phases"] = {k: dict(v)
                                    for k, v in self._perf_phases.items()}
            stats["perf_peaks"] = {"flops": cm.peak_flops,
                                   "hbm": cm.peak_hbm}
            stats["mfu"] = cm.mfu(self._perf_flops, dev_s)
            stats["mbu"] = cm.mbu(sum(self._perf_bytes.values()), dev_s)
        if self._device_telemetry:
            from vllm_distributed_tpu.metrics import telemetry
            stats["device_wait_seconds"] = self.device_wait_hist.to_dict()
            stats.update(telemetry.device_memory_stats(self.mesh))
        if self.spec_k:
            stats.update({
                "spec_num_drafts": self.spec_num_drafts,
                "spec_num_draft_tokens": self.spec_num_draft_tokens,
                "spec_num_accepted_tokens": self.spec_num_accepted_tokens,
                "spec_acceptance_rate":
                (self.spec_num_accepted_tokens /
                 max(self.spec_num_draft_tokens, 1)),
            })
        return stats

    def profile_memory_bytes(self) -> int:
        """Bytes of HBM available for KV pages, from a MEASURED peak: run
        the largest-shape forward against a small scratch cache and read
        the device's peak allocation (weights + real activation/workspace
        footprint), mirroring the reference's profile run
        (gpu_worker.py:200, tpu_worker.py:163). Returns 0 when the
        platform exposes no memory stats (CPU tests)."""
        try:
            dev = next(iter(self.mesh.devices.flat))
            stats = dev.memory_stats() or {}
            limit = int(stats.get("bytes_limit", 0))
        except Exception:  # pragma: no cover - platform specific
            return 0
        if not limit:
            return 0
        util = self.config.cache_config.gpu_memory_utilization
        try:
            peak = self._profile_peak_bytes(dev)
        except Exception as e:  # pragma: no cover - defensive
            logger.warning("profile run failed (%s); using current usage",
                           e)
            peak = int(stats.get("peak_bytes_in_use",
                                 stats.get("bytes_in_use", 0)))
        return max(int(limit * util) - peak, 0)

    def _profile_peak_bytes(self, dev) -> int:
        """Execute the largest forward shape with a 16-page scratch cache
        and return the device peak bytes."""
        assert self.model is not None
        scratch = self._make_sharded_caches(16)
        if self._forward_fn is None:
            self._build_step_fn()
        T, max_q, G = max(self.forward_shapes())
        token_ids, batch = self._dummy_step_inputs(T, max_q, G)
        with self.mesh:
            scratch, hidden = self._forward_fn(self.params, scratch,
                                               token_ids, batch)
            jax.block_until_ready(hidden)
        del scratch, hidden
        stats = dev.memory_stats() or {}
        peak = int(stats.get("peak_bytes_in_use",
                             stats.get("bytes_in_use", 0)))
        logger.info("profiled peak HBM (weights + workspace): %.2f GiB",
                    peak / 2**30)
        return peak
