"""TPU model runner: flat ragged batches, bucketed static shapes, one
jitted step.

Reference: vllm/v1/worker/gpu_model_runner.py:101 (``GPUModelRunner``:
_prepare_inputs :892, execute_model :1614, CUDA-graph capture :2683) and
the TPU variant tpu_model_runner.py:98 (bucketed precompilation
:1248-1443). TPU-native re-design:

* The whole forward + logits + sampling step is ONE jitted function; KV
  caches are donated so XLA updates them in place.
* Dynamic quantities (num tokens T, num sampling reqs R) are padded to a
  bucket lattice; each (T, R) pair compiles once. There is no CUDA-graph
  equivalent to manage — jit caching plays that role.
* Sharding: params/caches carry NamedShardings over the engine mesh; the
  same runner code is TP=1 and TP=N (GSPMD inserts the collectives).
"""

import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.output import (ModelRunnerOutput,
                                                    SchedulerOutput)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.models.common import AttentionBatch
from vllm_distributed_tpu.sample.metadata import SamplingMetadata
from vllm_distributed_tpu.sample.sampler import sample_tokens
from vllm_distributed_tpu.utils import cdiv, make_buckets, pad_to_bucket
from vllm_distributed_tpu.worker.input_batch import InputBatch

logger = init_logger(__name__)


class TPUModelRunner:

    def __init__(self, config: EngineConfig, mesh,
                 model=None, params=None) -> None:
        self.config = config
        self.mesh = mesh
        sched_cfg = config.scheduler_config
        self.page_size = config.cache_config.block_size
        self.max_num_reqs = sched_cfg.max_num_seqs
        self.max_model_len = sched_cfg.max_model_len
        self.max_pages_per_req = cdiv(self.max_model_len, self.page_size)

        self.model = model
        self.params = params
        self.kv_caches: Optional[dict] = None

        self.input_batch = InputBatch(
            max_num_reqs=self.max_num_reqs,
            max_model_len=self.max_model_len,
            max_pages_per_req=self.max_pages_per_req,
            page_size=self.page_size,
        )

        self.token_buckets = make_buckets(
            16, sched_cfg.max_num_batched_tokens)
        self.req_buckets = make_buckets(8, self.max_num_reqs)
        # Per-sequence query-length buckets for the attention kernel:
        # 1 (pure decode) then powers of 4 up to the token budget.
        self.max_q_buckets = [1] + [
            b for b in make_buckets(8, sched_cfg.max_num_batched_tokens)
            if b > 1
        ]
        # KV-write runs: worst case one partial + the full pages per req.
        max_runs = (cdiv(sched_cfg.max_num_batched_tokens, self.page_size)
                    + self.max_num_reqs)
        self.kv_run_buckets = make_buckets(8, max_runs)

        self._step_fn = None
        self._rng = np.random.default_rng(config.model_config.seed)
        self._compiled_shapes: set[tuple[int, int]] = set()

    # ------------------------------------------------------------------
    def load_model(self) -> None:
        """Build the model and load weights per LoadConfig."""
        from vllm_distributed_tpu.models.loader import get_model
        self.model, self.params = get_model(self.config, self.mesh)

    def initialize_kv_cache(self, num_pages: int) -> None:
        from jax.sharding import NamedSharding
        assert self.model is not None
        self.num_pages = num_pages
        with self.mesh:
            caches = self.model.make_kv_caches(num_pages, self.page_size)
            specs = self.model.kv_cache_specs()
            self.kv_caches = jax.tree.map(
                lambda x, s: jax.device_put(
                    x, NamedSharding(self.mesh, s)), caches, specs,
                is_leaf=lambda x: isinstance(x, jax.Array))
        self._build_step_fn()

    def kv_cache_bytes_per_page(self) -> int:
        from vllm_distributed_tpu.ops.attention import storage_head_dim
        c = self.model.cfg
        itemsize = jnp.dtype(c.dtype).itemsize
        return (2 * c.num_layers * self.page_size * c.num_kv_heads *
                storage_head_dim(c.head_dim) * itemsize)

    def _build_step_fn(self) -> None:
        model = self.model

        def step(params, kv_caches, token_ids, batch: AttentionBatch,
                 logits_indices, sampling_md: SamplingMetadata):
            hidden, kv_caches = model.forward(params, kv_caches, token_ids,
                                              batch)
            sel = hidden[logits_indices]
            logits = model.compute_logits(params, sel)
            tokens, logprobs = sample_tokens(logits, sampling_md)
            return kv_caches, tokens, logprobs

        # Donate the caches: XLA aliases them in place of a copy.
        self._step_fn = jax.jit(step, donate_argnums=(1, ))
        self._build_multi_step_fn()

    def _build_multi_step_fn(self) -> None:
        """N fused decode steps in one jitted lax.scan: the host pays one
        dispatch+sync per burst instead of per token (TPU answer to the
        reference's multi-step scheduling + advance_step.cu in-place input
        update; sampled tokens feed the next step on-device)."""
        import dataclasses

        model = self.model
        page_size = self.page_size

        def multi_step(params, kv_caches, tok0, pos0, block_tables,
                       sampling_md: SamplingMetadata, seeds, num_active):
            R = tok0.shape[0]
            rows = jnp.arange(R, dtype=jnp.int32)
            ones = jnp.ones((R, ), jnp.int32)

            def one(carry, seeds_t):
                kv, tok, pos = carry
                active = rows < num_active[0]
                page = block_tables[rows, pos // page_size]
                off = pos % page_size
                slot = jnp.where(active, page * page_size + off, -1)
                seq_info = jnp.stack([rows, ones, pos + 1, rows], axis=1)
                # One single-token page-write run per active request.
                kv_runs = jnp.stack(
                    [page, off, rows - off + page_size,
                     jnp.where(active, 1, 0)], axis=1)
                batch = AttentionBatch(
                    req_idx=rows, positions=pos, slot_mapping=slot,
                    block_tables=block_tables, seq_lens=pos + 1,
                    seq_info=seq_info, num_seqs=num_active,
                    kv_runs=kv_runs, num_kv_runs=num_active, max_q=1)
                hidden, kv = model.forward(params, kv, tok, batch)
                logits = model.compute_logits(params, hidden)
                md_t = dataclasses.replace(sampling_md, seeds=seeds_t)
                tok_next, logprobs = sample_tokens(logits, md_t)
                return (kv, tok_next, pos + 1), (tok_next, logprobs)

            (kv, _, _), (toks, lps) = jax.lax.scan(
                one, (kv_caches, tok0, pos0), seeds)
            return kv, toks, lps

        self._multi_step_fn = jax.jit(multi_step, donate_argnums=(1, ))

    # ------------------------------------------------------------------
    def _update_states(self, scheduler_output: SchedulerOutput) -> None:
        for req_id in scheduler_output.finished_req_ids:
            self.input_batch.remove_request(req_id)
        for new_req in scheduler_output.scheduled_new_reqs:
            self.input_batch.add_request(new_req)
        self.input_batch.update_cached(scheduler_output.scheduled_cached_reqs)

    def _prepare_inputs(self, scheduler_output: SchedulerOutput):
        """Flatten the scheduled requests into padded per-token arrays."""
        ib = self.input_batch
        num_sched = scheduler_output.num_scheduled_tokens
        total_tokens = scheduler_output.total_num_scheduled_tokens
        # Static q-length bucket for the Pallas kernel (1 = pure decode);
        # token arrays carry one extra q tile of padding so a sequence's
        # final tile may spill past its q_len (see ops/pallas_attention.py).
        max_q = pad_to_bucket(max(num_sched.values()), self.max_q_buckets)
        q_tile = min(max_q, 128)
        T = pad_to_bucket(total_tokens, self.token_buckets) + q_tile

        token_ids = np.zeros((T, ), np.int32)
        positions = np.zeros((T, ), np.int32)
        req_idx = np.zeros((T, ), np.int32)
        slot_mapping = np.full((T, ), -1, np.int32)
        seq_info = np.zeros((self.max_num_reqs, 4), np.int32)
        kv_runs: list[tuple[int, int, int, int]] = []
        ps = self.page_size

        sampling_rows: list[int] = []
        sampling_req_ids: list[str] = []
        logits_idx: list[int] = []

        t = 0
        num_runs = 0
        for req_id, n in num_sched.items():
            row = ib.req_id_to_index[req_id]
            start = ib.num_computed[row]
            end = start + n
            token_ids[t:t + n] = ib.token_ids[row, start:end]
            positions[t:t + n] = np.arange(start, end, dtype=np.int32)
            req_idx[t:t + n] = row
            pos = np.arange(start, end)
            slot_mapping[t:t + n] = (
                ib.block_table[row, pos // ps] * ps + pos % ps)
            seq_info[num_runs] = (t, n, end, row)
            num_runs += 1
            # Page-write runs for the Pallas KV-write kernel: maximal
            # consecutive-slot spans within one page.
            consumed = 0
            while consumed < n:
                p = start + consumed
                off = p % ps
                run_len = min(ps - off, n - consumed)
                src = t + consumed
                kv_runs.append((int(ib.block_table[row, p // ps]), off,
                                src - off + ps, run_len))
                consumed += run_len
            if end >= ib.num_tokens[row]:
                # This step finishes all known tokens: sample.
                sampling_rows.append(row)
                sampling_req_ids.append(req_id)
                logits_idx.append(t + n - 1)
            t += n

        G = pad_to_bucket(max(len(kv_runs), 1), self.kv_run_buckets)
        kv_runs_arr = np.zeros((G, 4), np.int32)
        if kv_runs:
            kv_runs_arr[:len(kv_runs)] = kv_runs

        R = pad_to_bucket(max(len(sampling_rows), 1), self.req_buckets)
        rows = np.asarray(sampling_rows +
                          [0] * (R - len(sampling_rows)), np.int32)
        logits_indices = np.asarray(logits_idx + [0] *
                                    (R - len(logits_idx)), np.int32)

        # Seeds: seeded requests fold (user_seed, step-in-request) so runs
        # reproduce; unseeded draw from the engine rng.
        user_seed = ib.seed[rows]
        step_in_req = ib.num_tokens[rows].astype(np.int64)
        random_part = self._rng.integers(0, 2**31 - 1, size=R)
        seeds = np.where(user_seed >= 0,
                         user_seed * 1000003 + step_in_req, random_part)

        sampling_md = SamplingMetadata(
            temperature=jnp.asarray(ib.temperature[rows]),
            top_k=jnp.asarray(ib.top_k[rows]),
            top_p=jnp.asarray(ib.top_p[rows]),
            min_p=jnp.asarray(ib.min_p[rows]),
            seeds=jnp.asarray(seeds),
        )
        batch = AttentionBatch(
            req_idx=jnp.asarray(req_idx),
            positions=jnp.asarray(positions),
            slot_mapping=jnp.asarray(slot_mapping),
            block_tables=jnp.asarray(ib.block_table),
            seq_lens=jnp.asarray(ib.num_computed),
            seq_info=jnp.asarray(seq_info),
            num_seqs=jnp.asarray([num_runs], np.int32),
            kv_runs=jnp.asarray(kv_runs_arr),
            num_kv_runs=jnp.asarray([len(kv_runs)], np.int32),
            max_q=max_q,
        )
        return (jnp.asarray(token_ids), batch,
                jnp.asarray(logits_indices), sampling_md,
                sampling_req_ids, (T, R))

    # ------------------------------------------------------------------
    def execute_model(self,
                      scheduler_output: SchedulerOutput) -> ModelRunnerOutput:
        self._update_states(scheduler_output)
        if scheduler_output.total_num_scheduled_tokens == 0:
            return ModelRunnerOutput()
        if scheduler_output.multi_step > 1:
            return self._execute_multi_step(scheduler_output)

        (token_ids, batch, logits_indices, sampling_md, sampling_req_ids,
         shape) = self._prepare_inputs(scheduler_output)

        if shape not in self._compiled_shapes:
            logger.info("compiling step for shape (tokens=%d, reqs=%d)",
                        *shape)
            start = time.perf_counter()
        with self.mesh:
            self.kv_caches, tokens, logprobs = self._step_fn(
                self.params, self.kv_caches, token_ids, batch,
                logits_indices, sampling_md)
        if shape not in self._compiled_shapes:
            self._compiled_shapes.add(shape)
            logger.info("compiled in %.1fs", time.perf_counter() - start)

        tokens_np = np.asarray(jax.device_get(tokens))
        logprobs_np = np.asarray(jax.device_get(logprobs))

        # Record sampled tokens so next step's decode inputs include them.
        req_ids, sampled, lps = [], [], []
        for i, req_id in enumerate(sampling_req_ids):
            token = int(tokens_np[i])
            self.input_batch.append_token(req_id, token)
            req_ids.append(req_id)
            sampled.append([token])
            lps.append([{token: float(logprobs_np[i])}])
        # Partial-prefill requests report no samples.
        sampling_set = set(sampling_req_ids)
        for req_id in scheduler_output.num_scheduled_tokens:
            if req_id not in sampling_set:
                req_ids.append(req_id)
                sampled.append([])
                lps.append([])
        return ModelRunnerOutput(req_ids=req_ids,
                                 sampled_token_ids=sampled,
                                 logprobs=lps)

    # ------------------------------------------------------------------
    def _execute_multi_step(
            self, scheduler_output: SchedulerOutput) -> ModelRunnerOutput:
        """Run scheduler_output.multi_step fused decode steps (pure-decode
        batch; one host roundtrip for the whole burst)."""
        ib = self.input_batch
        n_steps = scheduler_output.multi_step
        req_ids = list(scheduler_output.num_scheduled_tokens)
        num_active = len(req_ids)
        R = pad_to_bucket(num_active, self.req_buckets)
        rows = np.zeros((R, ), np.int32)
        rows[:num_active] = [ib.req_id_to_index[r] for r in req_ids]

        pos0 = ib.num_computed[rows].astype(np.int32)
        tok0 = ib.token_ids[rows, pos0].astype(np.int32)
        block_tables = ib.block_table[rows]

        user_seed = ib.seed[rows]
        step_in_req = ib.num_tokens[rows].astype(np.int64)
        seeds = np.empty((n_steps, R), np.int64)
        for t in range(n_steps):
            random_part = self._rng.integers(0, 2**31 - 1, size=R)
            seeds[t] = np.where(user_seed >= 0,
                                user_seed * 1000003 + step_in_req + t,
                                random_part)
        sampling_md = SamplingMetadata(
            temperature=jnp.asarray(ib.temperature[rows]),
            top_k=jnp.asarray(ib.top_k[rows]),
            top_p=jnp.asarray(ib.top_p[rows]),
            min_p=jnp.asarray(ib.min_p[rows]),
            seeds=jnp.asarray(seeds[0]),
        )

        shape = (-n_steps, R)
        if shape not in self._compiled_shapes:
            logger.info("compiling multi-step fn (steps=%d, reqs=%d)",
                        n_steps, R)
            start = time.perf_counter()
        with self.mesh:
            self.kv_caches, toks, lps = self._multi_step_fn(
                self.params, self.kv_caches, jnp.asarray(tok0),
                jnp.asarray(pos0), jnp.asarray(block_tables), sampling_md,
                jnp.asarray(seeds),
                jnp.asarray([num_active], np.int32))
        if shape not in self._compiled_shapes:
            self._compiled_shapes.add(shape)
            logger.info("compiled in %.1fs", time.perf_counter() - start)

        toks_np = np.asarray(jax.device_get(toks))  # [n_steps, R]
        lps_np = np.asarray(jax.device_get(lps))

        out_req_ids, sampled, out_lps = [], [], []
        for i, req_id in enumerate(req_ids):
            tokens = [int(t) for t in toks_np[:, i]]
            for tok in tokens:
                self.input_batch.append_token(req_id, tok)
            out_req_ids.append(req_id)
            sampled.append(tokens)
            out_lps.append([{tok: float(lp)}
                            for tok, lp in zip(tokens, lps_np[:, i])])
        return ModelRunnerOutput(req_ids=out_req_ids,
                                 sampled_token_ids=sampled,
                                 logprobs=out_lps)

    # ------------------------------------------------------------------
    def precompile(self) -> None:
        """Warm the (T, R) lattice ahead of serving (reference:
        tpu_model_runner.py:1248 precompilation suite). Compiles the
        smallest and largest shapes; the rest compile on demand."""
        pass

    def profile_memory_bytes(self) -> int:
        """Bytes of HBM available for KV pages after weights."""
        try:
            stats = jax.local_devices()[0].memory_stats()
            limit = stats.get("bytes_limit")
            in_use = stats.get("bytes_in_use")
            if limit:
                util = self.config.cache_config.gpu_memory_utilization
                return max(int(limit * util) - int(in_use or 0), 0)
        except Exception:  # pragma: no cover - platform specific
            pass
        return 0
