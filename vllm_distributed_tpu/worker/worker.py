"""TPU worker: device/mesh init, memory profiling for KV sizing, model
execution entry.

Reference: vllm/v1/worker/gpu_worker.py:44 (init_device:129,
determine_available_memory:200, execute_model:313) and tpu_worker.py:34.
In SPMD mode one worker drives the whole mesh (the reference's per-rank
process world collapses into GSPMD sharding).
"""

from typing import Optional

import jax

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.output import (ModelRunnerOutput,
                                                    SchedulerOutput)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.parallel.mesh import (build_mesh, global_mesh,
                                                set_global_mesh)
from vllm_distributed_tpu.worker.model_runner import TPUModelRunner

logger = init_logger(__name__)

# Floor so tiny test configs still schedule (matches the spirit of the
# reference's num_gpu_blocks_override escape hatch).
_MIN_PAGES = 16


class TPUWorker:

    def __init__(self, config: EngineConfig) -> None:
        self.config = config
        self.mesh = None
        self.model_runner: Optional[TPUModelRunner] = None

    # ------------------------------------------------------------------
    def init_device(self) -> None:
        from vllm_distributed_tpu import envs
        platform = envs.VDT_PLATFORM
        if platform != "auto":
            # Pin before any backend initializes: a bare jax.devices() lets
            # every installed plugin init, and a tunnelled TPU plugin can
            # block for minutes on non-TPU hosts.
            try:
                jax.config.update("jax_platforms", platform)
            except Exception as e:  # pragma: no cover - jax internals
                logger.warning("could not pin platform %r: %s", platform, e)
        self._maybe_init_multihost()
        devices = jax.devices()
        logger.info("devices: %s", devices)
        cache_dir = envs.VDT_COMPILE_CACHE_DIR
        if cache_dir and devices[0].platform != "cpu":
            # Persistent compile cache: on the tunnelled TPU first
            # compiles dominate bench time and the tunnel can drop
            # mid-run — cached retries resume almost instantly. CPU is
            # excluded: its AOT cache reload warns about machine-feature
            # mismatches (possible SIGILL) and CPU compiles are cheap.
            try:
                jax.config.update("jax_compilation_cache_dir", cache_dir)
                # Cache every graph: the bucketed lattice is many small
                # compiles below the default time threshold.
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
            except Exception as e:  # pragma: no cover - jax internals
                logger.warning("compile cache unavailable: %s", e)
        pc = self.config.parallel_config
        if pc.data_parallel_mode == "engine" and (
                pc.data_parallel_rank
                or pc.data_parallel_device_offset is not None):
            # Engine-replicated DP: each replica owns a disjoint
            # contiguous device slice (requires all replica devices
            # visible in-process — single host; multi-host DP carves by
            # process instead). The disagg pool planner sets an explicit
            # offset when pools have asymmetric TP degrees (replica
            # world sizes differ, so rank * world_size is wrong).
            per = pc.world_size
            start = (pc.data_parallel_device_offset
                     if pc.data_parallel_device_offset is not None
                     else pc.data_parallel_rank * per)
            if start + per > len(devices):
                raise ValueError(
                    f"DP rank {pc.data_parallel_rank} needs devices "
                    f"[{start}, {start + per}), only {len(devices)} exist")
            devices = devices[start:start + per]
        self.mesh = build_mesh(pc, devices)
        set_global_mesh(self.mesh)
        from vllm_distributed_tpu.models.loader import resolve_encoder_only
        if resolve_encoder_only(self.config.model_config):
            from vllm_distributed_tpu.worker.encoder_runner import (
                EncoderModelRunner)
            self.model_runner = EncoderModelRunner(self.config, self.mesh)
        elif self.config.parallel_config.pipeline_parallel_size > 1:
            from vllm_distributed_tpu.worker.pp_runner import PPModelRunner
            self.model_runner = PPModelRunner(self.config, self.mesh)
        else:
            self.model_runner = TPUModelRunner(self.config, self.mesh)

    def _maybe_init_multihost(self) -> None:
        """Join the pod-wide distributed runtime BEFORE any device access
        (reference boundary: per-rank process bootstrap,
        multiproc_executor.py:42 / StatelessProcessGroup,
        distributed/utils.py:138). After this, ``jax.devices()`` spans
        every host's chips and one SPMD mesh covers the pod; each host
        runs this same engine program multi-controller style."""
        pc = self.config.parallel_config
        if pc.num_hosts <= 1:
            return
        # NOTE: jax.process_count() would itself initialize the backend,
        # which must not happen before jax.distributed.initialize —
        # consult the distributed runtime's own state instead.
        from jax._src import distributed as _dist
        if getattr(_dist.global_state, "client", None) is not None:
            return  # already joined (e.g. a second engine in-process)
        logger.info("joining multi-host runtime: rank %d/%d via %s",
                    pc.host_rank, pc.num_hosts,
                    pc.coordinator_address or "auto-detect")
        jax.distributed.initialize(
            coordinator_address=pc.coordinator_address,
            num_processes=pc.num_hosts,
            process_id=pc.host_rank)

    def load_model(self) -> None:
        # Every entry point re-asserts this worker's mesh as the global
        # one: with in-process DP engine replicas, another replica's init
        # may have pointed the global mesh elsewhere between calls (the
        # collective helpers in ops/ read it during jit tracing).
        with global_mesh(self.mesh):
            self.model_runner.load_model()

    def determine_num_available_blocks(self) -> int:
        """Size the KV pool from measured HBM after a profiled dummy
        forward at the largest token shape (reference: gpu_worker.py:200
        determine_available_memory runs profile_run before reading free
        memory; TPU variant tpu_worker.py:163)."""
        # The page array shards evenly over the token-parallel axis.
        tknp = self.config.parallel_config.token_parallel_size

        def rounded(pages: int) -> int:
            pages = max(pages, _MIN_PAGES)
            return (pages // tknp) * tknp if tknp > 1 else pages

        override = self.config.cache_config.num_gpu_blocks_override
        if override:
            # Honored verbatim (tests use tiny pools to force preemption);
            # token-axis divisibility was validated at config time.
            return override
        with global_mesh(self.mesh):
            avail = self.model_runner.profile_memory_bytes()
        page_bytes = self.model_runner.kv_cache_bytes_per_page()
        # Fixed-size per-request state (SSM conv/ssm rows) PLUS the
        # state-snapshot pool (core/state_cache.py) are charged up
        # front; the page pool only gets what remains.
        fixed = (self.model_runner.model_fixed_cache_bytes() +
                 getattr(self.model_runner, "state_pool_bytes",
                         lambda: 0)())
        if avail > 0 and fixed > avail:
            raise RuntimeError(
                f"per-request SSM state + snapshot pool "
                f"({fixed / 2**30:.2f} GiB for "
                f"{self.config.scheduler_config.max_num_seqs} slots) "
                f"exceeds free HBM ({avail / 2**30:.2f} GiB); lower "
                f"max_num_seqs or VDT_SSM_STATE_CACHE_SLOTS")
        avail -= fixed
        if page_bytes == 0:
            # Stateful-only models (pure Mamba): pages carry no bytes, so
            # give every schedulable request full-length coverage.
            pages = (self.config.max_pages_per_req *
                     self.config.scheduler_config.max_num_seqs)
            logger.info("no paged layers; %d free KV pages", pages)
            return rounded(pages)
        if avail <= 0:
            # No memory stats (CPU tests): cover max_model_len for
            # max_num_seqs/4 requests.
            pages = (self.config.max_pages_per_req *
                     max(self.config.scheduler_config.max_num_seqs // 4, 4))
            logger.info("no memory stats; defaulting to %d KV pages", pages)
            return rounded(pages)
        pages = avail // page_bytes
        shards = getattr(
            getattr(self.model_runner.model, "cfg", None),
            "tpla_shards", 1) or 1
        if shards > 1:
            # TPLA (ops/mla.py): page_bytes is the PER-RANK cost of a
            # latent page (1/TP of the replicated row plus the rope
            # sidecar), so the same per-device budget admits ~TP x the
            # pages — the capacity win this layout exists for.
            logger.info(
                "HBM for KV: %.2f GiB -> %d latent pages of %d "
                "bytes/rank (TPLA x%d sharding)",
                avail / 2**30, pages, page_bytes, shards)
        else:
            logger.info("HBM for KV: %.2f GiB -> %d pages of %d bytes",
                        avail / 2**30, pages, page_bytes)
        return rounded(pages)

    def initialize_kv_cache(self, num_pages: int) -> None:
        with global_mesh(self.mesh):
            self.model_runner.initialize_kv_cache(num_pages)

    def compile_or_warm_up_model(self) -> None:
        from vllm_distributed_tpu import envs
        mode = envs.VDT_PRECOMPILE
        if mode == "0":
            return
        platform = next(iter(self.mesh.devices.flat)).platform
        if mode == "auto" and platform == "cpu":
            return  # lazy compiles are cheap on the CPU test mesh
        with global_mesh(self.mesh):
            self.model_runner.precompile()

    # ------------------------------------------------------------------
    def execute_model(self,
                      scheduler_output: SchedulerOutput) -> ModelRunnerOutput:
        with global_mesh(self.mesh):
            return self.model_runner.execute_model(scheduler_output)

    def dispatch_model(self, scheduler_output: SchedulerOutput):
        with global_mesh(self.mesh):
            return self.model_runner.dispatch_model(scheduler_output)

    def wait_model(self, handle) -> ModelRunnerOutput:
        with global_mesh(self.mesh):
            return self.model_runner.wait_model(handle)

    def get_stats(self) -> dict:
        """Runner stats plus this worker's labeled telemetry entry.

        The per-worker keys MOVE into ``workers[label]`` (they are not
        left flat): the DP aggregator sums flat numeric leaves, and a
        summed "peak device memory" or a twice-counted recompile would
        fabricate fleet state. ``num_recompiles`` stays flat as well as
        labeled — it is a counter, so the flat DP sum is the correct
        fleet total while the labeled copy says WHICH worker leaked a
        shape."""
        stats = self.model_runner.get_stats()
        from vllm_distributed_tpu.metrics import telemetry
        per_worker = {}
        # mfu/mbu are per-worker RATIOS against this worker's own
        # device time and peak — the DP flat numeric-sum would add
        # them into nonsense, so they ride the labeled map like the
        # memory peaks (union merge, never summed).
        for key in ("device_wait_seconds", "device_memory_peak_bytes",
                    "device_memory_in_use_bytes", "tpla_latent_shards",
                    "mla_latent_page_bytes", "mfu", "mbu"):
            if key in stats:
                per_worker[key] = stats.pop(key)
        if "num_recompiles" in stats:
            per_worker["num_recompiles"] = stats["num_recompiles"]
        if per_worker:
            label = telemetry.worker_label(self.config.parallel_config)
            stats["workers"] = {label: per_worker}
        return stats
