"""Runner for encoder-only (BERT/RoBERTa) models.

Reference surface: the pooling-model path of the reference runner
(vllm/v1/worker/gpu_model_runner.py ``_pool`` + v1/pool/) serving
BertEmbeddingModel / cross-encoder checkpoints
(vllm/model_executor/models/bert.py, roberta.py).

TPU design: encoder inference has no KV cache, no sampling and no
decode steps — every request is one full-prompt prefill. So instead of
flowing through the ragged paged decoder step, batches run as a dense
padded [R, L] program jitted per (R, L) bucket: large static matmuls
(MXU-shaped), bidirectional attention as one [R, heads, L, L] einsum,
every pooling variant computed on-device in the same program. The
scheduler is unchanged — chunked prefill and prefix caching are
disabled for encoder archs (a bidirectional layer needs the whole
sequence at once; see core/sched/scheduler.py construction), so each
scheduled request carries its complete prompt and finishes in the same
step (the ``pooled`` path of scheduler.update_from_output).
"""

import functools
import time
from typing import Any, Optional

import jax
import numpy as np

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.output import (ModelRunnerOutput,
                                                    SchedulerOutput)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.utils import make_buckets, pad_to_bucket

logger = init_logger(__name__)


class EncoderModelRunner:
    """Drop-in for TPUModelRunner when the arch is encoder-only."""

    def __init__(self, config: EngineConfig, mesh,
                 model=None, params=None) -> None:
        self.config = config
        self.mesh = mesh
        self.model = model
        self.params = params
        sched_cfg = config.scheduler_config
        self.max_num_reqs = sched_cfg.max_num_seqs
        self.max_model_len = sched_cfg.max_model_len
        self.req_buckets = make_buckets(8, self.max_num_reqs)
        # Length buckets only up to what admission can actually let
        # through: the model window, the one-step token budget, and the
        # position-table capacity — anything larger would precompile
        # unreachable shapes (minutes of XLA time on TPU).
        from vllm_distributed_tpu.models.loader import (
            resolve_encoder_limits)
        _, pos_capacity = resolve_encoder_limits(config.model_config)
        max_len = min(self.max_model_len,
                      sched_cfg.max_num_batched_tokens,
                      pos_capacity or self.max_model_len)
        self.len_buckets = make_buckets(16, max_len)
        # req_id -> (prompt_token_ids, pooling_params); kept until the
        # request finishes or is aborted (covers resume-from-preemption,
        # where CachedRequestData carries no pooling params).
        self._req_meta: dict[str, tuple[list[int], dict]] = {}
        self._steps = 0

    # ------------------------------------------------------------------
    def load_model(self) -> None:
        from vllm_distributed_tpu.models.loader import get_model
        if self.model is None:
            self.model, self.params = get_model(self.config, self.mesh)
        assert getattr(self.model, "ENCODER_ONLY", False), \
            "EncoderModelRunner requires an encoder-only arch"

        model = self.model

        @functools.partial(jax.jit, static_argnums=())
        def _step(params, token_ids, type_ids, valid):
            hidden = model.encode(params, token_ids, type_ids, valid)
            return model.pool(params, hidden, valid)

        self._jit_step = _step

    # ------------------------------------------------------------------
    # Sizing hooks (worker.determine_num_available_blocks): pages carry
    # no bytes — the pool is sized to cover every schedulable request.
    # ------------------------------------------------------------------
    def profile_memory_bytes(self) -> int:
        return 0

    def kv_cache_bytes_per_page(self) -> int:
        return 0

    def model_fixed_cache_bytes(self) -> int:
        return 0

    def initialize_kv_cache(self, num_pages: int) -> None:
        self.num_pages = num_pages

    def precompile(self) -> None:
        """Warm the FULL (R, L) lattice — jit caches per exact shape,
        so every pair must compile up front or the first batch that
        pads to it stalls a serving step (the VDT_PRECOMPILE contract
        of the decoder runner)."""
        start = time.perf_counter()
        n = 0
        with self.mesh:
            for L in self.len_buckets:
                for R in self.req_buckets:
                    self._run(np.zeros((R, L), np.int32),
                              np.zeros((R, L), np.int32),
                              np.zeros((R, L), bool))
                    n += 1
        logger.info("encoder precompile: %d shapes in %.1fs", n,
                    time.perf_counter() - start)

    def _run(self, token_ids, type_ids, valid):
        with self.mesh:
            return self._jit_step(self.params, token_ids, type_ids, valid)

    # ------------------------------------------------------------------
    def dispatch_model(self, scheduler_output: SchedulerOutput):
        for req_id in scheduler_output.finished_req_ids:
            self._req_meta.pop(req_id, None)

        rows: list[tuple[str, list[int], dict]] = []
        for nr in scheduler_output.scheduled_new_reqs:
            pooling = nr.pooling_params or {"type": "cls"}
            self._req_meta[nr.req_id] = (list(nr.prompt_token_ids), pooling)
            rows.append((nr.req_id, list(nr.prompt_token_ids), pooling))
        cached = scheduler_output.scheduled_cached_reqs
        for i, req_id in enumerate(cached.req_ids):
            # Only resume-from-preemption reaches here (encoder requests
            # never persist across steps); tokens were stashed at
            # admission.
            toks, pooling = self._req_meta[req_id]
            rows.append((req_id, toks, pooling))

        if not rows:
            return {"ready": ModelRunnerOutput()}

        for req_id, toks, _ in rows:
            n = scheduler_output.num_scheduled_tokens[req_id]
            assert n == len(toks), (
                f"encoder request {req_id} scheduled {n}/{len(toks)} "
                f"tokens: chunked prefill must be disabled for "
                f"encoder-only models")

        R = pad_to_bucket(len(rows), self.req_buckets)
        L = pad_to_bucket(max(len(t) for _, t, _ in rows),
                          self.len_buckets)
        token_ids = np.zeros((R, L), np.int32)
        type_ids = np.zeros((R, L), np.int32)
        valid = np.zeros((R, L), bool)
        for i, (_, toks, pooling) in enumerate(rows):
            token_ids[i, :len(toks)] = toks
            valid[i, :len(toks)] = True
            tt = pooling.get("token_type_ids")
            if tt:
                type_ids[i, :min(len(tt), len(toks))] = \
                    tt[:len(toks)]
        dev = self._run(token_ids, type_ids, valid)
        self._steps += 1
        return {"dev": dev, "rows": rows}

    def wait_model(self, handle: dict) -> ModelRunnerOutput:
        if "ready" in handle:
            return handle["ready"]
        rows = handle["rows"]
        host = jax.device_get(handle["dev"])
        pooled: dict[str, list[float]] = {}
        req_ids = []
        for i, (req_id, _, pooling) in enumerate(rows):
            req_ids.append(req_id)
            ptype = pooling.get("type", "cls")
            if ptype == "score":
                if "score" not in host:
                    raise ValueError(
                        "score pooling needs a classification "
                        "checkpoint (BertForSequenceClassification)")
                pooled[req_id] = [float(host["score"][i])]
            else:
                vec = host.get(ptype)
                if vec is None:
                    raise ValueError(f"unknown pooling type {ptype!r}")
                pooled[req_id] = np.asarray(
                    vec[i], np.float32).tolist()
            self._req_meta.pop(req_id, None)
        return ModelRunnerOutput(
            req_ids=req_ids,
            sampled_token_ids=[[] for _ in req_ids],
            pooled=pooled)

    def execute_model(
            self, scheduler_output: SchedulerOutput) -> ModelRunnerOutput:
        return self.wait_model(self.dispatch_model(scheduler_output))

    # ------------------------------------------------------------------
    def get_stats(self) -> dict:
        return {"encoder_steps": float(self._steps)}

    def save_sharded_state(self, path: str) -> None:
        import orbax.checkpoint as ocp
        import os
        ocp.StandardCheckpointer().save(os.path.abspath(path),
                                        jax.device_get(self.params))
