"""Pipeline-parallel model runner: per-stage sub-meshes, staged jits,
activation handoff via device_put.

TPU-native PP (vs the reference's one-process-per-rank design sending
IntermediateTensors over NCCL, vllm/v1/worker/gpu_model_runner.py +
parallel_state.py:629 send_tensor_dict): the ``pipe`` axis of the global
mesh is sliced into P sub-meshes; stage p holds its contiguous layer
slice's weights and KV cache on its sub-mesh and runs ONE jitted
program (models/llama.py run_layers). Activations hop stages with
``jax.device_put`` — an ICI/DCN device-to-device copy the runtime
overlaps with compute via async dispatch, so consecutive engine steps
pipeline across stages without an explicit microbatch queue (the engine
core's batch-queue overlap, reference core.py:242, adds depth on top).

Tensor parallelism composes: each sub-mesh keeps the (token, model) axes,
so GSPMD TP and the shard_map'd Pallas kernels work per stage unchanged.
"""

import time
from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.parallel.mesh import global_mesh
from vllm_distributed_tpu.parallel.pipeline import (partition_layers,
                                                    stage_submesh)
from vllm_distributed_tpu.worker.model_runner import TPUModelRunner

logger = init_logger(__name__)


class PPModelRunner(TPUModelRunner):

    def __init__(self, config: EngineConfig, mesh,
                 model=None, params=None) -> None:
        super().__init__(config, mesh, model, params)
        self.pp = config.parallel_config.pipeline_parallel_size
        assert self.pp > 1
        self.stage_meshes = [stage_submesh(mesh, p) for p in range(self.pp)]
        self.layer_ranges: Optional[list[tuple[int, int]]] = None
        self.stage_params: list[dict] = []
        self.embed_params: Optional[dict] = None

    # ------------------------------------------------------------------
    def load_model(self) -> None:
        from vllm_distributed_tpu.models.loader import get_model
        self.model, host_params = get_model(self.config, self.mesh,
                                            shard=False)
        L = self.model.cfg.num_layers
        if self.pp > L:
            raise ValueError(
                f"pipeline_parallel_size={self.pp} exceeds the model's "
                f"{L} layers")
        self.layer_ranges = partition_layers(L, self.pp)
        logger.info("pipeline stages (layer ranges): %s", self.layer_ranges)
        specs = self.model.param_specs()
        self.stage_params = []
        for p, (s, e) in enumerate(self.layer_ranges):
            sm = self.stage_meshes[p]
            sliced = self.model.slice_layer_params(
                host_params["layers"], s, e)
            self.stage_params.append({
                k: jax.device_put(v, NamedSharding(sm,
                                                   specs["layers"][k]))
                for k, v in sliced.items()
            })
        sm0, sml = self.stage_meshes[0], self.stage_meshes[-1]
        self.embed_params = {
            "embed": jax.device_put(host_params["embed"],
                                    NamedSharding(sm0, specs["embed"])),
        }
        for extra in ("embed_pos", "embed_ln_w", "embed_ln_b"):
            # Learned-position tables / embedding norms ride stage 0.
            if extra in host_params:
                self.embed_params[extra] = jax.device_put(
                    host_params[extra], NamedSharding(sm0, specs[extra]))
        self._init_lora_manager()
        # The sampler's params (final norm + LM head) live with the last
        # stage; the base class passes self.params to the sample fns.
        self.params = {
            "final_ln": jax.device_put(
                host_params["final_ln"],
                NamedSharding(sml, specs["final_ln"])),
            "lm_head": jax.device_put(
                host_params["lm_head"],
                NamedSharding(sml, specs["lm_head"])),
        }
        for extra in ("final_ln_b", "lm_head_b"):
            if extra in host_params:
                self.params[extra] = jax.device_put(
                    host_params[extra], NamedSharding(sml, specs[extra]))

    def lora_buffer_trees(self):
        return [(self.stage_params[p], rng)
                for p, rng in enumerate(self.layer_ranges)]

    # ------------------------------------------------------------------
    def _stage_caches(self, num_pages: int) -> list[dict]:
        specs = self.model.kv_cache_specs()
        out = []
        for p, (s, e) in enumerate(self.layer_ranges):
            sm = self.stage_meshes[p]
            with sm:
                caches = self.model.make_kv_caches(
                    num_pages, self.page_size, num_layers=e - s)
                out.append(
                    jax.tree.map(
                        lambda x, sp: jax.device_put(
                            x, NamedSharding(sm, sp)), caches, specs,
                        is_leaf=lambda x: isinstance(x, jax.Array)))
        return out

    def initialize_kv_cache(self, num_pages: int) -> None:
        assert self.model is not None
        self.num_pages = num_pages
        # List of per-stage {"k","v"} slices instead of one stacked cache.
        self.kv_caches = self._stage_caches(num_pages)
        if self._forward_fn is None:
            self._build_step_fn()

    def _build_step_fn(self) -> None:
        model = self.model

        def embed(params, token_ids, positions=None):
            h = model.embed(params, token_ids, positions)
            # Replicate INSIDE the jit (GSPMD all-gather over the stage
            # mesh, where collectives are legal) so the inter-stage hop
            # only moves locally-complete values — multi-controller
            # device_put cannot gather across hosts (see _hop).
            return jax.lax.with_sharding_constraint(h, PartitionSpec())

        def stage(layer_params, kv_caches, hidden, batch, first_layer=0):
            hidden, kv_caches = model.run_layers(layer_params, kv_caches,
                                                 hidden, batch,
                                                 first_layer=first_layer)
            hidden = jax.lax.with_sharding_constraint(
                hidden, PartitionSpec())
            return kv_caches, hidden

        self._embed_fn = jax.jit(embed)
        self._stage_fn = jax.jit(stage, donate_argnums=(1, ),
                                 static_argnames=("first_layer", ))
        # Base sampler jits (compute_logits + sampling) work unchanged —
        # they only touch self.params (final_ln/lm_head on the last
        # stage's sub-mesh).
        super()._build_step_fn()
        self._forward_fn = self._not_supported  # stage loop replaces it
        self._multi_step_fn = self._not_supported

    @staticmethod
    def _not_supported(*_a, **_k):  # pragma: no cover - guard
        raise RuntimeError("single-program forward is not used under PP")

    def dispatch_model(self, scheduler_output):
        """Perf-attribution host share under PP: the dominant host cost
        of a dispatch is the synchronous stage loop (inter-stage hops +
        per-stage launches), not ``_prepare_inputs`` — fold the whole
        dispatch wall into the pending charge's host seconds so the
        roofline's host-bound classification stays honest per stage."""
        t0 = time.perf_counter()
        handle = super().dispatch_model(scheduler_output)
        if isinstance(handle, dict) and handle.get("perf") is not None:
            handle["perf_prep_s"] = time.perf_counter() - t0
        return handle

    # ------------------------------------------------------------------
    def _stage_first_layer(self, p: int) -> int:
        """Global layer offset of stage p — nonzero only for mixed
        window layouts, so uniform models keep sharing one compiled
        stage program across equal-shape stages."""
        return (self.layer_ranges[p][0]
                if self.model.cfg.window_pattern else 0)

    def _hop(self, hidden, sm):
        """Activation handoff onto stage ``sm`` (reference analogue:
        IntermediateTensors send/recv). Single-controller: one async
        device_put over ICI. Multi-controller: the stage jits emit the
        activation REPLICATED (each process holds the full value on its
        own stage devices), so the hop rebuilds the array from the
        local shard — no cross-host device_put, which multi-controller
        JAX restricts to identical device sets."""
        target = NamedSharding(sm, PartitionSpec())
        if jax.process_count() == 1:
            return jax.device_put(hidden, target)
        if hidden.sharding.device_set == target.device_set:
            return jax.device_put(hidden, target)
        import numpy as np
        local = np.asarray(hidden.addressable_shards[0].data)
        return jax.make_array_from_callback(
            hidden.shape, target, lambda idx: local[idx])

    def _launch_device_step(self, token_ids, batch, logits_indices,
                            sampling_md, fwd_shape, ext_md, want_topk,
                            vocab_mask=None, plp=None, spec_q=None):
        sm0 = self.stage_meshes[0]
        with global_mesh(sm0), sm0:
            with self._compile_watch(("embed", fwd_shape[0])):
                hidden = self._embed_fn(self.embed_params, token_ids,
                                 batch.positions)
        for p in range(self.pp):
            sm = self.stage_meshes[p]
            # Activation handoff: ICI/DCN copy to the next stage's
            # sub-mesh. Dispatch is async end-to-end on one controller:
            # nothing here blocks the host, so when the engine core
            # keeps multiple batches in flight, stage p of batch i+1
            # runs under stage p+1 of batch i (each stage's KV cache
            # chains only to ITS OWN previous-batch output).
            hidden = self._hop(hidden, sm)
            with global_mesh(sm), sm:
                with self._compile_watch(("stage", p) + fwd_shape):
                    self.kv_caches[p], hidden = self._stage_fn(
                        self.stage_params[p], self.kv_caches[p], hidden,
                        batch, first_layer=self._stage_first_layer(p))
        sml = self.stage_meshes[-1]
        with global_mesh(sml), sml:
            return self._launch_sample(hidden, logits_indices,
                                       sampling_md, ext_md, want_topk,
                                       sml, vocab_mask, plp=plp,
                                       spec_q=spec_q)

    # ------------------------------------------------------------------
    def precompile(self) -> None:
        """Warm embed + every stage + samplers over the shape lattice
        (reference: tpu_model_runner.py:1248; PP warms per-stage graphs)."""
        assert self.kv_caches is not None, "initialize_kv_cache first"
        import time
        start = time.perf_counter()
        n = 0
        for T, max_q, G in sorted(self.forward_shapes()):
            token_ids, batch = self._dummy_step_inputs(T, max_q, G)
            sm0 = self.stage_meshes[0]
            with global_mesh(sm0), sm0:
                with self._compile_watch(("embed", T)):
                    hidden = self._embed_fn(self.embed_params, token_ids,
                                 batch.positions)
            n += 1
            for p in range(self.pp):
                sm = self.stage_meshes[p]
                hidden = self._hop(hidden, sm)
                with global_mesh(sm), sm:
                    with self._compile_watch(("stage", p, T, max_q, G)):
                        self.kv_caches[p], hidden = self._stage_fn(
                            self.stage_params[p], self.kv_caches[p],
                            hidden, batch,
                            first_layer=self._stage_first_layer(p))
                n += 1
            jax.block_until_ready(hidden)
        sml = self.stage_meshes[-1]
        with global_mesh(sml), sml:
            self._precompile_samplers(sml)
            self._precompile_plp(sml)
        self._precompiled = True
        self.precompile_graphs = n
        logger.info("PP precompile done in %.1fs",
                    time.perf_counter() - start)

    # ------------------------------------------------------------------
    def kv_cache_bytes_per_page(self) -> int:
        # Per-DEVICE bytes, sized by the LARGEST stage's layer count (an
        # uneven split gives the early stages the remainder layers).
        from vllm_distributed_tpu.utils import cdiv
        full = super().kv_cache_bytes_per_page()
        L = self.model.cfg.num_layers
        if self.layer_ranges is not None:
            max_layers = max(e - s for s, e in self.layer_ranges)
        else:
            max_layers = cdiv(L, self.pp)
        return max(full * max_layers // L, 1)

    def _profile_peak_bytes(self, dev) -> int:
        """Largest-shape pipeline pass against per-stage scratch caches;
        peak taken as the max over one device per stage. (The base
        class's limit/util/fallback logic wraps this.)"""
        scratch = self._stage_caches(16)
        if self._forward_fn is None:
            self._build_step_fn()
        T, max_q, G = max(self.forward_shapes())
        token_ids, batch = self._dummy_step_inputs(T, max_q, G)
        sm0 = self.stage_meshes[0]
        with global_mesh(sm0), sm0:
            hidden = self._embed_fn(self.embed_params, token_ids,
                                 batch.positions)
        for p in range(self.pp):
            sm = self.stage_meshes[p]
            hidden = self._hop(hidden, sm)
            with global_mesh(sm), sm:
                scratch[p], hidden = self._stage_fn(
                    self.stage_params[p], scratch[p], hidden, batch,
                    first_layer=self._stage_first_layer(p))
        jax.block_until_ready(hidden)
        del scratch, hidden
        peak = 0
        for sm in self.stage_meshes:
            d = next(iter(sm.devices.flat))
            s = d.memory_stats() or {}
            peak = max(peak,
                       int(s.get("peak_bytes_in_use",
                                 s.get("bytes_in_use", 0))))
        logger.info("profiled PP peak HBM: %.2f GiB", peak / 2**30)
        return peak
