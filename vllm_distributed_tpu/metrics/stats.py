"""Front-end request latency stats: TTFT / ITL / e2e histograms.

Reference: vllm/v1/metrics/stats.py (IterationStats computing TTFT and
inter-token latency from arrival/first-token timestamps) + loggers.py:50
(LoggingStatLogger's periodic throughput lines) and :143
(PrometheusStatLogger histogram families). Rendered without the
prometheus_client registry for the same reason as metrics/prometheus.py:
the global registry complicates multi-engine tests.
"""

import time
from bisect import bisect_left
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

# Bucket boundaries (seconds) mirroring the reference's latency families.
TTFT_BUCKETS = (0.001, 0.005, 0.01, 0.02, 0.04, 0.06, 0.08, 0.1, 0.25,
                0.5, 0.75, 1.0, 2.5, 5.0, 7.5, 10.0, 20.0, 40.0, 80.0)
ITL_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.02, 0.04, 0.06, 0.08, 0.1,
               0.25, 0.5, 1.0, 2.5, 5.0)
E2E_BUCKETS = (0.3, 0.5, 0.8, 1.0, 1.5, 2.0, 2.5, 5.0, 10.0, 15.0, 30.0,
               40.0, 50.0, 60.0, 120.0, 240.0, 480.0, 960.0)
# Engine-core host gap (wait_model return -> next dispatch): the device
# idle window async scheduling exists to hide; sub-millisecond when a
# batch was already waiting, tens of milliseconds when the host
# schedules synchronously between steps.
HOST_GAP_BUCKETS = (0.0002, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.02,
                    0.05, 0.1, 0.25, 1.0)
# Engine-core step phases (schedule / prepare_inputs / dispatch /
# device wait / update_from_output): microseconds for the host control
# plane up to seconds for a first-compile device wait.
STEP_PHASE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                      0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                      5.0, 10.0)


def render_histogram_lines(name: str, help_text: str, buckets, counts,
                           total: float, count: int, label: str = "",
                           header: bool = True) -> list[str]:
    """Prometheus exposition lines for one histogram family: cumulative
    ``_bucket`` series (``counts`` carries one trailing +Inf slot),
    ``_sum`` and ``_count``. Single source of truth for the shape —
    shared by live Histogram objects and the serialized-dict stats
    entries engines ship over the stats RPC. ``label`` (e.g.
    ``phase="dispatch"``) renders one labeled series of a family;
    pass ``header=False`` for every series after the first."""
    lines = ([f"# HELP {name} {help_text}", f"# TYPE {name} histogram"]
             if header else [])
    lbl = f"{label}," if label else ""
    suffix = f"{{{label}}}" if label else ""
    cumulative = 0
    for b, c in zip(buckets, counts):
        cumulative += int(c)
        lines.append(f'{name}_bucket{{{lbl}le="{b}"}} {cumulative}')
    if counts:
        cumulative += int(counts[-1])
    lines.append(f'{name}_bucket{{{lbl}le="+Inf"}} {cumulative}')
    lines.append(f"{name}_sum{suffix} {total}")
    lines.append(f"{name}_count{suffix} {count}")
    return lines


class Histogram:
    """Fixed-bucket histogram in Prometheus exposition shape."""

    def __init__(self, buckets: tuple[float, ...]) -> None:
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # last = +Inf
        self.total = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        # bisect_left finds the first bucket with value <= bound (runs
        # per token on the ITL path; a linear scan of ~20 bounds costs
        # more than the observation it records).
        self.counts[bisect_left(self.buckets, value)] += 1

    def render(self, name: str, help_text: str) -> list[str]:
        return render_histogram_lines(name, help_text, self.buckets,
                                      self.counts, self.total, self.count)

    def to_dict(self) -> dict:
        """Serialized stats-RPC form; render_histogram_lines over this
        dict is byte-identical to render() on the live object."""
        return {"buckets": list(self.buckets), "counts": list(self.counts),
                "sum": self.total, "count": self.count}


def merge_histogram_dicts(hists: list[dict]) -> Optional[dict]:
    """Element-wise merge of serialized histogram dicts (DP stats
    aggregation). Mismatched bucket layouts (mixed versions mid-upgrade)
    are skipped rather than mis-summed."""
    hists = [h for h in hists if isinstance(h, dict) and h.get("buckets")]
    if not hists:
        return None
    merged = {"buckets": list(hists[0]["buckets"]),
              "counts": [0] * len(hists[0]["counts"]),
              "sum": 0.0, "count": 0}
    for h in hists:
        if list(h["buckets"]) != merged["buckets"]:
            continue
        merged["counts"] = [a + b for a, b in zip(merged["counts"],
                                                  h["counts"])]
        merged["sum"] += h["sum"]
        merged["count"] += h["count"]
    return merged


class BurnRateWatchdog:
    """Multi-window SLO burn-rate watchdog over the goodput plane.

    The SRE-standard burn-rate alert: a window's burn rate is its miss
    fraction divided by the error budget (1 - VDT_SLO_TARGET), so 1.0
    means "missing exactly as fast as the budget allows" and 14 means
    "the whole monthly budget gone in ~2 days". DEGRADED requires BOTH
    the fast (1 m) and slow (10 m) windows to burn past
    VDT_SLO_BURN_THRESHOLD — the fast window confirms the problem is
    live, the slow one that it is sustained, which is what makes the
    flag safe to feed the fleet controller as scale-out pressure.

    Per-request verdicts bucket into coarse time bins (one
    [scored, missed] pair per bin, pruned past the slow window), so
    memory is O(windows), not O(traffic).
    """

    WINDOWS = (("1m", 60.0), ("10m", 600.0))
    BIN_S = 5.0

    def __init__(self, target: Optional[float] = None,
                 threshold: Optional[float] = None) -> None:
        from vllm_distributed_tpu import envs
        self.target = (envs.VDT_SLO_TARGET
                       if target is None else target)
        self.threshold = (envs.VDT_SLO_BURN_THRESHOLD
                          if threshold is None else threshold)
        self.budget = max(1e-6, 1.0 - self.target)
        self._horizon = max(w for _, w in self.WINDOWS) + self.BIN_S
        self._bins: "OrderedDict[int, list[int]]" = OrderedDict()

    def observe(self, good: bool, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        key = int(now // self.BIN_S)
        bucket = self._bins.get(key)
        if bucket is None:
            bucket = self._bins[key] = [0, 0]
            cutoff = key - int(self._horizon // self.BIN_S) - 1
            while self._bins and next(iter(self._bins)) < cutoff:
                self._bins.popitem(last=False)
        bucket[0] += 1
        if not good:
            bucket[1] += 1

    def burn_rates(self, now: Optional[float] = None) -> dict[str, float]:
        """{window: burn rate} (0.0 for an empty window — no traffic
        is not an SLO violation)."""
        now = time.monotonic() if now is None else now
        rates: dict[str, float] = {}
        for name, w in self.WINDOWS:
            cutoff = int((now - w) // self.BIN_S)
            scored = missed = 0
            for key, (s, m) in self._bins.items():
                if key >= cutoff:
                    scored += s
                    missed += m
            frac = missed / scored if scored else 0.0
            rates[name] = frac / self.budget
        return rates

    def degraded(self, now: Optional[float] = None) -> bool:
        if self.threshold <= 0:
            return False
        rates = self.burn_rates(now)
        return all(r > self.threshold for r in rates.values())


@dataclass
class RequestTimes:
    """Per-request timestamps the output processor maintains."""

    arrival: float
    first_token: Optional[float] = None
    last_token: Optional[float] = None


@dataclass
class FrontendStats:
    """Latency histograms + throughput counters, updated by the output
    processor as tokens stream out, rendered into /metrics."""

    ttft: Histogram = field(default_factory=lambda: Histogram(TTFT_BUCKETS))
    itl: Histogram = field(default_factory=lambda: Histogram(ITL_BUCKETS))
    e2e: Histogram = field(default_factory=lambda: Histogram(E2E_BUCKETS))
    num_prompt_tokens: int = 0
    num_generation_tokens: int = 0
    num_finished: int = 0
    # Engine-core death/restart events detected by the health monitor
    # (AsyncLLM increments when it fails pending requests).
    num_engine_deaths: int = 0
    # Recovery-layer counters: journaled requests resubmitted to a
    # freshly restarted core as continuation prefills, and requests
    # refused at the API admission gate (429/503 shed).
    num_requests_replayed: int = 0
    num_requests_shed: int = 0
    # Wall seconds the last SIGTERM drain took from "stop admitting" to
    # "in-flight work finished" (0 until a drain runs).
    drain_duration_seconds: float = 0.0
    # SLO goodput accounting: TTFT / TPOT budgets in milliseconds
    # (VDT_SLO_TTFT_MS / VDT_SLO_TPOT_MS; 0 disables that target, both
    # 0 disables scoring — the vdt:slo_* families are then not
    # rendered). A request is GOOD when it met every enabled target;
    # goodput_frac = good / scored, the paper-standard "fraction of
    # traffic that met its latency target at this load".
    slo_ttft_ms: float = 0.0
    slo_tpot_ms: float = 0.0
    slo_scored: int = 0
    slo_good: int = 0
    slo_ttft_misses: int = 0
    slo_tpot_misses: int = 0
    # Per-tenant goodput (vdt:tenant_goodput_frac{tenant}; QoS plane):
    # {tenant bucket: [scored, good]}. Fed only when the output
    # processor runs with VDT_QOS=1 — keys are already
    # bounded-cardinality buckets (qos.bucket_tenant), so rendering one
    # series per key is safe.
    slo_by_tenant: dict = field(default_factory=dict)
    # SLO burn-rate watchdog (constructed by the output processor when
    # any SLO target is enabled; None otherwise): multi-window burn
    # rates + the degraded flag /health and /debug/engine surface.
    burn: Optional[BurnRateWatchdog] = None
    # Periodic logging window (LoggingStatLogger equivalent).
    _window_start: float = field(default_factory=time.monotonic)
    _window_gen_tokens: int = 0
    log_interval_s: float = 10.0

    def on_tokens(self, times: RequestTimes, num_new: int,
                  now: Optional[float] = None) -> None:
        if num_new <= 0:
            return
        now = time.monotonic() if now is None else now
        if times.first_token is None:
            times.first_token = now
            self.ttft.observe(now - times.arrival)
            extra = num_new - 1
        else:
            extra = num_new
        if extra > 0 and times.last_token is not None:
            per_token = (now - times.last_token) / extra
            for _ in range(extra):
                self.itl.observe(per_token)
        times.last_token = now
        self.num_generation_tokens += num_new
        self._window_gen_tokens += num_new
        self._maybe_log(now)

    def on_finished(self, times: RequestTimes, num_prompt_tokens: int,
                    now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self.e2e.observe(now - times.arrival)
        self.num_prompt_tokens += num_prompt_tokens
        self.num_finished += 1

    @property
    def slo_enabled(self) -> bool:
        return self.slo_ttft_ms > 0 or self.slo_tpot_ms > 0

    def on_slo(self, times: RequestTimes, num_output_tokens: int,
               tenant: Optional[str] = None) -> None:
        """Score one finished request against the configured SLO
        targets. Only token-producing requests score (an aborted
        request that never emitted is an availability event, not a
        latency one); TPOT needs >= 2 tokens to be defined. A request
        where NO enabled target was evaluable (e.g. only TPOT enabled
        and max_tokens=1) is not scored at all — counting it as good
        would inflate goodput with requests the targets never saw.
        ``tenant`` (an already-bucketed QoS tenant key, or None when
        the QoS plane is off) additionally banks the verdict into the
        per-tenant goodput family."""
        if not self.slo_enabled:
            return
        if times is None or times.first_token is None:
            return
        evaluated = False
        good = True
        if self.slo_ttft_ms > 0:
            evaluated = True
            ttft_ms = (times.first_token - times.arrival) * 1e3
            if ttft_ms > self.slo_ttft_ms:
                self.slo_ttft_misses += 1
                good = False
        if (self.slo_tpot_ms > 0 and num_output_tokens > 1
                and times.last_token is not None):
            evaluated = True
            tpot_ms = ((times.last_token - times.first_token) * 1e3
                       / (num_output_tokens - 1))
            if tpot_ms > self.slo_tpot_ms:
                self.slo_tpot_misses += 1
                good = False
        if not evaluated:
            return
        self.slo_scored += 1
        if good:
            self.slo_good += 1
        if self.burn is not None:
            self.burn.observe(good)
        if tenant is not None:
            bank = self.slo_by_tenant.setdefault(tenant, [0, 0])
            bank[0] += 1
            if good:
                bank[1] += 1

    def _maybe_log(self, now: float) -> None:
        dt = now - self._window_start
        if dt < self.log_interval_s:
            return
        logger.info("engine throughput: %.1f tok/s generation, "
                    "%d finished requests total",
                    self._window_gen_tokens / dt, self.num_finished)
        self._window_start = now
        self._window_gen_tokens = 0

    # ------------------------------------------------------------------
    def render(self, fault_extra: Optional[dict] = None) -> str:
        """Exposition text. ``fault_extra`` merges follower-process
        fault-injection counter snapshots (shipped over the get_stats
        feed and pid-deduped by dp_client) so the
        vdt:fault_injections_total family is fleet-exact instead of
        front-end-process-local."""
        lines = self.ttft.render(
            "vdt:time_to_first_token_seconds",
            "Time from request arrival to first output token")
        lines += self.itl.render(
            "vdt:inter_token_latency_seconds",
            "Latency between consecutive output tokens")
        lines += self.e2e.render(
            "vdt:e2e_request_latency_seconds",
            "Request arrival to completion latency")
        for name, help_text, value in (
            ("vdt:prompt_tokens_total",
             "Cumulative prompt tokens of finished requests",
             self.num_prompt_tokens),
            ("vdt:generation_tokens_total",
             "Cumulative generated output tokens",
             self.num_generation_tokens),
            ("vdt:request_success_total",
             "Cumulative finished requests", self.num_finished),
            ("vdt:engine_restarts_total",
             "Engine-core death/restart events detected by the health "
             "monitor", self.num_engine_deaths),
            ("vdt:requests_replayed_total",
             "Journaled requests resubmitted to a restarted engine core "
             "as continuation prefills", self.num_requests_replayed),
            ("vdt:requests_shed_total",
             "Requests refused at the API admission gate (overload "
             "shed / drain mode)", self.num_requests_shed),
        ):
            lines += [f"# HELP {name} {help_text}",
                      f"# TYPE {name} counter", f"{name} {value}"]
        lines += [
            "# HELP vdt:drain_duration_seconds Duration of the last "
            "SIGTERM graceful drain",
            "# TYPE vdt:drain_duration_seconds gauge",
            f"vdt:drain_duration_seconds {self.drain_duration_seconds}",
        ]
        if self.slo_enabled:
            goodput = self.slo_good / max(self.slo_scored, 1)
            lines += [
                "# HELP vdt:slo_goodput_frac Fraction of scored "
                "requests that met every enabled SLO target "
                "(VDT_SLO_TTFT_MS / VDT_SLO_TPOT_MS)",
                "# TYPE vdt:slo_goodput_frac gauge",
                f"vdt:slo_goodput_frac {round(goodput, 6)}",
                "# HELP vdt:slo_requests_scored_total Finished "
                "token-producing requests scored against the SLO "
                "targets",
                "# TYPE vdt:slo_requests_scored_total counter",
                f"vdt:slo_requests_scored_total {self.slo_scored}",
                "# HELP vdt:slo_ttft_misses_total Requests whose time "
                "to first token exceeded VDT_SLO_TTFT_MS",
                "# TYPE vdt:slo_ttft_misses_total counter",
                f"vdt:slo_ttft_misses_total {self.slo_ttft_misses}",
                "# HELP vdt:slo_tpot_misses_total Requests whose mean "
                "time per output token exceeded VDT_SLO_TPOT_MS",
                "# TYPE vdt:slo_tpot_misses_total counter",
                f"vdt:slo_tpot_misses_total {self.slo_tpot_misses}",
            ]
            if self.slo_by_tenant:
                name = "vdt:tenant_goodput_frac"
                lines += [
                    f"# HELP {name} Fraction of a tenant bucket's "
                    "scored requests that met every enabled SLO target "
                    "(QoS plane; bucketing bounded by "
                    "VDT_QOS_MAX_TRACKED_TENANTS)",
                    f"# TYPE {name} gauge",
                ]
                lines += [
                    f'{name}{{tenant="{t}"}} '
                    f"{round(good / max(scored, 1), 6)}"
                    for t, (scored, good)
                    in sorted(self.slo_by_tenant.items())
                ]
            if self.burn is not None:
                rates = self.burn.burn_rates()
                name = "vdt:slo_burn_rate"
                lines += [
                    f"# HELP {name} SLO error-budget burn rate per "
                    "window (miss fraction / (1 - VDT_SLO_TARGET); "
                    "1.0 = burning exactly at budget)",
                    f"# TYPE {name} gauge",
                ]
                lines += [f'{name}{{window="{w}"}} {round(r, 6)}'
                          for w, r in sorted(rates.items())]
                lines += [
                    "# HELP vdt:slo_degraded 1 when every burn window "
                    "exceeds VDT_SLO_BURN_THRESHOLD (sustained SLO "
                    "burn; also surfaced in /health)",
                    "# TYPE vdt:slo_degraded gauge",
                    f"vdt:slo_degraded {int(self.burn.degraded())}",
                ]
        lines += render_fault_injections(fault_extra)
        return "\n".join(lines) + "\n"


def render_fault_injections(extra: Optional[dict] = None) -> list[str]:
    """Per-fault-point fire counters (empty when no faults configured),
    so robustness drills show up on the same /metrics scrape as their
    effects. ``extra`` ({point: n}) folds in follower-process snapshots
    — the in-process registry only sees THIS process's fires, so
    spawned engine cores' drills were invisible here until PR 19."""
    from vllm_distributed_tpu.utils import fault_injection
    counts = dict(fault_injection.counters())
    for point, n in (extra or {}).items():
        counts[point] = counts.get(point, 0) + int(n)
    if not counts:
        return []
    name = "vdt:fault_injections_total"
    lines = [f"# HELP {name} Injected fault fires per fault point",
             f"# TYPE {name} counter"]
    lines += [f'{name}{{point="{point}"}} {n}'
              for point, n in sorted(counts.items())]
    return lines
