"""Analytic per-dispatch cost model: FLOPs and HBM bytes from arch shapes.

The performance-attribution plane's arithmetic core. Built ONCE at model
load (models/loader.py attaches it to the arch config), it prices every
runner dispatch in model FLOPs and HBM bytes so the engine can report
MFU / MBU / roofline placement from its own counters instead of a
bench-side ``2 * params * tok/s`` guess that ignores attention and KV
traffic entirely (the formula behind the unattributable 0.0068-MFU
record in BENCH_tpu.json).

Accounting conventions (documented in the README assumptions table;
tests/metrics/test_costmodel.py pins them with hand-computed counts):

* **FLOPs are useful model FLOPs** — one multiply-add = 2 FLOPs over
  the real (unpadded) tokens of a wave. Bucket padding, replicated
  TPLA rope-score work and KV-head replicas burn real device cycles
  but count toward the denominator (device time), not the numerator —
  exactly what MFU is supposed to expose.
* **Weights stream once per dispatch** — each forward pass reads every
  resident dense weight once regardless of batch width (the decode
  regime this plane exists for); MoE layers read only the routed
  experts, ``min(tokens * top_k, num_experts)`` per layer.
* **KV bytes are storage bytes** — per-token-position row cost comes
  from the model's own ``kv_cache_page_bytes`` (so fp8 caches, TPU
  lane padding, KV-head replicas and the TPLA per-rank latent slice
  are priced exactly as stored); TPLA multiplies the per-rank row by
  the shard count (each rank reads its disjoint slice plus its own
  rope-sidecar copy). SSM state rows ride the same kv_read/kv_write
  kinds (they are the recurrence's KV analogue).
* **Attention pairs** — the runner sums, over each scheduled token,
  the KV length it attends (``kv_terms``); causal prefill therefore
  charges ``ctx*n + n(n+1)/2`` pairs per request chunk and decode
  ``ctx+1``. A uniform sliding window clamps the span.
* **Peaks are fleet peaks** — per-chip public-spec numbers (shared
  with bench.py) times the mesh's device count; non-TPU backends get
  a nominal host peak so CPU-smoke MFU/MBU stay comparable
  run-to-run (they are not absolute utilization there).
"""

import math
from dataclasses import dataclass, field
from typing import Any, Optional

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

# Peak dense-matmul FLOP/s per chip (public specs, bf16). Single source
# for bench.py and the in-engine plane.
PEAK_FLOPS_PER_CHIP = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}

# Peak HBM bandwidth per chip (public specs, bytes/s) — the decode
# roofline (decode is weight/KV-bandwidth-bound, not FLOP-bound).
PEAK_HBM_PER_CHIP = {
    "v4": 1228e9,
    "v5e": 819e9,
    "v5p": 2765e9,
    "v6e": 1638e9,
}

# device_kind spellings that do not literally contain the generation
# key ("TPU v5 lite" is a v5e).
_KIND_ALIASES = (("v5 lite", "v5e"), ("v5lite", "v5e"), ("v6 lite", "v6e"))

# Nominal peaks for non-TPU backends (CPU smoke): MFU/MBU become
# machine-relative trend numbers, not absolute utilization.
HOST_PEAK_FLOPS = 1e12
HOST_PEAK_HBM = 100e9

# vdt:roofline_bound{phase} gauge encoding (rendered + README-documented).
ROOFLINE_CODES = {"host": 0, "bandwidth": 1, "compute": 2}


def peak_flops_per_chip(device_kind: str, default: str = "v5e") -> float:
    return _lookup_peak(PEAK_FLOPS_PER_CHIP, device_kind, default)


def peak_hbm_per_chip(device_kind: str, default: str = "v5e") -> float:
    return _lookup_peak(PEAK_HBM_PER_CHIP, device_kind, default)


def _lookup_peak(table: dict, device_kind: str, default: str) -> float:
    kind = (device_kind or "").lower()
    for alias, gen in _KIND_ALIASES:
        if alias in kind:
            return table[gen]
    for gen, peak in table.items():
        if gen in kind:
            return peak
    if "cpu" in kind or "host" in kind or not kind:
        return (HOST_PEAK_FLOPS if table is PEAK_FLOPS_PER_CHIP
                else HOST_PEAK_HBM)
    import os
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    return table.get(gen, table[default])


@dataclass(frozen=True)
class WaveCost:
    """Price of one dispatched wave (or one fused multi-step burst)."""
    flops: float = 0.0
    weight_bytes: float = 0.0
    kv_read_bytes: float = 0.0
    kv_write_bytes: float = 0.0
    act_bytes: float = 0.0

    @property
    def total_bytes(self) -> float:
        return (self.weight_bytes + self.kv_read_bytes +
                self.kv_write_bytes + self.act_bytes)


def classify_roofline(phase_entry: dict, peaks: dict,
                      host_factor: float = 1.0) -> str:
    """Place one phase's accumulated (device_seconds, host_seconds,
    flops, bytes) on the roofline: "host" when the host-side share of
    the phase's wall time exceeds the device share (the device is
    starved, not saturated), else "compute" vs "bandwidth" by which
    peak fraction the measured device time is closer to."""
    dev_s = float(phase_entry.get("device_seconds", 0.0))
    if dev_s <= 0.0:
        return "host"
    if float(phase_entry.get("host_seconds", 0.0)) > host_factor * dev_s:
        return "host"
    pf = float(peaks.get("flops", 0.0)) or HOST_PEAK_FLOPS
    pb = float(peaks.get("hbm", 0.0)) or HOST_PEAK_HBM
    flops_frac = float(phase_entry.get("flops", 0.0)) / (dev_s * pf)
    bw_frac = float(phase_entry.get("bytes", 0.0)) / (dev_s * pb)
    return "compute" if flops_frac >= bw_frac else "bandwidth"


@dataclass
class CostModel:
    """Per-dispatch analytic cost constants for one loaded model.

    All per-token constants are whole-model (summed over layers and,
    under TP, over ranks where work is disjoint — sharded matmuls count
    once, which is also what "useful FLOPs" means)."""

    # -- FLOPs ----------------------------------------------------------
    # Projections + MLP/MoE/SSM per token through the whole stack
    # (everything except attention pairs and the LM head).
    linear_flops_per_token: float = 0.0
    # Attention FLOPs per (query token, attended KV position) pair,
    # summed over attention layers: scores + PV.
    attn_flops_per_token_kv: float = 0.0
    # LM-head matmul per sampled row.
    lm_head_flops_per_row: float = 0.0
    # -- HBM bytes ------------------------------------------------------
    # Dense weights (incl. LM head + embeddings) streamed once per
    # forward pass.
    dense_weight_bytes: float = 0.0
    # MoE: bytes of ONE expert's FFN weights at ONE layer, and the
    # routing width, for the min(tokens*topk, E) per-layer read.
    moe_layers: int = 0
    num_experts: int = 0
    experts_per_token: int = 0
    expert_bytes: float = 0.0
    # Paged-KV row cost per token position (all layers, storage bytes;
    # 0 for pure-SSM stacks).
    kv_row_read_bytes: float = 0.0
    kv_row_write_bytes: float = 0.0
    # SSM recurrence state read+write per token (0 for pure attention).
    state_read_bytes_per_token: float = 0.0
    state_write_bytes_per_token: float = 0.0
    # Residual-stream traffic per token + materialized logits per row.
    act_bytes_per_token: float = 0.0
    logits_bytes_per_row: float = 0.0
    # Uniform sliding window (tokens) clamping the attention span, if
    # every layer is windowed; None = full causal.
    attn_window: Optional[int] = None
    # -- peaks ----------------------------------------------------------
    num_chips: int = 1
    peak_flops: float = HOST_PEAK_FLOPS
    peak_hbm: float = HOST_PEAK_HBM
    # Assumption echo for /debug/perf + README cross-checks.
    meta: dict = field(default_factory=dict)

    # ------------------------------------------------------------------
    def wave_cost(self, q_tokens: int, kv_terms: float,
                  sampled_rows: int, passes: int = 1) -> WaveCost:
        """Price one dispatch: ``q_tokens`` scheduled (real) tokens
        attending ``kv_terms`` total KV positions, sampling
        ``sampled_rows`` logits rows, across ``passes`` forward passes
        (1 for a normal wave; the fused multi-step burst streams the
        weights once per in-graph step)."""
        flops = (q_tokens * self.linear_flops_per_token +
                 kv_terms * self.attn_flops_per_token_kv +
                 sampled_rows * self.lm_head_flops_per_row)
        weights = passes * self.dense_weight_bytes
        if self.moe_layers and passes:
            per_pass = max(q_tokens // passes, 1)
            weights += (passes * self.moe_layers *
                        min(per_pass * self.experts_per_token,
                            self.num_experts) * self.expert_bytes)
        kv_read = (kv_terms * self.kv_row_read_bytes +
                   q_tokens * self.state_read_bytes_per_token)
        kv_write = (q_tokens * self.kv_row_write_bytes +
                    q_tokens * self.state_write_bytes_per_token)
        act = (q_tokens * self.act_bytes_per_token +
               sampled_rows * self.logits_bytes_per_row)
        return WaveCost(flops=flops, weight_bytes=weights,
                        kv_read_bytes=kv_read, kv_write_bytes=kv_write,
                        act_bytes=act)

    def clamp_span(self, kv_len: float) -> float:
        """Attention span for one token at KV length ``kv_len`` under
        the model's uniform window (identity when full-causal)."""
        if self.attn_window is not None:
            return min(kv_len, float(self.attn_window))
        return kv_len

    def span_sum(self, ctx: float, n: int) -> float:
        """Total attended KV positions for ``n`` consecutive tokens
        starting at context ``ctx`` (token j attends ctx+j positions,
        window-clamped) — closed form, O(1) regardless of chunk width
        (this runs per request per dispatch on the engine-core
        thread)."""
        if self.attn_window is None:
            return n * ctx + n * (n + 1) / 2
        w = float(self.attn_window)
        # First k tokens still fit under the window, the rest saturate.
        k = max(0.0, min(float(n), w - ctx))
        return k * ctx + k * (k + 1) / 2 + (n - k) * w

    # -- bench helpers --------------------------------------------------
    def decode_flops_per_token(self, ctx: float) -> float:
        """FLOPs one generated token costs at context length ``ctx``
        (attention + projections + LM head) — the honest replacement
        for ``2 * params``."""
        return (self.linear_flops_per_token +
                self.clamp_span(ctx + 1) * self.attn_flops_per_token_kv +
                self.lm_head_flops_per_row)

    def decode_step_bytes(self, batch: int, ctx: float) -> float:
        """HBM bytes one decode step of ``batch`` sequences at context
        ``ctx`` must stream (weights once + per-sequence KV window +
        state + activations)."""
        c = self.wave_cost(batch, batch * self.clamp_span(ctx + 1), batch)
        return c.total_bytes

    def mfu(self, flops: float, device_seconds: float) -> float:
        if device_seconds <= 0:
            return 0.0
        return flops / (device_seconds * self.peak_flops)

    def mbu(self, total_bytes: float, device_seconds: float) -> float:
        if device_seconds <= 0:
            return 0.0
        return total_bytes / (device_seconds * self.peak_hbm)

    # ------------------------------------------------------------------
    @classmethod
    def from_model(cls, model: Any, config: Any,
                   mesh=None) -> "CostModel":
        """Build from a constructed model + engine config (called once
        in models/loader.get_model). Never raises — an arch this
        arithmetic cannot price returns None from the caller's
        perspective via the exception guard there."""
        arch = model.cfg
        page_size = config.cache_config.block_size
        kv_row = 0.0
        try:
            shards = int(getattr(arch, "tpla_shards", 1) or 1)
            kv_row = (model.kv_cache_page_bytes(page_size) / page_size
                      * max(shards, 1))
        except Exception:  # noqa: BLE001 - families without paged KV
            kv_row = 0.0
        n_chips = 1
        device_kind = ""
        try:
            if mesh is not None:
                devices = list(mesh.devices.flat)
                n_chips = len(devices)
                device_kind = getattr(devices[0], "device_kind",
                                      devices[0].platform)
        except Exception:  # noqa: BLE001 - defensive
            pass
        # Hybrid SSM stacks: the model knows its layer kinds.
        attn_layers = None
        if getattr(arch, "stateful", False):
            a = getattr(model, "_attn_layers", None)
            if a is not None:
                attn_layers = len(a)
        return cls.from_arch(arch, kv_row_bytes=kv_row, num_chips=n_chips,
                             device_kind=device_kind,
                             attn_layers=attn_layers)

    @classmethod
    def from_arch(cls, arch: Any, *, kv_row_bytes: float,
                  num_chips: int = 1, device_kind: str = "",
                  attn_layers: Optional[int] = None) -> "CostModel":
        g = lambda k, d=None: getattr(arch, k, d)  # noqa: E731
        H = int(g("hidden_size"))
        L = int(g("num_layers"))
        I = int(g("intermediate_size"))  # noqa: E741
        V = int(g("vocab_size"))
        hd = int(g("head_dim") or H // int(g("num_q_heads", 1)))
        NQ = int(g("num_q_heads", 1))
        NKV = int(g("num_kv_heads", NQ))
        import jax.numpy as jnp
        dtype_bytes = jnp.dtype(g("dtype", jnp.float32)).itemsize
        quant = g("quantization")
        w_bytes = 1 if quant in ("int8", "w8a8", "fp8") else dtype_bytes
        mla = bool(g("mla", False))
        stateful = bool(g("stateful", False))
        gated = bool(g("mlp_gated", True))
        mlp_mults = 3 if gated else 2

        # Layer-kind split: attention layers vs SSM layers; MoE layers
        # vs dense-MLP layers.
        if stateful:
            n_attn = (attn_layers if attn_layers is not None
                      else (0 if kv_row_bytes == 0 else L))
        else:
            n_attn = L
        n_ssm = L - n_attn if stateful else 0
        E = int(g("num_experts", 0) or 0)
        topk = int(g("num_experts_per_tok", 0) or 0)
        # Layers carrying a dense FFN vs routed MoE FFN. Pure-SSM stacks
        # carry no FFN at all (the mamba block is mixer-only); hybrid
        # stacks without MoE keep the FFN on their attention layers.
        moe_layers = 0
        if E:
            dense_head = max(int(g("first_k_dense_replace", 0) or 0),
                             int(g("dense_prefix", 0) or 0))
            dense_mlp_layers = min(dense_head, L)
            moe_layers = L - dense_mlp_layers
        elif stateful:
            dense_mlp_layers = n_attn
        else:
            dense_mlp_layers = L
        Im = int(g("moe_intermediate_size") or I)
        shared_I = int(g("shared_expert_intermediate_size", 0) or 0)

        # -- per-token projection + MLP FLOPs (2 flops per mult-add) ---
        if mla:
            Lkv = int(g("kv_lora_rank"))
            dr = int(g("qk_rope_head_dim"))
            dn = int(g("qk_nope_head_dim"))
            dv = int(g("v_head_dim"))
            qlr = g("q_lora_rank")
            q_proj = ((2 * H * qlr + 2 * qlr * NQ * (dn + dr))
                      if qlr else 2 * H * NQ * (dn + dr))
            attn_proj = (q_proj + 2 * H * (Lkv + dr)  # KV down-proj
                         + 2 * NQ * dn * Lkv          # absorbed q·W_UK
                         + 2 * NQ * Lkv * dv          # out·W_UV
                         + 2 * NQ * dv * H)           # o proj
            # Exact TPLA attention: per-rank latent slices are disjoint
            # and the score psum is counted ONCE; the replicated rope
            # score is useful work once (the TP-1 extra copies are
            # layout overhead, excluded from useful FLOPs).
            attn_pair = 2 * NQ * (Lkv + dr) + 2 * NQ * Lkv
        else:
            Dq = NQ * hd
            Dkv = NKV * hd
            attn_proj = 2 * H * (Dq + 2 * Dkv) + 2 * Dq * H
            attn_pair = 4 * NQ * hd  # QK^T + PV per q head
        mlp_dense = mlp_mults * 2 * H * I
        mlp_moe = 0.0
        if E:
            mlp_moe = (topk * mlp_mults * 2 * H * Im  # routed experts
                       + 2 * H * E)                   # router
            if shared_I:
                mlp_moe += mlp_mults * 2 * H * shared_I + 2 * H
        ssm_per_layer = 0.0
        state_bytes = 0.0
        if stateful:
            Di = int(g("d_inner", 0) or g("intermediate_size"))
            N = int(g("ssm_state_size", 16) or 16)
            K = int(g("conv_kernel", 4) or 4)
            R = int(g("dt_rank", max(H // 16, 1)) or 1)
            ssm_per_layer = (2 * H * 2 * Di        # in_proj (x, gate)
                             + 2 * Di * K          # depthwise conv
                             + 2 * Di * (R + 2 * N)  # x_proj
                             + 2 * R * Di          # dt_proj
                             + 6 * Di * N          # selective scan
                             + 2 * Di * H)         # out_proj
            # fp32 recurrence state (conv tail + ssm state) per token.
            state_bytes = n_ssm * (Di * N + Di * (K - 1)) * 4.0

        linear = (n_attn * attn_proj + n_ssm * ssm_per_layer +
                  dense_mlp_layers * mlp_dense + moe_layers * mlp_moe)

        # -- dense weight bytes streamed once per pass ------------------
        if mla:
            qlr = g("q_lora_rank")
            attn_w = ((H * qlr + qlr * NQ * (dn + dr)) if qlr
                      else H * NQ * (dn + dr))
            attn_w += H * (Lkv + dr) + NQ * dn * Lkv + NQ * Lkv * dv
            attn_w += NQ * dv * H
        else:
            attn_w = H * (NQ * hd + 2 * NKV * hd) + NQ * hd * H
        dense_w = n_attn * attn_w * w_bytes
        dense_w += dense_mlp_layers * mlp_mults * H * I * w_bytes
        if E and shared_I:
            dense_w += moe_layers * (mlp_mults * H * shared_I + H * E
                                     ) * w_bytes
        elif E:
            dense_w += moe_layers * H * E * w_bytes  # router table
        if stateful:
            Di = int(g("d_inner", 0) or g("intermediate_size"))
            N = int(g("ssm_state_size", 16) or 16)
            K = int(g("conv_kernel", 4) or 4)
            R = int(g("dt_rank", max(H // 16, 1)) or 1)
            dense_w += n_ssm * (H * 2 * Di + Di * K +
                                Di * (R + 2 * N) + R * Di +
                                Di * N + Di * H) * w_bytes
        dense_w += 2 * L * H * dtype_bytes  # per-layer norms
        dense_w += V * H * w_bytes          # LM head (read per pass)
        expert_bytes = mlp_mults * H * Im * w_bytes if E else 0.0

        window = None
        wp = g("window_pattern")
        if wp and all(wp) and len(set(wp)) == 1:
            window = int(wp[0])
        elif not wp and g("sliding_window"):
            window = int(g("sliding_window"))

        peak_f = peak_flops_per_chip(device_kind)
        peak_b = peak_hbm_per_chip(device_kind)
        return cls(
            linear_flops_per_token=float(linear),
            attn_flops_per_token_kv=float(n_attn * attn_pair),
            lm_head_flops_per_row=float(2 * H * V),
            dense_weight_bytes=float(dense_w),
            moe_layers=moe_layers,
            num_experts=E,
            experts_per_token=topk,
            expert_bytes=float(expert_bytes),
            kv_row_read_bytes=float(kv_row_bytes),
            kv_row_write_bytes=float(kv_row_bytes),
            state_read_bytes_per_token=float(state_bytes),
            state_write_bytes_per_token=float(state_bytes),
            # Residual stream: 2 reads + 2 writes per layer, plus the
            # embedding row gather feeding layer 0.
            act_bytes_per_token=float(4 * L * H * dtype_bytes +
                                      H * dtype_bytes),
            logits_bytes_per_row=float(V * 4),  # fp32 logits
            attn_window=window,
            num_chips=max(num_chips, 1),
            peak_flops=peak_f * max(num_chips, 1),
            peak_hbm=peak_b * max(num_chips, 1),
            meta={
                "device_kind": device_kind or "host",
                "num_chips": max(num_chips, 1),
                "peak_flops_per_chip": peak_f,
                "peak_hbm_per_chip": peak_b,
                "mla": mla,
                "stateful": stateful,
                "moe_layers": moe_layers,
                "attn_window": window,
                "weight_dtype_bytes": w_bytes,
                "kv_row_bytes": float(kv_row_bytes),
            },
        )

    @classmethod
    def from_hf_dims(cls, hf: dict, *, dtype_bytes: int = 2,
                     device_kind: str = "", num_chips: int = 1,
                     kv_cache_dtype_bytes: Optional[int] = None,
                     page_padded_head_dim: Optional[int] = None,
                     ) -> "CostModel":
        """bench.py entry: price the bench model straight from HF dims
        (no engine needed), mirroring the llama storage layout."""
        H = hf["hidden_size"]
        hd = hf.get("head_dim") or H // hf["num_attention_heads"]
        shd = page_padded_head_dim or hd

        class _Arch:
            pass

        a = _Arch()
        a.hidden_size = H
        a.num_layers = hf["num_hidden_layers"]
        a.intermediate_size = hf["intermediate_size"]
        a.vocab_size = hf["vocab_size"]
        a.head_dim = hd
        a.num_q_heads = hf["num_attention_heads"]
        a.num_kv_heads = hf.get("num_key_value_heads",
                                hf["num_attention_heads"])
        a.dtype = {2: "bfloat16", 4: "float32"}.get(dtype_bytes,
                                                    "float32")
        kv_bytes = kv_cache_dtype_bytes or dtype_bytes
        kv_row = (2 * a.num_layers * a.num_kv_heads * shd * kv_bytes)
        return cls.from_arch(a, kv_row_bytes=kv_row,
                             num_chips=num_chips,
                             device_kind=device_kind)


def resolve_cost_model(model: Any, config: Any, mesh=None
                       ) -> Optional[CostModel]:
    """Loader hook: build the model's cost model once, honoring the
    VDT_PERF_ATTRIB kill switch. Returns None (plane fully off, zero
    per-step work) when disabled or the arch cannot be priced."""
    from vllm_distributed_tpu import envs
    if not envs.VDT_PERF_ATTRIB:
        return None
    try:
        cm = CostModel.from_model(model, config, mesh=mesh)
    except Exception as e:  # noqa: BLE001 - observability must not
        # take serving down; an unpriceable arch just goes unmetered.
        logger.warning("perf-attribution cost model unavailable for "
                       "this arch (%s); MFU/MBU unmetered", e)
        return None
    if not math.isfinite(cm.linear_flops_per_token):
        return None
    logger.info(
        "perf attribution: %.3f GFLOP/token linear, %.1f kFLOP/tok/kv "
        "attention, %.1f MB weight stream, %.1f B/pos KV row, peak "
        "%.1f TFLOP/s x %d chip(s)",
        cm.linear_flops_per_token / 1e9,
        cm.attn_flops_per_token_kv / 1e3,
        cm.dense_weight_bytes / 1e6, cm.kv_row_read_bytes,
        cm.peak_flops / 1e12 / cm.num_chips, cm.num_chips)
    return cm
