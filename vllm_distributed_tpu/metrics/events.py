"""Request-lifecycle event timeline.

The reference records one flat span per finished request
(vllm/tracing.py SpanAttributes) — enough for dashboards, useless for
answering "where did this request's 4 seconds go" across queue ->
KV-pull -> prefill -> preemption -> decode -> replay. This module is the
shared recording substrate for that question:

* ``EventRecorder`` — a bounded, lock-light ring buffer of
  ``(monotonic_ts, request_id, event, detail)`` tuples. Each component
  (scheduler, engine core, output processor) owns its own recorder, so
  the hot paths never contend on a global lock; buffers are drained on
  ``get_stats`` and ship over the existing stats RPC (DP-merged like the
  step-gap histograms).
* per-request event lists — the scheduler accumulates a request's
  lifecycle transitions on the ``Request`` itself and attaches them to
  the next ``EngineCoreOutput`` for that request, so the front-end's
  ``OutputProcessor`` can stitch them (plus its own arrival/first-token/
  replay events) into one parent span with child phase spans.
* ``phases_from_timeline`` — turns a request's merged event timeline
  into phase intervals (queue, kv_pull, prefill, decode, stalls).

Recording is on by default and costs one list-append per lifecycle
TRANSITION (not per token/step); ``VDT_REQUEST_TIMELINE=0`` disables it
globally (the bench harness runs both legs to bound the overhead).
"""

import threading
import time
from typing import Any, Optional

# Lifecycle event names (one vocabulary across all components).
ARRIVED = "arrived"  # front-end accepted the request
QUEUED = "queued"  # entered the scheduler's waiting queue
SCHEDULED = "scheduled"  # first tokens granted (prefill start)
PREFILL_CHUNK = "prefill_chunk"  # chunked-prefill progress
FIRST_TOKEN = "first_token"  # first output token reached the front-end
KV_PULL_WAIT = "kv_pull_wait"  # entered WAITING_FOR_REMOTE_KVS
KV_PULL_DONE = "kv_pull_done"  # async pull landed; back in the queue
KV_PULL_RETRY = "kv_pull_retry"  # failed pull re-staged
KV_PULL_TIMEOUT = "kv_pull_timeout"  # watchdog swept the hold
KV_PULL_LOCAL = "kv_pull_local_fallback"  # degraded to local recompute
PREEMPTED = "preempted"
RESUMED = "resumed"
SPEC_GRANT = "spec_grant"  # entered async run-ahead mode (first grant)
BATCH_DISPATCH = "batch_dispatch"  # engine-core batch in flight (rid="")
BATCH_RETIRE = "batch_retire"  # engine-core batch retired (rid="")
ENGINE_DEATH = "engine_death"  # core died with this request in flight
JOURNAL_REPLAY = "journal_replay"  # replayed as a continuation prefill
SHED = "shed"  # refused at the admission gate (rid="")
FINISHED = "finished"
ABORTED = "aborted"
# Elastic-fleet control-loop actions (engine/fleet.py; all rid="").
FLEET_SCALE_OUT = "fleet_scale_out"  # replica entered rotation
FLEET_SCALE_IN = "fleet_scale_in"  # replica drained and retired
FLEET_RESPLIT = "fleet_resplit"  # replica converted between pools
FLEET_WEDGE_CYCLE = "fleet_wedge_cycle"  # stuck replica force-cycled
FLEET_FREEZE = "fleet_freeze"  # actuation skipped (stale/budget/...)
# HA control plane (engine/control_plane.py; all rid="").
FLEET_LEADER_TAKEOVER = "fleet_leader_takeover"  # lease acquired
FLEET_FENCED = "fleet_fenced"  # stale-epoch actuation rejected
FLEET_JOURNAL_REPLAY = "fleet_journal_replay"  # successor resumed a
# half-done drain from the actuation journal
FLEET_CONTROLLER_DOWN = "fleet_controller_down"  # controller died
# (fleet.controller_die drill) — standbys take over within the TTL
# Trace-plane additions (front-end + scheduler; PR 19).
ROUTER_PICK = "router_pick"  # front-end placement decision (replica/pool)
DISAGG_HANDOFF = "disagg_handoff"  # prefill producer re-admitted the
# request to its decode home (the consumer's kv_pull span links back)
KV_TIER_PROMOTE = "kv_tier_promote"  # spill-tier pages scattered back
KV_TIER_DEMOTE = "kv_tier_demote"  # evicted pages demoted to a tier
# (page-level batch; rid="")
# Correctness sentinel (correctness_plane.py; both rid="" — the
# detail map carries the replica and the divergence cause).
CANARY_DIVERGENCE = "canary_divergence"  # canary probe strayed from
# the reference journal / the cross-replica vote
FLEET_QUARANTINE = "fleet_quarantine"  # suspect replica force-cycled
# on the sentinel's quarantine hint (VDT_FLEET_SIGNALS)

# Canonical event registry: every name recordable via
# ``EventRecorder.record`` with a one-line operator-facing doc.
# scripts/lint_events.py enforces that each module-level event constant
# above appears here AND as a backticked row in the README event table
# (the lint_metrics contract, applied to trace span types) — an event
# name that drifts undocumented fails tier-1.
EVENT_REGISTRY: dict[str, str] = {
    ARRIVED: "front-end accepted the request",
    QUEUED: "entered the scheduler's waiting queue",
    SCHEDULED: "first tokens granted (prefill start)",
    PREFILL_CHUNK: "chunked-prefill progress",
    FIRST_TOKEN: "first output token reached the front-end",
    KV_PULL_WAIT: "entered WAITING_FOR_REMOTE_KVS",
    KV_PULL_DONE: "async pull landed; back in the queue",
    KV_PULL_RETRY: "failed pull re-staged",
    KV_PULL_TIMEOUT: "watchdog swept the hold",
    KV_PULL_LOCAL: "degraded to local recompute",
    PREEMPTED: "pages reclaimed; request parked",
    RESUMED: "preempted request granted again",
    SPEC_GRANT: "entered async run-ahead mode (first grant)",
    BATCH_DISPATCH: "engine-core batch in flight (rid=\"\")",
    BATCH_RETIRE: "engine-core batch retired (rid=\"\")",
    ENGINE_DEATH: "core died with this request in flight",
    JOURNAL_REPLAY: "replayed as a continuation prefill",
    SHED: "refused at the admission gate (rid=\"\")",
    FINISHED: "request completed",
    ABORTED: "request aborted",
    FLEET_SCALE_OUT: "replica entered rotation",
    FLEET_SCALE_IN: "replica drained and retired",
    FLEET_RESPLIT: "replica converted between pools",
    FLEET_WEDGE_CYCLE: "stuck replica force-cycled",
    FLEET_FREEZE: "actuation skipped (stale/budget/...)",
    FLEET_LEADER_TAKEOVER: "lease acquired by this controller",
    FLEET_FENCED: "stale-epoch actuation rejected",
    FLEET_JOURNAL_REPLAY: "successor resumed a journaled action",
    FLEET_CONTROLLER_DOWN: "controller died; standbys take over",
    ROUTER_PICK: "front-end placement decision (replica/pool)",
    DISAGG_HANDOFF: "prefill producer handed the request to decode",
    KV_TIER_PROMOTE: "spill-tier pages scattered back to HBM",
    KV_TIER_DEMOTE: "evicted pages demoted to a spill tier (rid=\"\")",
    CANARY_DIVERGENCE: "canary probe strayed from reference/vote",
    FLEET_QUARANTINE: "suspect replica force-cycled on sentinel hint",
}


def timeline_enabled() -> bool:
    """Read once per recorder (NOT per event): the envs registry
    re-evaluates os.getenv on every attribute access. The trace plane
    rides the event stream, so VDT_TRACE_PLANE=1 implies recording even
    if the operator disabled the plain timeline."""
    from vllm_distributed_tpu import envs
    return envs.VDT_REQUEST_TIMELINE or envs.VDT_TRACE_PLANE


def trace_plane_enabled() -> bool:
    """Read once per component at construction (same discipline as
    timeline_enabled): the distributed trace plane's master switch."""
    from vllm_distributed_tpu import envs
    return envs.VDT_TRACE_PLANE


# Reserved detail keys the trace plane merges into event details.
# Compact on purpose: every stamped event carries them over the stats
# wire. "tr" = trace id (hex), "rep" = DP replica index the event was
# drained from (stamped by the front-end aggregator, pid of the
# Perfetto export), "co" = monotonic clock offset already applied.
TRACE_KEY = "tr"
REPLICA_KEY = "rep"


def stamp_trace(detail: Optional[dict],
                trace_ctx: Optional[dict]) -> Optional[dict]:
    """Merge the compact trace id into an event detail dict. Returns
    ``detail`` untouched (possibly None) when there is no trace context
    — the stamped path allocates a fresh dict so callers may share
    detail literals."""
    if not trace_ctx:
        return detail
    tid = trace_ctx.get("trace_id")
    if not tid:
        return detail
    d = dict(detail) if detail else {}
    d[TRACE_KEY] = tid
    return d


class EventRecorder:
    """Bounded ring buffer of lifecycle events for one component.

    ``record`` is the hot call: one tuple append under a lock (appends
    are rare — lifecycle transitions, not tokens). ``drain`` hands the
    buffered events to the stats RPC and clears; ``snapshot`` reads
    without clearing (debug endpoints). Overflow drops the OLDEST
    events — forensics care about the recent past.
    """

    def __init__(self, maxlen: int = 4096,
                 enabled: Optional[bool] = None) -> None:
        from collections import deque
        self.maxlen = maxlen
        self.enabled = (timeline_enabled()
                        if enabled is None else enabled)
        self._lock = threading.Lock()
        # deque(maxlen) drops the oldest in O(1); a plain list would
        # memmove the whole ring per append once full (which an
        # unpolled recorder permanently is).
        self._events: "deque[tuple]" = deque(maxlen=maxlen)
        self.num_dropped = 0

    def record(self, request_id: str, event: str,
               detail: Optional[dict] = None,
               ts: Optional[float] = None) -> None:
        if not self.enabled:
            return
        entry = (time.monotonic() if ts is None else ts,
                 request_id, event, detail)
        with self._lock:
            if len(self._events) == self.maxlen:
                self.num_dropped += 1
            self._events.append(entry)

    def drain(self) -> list[list]:
        """Take (and clear) the buffered events in wire shape:
        ``[ts, request_id, event, detail]`` lists (msgpack-friendly)."""
        with self._lock:
            events = list(self._events)
            self._events.clear()
        return [[ts, rid, ev, detail] for ts, rid, ev, detail in events]

    def absorb(self, events: list) -> None:
        """Retain wire-shape events drained from ANOTHER recorder (the
        core-side rings ship over the stats RPC; the front end keeps
        them here so /debug/engine's recent-events view covers the
        scheduler/engine stream, not just front-end events)."""
        if not events:
            return
        with self._lock:
            for e in events:
                if len(self._events) == self.maxlen:
                    self.num_dropped += 1
                self._events.append(tuple(e))

    def snapshot(self, limit: int = 256) -> list[list]:
        """Most recent events without clearing (debug endpoints)."""
        with self._lock:
            events = list(self._events)[-limit:]
        return [[ts, rid, ev, detail] for ts, rid, ev, detail in events]

    def __len__(self) -> int:
        return len(self._events)


# ---------------------------------------------------------------------------
# Phase stitching: merged event timeline -> phase intervals
# ---------------------------------------------------------------------------

# Backward jump (seconds) in an arrival-ordered timeline past which the
# clock is treated as a fresh monotonic epoch (restarted engine core /
# another host) rather than cross-recorder jitter. Jitter between the
# front-end and core recorders is sub-second; an epoch reset jumps back
# by the old core's whole uptime.
EPOCH_RESET_S = 30.0


def rebase_epochs(timeline: list,
                  threshold: float = EPOCH_RESET_S) -> list:
    """Re-base timestamps across monotonic-epoch resets.

    ``timeline`` is one request's events in ARRIVAL order (``(ts, ...)``
    tuples or wire-shape lists). Events absorbed from a restarted engine
    core carry a fresh monotonic epoch: their timestamps jump backward
    by the dead core's uptime, so sorting by ts misorders the lifecycle
    and phase math goes negative. A backward jump beyond ``threshold``
    is treated as an epoch reset: the offending event and everything
    after it in the same epoch shift forward to continue just past the
    latest re-based timestamp. Sane timelines pass through unchanged
    (identity for jitter under the threshold); multiple resets (restart
    storms) accumulate. Element shape (tuple vs list) is preserved.
    """
    if not timeline:
        return timeline
    out: list = []
    offset = 0.0
    high: Optional[float] = None
    for entry in timeline:
        ts = entry[0]
        if high is not None and ts + offset < high - threshold:
            offset = high - ts + 1e-6
        rebased = ts + offset
        if high is None or rebased > high:
            high = rebased
        rest = entry[1:]
        out.append([rebased, *rest] if isinstance(entry, list)
                   else (rebased, *rest))
    return out


def _first(timeline: list[tuple], *names: str) -> Optional[tuple]:
    for entry in timeline:
        if entry[1] in names:
            return entry
    return None


def phases_from_timeline(timeline: list[tuple],
                         now: Optional[float] = None) -> list[dict]:
    """Phase intervals from one request's merged timeline of
    ``(ts, event, detail)`` tuples (sorted by ts by the caller):

    * ``queue``   — arrival to the first grant (or kv-pull hold),
    * ``kv_pull`` — each WAITING_FOR_REMOTE_KVS hold,
    * ``prefill`` — first grant to the first output token,
    * ``decode``  — first output token to finish,
    * ``stall``   — each preemption hold and each engine-death ->
      journal-replay window.

    Open-ended phases (request still live) end at ``now``. Returns
    ``[{"phase", "start", "end"}...]`` in monotonic-clock seconds.
    """
    now = time.monotonic() if now is None else now
    phases: list[dict] = []

    def add(phase: str, start: float, end: float) -> None:
        if end >= start:
            phases.append({"phase": phase, "start": start, "end": end})

    arrived = _first(timeline, ARRIVED, QUEUED)
    granted = _first(timeline, SCHEDULED)
    first_tok = _first(timeline, FIRST_TOKEN)
    done = _first(timeline, FINISHED, ABORTED)
    end_ts = done[0] if done else now

    if arrived:
        queue_end = min(
            (e[0] for e in (granted, _first(timeline, KV_PULL_WAIT))
             if e is not None), default=end_ts)
        add("queue", arrived[0], queue_end)

    # KV-pull holds (possibly several across retries).
    hold_start: Optional[float] = None
    for ts, ev, _detail in timeline:
        if ev == KV_PULL_WAIT and hold_start is None:
            hold_start = ts
        elif hold_start is not None and ev in (
                KV_PULL_DONE, KV_PULL_TIMEOUT, KV_PULL_LOCAL,
                KV_PULL_RETRY, FINISHED, ABORTED):
            add("kv_pull", hold_start, ts)
            hold_start = None
    if hold_start is not None:
        add("kv_pull", hold_start, end_ts)

    if granted:
        add("prefill", granted[0], first_tok[0] if first_tok else end_ts)
    if first_tok:
        add("decode", first_tok[0], end_ts)

    # Stalls: preemption holds and engine-death -> replay windows.
    stall_start: Optional[float] = None
    for ts, ev, _detail in timeline:
        if ev in (PREEMPTED, ENGINE_DEATH) and stall_start is None:
            stall_start = ts
        elif stall_start is not None and ev in (RESUMED, JOURNAL_REPLAY,
                                                SCHEDULED, FINISHED,
                                                ABORTED):
            add("stall", stall_start, ts)
            stall_start = None
    if stall_start is not None:
        add("stall", stall_start, end_ts)
    return phases


def phase_durations(phases: list[dict]) -> dict[str, float]:
    """Total seconds per phase name (stall windows sum)."""
    out: dict[str, float] = {}
    for p in phases:
        out[p["phase"]] = (out.get(p["phase"], 0.0)
                           + p["end"] - p["start"])
    return out


def current_phase(timeline: list[tuple]) -> Optional[str]:
    """Best-effort current phase of a LIVE request (debug endpoints):
    the last lifecycle transition wins. Grant events after the first
    output token map to "decode", not "prefill" — a preempted-then-
    resumed (or replayed) decode request is still decoding from the
    operator's viewpoint, matching phases_from_timeline's accounting
    (the hold itself is a stall; decode runs first_token -> finish).
    An EMPTY timeline (VDT_REQUEST_TIMELINE=0) returns None — "no
    timeline" must not read as a server full of queued requests."""
    if not timeline:
        return None
    phase = "queued"
    decoding = False
    for _ts, ev, _detail in timeline:
        if ev in (ARRIVED, QUEUED):
            phase = "queued"
        elif ev == KV_PULL_WAIT:
            phase = "kv_pull"
        elif ev in (SCHEDULED, PREFILL_CHUNK, KV_PULL_DONE, RESUMED,
                    JOURNAL_REPLAY):
            phase = "decode" if decoding else "prefill"
        elif ev == FIRST_TOKEN:
            decoding = True
            phase = "decode"
        elif ev == PREEMPTED:
            phase = "preempted"
        elif ev == ENGINE_DEATH:
            phase = "replaying"
        elif ev == FINISHED:
            phase = "finished"
        elif ev == ABORTED:
            phase = "aborted"
    return phase


def merge_event_lists(*lists: Any) -> list[list]:
    """Merge drained event lists (e.g. per-DP-replica) by timestamp."""
    merged: list[list] = []
    for events in lists:
        if events:
            merged.extend(events)
    merged.sort(key=lambda e: e[0])
    return merged
