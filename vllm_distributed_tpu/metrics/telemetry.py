"""Cluster telemetry plane: worker identity + transport-level recorders.

The request-lifecycle timeline (metrics/events.py) makes the REQUEST
legible; this module makes the layers the paper actually scales legible —
the TPU device, the collectives/transports, and the paged KV cache.
EQuARX (PAPERS.md) shows collective cost is a first-order term in
distributed serving, and the disaggregated-prefill KV path we already
run (dcn_pull / shared_storage / p2p) was entirely dark: no bytes, no
latency, no inflight count.

Three pieces, all flowing up the EXISTING ``get_stats`` RPC (no new
channel):

* ``worker_label`` — one stable identity string per worker
  (``dp<rank>-h<host>``), stamped at the SOURCE so per-worker stats
  survive executor fan-in and DP merge without re-keying (merging is a
  dict union; counters can never double-count because every worker's
  key is unique fleet-wide).
* ``TransportRecorder`` — a lock-guarded, process-local recorder of
  per-connector transfer bytes/latency/inflight and shm-ring
  wait/lag. Each engine core owns ONE recorder (installed around its
  construction via ``install_recorder`` so the connectors and message
  queues built inside capture it) — in-process DP replicas therefore
  record into DISJOINT recorders and the DP merge can sum per label.
* ``device_memory_stats`` — the jax device memory high-water mark
  (weights + workspace + KV), read per stats poll, never on the hot
  path.

Kill switches: ``VDT_TRANSPORT_TELEMETRY=0`` stops all transport
recording (checked per record — the bench harness toggles it between
legs); ``VDT_DEVICE_TELEMETRY=0`` disables the device-memory reads and
the runner's device-wait timer (read once per runner).
"""

import threading
import time
from typing import Callable, Optional

from vllm_distributed_tpu.metrics.stats import (Histogram,
                                                merge_histogram_dicts)

# One KV-page transfer (socket pull, file load, device scatter chunk):
# sub-millisecond for a local file hit up to minutes for a cross-DC pull
# riding a congested DCN.
TRANSFER_SECONDS_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                            0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                            30.0, 60.0, 120.0)
# shm ring enqueue/dequeue wait: nanoseconds when the slot is free /
# a message is waiting, up to the full handshake timeout when a reader
# stalls or the writer laps.
SHM_WAIT_BUCKETS = (0.000001, 0.000005, 0.00001, 0.00005, 0.0001,
                    0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                    5.0, 30.0)

_DIRECTIONS = ("tx", "rx")


def worker_label(parallel_config) -> str:
    """Fleet-unique worker identity for telemetry labels: DP replica
    rank + host rank (the two axes along which workers multiply)."""
    return (f"dp{parallel_config.data_parallel_rank}"
            f"-h{parallel_config.host_rank}")


def device_telemetry_enabled() -> bool:
    from vllm_distributed_tpu import envs
    return envs.VDT_DEVICE_TELEMETRY


def device_memory_stats(mesh) -> dict:
    """Device HBM telemetry from the mesh's first device (SPMD: one
    process sees the whole slice; per-chip skew is an XLA bug, not an
    ops signal). Empty on platforms without memory stats (CPU tests)."""
    try:
        dev = next(iter(mesh.devices.flat))
        stats = dev.memory_stats() or {}
    except Exception:  # pragma: no cover - platform specific
        return {}
    out = {}
    if stats.get("peak_bytes_in_use"):
        out["device_memory_peak_bytes"] = int(stats["peak_bytes_in_use"])
    if stats.get("bytes_in_use"):
        out["device_memory_in_use_bytes"] = int(stats["bytes_in_use"])
    return out


class TransportRecorder:
    """Per-engine-core transport stats (see module docstring).

    Thread-safe: connector pull threads, shm reader threads and the
    stats RPC all touch it. ``enabled`` consults the env per record
    unless forced — the bench harness flips VDT_TRANSPORT_TELEMETRY
    between legs of one process."""

    def __init__(self, enabled: Optional[bool] = None) -> None:
        self._forced = enabled
        self._lock = threading.Lock()
        # connector -> {tx_bytes, rx_bytes, failures, inflight, seconds}
        self._kv: dict[str, dict] = {}
        # side ("write"/"read") -> {messages, wait_seconds}
        self._shm: dict[str, dict] = {}
        # Reader backlog (writer_seq - reader_seq) at the last dequeue.
        self._shm_lag = 0
        # Quantized-communication plane: per-path exact payload savings
        # and raw-precision fallbacks (path = connector label; the
        # in-graph tknp/ep/tp paths count through
        # parallel/collectives.py instead — unreachable per step inside
        # jit — and merge at render time).
        self._qcomm: dict[str, dict] = {}

    @property
    def enabled(self) -> bool:
        if self._forced is not None:
            return self._forced
        from vllm_distributed_tpu import envs
        return envs.VDT_TRANSPORT_TELEMETRY

    # -- KV-transfer connectors ----------------------------------------
    def _conn(self, connector: str) -> dict:
        entry = self._kv.get(connector)
        if entry is None:
            entry = {"tx_bytes": 0, "rx_bytes": 0, "failures": 0,
                     "inflight": 0,
                     "seconds": Histogram(TRANSFER_SECONDS_BUCKETS)}
            self._kv[connector] = entry
        return entry

    def record_transfer(self, connector: str, direction: str,
                        num_bytes: int,
                        seconds: Optional[float] = None) -> None:
        assert direction in _DIRECTIONS, direction
        if not self.enabled:
            return
        with self._lock:
            entry = self._conn(connector)
            entry[f"{direction}_bytes"] += int(num_bytes)
            if seconds is not None:
                entry["seconds"].observe(seconds)

    def record_failure(self, connector: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._conn(connector)["failures"] += 1

    def adjust_inflight(self, connector: str, delta: int) -> None:
        # Deliberately NOT gated on ``enabled``: the flag is checked
        # per record and may flip between a transfer's +1 and its
        # finally-block -1 (the bench harness flips it between legs) —
        # a gated -1 would no-op and wedge the gauge nonzero forever.
        # One lock+dict op per transfer (not per byte) is negligible.
        with self._lock:
            entry = self._conn(connector)
            entry["inflight"] = max(entry["inflight"] + delta, 0)

    # -- quantized communication plane ---------------------------------
    def _qcomm_entry(self, path: str) -> dict:
        entry = self._qcomm.get(path)
        if entry is None:
            entry = {"bytes_saved": 0, "fallbacks": 0}
            self._qcomm[path] = entry
        return entry

    def record_qcomm(self, path: str, bytes_saved: int) -> None:
        """Exact wire/disk bytes a quantized payload saved vs its raw
        form. Credited where the OUTCOME is known: the consumer after a
        successful wire decode (dcn_pull/p2p — a degraded pull must
        never count), the writer for storage artifacts (a write either
        lands or raises)."""
        if not self.enabled:
            return
        with self._lock:
            self._qcomm_entry(path)["bytes_saved"] += max(
                int(bytes_saved), 0)

    def record_qcomm_fallback(self, path: str) -> None:
        """A quantized payload failed validation and the raw-precision
        form was (re)requested instead."""
        if not self.enabled:
            return
        with self._lock:
            self._qcomm_entry(path)["fallbacks"] += 1

    # -- shm broadcast ring --------------------------------------------
    def record_shm(self, side: str, wait_s: float,
                   lag: Optional[int] = None) -> None:
        if not self.enabled:
            return
        with self._lock:
            entry = self._shm.get(side)
            if entry is None:
                entry = {"messages": 0,
                         "wait_seconds": Histogram(SHM_WAIT_BUCKETS)}
                self._shm[side] = entry
            entry["messages"] += 1
            entry["wait_seconds"].observe(wait_s)
            if lag is not None:
                self._shm_lag = int(lag)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        """Serializable (msgpack-clean) snapshot for the stats RPC."""
        with self._lock:
            kv = {
                conn: {"tx_bytes": e["tx_bytes"],
                       "rx_bytes": e["rx_bytes"],
                       "failures": e["failures"],
                       "inflight": e["inflight"],
                       "seconds": e["seconds"].to_dict()}
                for conn, e in self._kv.items()
            }
            shm = {
                side: {"messages": e["messages"],
                       "wait_seconds": e["wait_seconds"].to_dict()}
                for side, e in self._shm.items()
            }
            return {"kv": kv, "shm": shm,
                    "shm_lag_chunks": self._shm_lag,
                    "qcomm": {path: dict(e)
                              for path, e in self._qcomm.items()}}


# Process default (standalone tools, follower processes, tests);
# engine cores install their own so in-process DP replicas never share
# one registry (shared totals would double-count under the DP sum).
recorder = TransportRecorder()
_current = recorder
_install_lock = threading.Lock()


def current_recorder() -> TransportRecorder:
    return _current


def install_recorder(rec: TransportRecorder) -> Callable[[], None]:
    """Point ``current_recorder`` at ``rec`` for the duration of an
    engine core's construction (the connectors / message queues built
    inside capture it); returns the restore callable. Serialized —
    cores are constructed sequentially even with in-process DP."""
    global _current
    _install_lock.acquire()
    prev, _current = _current, rec

    def restore() -> None:
        global _current
        _current = prev
        _install_lock.release()

    return restore


def now() -> float:
    return time.perf_counter()


# ---------------------------------------------------------------------------
# Follower-process stats export (closes the PR 5 named gap: the shm
# ring's READ side lives in multi-host follower processes that have no
# stats RPC — they publish snapshots to a shared directory and host 0's
# executor folds them into the standard worker/transport merges, so
# vdt:shm_ring_*{side="read"} and follower device telemetry reach
# /metrics like any DP leg).
# ---------------------------------------------------------------------------

def publish_follower_stats(stats_dir: str, host_rank: int,
                           worker) -> Optional[str]:
    """Atomically write one follower's telemetry snapshot (its labeled
    worker map + its process recorder, which captured the shm-ring
    dequeues) to ``stats_dir``. tmp+rename so host 0 never reads a
    torn file; one file per host rank so republishing overwrites in
    place."""
    import json
    import os
    if not stats_dir:
        return None
    stats = worker.get_stats() if worker is not None else {}
    payload = {
        "host_rank": int(host_rank),
        "workers": stats.get("workers") or {},
        "transport": current_recorder().snapshot(),
    }
    os.makedirs(stats_dir, exist_ok=True)
    path = os.path.join(stats_dir, f"follower-h{host_rank}.json")
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def collect_follower_stats(stats_dir: str) -> list:
    """Read every published follower snapshot under ``stats_dir``
    (empty list when the export is off or nothing published yet);
    unreadable/torn files are skipped, never fatal to a stats poll."""
    import glob
    import json
    import os
    if not stats_dir or not os.path.isdir(stats_dir):
        return []
    out = []
    for path in sorted(glob.glob(os.path.join(stats_dir,
                                              "follower-h*.json"))):
        try:
            with open(path, encoding="utf-8") as f:
                snap = json.load(f)
        except Exception:  # noqa: BLE001 - mid-write/corrupt file
            continue
        if isinstance(snap, dict):
            out.append(snap)
    return out


# ---------------------------------------------------------------------------
# DP-merge helpers (labels preserved; counters summed exactly once)
# ---------------------------------------------------------------------------

def merge_worker_telemetry(maps: list) -> dict:
    """Union of per-replica ``{worker_label: stats}`` maps. Labels are
    fleet-unique by construction (dp rank + host rank), so a plain
    union preserves every worker's series without summing anything
    twice; a pathological collision keeps the first seen."""
    merged: dict = {}
    for m in maps:
        if not isinstance(m, dict):
            continue
        for worker, stats in m.items():
            if worker not in merged:
                merged[worker] = stats
    return merged


def merge_kv_cache_stats(maps: list) -> Optional[dict]:
    """Fleet view of per-replica block-pool telemetry: page counts and
    window tallies sum (each replica owns a disjoint pool), ratio
    gauges recompute from the summed tallies — an unweighted average
    would let idle replicas' zeros dilute the fleet hit rate — and the
    preemption-cause tallies merge by summed cause."""
    maps = [m for m in maps if isinstance(m, dict)]
    if not maps:
        return None
    merged: dict = {}
    causes: dict = {}
    frag_weighted = 0.0
    for m in maps:
        for cause, n in (m.get("preemption_causes") or {}).items():
            causes[cause] = causes.get(cause, 0) + int(n)
        # Weight each replica's fragmentation by the pages it holds
        # (the exact fleet figure; an empty replica contributes 0/0).
        frag_weighted += (float(m.get("fragmentation_frac", 0.0))
                          * m.get("held_blocks", 0))
        for k, v in m.items():
            if k in ("preemption_causes", "fragmentation_frac",
                     "window_hit_rate") or not isinstance(
                         v, (int, float)):
                continue
            merged[k] = merged.get(k, 0) + v
    held = merged.get("held_blocks", 0)
    merged["fragmentation_frac"] = (frag_weighted / held
                                    if held else 0.0)
    wq = merged.get("window_queries", 0)
    merged["window_hit_rate"] = (merged.get("window_hits", 0) / wq
                                 if wq else 0.0)
    merged["preemption_causes"] = causes
    return merged


def merge_transport_snapshots(snaps: list) -> Optional[dict]:
    """Element-wise merge of per-replica TransportRecorder snapshots.
    Connector/side labels are preserved; numeric leaves sum (each
    replica's recorder is disjoint, so the sum is exact) and latency
    histograms merge bucket-wise."""
    snaps = [s for s in snaps if isinstance(s, dict)]
    if not snaps:
        return None
    kv: dict = {}
    shm: dict = {}
    qcomm: dict = {}
    lag = 0
    for snap in snaps:
        for path, e in (snap.get("qcomm") or {}).items():
            tgt = qcomm.setdefault(path, {"bytes_saved": 0,
                                          "fallbacks": 0})
            tgt["bytes_saved"] += int(e.get("bytes_saved", 0))
            tgt["fallbacks"] += int(e.get("fallbacks", 0))
        for conn, e in (snap.get("kv") or {}).items():
            tgt = kv.setdefault(conn, {"tx_bytes": 0, "rx_bytes": 0,
                                       "failures": 0, "inflight": 0,
                                       "seconds": None})
            for k in ("tx_bytes", "rx_bytes", "failures", "inflight"):
                tgt[k] += int(e.get(k, 0))
            merged = merge_histogram_dicts(
                [tgt["seconds"], e.get("seconds")])
            if merged is not None:
                tgt["seconds"] = merged
        for side, e in (snap.get("shm") or {}).items():
            tgt = shm.setdefault(side, {"messages": 0,
                                        "wait_seconds": None})
            tgt["messages"] += int(e.get("messages", 0))
            merged = merge_histogram_dicts(
                [tgt["wait_seconds"], e.get("wait_seconds")])
            if merged is not None:
                tgt["wait_seconds"] = merged
        lag = max(lag, int(snap.get("shm_lag_chunks", 0)))
    return {"kv": kv, "shm": shm, "shm_lag_chunks": lag,
            "qcomm": qcomm}
