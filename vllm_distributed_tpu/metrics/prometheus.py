"""Prometheus text rendering of engine stats.

Reference: vllm/v1/metrics/prometheus.py + loggers.py:143
(PrometheusStatLogger gauges/counters served at /metrics). The stats dict
comes from Scheduler.get_stats() — rendered directly into the exposition
format so scraping works without the prometheus_client registry (which
is process-global and complicates multi-engine tests); names mirror the
reference's vllm:* metric family.
"""

_GAUGES = {
    "num_running_reqs": ("vdt:num_requests_running",
                         "Number of requests currently running"),
    "num_waiting_reqs": ("vdt:num_requests_waiting",
                         "Number of requests waiting to be scheduled"),
    "kv_cache_usage": ("vdt:kv_cache_usage_perc",
                       "Fraction of KV pages in use"),
    "spec_acceptance_rate": ("vdt:spec_decode_acceptance_rate",
                             "Accepted / proposed draft tokens"),
    # Engine-core batch pipeline (PP microbatches / async scheduling).
    "inflight_batches": ("vdt:inflight_batches",
                         "Dispatched-but-unretired batches in the "
                         "engine core's pipeline right now"),
    "max_concurrent_batches": ("vdt:max_concurrent_batches",
                               "Peak in-flight batch depth since start "
                               "(>= 2 proves host/device overlap "
                               "happened)"),
    "decode_overlap_frac": ("vdt:decode_overlap_frac",
                            "Fraction of dispatches issued while "
                            "another batch was already executing"),
    # Step batch composition (most recent non-empty step). Under DP
    # these sum across replicas — the fleet's current step mix, same
    # as PromQL sum() over per-instance gauges.
    "last_step_prefill_tokens": ("vdt:step_prefill_tokens",
                                 "Prefill tokens granted in the most "
                                 "recent non-empty scheduler step "
                                 "(summed across DP replicas)"),
    "last_step_decode_tokens": ("vdt:step_decode_tokens",
                                "Decode tokens granted in the most "
                                "recent non-empty scheduler step "
                                "(summed across DP replicas)"),
    # SSM state cache (core/state_cache.py; stateful models only —
    # the scheduler omits the key otherwise).
    "ssm_state_bytes_held": ("vdt:ssm_state_bytes_held",
                             "Device bytes held by live SSM state "
                             "snapshots (summed across DP replicas)"),
}

_COUNTERS = {
    "num_preemptions": ("vdt:num_preemptions_total",
                        "Cumulative preempted requests"),
    "hits": ("vdt:prefix_cache_hits_total",
             "Cumulative prefix-cache token hits"),
    "queries": ("vdt:prefix_cache_queries_total",
                "Cumulative prefix-cache token queries"),
    # Spec decode (reference: v1/metrics SpecDecodingStats -> the
    # vllm:spec_decode_* family).
    "spec_num_draft_tokens": ("vdt:spec_decode_num_draft_tokens_total",
                              "Cumulative proposed draft tokens"),
    "spec_num_accepted_tokens": (
        "vdt:spec_decode_num_accepted_tokens_total",
        "Cumulative accepted draft tokens"),
    "spec_num_drafts": ("vdt:spec_decode_num_drafts_total",
                        "Cumulative draft proposals"),
    # Fault-tolerance layer (scheduler watchdog + KV-pull retry).
    "watchdog_timeouts": ("vdt:watchdog_timeouts_total",
                          "Requests swept out of WAITING_FOR_REMOTE_KVS "
                          "by the watchdog deadline"),
    "kv_pull_retries": ("vdt:kv_pull_retries_total",
                        "Request-level remote-KV pull retries"),
    "kv_pull_failures": ("vdt:kv_pull_failures_total",
                         "Failed remote-KV pulls (each requeued for "
                         "retry or local recompute)"),
    # Engine-core batch pipeline throughput accounting.
    "steps_dispatched": ("vdt:engine_steps_dispatched_total",
                         "Batches dispatched by the engine core"),
    "steps_overlapped": ("vdt:engine_steps_overlapped_total",
                         "Batches dispatched while another was already "
                         "in flight"),
    "num_async_spec_grants": ("vdt:async_spec_grants_total",
                              "Speculative run-ahead decode grants "
                              "issued by the async scheduler"),
    # DP front-end recovery (dp_client failover + resurrection).
    "replica_failovers": ("vdt:replica_failovers_total",
                          "Dead DP replicas taken out of rotation with "
                          "their journaled requests migrated"),
    "replica_resurrections": ("vdt:replica_resurrections_total",
                              "Downed DP replicas successfully "
                              "restarted and returned to rotation"),
    # Request-lifecycle timeline (metrics/events.py).
    "timeline_events_dropped": ("vdt:timeline_events_dropped_total",
                                "Lifecycle events dropped by full ring "
                                "buffers (oldest-first overflow)"),
    # Compile-lattice size: graphs warmed by precompile() (summed across
    # DP replicas; the mega-kernel's collapsed lattice shows up here as
    # a smaller warm-up at unchanged bucket configs).
    "precompile_graphs": ("vdt:precompile_graphs_total",
                          "XLA graphs compiled by the precompile "
                          "warm-up suite"),
    # Fused decode-block dispatch (ops/pallas_block.py): rendered only
    # while the loader enabled VDT_BLOCK_FUSION for this model.
    "block_fusion_calls": ("vdt:block_fusion_calls_total",
                           "Decode-only waves dispatched through the "
                           "fused transformer-block kernel (one Pallas "
                           "call per layer)"),
    # SSM state cache (core/state_cache.py): prefix-style admission at
    # snapshot boundaries for stateful (Mamba/Jamba) models.
    "ssm_state_cache_hits": ("vdt:ssm_state_cache_hits_total",
                             "Stateful admissions resumed from a state "
                             "snapshot instead of token 0"),
    "ssm_state_cache_queries": ("vdt:ssm_state_cache_queries_total",
                                "Stateful admission lookups against "
                                "the state-snapshot index"),
    "ssm_state_cache_evictions": ("vdt:ssm_state_cache_evictions_total",
                                  "State snapshots evicted (LRU) to "
                                  "make room for new checkpoints"),
    "ssm_checkpoints": ("vdt:ssm_checkpoints_total",
                        "SSM state snapshots committed at checkpoint "
                        "boundaries (periodic cadence + preempt parks)"),
    "ssm_journal_reclaimed": ("vdt:ssm_journal_reclaimed_total",
                              "Checkpoint-journal files reclaimed by "
                              "the retention sweep (TTL expiry + "
                              "size-budget eviction at init/sleep)"),
    "ssm_journal_demotions": ("vdt:ssm_journal_demotions_total",
                              "Evicted state snapshots demoted to the "
                              "checkpoint journal instead of discarded "
                              "(hierarchical tiering's journal-as-"
                              "second-tier; VDT_KV_TIERING=1)"),
    # Performance-attribution plane (metrics/costmodel.py): analytic
    # model FLOPs charged per dispatch, summed across DP replicas.
    "model_flops": ("vdt:model_flops_total",
                    "Analytic model FLOPs charged for dispatched "
                    "waves (useful FLOPs over real tokens; the "
                    "vdt:mfu numerator)"),
}


# Histogram-valued stats entries: the engine ships them as
# {"buckets": [...], "counts": [...], "sum": s, "count": n} dicts
# (counts has one extra +Inf slot), rendered here in full exposition
# shape.
_HISTOGRAMS = {
    "step_host_gap_seconds": (
        "vdt:step_host_gap_seconds",
        "Host gap between a step's wait_model return and the next "
        "dispatch (device idle time the async scheduler hides)"),
}


# Label names per labeled vdt: family — the single source of truth for
# the renderers below AND scripts/lint_metrics.py (which parses this
# literal and cross-checks every entry against the README metrics
# table, so an undocumented label set fails tier-1).
LABELED_METRICS = {
    "vdt:step_phase_seconds": ("phase", ),
    "vdt:fault_injections_total": ("point", ),
    # Telemetry plane: per-worker device/compilation series.
    "vdt:recompiles_total": ("worker", ),
    "vdt:device_memory_peak_bytes": ("worker", ),
    "vdt:device_memory_in_use_bytes": ("worker", ),
    "vdt:device_wait_seconds": ("worker", ),
    # TPLA latent-pool geometry (ops/mla.py; MLA models only).
    "vdt:tpla_latent_shards": ("worker", ),
    "vdt:mla_latent_page_bytes": ("worker", ),
    # Performance-attribution plane (metrics/costmodel.py): per-worker
    # utilization ratios, analytic HBM traffic by kind, and the
    # per-phase roofline placement.
    "vdt:mfu": ("worker", ),
    "vdt:mbu": ("worker", ),
    "vdt:hbm_bytes_total": ("kind", ),
    "vdt:roofline_bound": ("phase", ),
    # Telemetry plane: per-connector KV transfer + shm ring.
    "vdt:kv_transfer_bytes_total": ("connector", "direction"),
    "vdt:kv_transfer_failures_total": ("connector", ),
    "vdt:kv_transfer_inflight": ("connector", ),
    "vdt:kv_transfer_seconds": ("connector", ),
    "vdt:shm_ring_messages_total": ("side", ),
    "vdt:shm_ring_wait_seconds": ("side", ),
    # Telemetry plane: block-pool introspection.
    "vdt:kv_blocks": ("state", ),
    "vdt:preemptions_by_cause_total": ("cause", ),
    # Hierarchical KV memory (core/kv_tier.py; VDT_KV_TIERING=1):
    # spill-tier occupancy and flow, by tier (host|disk).
    "vdt:kv_tier_pages": ("tier", ),
    "vdt:kv_tier_bytes": ("tier", ),
    "vdt:kv_tier_demotions_total": ("tier", ),
    "vdt:kv_tier_demotion_bytes_total": ("tier", ),
    "vdt:kv_tier_promotions_total": ("tier", ),
    "vdt:kv_tier_misses_total": ("tier", ),
    # Attention dispatch: which kernel family each step ran
    # (fused_block|unified|decode|general|cascade|naive).
    "vdt:attn_kernel_calls_total": ("kernel", ),
    # Fused-block waves that fell back to the per-op path while fusion
    # was enabled (mixed_wave|cascade|multi_step).
    "vdt:block_fusion_fallbacks_total": ("reason", ),
    # Quantized communication plane (parallel/collectives.py +
    # kv_transfer/quant.py): per-path wire/disk bytes saved.
    "vdt:qcomm_bytes_saved_total": ("path", ),
    # DP balancer + routing tier (engine/dp_client.py, engine/router.py).
    "vdt:dp_replica_load": ("replica", ),
    "vdt:router_prefix_index_entries": ("replica", ),
    # Disaggregated serving tier (engine/disagg.py).
    "vdt:disagg_fallbacks_total": ("reason", ),
    "vdt:pool_occupancy": ("pool", ),
    # Weighted admission shedding (entrypoints/openai/admission.py).
    "vdt:requests_shed_by_class_total": ("class", ),
    # Elastic-fleet control loop (engine/fleet.py; VDT_FLEET=1):
    # ticks/actions skipped, by freeze reason (stale_stats | budget |
    # scale_stall | at_max | asym_tp | partition).
    "vdt:fleet_freezes_total": ("reason", ),
    # HA control plane (engine/control_plane.py; VDT_FLEET_CONTROLLER
    # =1): stale-epoch/standby actuations rejected by the coordinator
    # fence, by action (scale_out | scale_in | retire | convert |
    # resplit | force_cycle | resurrect) — a fixed enum.
    "vdt:fleet_fenced_actions_total": ("action", ),
    # Per-tenant QoS (core/sched/qos.py; VDT_QOS=1). Label cardinality
    # is bounded: tenants past VDT_QOS_MAX_TRACKED_TENANTS hash into 8
    # shared "~<n>" overflow buckets, tenantless traffic shares
    # "_anon" (qos.bucket_tenant is the shared bucketing function;
    # each component's first-come tracked set is its own, so overflow
    # assignment can differ per replica past the cap).
    "vdt:tenant_granted_tokens_total": ("tenant", ),
    "vdt:tenant_kv_blocks": ("tenant", ),
    "vdt:tenant_preemptions_total": ("tenant", ),
    "vdt:tenant_goodput_frac": ("tenant", ),
    # SLO burn-rate watchdog (metrics/stats.py BurnRateWatchdog): error
    # budget burn per rolling window (a fixed enum: 1m | 10m).
    "vdt:slo_burn_rate": ("window", ),
    # Correctness sentinel (correctness_plane.py; VDT_CORRECTNESS=1).
    # All per-replica — a cross-replica sum would erase exactly the
    # per-replica divergence the sentinel exists to expose. Causes are
    # a fixed enum: reference | logprob | vote | timeout | nan_logits
    # | numerics_drift.
    "vdt:canary_probes_total": ("replica", ),
    "vdt:canary_divergences_total": ("replica", "cause"),
    "vdt:replica_suspect": ("replica", ),
    "vdt:logits_nan_steps_total": ("replica", ),
    "vdt:logits_entropy": ("replica", ),
    "vdt:logits_top_margin": ("replica", ),
}


def _render_dp_balancer(stats: dict) -> list[str]:
    """DP front-end balancer gauges: per-replica live request counts
    and the alive-replica count. Rendered whenever the stats flowed
    through DPEngineClient — with the router ON or OFF, so replica
    imbalance stays visible while debugging either path."""
    counts = stats.get("dp_request_counts")
    if not isinstance(counts, list) or not counts:
        return []
    lines = ["# HELP vdt:dp_replica_load Live requests owned by each "
             "DP replica (the balancer's routing load signal)",
             "# TYPE vdt:dp_replica_load gauge"]
    lines += [f'vdt:dp_replica_load{{replica="{i}"}} {int(n)}'
              for i, n in enumerate(counts)]
    down = stats.get("dp_replicas_down") or []
    lines += ["# HELP vdt:replicas_in_rotation DP replicas currently "
              "alive and accepting placements",
              "# TYPE vdt:replicas_in_rotation gauge",
              f"vdt:replicas_in_rotation {len(counts) - len(down)}"]
    return lines


def _render_router(router: dict) -> list[str]:
    """Routing-tier families from the front-end ReplicaRouter (one
    instance owns fleet placement, so values are exact, not merged)."""
    lines: list[str] = []
    for name, key, help_text in (
        ("vdt:router_requests_routed_total", "requests_routed",
         "Admissions placed by the routing tier"),
        ("vdt:router_affinity_hits_total", "affinity_hits",
         "Admissions routed to a replica already holding part of "
         "their prefix"),
        ("vdt:router_spillovers_total", "spillovers",
         "Admissions whose affinity home was overridden because it "
         "was pressured"),
        ("vdt:router_stale_degradations_total", "stale_degradations",
         "Admissions placed by pure load balancing because every "
         "load snapshot was stale"),
    ):
        lines += [f"# HELP {name} {help_text}", f"# TYPE {name} counter",
                  f"{name} {int(router.get(key, 0))}"]
    entries = router.get("prefix_index_entries")
    if isinstance(entries, list) and entries:
        name = "vdt:router_prefix_index_entries"
        lines += [f"# HELP {name} Prefix-residency index entries per "
                  "replica (bounded LRU of page hashes)",
                  f"# TYPE {name} gauge"]
        lines += [f'{name}{{replica="{i}"}} {int(n)}'
                  for i, n in enumerate(entries)]
    return lines


def _render_disagg(disagg: dict) -> list[str]:
    """Disagg serving-tier families from the DisaggCoordinator (one
    coordinator owns every handoff, so values are exact)."""
    from vllm_distributed_tpu.metrics.stats import render_histogram_lines
    name = "vdt:disagg_handoffs_total"
    lines = [f"# HELP {name} Prefill->decode handoffs admitted by the "
             "disagg coordinator",
             f"# TYPE {name} counter",
             f"{name} {int(disagg.get('handoffs', 0))}"]
    name = "vdt:disagg_fallbacks_total"
    fallbacks = disagg.get("fallbacks") or {}
    lines += [f"# HELP {name} Disagg recovery-ladder fallbacks by "
              "reason (local_reprefill = failed/stalled pull recomputed "
              "on the decode home, pull_retry = bounded re-pull, "
              "prefill_death / decode_death = replica died mid-stage "
              "and the request re-admitted, pool_down = whole pool out "
              "of rotation, no_pull_coords = prompt under one page)",
              f"# TYPE {name} counter"]
    lines += [f'{name}{{reason="{r}"}} {int(n)}'
              for r, n in sorted(fallbacks.items())]
    h = disagg.get("handoff_seconds")
    if isinstance(h, dict):
        name = "vdt:disagg_handoff_seconds"
        lines += render_histogram_lines(
            name, "Wall seconds from handoff interception to the decode "
            "home's first token (routing + KV pull or its fallback + "
            "requeue + first decode step)",
            h.get("buckets", ()), h.get("counts", ()),
            h.get("sum", 0.0), h.get("count", 0))
    occ = disagg.get("pool_occupancy") or {}
    if occ:
        name = "vdt:pool_occupancy"
        lines += [f"# HELP {name} Live requests owned by each disagg "
                  "pool (prefill/decode)",
                  f"# TYPE {name} gauge"]
        lines += [f'{name}{{pool="{p}"}} {int(n)}'
                  for p, n in sorted(occ.items())]
    return lines


def _render_fleet(fleet: dict) -> list[str]:
    """Elastic-fleet control-loop families (engine/fleet.py; present
    only while VDT_FLEET=1 on a DP deployment)."""
    lines: list[str] = []
    for key, name, kind, help_text in (
        ("replicas", "vdt:fleet_replicas", "gauge",
         "DP replicas currently in rotation (not down, not retired)"),
        ("draining", "vdt:fleet_draining", "gauge",
         "Replicas draining toward retirement or a pool conversion"),
        ("scale_outs", "vdt:fleet_scale_outs_total", "counter",
         "Replicas added to rotation by the fleet controller"),
        ("scale_ins", "vdt:fleet_scale_ins_total", "counter",
         "Replicas drained and retired under the low watermark"),
        ("resplits", "vdt:fleet_resplits_total", "counter",
         "Live prefill<->decode pool conversions completed"),
        ("wedge_cycles", "vdt:fleet_wedge_cycles_total", "counter",
         "Alive-but-not-stepping replicas force-cycled (work migrated "
         "via the continuation journal, then restart-probed)"),
        ("warm_start_pages", "vdt:fleet_warm_start_pages_total",
         "counter",
         "Spill-tier pages found by new/converted replicas warm-"
         "starting from the shared tier-2 namespace"),
        ("quarantines", "vdt:fleet_quarantines_total", "counter",
         "Suspect replicas force-cycled on the correctness sentinel's "
         "quarantine hints (VDT_CORRECTNESS + VDT_FLEET_SIGNALS; same "
         "drain+respawn rung as a wedge cycle)"),
    ):
        if key in fleet:
            lines += [f"# HELP {name} {help_text}",
                      f"# TYPE {name} {kind}",
                      f"{name} {int(fleet.get(key, 0))}"]
    freezes = fleet.get("freezes") or {}
    name = "vdt:fleet_freezes_total"
    lines += [f"# HELP {name} Fleet actuation skipped, by reason "
              "(stale_stats = a rotation member's stats went quiet, "
              "budget = action budget exhausted, scale_stall = replica "
              "spawn failed, at_max = device budget reached, asym_tp = "
              "pools differ in per-replica world size, partition = "
              "control plane unreachable)",
              f"# TYPE {name} counter"]
    lines += [f'{name}{{reason="{r}"}} {int(n)}'
              for r, n in sorted(freezes.items())]
    # HA control plane (engine/control_plane.py; keys present only
    # with VDT_FLEET_CONTROLLER=1).
    for key, name, kind, help_text in (
        ("leader", "vdt:fleet_leader", "gauge",
         "1 while THIS front-end's controller holds the fleet lease "
         "(0 on standbys and partitioned/dead controllers)"),
        ("lease_epoch", "vdt:fleet_lease_epoch", "gauge",
         "Fencing epoch of the lease this controller last held "
         "(bumped by the coordinator on every holder change)"),
        ("leader_transitions", "vdt:fleet_leader_transitions_total",
         "counter",
         "Lease holder changes since boot (election + every "
         "failover takeover)"),
    ):
        if key in fleet:
            lines += [f"# HELP {name} {help_text}",
                      f"# TYPE {name} {kind}",
                      f"{name} {int(fleet.get(key, 0))}"]
    if "fenced_actions" in fleet:
        fenced = fleet.get("fenced_actions") or {}
        name = "vdt:fleet_fenced_actions_total"
        lines += [f"# HELP {name} Actuations rejected by the "
                  "coordinator's epoch fence (stale ex-leader "
                  "commands) or skipped on a standby, by action",
                  f"# TYPE {name} counter"]
        lines += [f'{name}{{action="{a}"}} {int(n)}'
                  for a, n in sorted(fenced.items())]
    return lines


def _render_worker_telemetry(workers: dict) -> list[str]:
    """Per-worker device/compilation series from the DP-merged
    ``{worker_label: stats}`` map (labels are fleet-unique, so every
    series survives the merge unsummed)."""
    from vllm_distributed_tpu.metrics.stats import render_histogram_lines
    lines: list[str] = []
    families = (
        ("num_recompiles", "vdt:recompiles_total", "counter",
         "Graphs compiled AFTER precompile warm-up (a steady-state "
         "recompile is a shape-lattice leak)"),
        ("device_memory_peak_bytes", "vdt:device_memory_peak_bytes",
         "gauge", "Peak device HBM bytes in use (weights + workspace "
         "+ KV high-water mark)"),
        ("device_memory_in_use_bytes", "vdt:device_memory_in_use_bytes",
         "gauge", "Device HBM bytes in use at the last stats poll"),
        # MLA latent-pool geometry (ops/mla.py TPLA layout; present only
        # for MLA models): shards > 1 = the latent cache is TP-sharded,
        # page bytes = PER-RANK HBM one latent page costs this worker.
        ("tpla_latent_shards", "vdt:tpla_latent_shards", "gauge",
         "TP shards of the MLA latent KV cache (1 = replicated layout "
         "/ VDT_TPLA off)"),
        ("mla_latent_page_bytes", "vdt:mla_latent_page_bytes", "gauge",
         "Per-rank HBM bytes one MLA latent page costs (1/TP of the "
         "replicated row under TPLA, plus the rope sidecar)"),
        # Performance-attribution plane: this worker's analytic FLOPs
        # / bytes over its measured device time against its mesh peak.
        ("mfu", "vdt:mfu", "gauge",
         "Model FLOPs utilization: analytic useful FLOPs over "
         "measured device seconds x mesh peak FLOP/s"),
        ("mbu", "vdt:mbu", "gauge",
         "Memory-bandwidth utilization: analytic HBM bytes over "
         "measured device seconds x mesh peak bandwidth"),
    )
    for key, name, kind, help_text in families:
        series = [(w, s[key]) for w, s in sorted(workers.items())
                  if isinstance(s, dict) and key in s]
        if not series:
            continue
        lines += [f"# HELP {name} {help_text}", f"# TYPE {name} {kind}"]
        lines += [f'{name}{{worker="{w}"}} {float(v)}'
                  for w, v in series]
    hist_name = "vdt:device_wait_seconds"
    first = True
    for worker, s in sorted(workers.items()):
        h = s.get("device_wait_seconds") if isinstance(s, dict) else None
        if not isinstance(h, dict):
            continue
        if first:
            lines += [f"# HELP {hist_name} Wall seconds the worker "
                      "blocked fetching a step's device results",
                      f"# TYPE {hist_name} histogram"]
            first = False
        lines += render_histogram_lines(
            hist_name, "", h.get("buckets", ()), h.get("counts", ()),
            h.get("sum", 0.0), h.get("count", 0),
            label=f'worker="{worker}"', header=False)
    return lines


def _render_transport(transport: dict) -> list[str]:
    """Per-connector KV-transfer and shm-ring families from a (possibly
    DP-merged) TransportRecorder snapshot."""
    from vllm_distributed_tpu.metrics.stats import render_histogram_lines
    lines: list[str] = []
    kv = {c: e for c, e in (transport.get("kv") or {}).items()
          if isinstance(e, dict)}
    if kv:
        name = "vdt:kv_transfer_bytes_total"
        lines += [f"# HELP {name} Bytes moved per KV-transfer "
                  "connector and direction (tx = served/saved, rx = "
                  "pulled/loaded)",
                  f"# TYPE {name} counter"]
        for conn in sorted(kv):
            for direction in ("tx", "rx"):
                lines.append(
                    f'{name}{{connector="{conn}",'
                    f'direction="{direction}"}} '
                    f'{int(kv[conn].get(f"{direction}_bytes", 0))}')
        name = "vdt:kv_transfer_failures_total"
        lines += [f"# HELP {name} Failed transfers per connector",
                  f"# TYPE {name} counter"]
        lines += [f'{name}{{connector="{c}"}} '
                  f'{int(kv[c].get("failures", 0))}' for c in sorted(kv)]
        name = "vdt:kv_transfer_inflight"
        lines += [f"# HELP {name} Transfers in flight right now per "
                  "connector",
                  f"# TYPE {name} gauge"]
        lines += [f'{name}{{connector="{c}"}} '
                  f'{int(kv[c].get("inflight", 0))}' for c in sorted(kv)]
        name = "vdt:kv_transfer_seconds"
        lines += [f"# HELP {name} Wall seconds per transfer, by "
                  "connector",
                  f"# TYPE {name} histogram"]
        for conn in sorted(kv):
            h = kv[conn].get("seconds")
            if isinstance(h, dict):
                lines += render_histogram_lines(
                    name, "", h.get("buckets", ()), h.get("counts", ()),
                    h.get("sum", 0.0), h.get("count", 0),
                    label=f'connector="{conn}"', header=False)
    shm = {s: e for s, e in (transport.get("shm") or {}).items()
           if isinstance(e, dict)}
    if shm:
        name = "vdt:shm_ring_messages_total"
        lines += [f"# HELP {name} Messages through the shm broadcast "
                  "ring, by side",
                  f"# TYPE {name} counter"]
        lines += [f'{name}{{side="{s}"}} '
                  f'{int(shm[s].get("messages", 0))}'
                  for s in sorted(shm)]
        name = "vdt:shm_ring_wait_seconds"
        lines += [f"# HELP {name} Wall seconds blocked in the native "
                  "ring write/read per message",
                  f"# TYPE {name} histogram"]
        for side in sorted(shm):
            h = shm[side].get("wait_seconds")
            if isinstance(h, dict):
                lines += render_histogram_lines(
                    name, "", h.get("buckets", ()), h.get("counts", ()),
                    h.get("sum", 0.0), h.get("count", 0),
                    label=f'side="{side}"', header=False)
    if kv or shm:
        name = "vdt:shm_ring_lag_chunks"
        lines += [f"# HELP {name} Reader backlog in ring CHUNKS "
                  "(writer_seq - reader_seq; a multi-chunk message "
                  "counts once per chunk) at the last dequeue; max "
                  "across DP replicas",
                  f"# TYPE {name} gauge",
                  f'{name} {int(transport.get("shm_lag_chunks", 0))}']
    return lines


def _render_qcomm(transport_qcomm, remote=None) -> list[str]:
    """Quantized-communication plane counters. Three sources merge
    here: the (possibly DP-merged) per-core telemetry recorders carry
    the connector payload paths exactly, parallel/collectives.py's
    trace-time counters carry this process's in-graph tknp/ep/tp
    paths, and ``remote`` carries the pid-deduped follower-process
    in-graph snapshots dp_client merged off the get_stats feed (so
    spawned cores' savings are no longer invisible — the
    vdt:fault_injections_total fix rides the same feed)."""
    from vllm_distributed_tpu.parallel import collectives
    merged = collectives.merged_qcomm_view(
        transport_qcomm if isinstance(transport_qcomm, dict) else None,
        remote if isinstance(remote, dict) else None)
    if not merged:
        return []
    name = "vdt:qcomm_bytes_saved_total"
    lines = [f"# HELP {name} Wire/disk bytes the quantized "
             "communication plane saved vs raw precision, per path "
             "(connector paths exact; in-graph paths analytic "
             "per-traced-collective)",
             f"# TYPE {name} counter"]
    lines += [f'{name}{{path="{p}"}} {int(merged[p]["bytes_saved"])}'
              for p in sorted(merged)]
    name = "vdt:qcomm_fallbacks_total"
    lines += [f"# HELP {name} Quantized payloads/collectives that "
              "degraded to raw precision (corrupt scale header, "
              "inapplicable axis, sub-byte dtype)",
              f"# TYPE {name} counter",
              f"{name} {sum(int(e['fallbacks']) for e in merged.values())}"]
    return lines


def _render_perf(stats: dict) -> list[str]:
    """Performance-attribution families: analytic HBM traffic by kind
    and the per-phase roofline placement, classified at RENDER time
    from the (possibly DP-merged) phase accumulators + hardware peaks
    — classifications are never merged, only recomputed."""
    lines: list[str] = []
    hbm = stats.get("hbm_bytes")
    if isinstance(hbm, dict) and hbm:
        name = "vdt:hbm_bytes_total"
        lines += [f"# HELP {name} Analytic HBM bytes charged for "
                  "dispatched waves, by traffic kind (weights = "
                  "streamed parameters, kv_read/kv_write = paged KV + "
                  "SSM state rows, activations = residual stream + "
                  "logits)",
                  f"# TYPE {name} counter"]
        lines += [f'{name}{{kind="{k}"}} {int(hbm[k])}'
                  for k in sorted(hbm)
                  if isinstance(hbm[k], (int, float))]
    phases = stats.get("perf_phases")
    peaks = stats.get("perf_peaks")
    if (isinstance(phases, dict) and phases
            and isinstance(peaks, dict)):
        from vllm_distributed_tpu.metrics.costmodel import (
            ROOFLINE_CODES, classify_roofline)
        name = "vdt:roofline_bound"
        lines += [f"# HELP {name} Roofline placement of each step "
                  "phase from measured device time vs analytic "
                  "FLOPs/bytes (0 = host-bound, 1 = bandwidth-bound, "
                  "2 = compute-bound)",
                  f"# TYPE {name} gauge"]
        for phase in sorted(phases):
            entry = phases[phase]
            if not isinstance(entry, dict):
                continue
            bound = classify_roofline(entry, peaks)
            lines.append(f'{name}{{phase="{phase}"}} '
                         f'{ROOFLINE_CODES[bound]}')
    return lines


def _render_kv_cache(kv: dict) -> list[str]:
    """Block-pool introspection families (free/used/tombstoned pages,
    fragmentation, windowed prefix-cache hit rate, preemption
    causes)."""
    lines: list[str] = []
    name = "vdt:kv_blocks"
    lines += [f"# HELP {name} KV pages by pool state (cached_free = "
              "reclaimable prefix-cache pages inside free)",
              f"# TYPE {name} gauge"]
    for state, key in (("free", "free_blocks"), ("used", "used_blocks"),
                       ("tombstoned", "tombstoned_blocks"),
                       ("cached_free", "cached_free_blocks")):
        lines.append(f'{name}{{state="{state}"}} '
                     f'{int(kv.get(key, 0))}')
    for name, key, help_text in (
        ("vdt:kv_fragmentation_frac", "fragmentation_frac",
         "Request-held page slots not covered by computed tokens "
         "(internal fragmentation)"),
        ("vdt:prefix_cache_hit_rate_window", "window_hit_rate",
         "Prefix-cache hit rate over the most recent lookups "
         "(sliding window)"),
    ):
        lines += [f"# HELP {name} {help_text}", f"# TYPE {name} gauge",
                  f"{name} {round(float(kv.get(key, 0.0)), 6)}"]
    causes = kv.get("preemption_causes")
    if isinstance(causes, dict) and causes:
        name = "vdt:preemptions_by_cause_total"
        lines += [f"# HELP {name} Preempted requests by cause "
                  "(capacity = evicted for another request's pages, "
                  "self = no eligible victim)",
                  f"# TYPE {name} counter"]
        lines += [f'{name}{{cause="{c}"}} {int(n)}'
                  for c, n in sorted(causes.items())]
    return lines


def _render_kv_tier(tier: dict) -> list[str]:
    """Hierarchical KV-memory families (core/kv_tier.py "kv_tier"
    stats entry, summed per tier across DP replicas)."""
    lines: list[str] = []
    for name, key, kind, help_text in (
        ("vdt:kv_tier_pages", "pages", "gauge",
         "Prefix pages currently held per spill tier (host = pinned "
         "host-RAM pool, disk = spill files)"),
        ("vdt:kv_tier_bytes", "bytes", "gauge",
         "Bytes currently held per spill tier"),
        ("vdt:kv_tier_demotions_total", "demotions", "counter",
         "Pages demoted into each tier (HBM eviction -> host, "
         "host-pool eviction -> disk)"),
        ("vdt:kv_tier_demotion_bytes_total", "demotion_bytes",
         "counter", "Bytes demoted into each tier"),
        ("vdt:kv_tier_promotions_total", "promotions", "counter",
         "Tier-resident pages promoted back into device pages at "
         "admission"),
        ("vdt:kv_tier_misses_total", "misses", "counter",
         "Tier lookups that failed despite an index entry (corrupt / "
         "missing / shape-foreign spill file -> clean recompute)"),
    ):
        per_tier = tier.get(key)
        if not isinstance(per_tier, dict):
            continue
        lines += [f"# HELP {name} {help_text}", f"# TYPE {name} {kind}"]
        lines += [f'{name}{{tier="{t}"}} {int(per_tier[t])}'
                  for t in sorted(per_tier)
                  if isinstance(per_tier[t], (int, float))]
    h = tier.get("promotion_seconds")
    if isinstance(h, dict):
        lines += _render_histogram(
            "vdt:kv_tier_promotion_seconds",
            "Host-side seconds to stage+dispatch one request's tier "
            "promotion (the scatter itself overlaps the forward)", h)
    return lines


def _render_tenants(tenants: dict) -> list[str]:
    """Per-tenant QoS families ({tenant: {granted_tokens, kv_blocks,
    preemptions}} from the scheduler's stats, summed per tenant across
    DP replicas). Cardinality is bounded at the source (qos.py
    bucket_tenant), so one series per bucket is safe to render."""
    lines: list[str] = []
    for name, key, kind, help_text in (
        ("vdt:tenant_granted_tokens_total", "granted_tokens", "counter",
         "Scheduler token grants per tenant bucket (the DRR charge "
         "stream)"),
        ("vdt:tenant_kv_blocks", "kv_blocks", "gauge",
         "KV pages currently held per tenant bucket"),
        ("vdt:tenant_preemptions_total", "preemptions", "counter",
         "Preemptions suffered per tenant bucket (all causes)"),
    ):
        lines += [f"# HELP {name} {help_text}", f"# TYPE {name} {kind}"]
        lines += [f'{name}{{tenant="{t}"}} {int(tenants[t].get(key, 0))}'
                  for t in sorted(tenants)
                  if isinstance(tenants[t], dict)]
    return lines


def _render_numerics(numerics: dict) -> list[str]:
    """In-flight numerics watch (correctness_plane.py NumericsTap;
    VDT_CORRECTNESS=1). DP ships {replica: snapshot} keyed by the
    aggregator; a single-engine deployment ships the runner's flat
    snapshot, rendered as replica 0. Per-replica series — NEVER summed:
    the drift detector's whole signal is replicas disagreeing."""
    from vllm_distributed_tpu.metrics.stats import render_histogram_lines
    if "nan_steps" in numerics:
        numerics = {0: numerics}
    per = {i: d for i, d in numerics.items() if isinstance(d, dict)}
    name = "vdt:logits_nan_steps_total"
    lines = [f"# HELP {name} Pre-sampling steps whose logits carried "
             "NaN/Inf, per replica (the poisoned step is excluded from "
             "the entropy/margin histograms)",
             f"# TYPE {name} counter"]
    lines += [f'{name}{{replica="{i}"}} {int(d.get("nan_steps", 0))}'
              for i, d in sorted(per.items())]
    for name, key, help_text in (
        ("vdt:logits_entropy", "entropy",
         "Per-step mean entropy of the pre-sampling logits, per "
         "replica (the numerics drift detector's primary signal)"),
        ("vdt:logits_top_margin", "top_margin",
         "Per-step mean top-1/top-2 logit margin, per replica (margin "
         "collapse flags quality degradation below the argmax)"),
    ):
        lines += [f"# HELP {name} {help_text}",
                  f"# TYPE {name} histogram"]
        for i, d in sorted(per.items()):
            h = d.get(key)
            if isinstance(h, dict):
                lines += render_histogram_lines(
                    name, "", h.get("buckets", ()), h.get("counts", ()),
                    h.get("sum", 0.0), h.get("count", 0),
                    label=f'replica="{i}"', header=False)
    return lines


def _render_correctness(cp: dict) -> list[str]:
    """Canary-probe families (correctness_plane.py; VDT_CORRECTNESS=1).
    One plane owns the fleet's canaries, so the counters attach exactly
    — the per-replica maps are labeled at the source, never merged."""
    lines: list[str] = []
    probes = cp.get("probes")
    if isinstance(probes, dict):
        name = "vdt:canary_probes_total"
        lines += [f"# HELP {name} Canary probes completed per replica "
                  "(pinned greedy golden prompts through the real "
                  "serving path)",
                  f"# TYPE {name} counter"]
        lines += [f'{name}{{replica="{i}"}} {int(n)}'
                  for i, n in sorted(probes.items())]
    div = cp.get("divergences")
    if isinstance(div, dict):
        name = "vdt:canary_divergences_total"
        lines += [f"# HELP {name} Correctness divergences per replica, "
                  "by cause (reference = tokens strayed from the "
                  "journal, logprob = fingerprint drift, vote = "
                  "cross-replica minority, timeout = probe unanswered, "
                  "nan_logits = NaN/Inf step, numerics_drift = entropy "
                  "window strayed from the fleet mean)",
                  f"# TYPE {name} counter"]
        lines += [f'{name}{{replica="{i}",cause="{c}"}} {int(n)}'
                  for i, causes in sorted(div.items())
                  if isinstance(causes, dict)
                  for c, n in sorted(causes.items())]
    suspects = cp.get("suspects")
    if isinstance(suspects, dict):
        name = "vdt:replica_suspect"
        lines += [f"# HELP {name} 1 while the correctness sentinel "
                  "holds live suspicion against the replica (any "
                  "strike ladder >= 1; clears on a clean round)",
                  f"# TYPE {name} gauge"]
        lines += [f'{name}{{replica="{i}"}} {int(v)}'
                  for i, v in sorted(suspects.items())]
    return lines


def _render_histogram(name: str, help_text: str, h: dict) -> list[str]:
    from vllm_distributed_tpu.metrics.stats import render_histogram_lines
    return render_histogram_lines(name, help_text, h.get("buckets", ()),
                                  h.get("counts", ()), h.get("sum", 0.0),
                                  h.get("count", 0))


def _render_step_phases(phases: dict) -> list[str]:
    """One labeled histogram family for the engine step-phase profiler:
    vdt:step_phase_seconds{phase="schedule"|"prepare_inputs"|"dispatch"
    |"wait"|"update"}. HELP/TYPE once, then the per-phase series —
    bucket/+Inf shape comes from the shared exposition helper."""
    from vllm_distributed_tpu.metrics.stats import render_histogram_lines
    name = "vdt:step_phase_seconds"
    lines = [f"# HELP {name} Wall seconds per engine-core step phase",
             f"# TYPE {name} histogram"]
    for phase in sorted(phases):
        h = phases[phase]
        if not isinstance(h, dict):
            continue
        lines += render_histogram_lines(
            name, "", h.get("buckets", ()), h.get("counts", ()),
            h.get("sum", 0.0), h.get("count", 0),
            label=f'phase="{phase}"', header=False)
    return lines


def render_metrics(stats: dict) -> str:
    lines: list[str] = []
    for key, (name, help_text) in _GAUGES.items():
        if key in stats:
            lines += [f"# HELP {name} {help_text}",
                      f"# TYPE {name} gauge",
                      f"{name} {float(stats[key])}"]
    for key, (name, help_text) in _COUNTERS.items():
        if key in stats:
            lines += [f"# HELP {name} {help_text}",
                      f"# TYPE {name} counter",
                      f"{name} {float(stats[key])}"]
    for key, (name, help_text) in _HISTOGRAMS.items():
        value = stats.get(key)
        if isinstance(value, dict):
            lines += _render_histogram(name, help_text, value)
    step_phases = stats.get("step_phase_seconds")
    if isinstance(step_phases, dict) and step_phases:
        lines += _render_step_phases(step_phases)
    # Attention kernel dispatch counts ({kernel: steps} from the runner,
    # summed per kernel across DP replicas).
    calls = stats.get("attn_kernel_calls")
    if isinstance(calls, dict) and calls:
        name = "vdt:attn_kernel_calls_total"
        lines += [f"# HELP {name} Steps dispatched per attention kernel "
                  "family (unified = mixed-batch mega-kernel, decode = "
                  "SB-batched decode, general = per-sequence tiles, "
                  "cascade = shared-prefix, naive = XLA reference)",
                  f"# TYPE {name} counter"]
        lines += [f'{name}{{kernel="{k}"}} {int(calls[k])}'
                  for k in sorted(calls)]
    # Fused-block fallback reasons ({reason: steps} from the runner,
    # present only while VDT_BLOCK_FUSION is live for the model).
    fb = stats.get("block_fusion_fallbacks")
    if isinstance(fb, dict):
        name = "vdt:block_fusion_fallbacks_total"
        lines += [f"# HELP {name} Waves that fell back from the fused "
                  "decode-block kernel to the per-op path while fusion "
                  "was enabled (mixed_wave = prefill tokens or per-token "
                  "features in the wave, cascade = shared-prefix split, "
                  "multi_step = fused decode burst)",
                  f"# TYPE {name} counter"]
        lines += [f'{name}{{reason="{k}"}} {int(fb[k])}'
                  for k in sorted(fb)]
    # Telemetry plane (worker device/compilation, transport, KV cache):
    # nested dicts shipped up the stats RPC, labeled at the source.
    workers = stats.get("workers")
    if isinstance(workers, dict) and workers:
        lines += _render_worker_telemetry(workers)
    transport = stats.get("transport")
    if isinstance(transport, dict):
        lines += _render_transport(transport)
    lines += _render_qcomm((transport or {}).get("qcomm")
                           if isinstance(transport, dict) else None,
                           stats.get("qcomm_traced_remote"))
    lines += _render_perf(stats)
    kv_cache = stats.get("kv_cache")
    if isinstance(kv_cache, dict) and kv_cache:
        lines += _render_kv_cache(kv_cache)
    kv_tier = stats.get("kv_tier")
    if isinstance(kv_tier, dict) and kv_tier:
        lines += _render_kv_tier(kv_tier)
    tenants = stats.get("tenants")
    if isinstance(tenants, dict) and tenants:
        lines += _render_tenants(tenants)
    # DP balancer load gauges + routing-tier counters (dp_client /
    # router stats entries; absent on single-replica deployments).
    lines += _render_dp_balancer(stats)
    router = stats.get("router")
    if isinstance(router, dict):
        lines += _render_router(router)
    disagg = stats.get("disagg")
    if isinstance(disagg, dict):
        lines += _render_disagg(disagg)
    fleet = stats.get("fleet")
    if isinstance(fleet, dict) and fleet:
        lines += _render_fleet(fleet)
    # Correctness sentinel (correctness_plane.py; keys present only
    # while VDT_CORRECTNESS=1).
    numerics = stats.get("numerics")
    if isinstance(numerics, dict) and numerics:
        lines += _render_numerics(numerics)
    correctness = stats.get("correctness")
    if isinstance(correctness, dict):
        lines += _render_correctness(correctness)
    return "\n".join(lines) + "\n"
