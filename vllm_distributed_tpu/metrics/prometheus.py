"""Prometheus text rendering of engine stats.

Reference: vllm/v1/metrics/prometheus.py + loggers.py:143
(PrometheusStatLogger gauges/counters served at /metrics). The stats dict
comes from Scheduler.get_stats() — rendered directly into the exposition
format so scraping works without the prometheus_client registry (which
is process-global and complicates multi-engine tests); names mirror the
reference's vllm:* metric family.
"""

_GAUGES = {
    "num_running_reqs": ("vdt:num_requests_running",
                         "Number of requests currently running"),
    "num_waiting_reqs": ("vdt:num_requests_waiting",
                         "Number of requests waiting to be scheduled"),
    "kv_cache_usage": ("vdt:kv_cache_usage_perc",
                       "Fraction of KV pages in use"),
    "spec_acceptance_rate": ("vdt:spec_decode_acceptance_rate",
                             "Accepted / proposed draft tokens"),
    # Engine-core batch pipeline (PP microbatches / async scheduling).
    "inflight_batches": ("vdt:inflight_batches",
                         "Dispatched-but-unretired batches in the "
                         "engine core's pipeline right now"),
    "max_concurrent_batches": ("vdt:max_concurrent_batches",
                               "Peak in-flight batch depth since start "
                               "(>= 2 proves host/device overlap "
                               "happened)"),
    "decode_overlap_frac": ("vdt:decode_overlap_frac",
                            "Fraction of dispatches issued while "
                            "another batch was already executing"),
    # Step batch composition (most recent non-empty step). Under DP
    # these sum across replicas — the fleet's current step mix, same
    # as PromQL sum() over per-instance gauges.
    "last_step_prefill_tokens": ("vdt:step_prefill_tokens",
                                 "Prefill tokens granted in the most "
                                 "recent non-empty scheduler step "
                                 "(summed across DP replicas)"),
    "last_step_decode_tokens": ("vdt:step_decode_tokens",
                                "Decode tokens granted in the most "
                                "recent non-empty scheduler step "
                                "(summed across DP replicas)"),
}

_COUNTERS = {
    "num_preemptions": ("vdt:num_preemptions_total",
                        "Cumulative preempted requests"),
    "hits": ("vdt:prefix_cache_hits_total",
             "Cumulative prefix-cache token hits"),
    "queries": ("vdt:prefix_cache_queries_total",
                "Cumulative prefix-cache token queries"),
    # Spec decode (reference: v1/metrics SpecDecodingStats -> the
    # vllm:spec_decode_* family).
    "spec_num_draft_tokens": ("vdt:spec_decode_num_draft_tokens_total",
                              "Cumulative proposed draft tokens"),
    "spec_num_accepted_tokens": (
        "vdt:spec_decode_num_accepted_tokens_total",
        "Cumulative accepted draft tokens"),
    "spec_num_drafts": ("vdt:spec_decode_num_drafts_total",
                        "Cumulative draft proposals"),
    # Fault-tolerance layer (scheduler watchdog + KV-pull retry).
    "watchdog_timeouts": ("vdt:watchdog_timeouts_total",
                          "Requests swept out of WAITING_FOR_REMOTE_KVS "
                          "by the watchdog deadline"),
    "kv_pull_retries": ("vdt:kv_pull_retries_total",
                        "Request-level remote-KV pull retries"),
    "kv_pull_failures": ("vdt:kv_pull_failures_total",
                         "Failed remote-KV pulls (each requeued for "
                         "retry or local recompute)"),
    # Engine-core batch pipeline throughput accounting.
    "steps_dispatched": ("vdt:engine_steps_dispatched_total",
                         "Batches dispatched by the engine core"),
    "steps_overlapped": ("vdt:engine_steps_overlapped_total",
                         "Batches dispatched while another was already "
                         "in flight"),
    "num_async_spec_grants": ("vdt:async_spec_grants_total",
                              "Speculative run-ahead decode grants "
                              "issued by the async scheduler"),
    # DP front-end recovery (dp_client failover + resurrection).
    "replica_failovers": ("vdt:replica_failovers_total",
                          "Dead DP replicas taken out of rotation with "
                          "their journaled requests migrated"),
    "replica_resurrections": ("vdt:replica_resurrections_total",
                              "Downed DP replicas successfully "
                              "restarted and returned to rotation"),
    # Request-lifecycle timeline (metrics/events.py).
    "timeline_events_dropped": ("vdt:timeline_events_dropped_total",
                                "Lifecycle events dropped by full ring "
                                "buffers (oldest-first overflow)"),
}


# Histogram-valued stats entries: the engine ships them as
# {"buckets": [...], "counts": [...], "sum": s, "count": n} dicts
# (counts has one extra +Inf slot), rendered here in full exposition
# shape.
_HISTOGRAMS = {
    "step_host_gap_seconds": (
        "vdt:step_host_gap_seconds",
        "Host gap between a step's wait_model return and the next "
        "dispatch (device idle time the async scheduler hides)"),
}


def _render_histogram(name: str, help_text: str, h: dict) -> list[str]:
    from vllm_distributed_tpu.metrics.stats import render_histogram_lines
    return render_histogram_lines(name, help_text, h.get("buckets", ()),
                                  h.get("counts", ()), h.get("sum", 0.0),
                                  h.get("count", 0))


def _render_step_phases(phases: dict) -> list[str]:
    """One labeled histogram family for the engine step-phase profiler:
    vdt:step_phase_seconds{phase="schedule"|"prepare_inputs"|"dispatch"
    |"wait"|"update"}. HELP/TYPE once, then the per-phase series —
    bucket/+Inf shape comes from the shared exposition helper."""
    from vllm_distributed_tpu.metrics.stats import render_histogram_lines
    name = "vdt:step_phase_seconds"
    lines = [f"# HELP {name} Wall seconds per engine-core step phase",
             f"# TYPE {name} histogram"]
    for phase in sorted(phases):
        h = phases[phase]
        if not isinstance(h, dict):
            continue
        lines += render_histogram_lines(
            name, "", h.get("buckets", ()), h.get("counts", ()),
            h.get("sum", 0.0), h.get("count", 0),
            label=f'phase="{phase}"', header=False)
    return lines


def render_metrics(stats: dict) -> str:
    lines: list[str] = []
    for key, (name, help_text) in _GAUGES.items():
        if key in stats:
            lines += [f"# HELP {name} {help_text}",
                      f"# TYPE {name} gauge",
                      f"{name} {float(stats[key])}"]
    for key, (name, help_text) in _COUNTERS.items():
        if key in stats:
            lines += [f"# HELP {name} {help_text}",
                      f"# TYPE {name} counter",
                      f"{name} {float(stats[key])}"]
    for key, (name, help_text) in _HISTOGRAMS.items():
        value = stats.get(key)
        if isinstance(value, dict):
            lines += _render_histogram(name, help_text, value)
    step_phases = stats.get("step_phase_seconds")
    if isinstance(step_phases, dict) and step_phases:
        lines += _render_step_phases(step_phases)
    return "\n".join(lines) + "\n"
