"""BART text encoder, run front-end-side at admission.

Reference: the encoder half of vllm/model_executor/models/bart.py
(BartEncoder: learned offset-2 positions, embedding LayerNorm,
post-norm bidirectional blocks). Placement mirrors the Whisper audio
encoder (multimodal/audio.py): the source text encodes ONCE at
admission and the [src, d_model] hidden states install into the
decoder's cross-KV state rows."""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.multimodal.audio import _ln

logger = init_logger(__name__)


_ACTS = {
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_new": lambda x: jax.nn.gelu(x, approximate=True),
    "gelu_pytorch_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
}


class BartTextEncoder:
    """Functional JAX BART encoder from an HF checkpoint."""

    def __init__(self, tensors: dict, hf_config) -> None:
        self.heads = hf_config.encoder_attention_heads
        self.hidden = hf_config.d_model
        self.head_dim = self.hidden // self.heads
        self.max_src = int(hf_config.max_position_embeddings)
        import math
        self.scale = (math.sqrt(self.hidden)
                      if getattr(hf_config, "scale_embedding", False)
                      else 1.0)
        act = getattr(hf_config, "activation_function", "gelu")
        if act not in _ACTS:
            # Silent substitution would yield wrong encoder states.
            raise ValueError(
                f"unsupported encoder activation {act!r}")
        self.act = act
        self.params = self._load(tensors, hf_config.encoder_layers)
        self._jit = jax.jit(self._forward)

    def _load(self, tensors: dict, L: int) -> dict:
        E = "model.encoder."

        def t(name):
            return np.asarray(tensors[name])

        def stack(fmt, transpose=True):
            mats = [t(fmt.format(i)) for i in range(L)]
            return jnp.asarray(
                np.stack([m.T if transpose else m for m in mats]),
                jnp.float32)

        lay = "layers.{}."
        return {
            "embed": jnp.asarray(np.asarray(
                tensors["model.shared.weight"]), jnp.float32),
            "pos": jnp.asarray(t(E + "embed_positions.weight"),
                               jnp.float32),
            "emb_ln": jnp.asarray(t(E + "layernorm_embedding.weight"),
                                  jnp.float32),
            "emb_ln_b": jnp.asarray(t(E + "layernorm_embedding.bias"),
                                    jnp.float32),
            "ln1": stack(E + lay + "self_attn_layer_norm.weight", False),
            "ln1_b": stack(E + lay + "self_attn_layer_norm.bias", False),
            "wq": stack(E + lay + "self_attn.q_proj.weight"),
            "bq": stack(E + lay + "self_attn.q_proj.bias", False),
            "wk": stack(E + lay + "self_attn.k_proj.weight"),
            "bk": stack(E + lay + "self_attn.k_proj.bias", False),
            "wv": stack(E + lay + "self_attn.v_proj.weight"),
            "bv": stack(E + lay + "self_attn.v_proj.bias", False),
            "wo": stack(E + lay + "self_attn.out_proj.weight"),
            "bo": stack(E + lay + "self_attn.out_proj.bias", False),
            "ln2": stack(E + lay + "final_layer_norm.weight", False),
            "ln2_b": stack(E + lay + "final_layer_norm.bias", False),
            "fc1": stack(E + lay + "fc1.weight"),
            "fc1_b": stack(E + lay + "fc1.bias", False),
            "fc2": stack(E + lay + "fc2.weight"),
            "fc2_b": stack(E + lay + "fc2.bias", False),
        }

    def _forward(self, params: dict, ids: jax.Array,
                 n: jax.Array) -> jax.Array:
        """ids padded to a length bucket; ``n`` = valid tokens (padding
        keys are masked out of the bidirectional attention so results
        are exact while the jit keys only on the bucket)."""
        F = ids.shape[0]
        valid = jnp.arange(F, dtype=jnp.int32) < n
        h = params["embed"][ids] * self.scale
        h = h + params["pos"][2 + jnp.arange(F)]  # offset-2 table
        h = _ln(h, params["emb_ln"], params["emb_ln_b"])
        nh, hd = self.heads, self.head_dim
        scale = hd ** -0.5
        _KEYS = ("ln1", "ln1_b", "wq", "bq", "wk", "bk", "wv", "bv",
                 "wo", "bo", "ln2", "ln2_b", "fc1", "fc1_b", "fc2",
                 "fc2_b")
        act = _ACTS[self.act]
        kmask = jnp.where(valid, 0.0, -1e30)[None, None, :]

        for i in range(params["wq"].shape[0]):
            p = {k: params[k][i] for k in _KEYS}
            q = ((h @ p["wq"] + p["bq"]) * scale).reshape(F, nh, hd)
            k = (h @ p["wk"] + p["bk"]).reshape(F, nh, hd)
            v = (h @ p["wv"] + p["bv"]).reshape(F, nh, hd)
            s = jnp.einsum("ind,jnd->nij", q, k) + kmask
            a = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("nij,jnd->ind", a, v).reshape(F, -1)
            h = _ln(h + ctx @ p["wo"] + p["bo"], p["ln1"], p["ln1_b"])
            m = act(h @ p["fc1"] + p["fc1_b"])
            h = _ln(h + m @ p["fc2"] + p["fc2_b"], p["ln2"], p["ln2_b"])
        return h

    def encode(self, input_ids) -> np.ndarray:
        from vllm_distributed_tpu.utils import make_buckets, \
            pad_to_bucket
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        n = ids.shape[0]
        if n > self.max_src:
            raise ValueError(
                f"encoder input has {n} tokens; the model's "
                f"source capacity is {self.max_src}")
        Fb = pad_to_bucket(n, make_buckets(16, self.max_src))
        padded = np.zeros((Fb, ), np.int32)
        padded[:n] = ids
        out = self._jit(self.params, jnp.asarray(padded),
                        jnp.asarray(n, jnp.int32))
        return np.asarray(jax.device_get(out), np.float32)[:n]


def build_text_encoder(model_path: str,
                       hf_config) -> Optional[BartTextEncoder]:
    import os
    if not os.path.isdir(model_path):
        return None
    from vllm_distributed_tpu.models.bart import _with_model_prefix
    from vllm_distributed_tpu.models.loader import load_hf_state_dict
    tensors = _with_model_prefix(load_hf_state_dict(
        model_path, prefixes=("model.encoder.", "model.shared.",
                              "encoder.", "shared.")))
    if not any(k.startswith("model.encoder.") for k in tensors):
        return None
    logger.info("loaded bart text encoder (%d tensors)", len(tensors))
    return BartTextEncoder(tensors, hf_config)
