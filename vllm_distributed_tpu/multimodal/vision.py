"""In-engine CLIP vision tower + projector for llava-style models.

Reference: the vision encoder path of vllm/model_executor/models/
llava.py + clip.py (CLIPVisionModel run inside the engine,
get_image_features -> multi_modal_projector). Functional JAX
implementation: pixel inputs are encoded at ADMISSION (the processor),
producing the same pre-computed embedding rows the rest of the
multimodal path already handles — the engine core, scheduler budget and
runner substitution are identical for pixels and embeddings.

The tower runs under jit on the default backend; image batches are tiny
next to decode traffic, and encoding at admission (not per step) mirrors
the reference's encoder-cache design.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


def _quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


_ACTS = {
    "quick_gelu": _quick_gelu,
    "gelu": functools.partial(jax.nn.gelu, approximate=False),
    "gelu_new": functools.partial(jax.nn.gelu, approximate=True),
    "gelu_pytorch_tanh": functools.partial(jax.nn.gelu, approximate=True),
}


def _lookup_act(name: str):
    try:
        return _ACTS[name]
    except KeyError:
        raise ValueError(
            f"unsupported vision activation {name!r}") from None


def _ln(x, w, b, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * w + b).astype(x.dtype)


class ClipVisionEncoder:
    """CLIP vision tower + llava projector from a llava checkpoint."""

    def __init__(self, tensors: dict, hf_config) -> None:
        vc = hf_config.vision_config
        self.patch = vc.patch_size
        self.image_size = vc.image_size
        self.heads = vc.num_attention_heads
        self.eps = getattr(vc, "layer_norm_eps", 1e-5)
        self.act = _lookup_act(getattr(vc, "hidden_act", "quick_gelu"))
        # The llava PROJECTOR has its own activation (default exact
        # gelu) — distinct from the tower's quick_gelu.
        self.proj_act = _lookup_act(
            getattr(hf_config, "projector_hidden_act", "gelu"))
        # Llava selection: hidden state index (-2 = features after the
        # second-to-last layer) and CLS handling.
        self.feature_layer = getattr(hf_config, "vision_feature_layer",
                                     -2)
        self.drop_cls = getattr(hf_config,
                                "vision_feature_select_strategy",
                                "default") == "default"
        self.params = self._load(tensors, vc.num_hidden_layers)
        self._fn = jax.jit(self._forward)

    # ------------------------------------------------------------------
    def _load(self, tensors: dict, L: int) -> dict:
        def lookup(bases, name):
            for base in bases:
                for wrap in ("model.", ""):
                    cand = f"{wrap}{base}.{name}"
                    if cand in tensors:
                        return jnp.asarray(np.asarray(tensors[cand]),
                                           jnp.float32)
            raise ValueError(
                f"vision tower tensor {name!r} not found in the "
                "checkpoint (unsupported naming variant); pass "
                "pre-computed image_embeds instead")

        def t(name):
            return lookup(("vision_tower.vision_model", ), name)

        def stack(fmt, transpose=False):
            mats = [np.asarray(t(fmt.format(i))) for i in range(L)]
            return jnp.asarray(
                np.stack([m.T if transpose else m for m in mats]))

        E = "encoder.layers.{}."
        params = {
            "patch": t("embeddings.patch_embedding.weight"),
            "cls": t("embeddings.class_embedding"),
            "pos": t("embeddings.position_embedding.weight"),
            "pre_ln_w": t("pre_layrnorm.weight"),
            "pre_ln_b": t("pre_layrnorm.bias"),
            "ln1_w": stack(E + "layer_norm1.weight"),
            "ln1_b": stack(E + "layer_norm1.bias"),
            "ln2_w": stack(E + "layer_norm2.weight"),
            "ln2_b": stack(E + "layer_norm2.bias"),
        }
        for proj in ("q", "k", "v", "out"):
            params[f"w{proj}"] = stack(
                E + f"self_attn.{proj}_proj.weight", transpose=True)
            params[f"b{proj}"] = stack(E + f"self_attn.{proj}_proj.bias")
        params["fc1"] = stack(E + "mlp.fc1.weight", transpose=True)
        params["fc1_b"] = stack(E + "mlp.fc1.bias")
        params["fc2"] = stack(E + "mlp.fc2.weight", transpose=True)
        params["fc2_b"] = stack(E + "mlp.fc2.bias")

        def p(name):
            return lookup(("multi_modal_projector", ), name)

        params["proj1"] = p("linear_1.weight").T
        params["proj1_b"] = p("linear_1.bias")
        params["proj2"] = p("linear_2.weight").T
        params["proj2_b"] = p("linear_2.bias")
        return params

    # ------------------------------------------------------------------
    def _forward(self, params: dict, pixels: jax.Array) -> jax.Array:
        """[N, 3, S, S] -> [N, n_tokens, H_text]."""
        N = pixels.shape[0]
        # Patch embed: conv with stride=kernel=patch, no bias.
        feat = jax.lax.conv_general_dilated(
            pixels.astype(jnp.float32), params["patch"],
            window_strides=(self.patch, self.patch), padding="VALID",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        H = feat.shape[1]
        feat = feat.reshape(N, H, -1).transpose(0, 2, 1)  # [N, P, H]
        cls = jnp.broadcast_to(params["cls"], (N, 1, H))
        h = jnp.concatenate([cls, feat], axis=1) + params["pos"][None]
        h = _ln(h, params["pre_ln_w"], params["pre_ln_b"], self.eps)

        L = params["ln1_w"].shape[0]
        # vision_feature_layer indexes the hidden-states tuple
        # (embeddings first): -2 means stop after layer L-2.
        fl = self.feature_layer
        stop = fl + 1 + L if fl < 0 else fl
        nh = self.heads
        scale = (H // nh) ** -0.5

        def layer(h, i):
            x = _ln(h, params["ln1_w"][i], params["ln1_b"][i], self.eps)
            T = x.shape[1]
            q = (x @ params["wq"][i] + params["bq"][i]) * scale
            k = x @ params["wk"][i] + params["bk"][i]
            v = x @ params["wv"][i] + params["bv"][i]
            q = q.reshape(N, T, nh, -1).transpose(0, 2, 1, 3)
            k = k.reshape(N, T, nh, -1).transpose(0, 2, 1, 3)
            v = v.reshape(N, T, nh, -1).transpose(0, 2, 1, 3)
            a = jax.nn.softmax(q @ k.transpose(0, 1, 3, 2), axis=-1)
            o = (a @ v).transpose(0, 2, 1, 3).reshape(N, T, H)
            h = h + (o @ params["wout"][i] + params["bout"][i])
            x2 = _ln(h, params["ln2_w"][i], params["ln2_b"][i], self.eps)
            m = self.act(x2 @ params["fc1"][i] + params["fc1_b"][i])
            h = h + (m @ params["fc2"][i] + params["fc2_b"][i])
            return h

        for i in range(stop):
            h = layer(h, i)
        if self.drop_cls:
            h = h[:, 1:]
        h = self.proj_act(h @ params["proj1"] + params["proj1_b"])
        return h @ params["proj2"] + params["proj2_b"]

    def encode(self, pixel_values: np.ndarray) -> list[np.ndarray]:
        """[N, 3, S, S] pixels -> one [n_tokens, H_text] array per
        image (the projector output the mm path substitutes)."""
        pixels = np.asarray(pixel_values, np.float32)
        if pixels.ndim == 3:
            pixels = pixels[None]
        out = np.asarray(self._fn(self.params, jnp.asarray(pixels)))
        return [out[i] for i in range(out.shape[0])]


def build_vision_encoder(model_path: str,
                         hf_config) -> Optional[ClipVisionEncoder]:
    """Load the vision tower from the checkpoint; None when the model
    has no (supported) tower."""
    if getattr(hf_config, "vision_config", None) is None:
        return None
    if hf_config.vision_config.model_type not in ("clip_vision_model", ):
        logger.warning("unsupported vision tower %s; pixel inputs "
                       "disabled (pass image_embeds instead)",
                       hf_config.vision_config.model_type)
        return None
    from vllm_distributed_tpu.models.loader import load_hf_state_dict
    # Only the tower + projector tensors — not a second full-checkpoint
    # read on the admission path.
    tensors = load_hf_state_dict(
        model_path, prefixes=("vision_tower.", "model.vision_tower.",
                              "multi_modal_projector.",
                              "model.multi_modal_projector."))
    return ClipVisionEncoder(tensors, hf_config)
