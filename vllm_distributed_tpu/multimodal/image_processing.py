"""Image decoding + CLIP preprocessing for the serving path.

Reference: the multimodal input mapper of vllm/multimodal/image.py +
entrypoints/chat_utils.py (data-URL images in chat content become
pixel tensors via the model's HF image processor). Implemented
directly against the checkpoint's ``preprocessor_config.json`` (CLIP
semantics: resize shortest side, center crop, rescale, normalize) so
serving needs no torch/transformers processor objects in the request
path."""

import base64
import json
import os
from typing import Optional

import numpy as np

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

# CLIP defaults (openai/clip-vit-*): used when the checkpoint ships no
# preprocessor_config.json.
_CLIP_MEAN = (0.48145466, 0.4578275, 0.40821073)
_CLIP_STD = (0.26862954, 0.26130258, 0.27577711)


class ImagePreprocessor:
    """pixel pipeline: PIL image -> [3, S, S] float32 (CHW)."""

    def __init__(self, model_path: str, hf_config) -> None:
        size = getattr(getattr(hf_config, "vision_config", None),
                       "image_size", 224)
        cfg: dict = {}
        pp = os.path.join(model_path, "preprocessor_config.json")
        if os.path.isfile(pp):
            with open(pp) as f:
                cfg = json.load(f)
        csize = cfg.get("crop_size", size)
        if isinstance(csize, dict):
            csize = csize.get("height", size)
        rsize = cfg.get("size", size)
        if isinstance(rsize, dict):
            rsize = rsize.get("shortest_edge",
                              rsize.get("height", size))
        self.resize_to = int(rsize)
        self.crop_to = int(csize)
        self.do_center_crop = bool(cfg.get("do_center_crop", True))
        self.rescale = float(cfg.get("rescale_factor", 1 / 255))
        self.mean = np.asarray(cfg.get("image_mean", _CLIP_MEAN),
                               np.float32)
        self.std = np.asarray(cfg.get("image_std", _CLIP_STD),
                              np.float32)

    def __call__(self, image) -> np.ndarray:
        from PIL import Image
        if not isinstance(image, Image.Image):
            image = Image.open(image)
        image = image.convert("RGB")
        # Resize shortest edge (CLIP), bicubic; the long edge TRUNCATES
        # like HF's get_resize_output_image_size (int(), not round()).
        w, h = image.size
        if w <= h:
            new_w = self.resize_to
            new_h = max(1, int(self.resize_to * h / w))
        else:
            new_h = self.resize_to
            new_w = max(1, int(self.resize_to * w / h))
        image = image.resize((new_w, new_h), Image.Resampling.BICUBIC)
        if self.do_center_crop:
            w, h = image.size
            left = (w - self.crop_to) // 2
            top = (h - self.crop_to) // 2
            image = image.crop((left, top, left + self.crop_to,
                                top + self.crop_to))
        arr = np.asarray(image, np.float32) * self.rescale  # [H, W, 3]
        arr = (arr - self.mean) / self.std
        return arr.transpose(2, 0, 1)  # [3, S, S]


def decode_data_url(url: str):
    """'data:image/...;base64,...' -> PIL image."""
    import io

    from PIL import Image
    if not url.startswith("data:"):
        raise ValueError(
            "only data: image URLs are supported (no egress from the "
            "serving host); got a remote URL")
    try:
        payload = url.split(",", 1)[1]
        image = Image.open(io.BytesIO(base64.b64decode(payload)))
        image.load()  # PIL is lazy: force the full decode HERE so a
        # truncated payload is a client error, not a later 500
        return image
    except Exception as e:  # noqa: BLE001 - client error
        raise ValueError(f"could not decode image data URL: {e}") from e


_PREPROCESSORS: dict[str, ImagePreprocessor] = {}


def preprocess_data_urls(urls: list[str], model_path: str,
                         hf_config) -> list[np.ndarray]:
    pre = _PREPROCESSORS.get(model_path)
    if pre is None:
        pre = ImagePreprocessor(model_path, hf_config)
        _PREPROCESSORS[model_path] = pre
    return [pre(decode_data_url(u)) for u in urls]


def image_token_string(tokenizer, hf_config) -> Optional[str]:
    """The placeholder token's string form (e.g. '<image>') for chat
    prompt construction; None when the model has no image token."""
    idx = getattr(hf_config, "image_token_index",
                  getattr(hf_config, "image_token_id", None))
    if idx is None or tokenizer is None:
        return None
    try:
        return tokenizer.convert_ids_to_tokens(int(idx))
    except Exception:  # noqa: BLE001
        return None
