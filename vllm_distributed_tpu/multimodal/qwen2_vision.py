"""Qwen2-VL vision tower: dynamic-resolution ViT with 2D rotary
embeddings and a 2x2 spatial patch merger.

Reference: vllm/model_executor/models/qwen2_vl.py (Qwen2VisionModel:
patch embed :303, rotary :345, blocks :405, PatchMerger :270). JAX
re-design, run at ADMISSION like the CLIP tower (multimodal/vision.py):
inputs are the HF image processor's flattened patches
([n_patches, C * temporal_patch * patch^2]) plus per-image/video
``grid_thw`` (t, h, w in PATCH units); output is
[n_patches / merge^2, text_hidden] embedding rows.

Semantics matched to HF Qwen2VLForConditionalGeneration.model.visual:

* The patch stream arrives in MERGE-GROUP order (the processor emits
  each 2x2 spatial group contiguously); the rotary (h, w) ids are
  built with the same grouped permutation, and the merger simply
  reshapes consecutive merge^2 rows together.
* Attention is full (bidirectional) but BLOCK-DIAGONAL per image/video
  (cu_seqlens): patches never attend across inputs in one batch.
* 2D rotary: half the rotary dims rotate by the h id, half by the w id
  (head_dim/4 frequencies each), applied rotate-half style to q and k.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


def _ln(x, w, b, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * w + b).astype(x.dtype)


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


class Qwen2VisionEncoder:
    """Tower + merger from a Qwen2-VL checkpoint's ``visual.*``."""

    PREFIXES = ("model.visual.", "visual.")

    def __init__(self, tensors: dict, hf_config) -> None:
        vc = hf_config.vision_config
        self.depth = vc.depth
        self.embed_dim = int(getattr(vc, "embed_dim", None)
                             or vc.hidden_size)
        self.heads = vc.num_heads
        self.head_dim = self.embed_dim // self.heads
        self.merge = int(getattr(vc, "spatial_merge_size", 2))
        self.patch = vc.patch_size
        self.temporal_patch = int(getattr(vc, "temporal_patch_size", 2))
        self.params = self._load(tensors)
        self._fn = jax.jit(self._forward,
                           static_argnames=("grid_thw", ))

    # ------------------------------------------------------------------
    def _load(self, tensors: dict) -> dict:
        def t(name):
            for p in self.PREFIXES:
                if p + name in tensors:
                    return np.asarray(tensors[p + name], np.float32)
            raise KeyError(f"visual tensor {name!r} missing")

        p = {
            "patch": t("patch_embed.proj.weight").reshape(
                self.embed_dim, -1).T,  # [C*tp*ps*ps, E]
            "ln_q": t("merger.ln_q.weight"),
            "ln_q_b": t("merger.ln_q.bias"),
            "m0": t("merger.mlp.0.weight").T,
            "m0_b": t("merger.mlp.0.bias"),
            "m2": t("merger.mlp.2.weight").T,
            "m2_b": t("merger.mlp.2.bias"),
            "layers": [],
        }
        for i in range(self.depth):
            b = f"blocks.{i}."
            p["layers"].append({
                "n1": t(b + "norm1.weight"), "n1_b": t(b + "norm1.bias"),
                "n2": t(b + "norm2.weight"), "n2_b": t(b + "norm2.bias"),
                "qkv": t(b + "attn.qkv.weight").T,
                "qkv_b": t(b + "attn.qkv.bias"),
                "proj": t(b + "attn.proj.weight").T,
                "proj_b": t(b + "attn.proj.bias"),
                "fc1": t(b + "mlp.fc1.weight").T,
                "fc1_b": t(b + "mlp.fc1.bias"),
                "fc2": t(b + "mlp.fc2.weight").T,
                "fc2_b": t(b + "mlp.fc2.bias"),
            })
        p["layers"] = jax.tree.map(
            lambda *xs: np.stack(xs), *p["layers"])
        return jax.tree.map(jnp.asarray, p)

    # ------------------------------------------------------------------
    def _rot_ids(self, grid_thw) -> np.ndarray:
        """[n_patches, 2] (h, w) rotary ids in merge-group order —
        matches HF rot_pos_emb (qwen2_vl.py:345)."""
        out = []
        m = self.merge
        for t, h, w in grid_thw:
            hp = (np.repeat(np.arange(h), w).reshape(h, w)
                  .reshape(h // m, m, w // m, m)
                  .transpose(0, 2, 1, 3).reshape(-1))
            wp = (np.tile(np.arange(w), h).reshape(h, w)
                  .reshape(h // m, m, w // m, m)
                  .transpose(0, 2, 1, 3).reshape(-1))
            ids = np.stack([hp, wp], axis=-1)
            out.append(np.tile(ids, (t, 1)))
        return np.concatenate(out, axis=0)

    def _forward(self, params, x, rot_ids, seg_ids, *, grid_thw):
        E, Hh, D = self.embed_dim, self.heads, self.head_dim
        n = x.shape[0]
        h = (x @ params["patch"]).astype(jnp.float32)  # [n, E]

        # 2D rotary tables: head_dim/4 freqs each for h and w ids.
        quarter = D // 4
        inv = 1.0 / (10000.0 ** (np.arange(0, quarter * 2, 2) / (
            quarter * 2)))
        inv = jnp.asarray(inv, jnp.float32)  # [quarter]
        fh = rot_ids[:, 0:1].astype(jnp.float32) * inv[None]
        fw = rot_ids[:, 1:2].astype(jnp.float32) * inv[None]
        emb = jnp.concatenate([fh, fw], axis=-1)  # [n, D/2]
        emb = jnp.concatenate([emb, emb], axis=-1)  # [n, D]
        cos, sin = jnp.cos(emb)[:, None, :], jnp.sin(emb)[:, None, :]

        # Block-diagonal mask per image/video segment.
        mask = seg_ids[:, None] == seg_ids[None, :]  # [n, n]
        bias = jnp.where(mask, 0.0, -1e9)

        def layer(h, lp):
            x1 = _ln(h, lp["n1"], lp["n1_b"])
            qkv = (x1 @ lp["qkv"] + lp["qkv_b"]).reshape(n, 3, Hh, D)
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            q = q * cos + _rotate_half(q) * sin
            k = k * cos + _rotate_half(k) * sin
            s = jnp.einsum("inh,jnh->nij", q, k) / np.sqrt(D)
            probs = jax.nn.softmax(s + bias[None], axis=-1)
            ctx = jnp.einsum("nij,jnh->inh", probs, v).reshape(n, E)
            h = h + ctx @ lp["proj"] + lp["proj_b"]
            x2 = _ln(h, lp["n2"], lp["n2_b"])
            m = jax.nn.gelu(x2 @ lp["fc1"] + lp["fc1_b"],
                            approximate=False)
            return h + m @ lp["fc2"] + lp["fc2_b"], None

        h, _ = jax.lax.scan(layer, h, params["layers"])

        # Patch merger: merge^2 consecutive rows -> one text token.
        g = self.merge ** 2
        hq = _ln(h, params["ln_q"], params["ln_q_b"]).reshape(
            n // g, g * E)
        out = jax.nn.gelu(hq @ params["m0"] + params["m0_b"],
                          approximate=False)
        return out @ params["m2"] + params["m2_b"]

    # ------------------------------------------------------------------
    def encode(self, pixel_values: np.ndarray,
               grid_thw) -> list[np.ndarray]:
        """Flattened patches + per-input grids -> one [n_merged, H]
        embedding array per image/video."""
        grids = [tuple(int(v) for v in g) for g in grid_thw]
        counts = [t * h * w for t, h, w in grids]
        if sum(counts) != int(pixel_values.shape[0]):
            raise ValueError(
                f"pixel_values rows ({pixel_values.shape[0]}) do not "
                f"match grid_thw patch count ({sum(counts)})")
        rot = self._rot_ids(grids)
        # Attention is per FRAME: HF's cu_seqlens repeat h*w per
        # temporal patch (qwen2_vl.py rot_pos_emb/cu_seqlens), so a
        # video's frames do not attend each other either.
        seg_parts = []
        sid = 0
        for t, h, w in grids:
            seg_parts.append(np.repeat(np.arange(sid, sid + t), h * w))
            sid += t
        seg = np.concatenate(seg_parts)
        out = np.asarray(self._fn(
            self.params, jnp.asarray(pixel_values, jnp.float32),
            jnp.asarray(rot), jnp.asarray(seg),
            grid_thw=tuple(grids)))
        m2 = self.merge ** 2
        splits = np.cumsum([c // m2 for c in counts])[:-1]
        return [np.ascontiguousarray(a)
                for a in np.split(out, splits)]


def build_qwen2_vision_encoder(model_path: str,
                               hf_config) -> Optional[Qwen2VisionEncoder]:
    import os
    if not os.path.isdir(model_path):
        return None
    from vllm_distributed_tpu.models.loader import load_hf_state_dict
    try:
        tensors = load_hf_state_dict(model_path,
                                     prefixes=("model.visual.",
                                               "visual."))
        if not tensors:
            return None
        return Qwen2VisionEncoder(tensors, hf_config)
    except (FileNotFoundError, KeyError) as e:
        logger.warning("qwen2 vision tower unavailable: %s", e)
        return None
