"""Multimodal inputs — minimum image slice.

Reference: vllm/multimodal/ (registry + processors, ~5.2k LoC) and the
V1 engine's encoder plumbing (v1/core/encoder_cache_manager.py). This
slice covers the llava-style flow with PRE-COMPUTED image embeddings
(the output of the vision tower + projector): the prompt carries one
placeholder token per image, the processor expands each to the image's
token count, and the runner substitutes the embedding rows for the
placeholder positions at prefill. Running the vision tower in-engine is
the follow-up step; the cache/scheduler/runner plumbing is identical.
"""

import hashlib
from dataclasses import dataclass

import numpy as np


@dataclass
class MultiModalInput:
    """One image's contribution to a request."""

    # Pre-computed embedding rows [n_tokens, hidden_size] (llava: the
    # projector output; reference: get_multimodal_embeddings()).
    embeds: np.ndarray
    # Index of the first placeholder position in the EXPANDED prompt.
    offset: int
    # M-RoPE grid (t, h, w) in MERGED token units for Qwen2-VL-style
    # vision segments (embeds rows raster over it); None for 1-D
    # placeholder families (llava).
    grid: "tuple[int, int, int] | None" = None

    @property
    def num_tokens(self) -> int:
        return int(self.embeds.shape[0])

    def content_hash(self) -> bytes:
        return hashlib.sha256(
            np.ascontiguousarray(self.embeds).tobytes()).digest()


def compute_mrope_positions(
        prompt_len: int,
        mm_inputs: "list[MultiModalInput] | None",
) -> tuple[np.ndarray, int]:
    """([prompt_len, 3] (t, h, w) rotary ids, decode delta) for a
    Qwen2-VL-style prompt (reference: qwen2_vl.py get_rope_index).

    Text tokens advance all three ids together; a vision segment's
    tokens raster (frame, row, col) starting at the running id, after
    which the running id jumps past max(t, h, w). ``delta`` is what
    decode positions add to their sequence index (st_max - prompt_len).
    """
    pos = np.zeros((prompt_len, 3), np.int64)
    st = 0
    p = 0
    for inp in sorted(mm_inputs or [], key=lambda i: i.offset):
        if inp.offset < 0 or inp.grid is None:
            continue
        # Text run before this vision segment.
        span = inp.offset - p
        pos[p:inp.offset] = (st + np.arange(span))[:, None]
        st += span
        t, h, w = inp.grid
        n = t * h * w
        tt = np.repeat(np.arange(t), h * w)
        hh = np.tile(np.repeat(np.arange(h), w), t)
        ww = np.tile(np.arange(w), t * h)
        pos[inp.offset:inp.offset + n, 0] = st + tt
        pos[inp.offset:inp.offset + n, 1] = st + hh
        pos[inp.offset:inp.offset + n, 2] = st + ww
        st += max(t, h, w)
        p = inp.offset + n
    span = prompt_len - p
    pos[p:] = (st + np.arange(span))[:, None]
    st += span
    return pos, int(st - prompt_len)


def expand_image_placeholders(
    prompt_token_ids: list[int],
    image_token_id: int,
    images: list[np.ndarray],
) -> tuple[list[int], list[MultiModalInput]]:
    """Each placeholder token becomes image.shape[0] repeated placeholder
    tokens (reference: the prompt-replacement pass of
    multimodal/processing.py); returns the expanded ids and the
    positioned inputs."""
    n_ph = sum(1 for t in prompt_token_ids if t == image_token_id)
    if n_ph != len(images):
        raise ValueError(
            f"prompt has {n_ph} image placeholder tokens but "
            f"{len(images)} images were provided")
    out: list[int] = []
    inputs: list[MultiModalInput] = []
    it = iter(images)
    for t in prompt_token_ids:
        if t == image_token_id:
            emb = np.asarray(next(it))
            if emb.ndim != 2:
                raise ValueError(
                    "image embeddings must be [n_tokens, hidden_size]; "
                    f"got shape {emb.shape}")
            inputs.append(MultiModalInput(embeds=emb, offset=len(out)))
            out.extend([image_token_id] * emb.shape[0])
        else:
            out.append(t)
    return out, inputs


def mm_content_hash(inputs: list[MultiModalInput]) -> bytes:
    """Combined content hash of a request's images — folded into the
    request's block hashes so two prompts with identical token ids but
    different images can never share prefix-cache pages (reference:
    the mm_hash keys of v1/core/kv_cache_utils.py block hashing)."""
    h = hashlib.sha256()
    for inp in inputs:
        h.update(inp.content_hash())
        # signed: offset -1 marks cross-attention payloads (audio).
        h.update(inp.offset.to_bytes(8, "little", signed=True))
    return h.digest()
