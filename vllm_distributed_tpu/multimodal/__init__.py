"""Multimodal inputs — minimum image slice.

Reference: vllm/multimodal/ (registry + processors, ~5.2k LoC) and the
V1 engine's encoder plumbing (v1/core/encoder_cache_manager.py). This
slice covers the llava-style flow with PRE-COMPUTED image embeddings
(the output of the vision tower + projector): the prompt carries one
placeholder token per image, the processor expands each to the image's
token count, and the runner substitutes the embedding rows for the
placeholder positions at prefill. Running the vision tower in-engine is
the follow-up step; the cache/scheduler/runner plumbing is identical.
"""

import hashlib
from dataclasses import dataclass

import numpy as np


@dataclass
class MultiModalInput:
    """One image's contribution to a request."""

    # Pre-computed embedding rows [n_tokens, hidden_size] (llava: the
    # projector output; reference: get_multimodal_embeddings()).
    embeds: np.ndarray
    # Index of the first placeholder position in the EXPANDED prompt.
    offset: int

    @property
    def num_tokens(self) -> int:
        return int(self.embeds.shape[0])

    def content_hash(self) -> bytes:
        return hashlib.sha256(
            np.ascontiguousarray(self.embeds).tobytes()).digest()


def expand_image_placeholders(
    prompt_token_ids: list[int],
    image_token_id: int,
    images: list[np.ndarray],
) -> tuple[list[int], list[MultiModalInput]]:
    """Each placeholder token becomes image.shape[0] repeated placeholder
    tokens (reference: the prompt-replacement pass of
    multimodal/processing.py); returns the expanded ids and the
    positioned inputs."""
    n_ph = sum(1 for t in prompt_token_ids if t == image_token_id)
    if n_ph != len(images):
        raise ValueError(
            f"prompt has {n_ph} image placeholder tokens but "
            f"{len(images)} images were provided")
    out: list[int] = []
    inputs: list[MultiModalInput] = []
    it = iter(images)
    for t in prompt_token_ids:
        if t == image_token_id:
            emb = np.asarray(next(it))
            if emb.ndim != 2:
                raise ValueError(
                    "image embeddings must be [n_tokens, hidden_size]; "
                    f"got shape {emb.shape}")
            inputs.append(MultiModalInput(embeds=emb, offset=len(out)))
            out.extend([image_token_id] * emb.shape[0])
        else:
            out.append(t)
    return out, inputs


def mm_content_hash(inputs: list[MultiModalInput]) -> bytes:
    """Combined content hash of a request's images — folded into the
    request's block hashes so two prompts with identical token ids but
    different images can never share prefix-cache pages (reference:
    the mm_hash keys of v1/core/kv_cache_utils.py block hashing)."""
    h = hashlib.sha256()
    for inp in inputs:
        h.update(inp.content_hash())
        # signed: offset -1 marks cross-attention payloads (audio).
        h.update(inp.offset.to_bytes(8, "little", signed=True))
    return h.digest()
