"""Whisper audio encoder, run front-end-side at admission.

Reference: the encoder half of vllm/model_executor/models/whisper.py
(WhisperEncoder: two mel convolutions — the second stride-2 — plus
sinusoidal positions and a bidirectional pre-LN transformer). Placed
like the CLIP vision tower (multimodal/vision.py): audio encodes ONCE
at admission, and the [frames, d_model] hidden states ride the request
to the worker, which projects them into per-layer cross-KV state rows
(models/whisper.py install_cross_states).
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


def _ln(x, w, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * w + b).astype(x.dtype)


class WhisperAudioEncoder:
    """Functional JAX Whisper encoder from an HF checkpoint."""

    def __init__(self, tensors: dict, hf_config) -> None:
        self.heads = hf_config.encoder_attention_heads
        self.hidden = hf_config.d_model
        self.head_dim = self.hidden // self.heads
        self.frames = int(hf_config.max_source_positions)
        L = hf_config.encoder_layers
        self.params = self._load(tensors, L)
        self._jit = jax.jit(self._forward)

    def _load(self, tensors: dict, L: int) -> dict:
        E = "model.encoder."

        def t(name):
            return np.asarray(tensors[E + name])

        def stack(fmt, transpose=True):
            mats = [t(fmt.format(i)) for i in range(L)]
            return jnp.asarray(
                np.stack([m.T if transpose else m for m in mats]),
                jnp.float32)

        lay = "layers.{}."
        return {
            # Conv1d weight [out, in, k] -> [k, in, out] for lax.conv.
            "conv1_w": jnp.asarray(
                np.transpose(t("conv1.weight"), (2, 1, 0)), jnp.float32),
            "conv1_b": jnp.asarray(t("conv1.bias"), jnp.float32),
            "conv2_w": jnp.asarray(
                np.transpose(t("conv2.weight"), (2, 1, 0)), jnp.float32),
            "conv2_b": jnp.asarray(t("conv2.bias"), jnp.float32),
            "pos": jnp.asarray(t("embed_positions.weight"), jnp.float32),
            "ln1": stack(lay + "self_attn_layer_norm.weight", False),
            "ln1_b": stack(lay + "self_attn_layer_norm.bias", False),
            "wq": stack(lay + "self_attn.q_proj.weight"),
            "bq": stack(lay + "self_attn.q_proj.bias", False),
            "wk": stack(lay + "self_attn.k_proj.weight"),
            "wv": stack(lay + "self_attn.v_proj.weight"),
            "bv": stack(lay + "self_attn.v_proj.bias", False),
            "wo": stack(lay + "self_attn.out_proj.weight"),
            "bo": stack(lay + "self_attn.out_proj.bias", False),
            "ln2": stack(lay + "final_layer_norm.weight", False),
            "ln2_b": stack(lay + "final_layer_norm.bias", False),
            "fc1": stack(lay + "fc1.weight"),
            "fc1_b": stack(lay + "fc1.bias", False),
            "fc2": stack(lay + "fc2.weight"),
            "fc2_b": stack(lay + "fc2.bias", False),
            "ln_f": jnp.asarray(t("layer_norm.weight"), jnp.float32),
            "ln_f_b": jnp.asarray(t("layer_norm.bias"), jnp.float32),
        }

    def _forward(self, params: dict, mel: jax.Array) -> jax.Array:
        """mel [num_mel_bins, 2*frames] -> hidden [frames, d_model]."""
        x = mel.T[None, :, :]  # [1, T, C]
        x = jax.nn.gelu(jax.lax.conv_general_dilated(
            x, params["conv1_w"], (1, ), "SAME",
            dimension_numbers=("NWC", "WIO", "NWC")) + params["conv1_b"],
            approximate=False)
        x = jax.nn.gelu(jax.lax.conv_general_dilated(
            x, params["conv2_w"], (2, ), "SAME",
            dimension_numbers=("NWC", "WIO", "NWC")) + params["conv2_b"],
            approximate=False)
        h = x[0] + params["pos"]  # [frames, H]
        nh, hd = self.heads, self.head_dim
        F = h.shape[0]
        scale = hd ** -0.5

        _LAYER_KEYS = ("ln1", "ln1_b", "wq", "bq", "wk", "wv", "bv",
                       "wo", "bo", "ln2", "ln2_b", "fc1", "fc1_b",
                       "fc2", "fc2_b")

        def layer(h, i):
            p = {k: params[k][i] for k in _LAYER_KEYS}
            x = _ln(h, p["ln1"], p["ln1_b"])
            q = ((x @ p["wq"] + p["bq"]) * scale).reshape(F, nh, hd)
            k = (x @ p["wk"]).reshape(F, nh, hd)
            v = (x @ p["wv"] + p["bv"]).reshape(F, nh, hd)
            s = jnp.einsum("ind,jnd->nij", q, k)
            a = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("nij,jnd->ind", a, v).reshape(F, -1)
            h = h + ctx @ p["wo"] + p["bo"]
            x = _ln(h, p["ln2"], p["ln2_b"])
            m = jax.nn.gelu(x @ p["fc1"] + p["fc1_b"], approximate=False)
            return h + m @ p["fc2"] + p["fc2_b"]

        for i in range(params["wq"].shape[0]):
            h = layer(h, i)
        return _ln(h, params["ln_f"], params["ln_f_b"])

    def encode(self, input_features: np.ndarray) -> np.ndarray:
        """[num_mel_bins, 2*frames] (or batched [1, ...]) -> [frames, H]
        float32 numpy."""
        mel = np.asarray(input_features, np.float32)
        if mel.ndim == 3:
            mel = mel[0]
        out = self._jit(self.params, jnp.asarray(mel))
        return np.asarray(jax.device_get(out), np.float32)


def build_audio_encoder(model_path: str,
                        hf_config) -> Optional[WhisperAudioEncoder]:
    """Load the encoder half of a Whisper checkpoint (None when the
    path is not a local checkpoint — dummy-weight runs)."""
    import os
    if not os.path.isdir(model_path):
        return None
    from vllm_distributed_tpu.models.loader import load_hf_state_dict
    tensors = load_hf_state_dict(model_path,
                                 prefixes=("model.encoder.", ))
    if not any(k.startswith("model.encoder.") for k in tensors):
        return None
    logger.info("loaded whisper audio encoder (%d tensors)", len(tensors))
    return WhisperAudioEncoder(tensors, hf_config)
