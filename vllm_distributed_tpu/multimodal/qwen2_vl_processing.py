"""Chat-API media preprocessing for Qwen2-VL-family models.

Reference: the image/video input pipeline of vllm's chat_utils +
multimodal/video.py — image_url parts (and video frames) turn into the
HF Qwen2VLImageProcessor's flattened-patch layout, which the engine's
admission path (engine/processor.py _process_qwen2_vl) consumes
directly. Videos arrive as FRAME LISTS (data-URL images); container
decoding is out of scope in this image-less environment — the frame
path is exactly what the reference's video loader produces after
decode.
"""

from typing import Optional

import numpy as np

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

_PROCESSORS: dict = {}


def _processor(hf_config):
    key = id(hf_config)
    proc = _PROCESSORS.get(key)
    if proc is None:
        from transformers.models.qwen2_vl.image_processing_qwen2_vl import \
            Qwen2VLImageProcessor
        vc = hf_config.vision_config
        patch = int(vc.patch_size)
        merge = int(getattr(vc, "spatial_merge_size", 2))
        tile = patch * merge
        proc = Qwen2VLImageProcessor(
            patch_size=patch,
            merge_size=merge,
            temporal_patch_size=int(getattr(vc, "temporal_patch_size",
                                            2)),
            # Bounds in PIXELS; keep the floor at one merged tile so
            # tiny test images survive, cap at ~4k tiles.
            min_pixels=tile * tile,
            max_pixels=tile * tile * 4096,
        )
        _PROCESSORS[key] = proc
    return proc


def preprocess_chat_media(image_urls: list[str],
                          video_frame_lists: list[list[str]],
                          hf_config) -> Optional[dict]:
    """data-URL images / frame lists -> the engine's qwen2-vl
    multi_modal_data dict (flattened patches + grid_thw)."""
    from vllm_distributed_tpu.multimodal.image_processing import \
        decode_data_url
    if not image_urls and not video_frame_lists:
        return None
    proc = _processor(hf_config)
    mm: dict = {}
    if image_urls:
        images = [decode_data_url(u).convert("RGB")
                  for u in image_urls]
        out = proc(images=images, return_tensors="np")
        mm["pixel_values"] = np.asarray(out["pixel_values"], np.float32)
        mm["image_grid_thw"] = np.asarray(out["image_grid_thw"])
    if video_frame_lists:
        videos = []
        for frames in video_frame_lists:
            if not frames:
                raise ValueError("video content part has no frames")
            videos.append([np.asarray(
                decode_data_url(u).convert("RGB")) for u in frames])
        out = proc(images=None, videos=videos, return_tensors="np")
        mm["pixel_values_videos"] = np.asarray(
            out["pixel_values_videos"], np.float32)
        mm["video_grid_thw"] = np.asarray(out["video_grid_thw"])
    return mm


def media_token_strings(tokenizer, hf_config):
    """(image_token, video_token) string forms, None where absent."""
    out = []
    for attr in ("image_token_id", "video_token_id"):
        idx = getattr(hf_config, attr, None)
        tok = None
        if idx is not None and tokenizer is not None:
            try:
                tok = tokenizer.convert_ids_to_tokens(int(idx))
            except Exception:  # noqa: BLE001
                tok = None
        out.append(tok)
    return tuple(out)
