"""Shared-memory broadcast MessageQueue over the native ring buffer.

TPU-native equivalent of the reference's
vllm/distributed/device_communicators/shm_broadcast.py (ShmRingBuffer +
MessageQueue): one writer process broadcasts pickled control messages
(scheduler outputs, RPCs) to N same-host reader processes through a
lock-free shared-memory ring — no socket hop, no per-message syscalls.
The ring itself is C++ (native/shm_ring.cpp, built on first use with the
system g++ and loaded via ctypes); this layer adds chunked framing for
messages larger than one slot and the writer/reader handshake.

Wire format: 8-byte little-endian payload length, then the pickle bytes;
the stream is split into chunk_size slots (the reference sizes its
"small" slots at 10 MiB and overflows to a side channel — here large
messages just span slots, which keeps one code path).
"""

import ctypes
import os
import pickle
import subprocess
import threading
from typing import Optional

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native",
                    "shm_ring.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(_SRC), "_build")

_lib = None
_lib_lock = threading.Lock()

DEFAULT_CHUNK = 1 << 20  # 1 MiB slots
DEFAULT_CHUNKS = 16


class ShmRingError(RuntimeError):
    pass


class ShmRingOverrun(ShmRingError):
    """The writer lapped this reader: the slot it needed was reused."""


def _compile_lib() -> str:
    """Build the .so from the C++ source once, keyed by source mtime."""
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so = os.path.join(_BUILD_DIR, "shm_ring.so")
    stamp = os.path.join(_BUILD_DIR, "shm_ring.stamp")
    src_mtime = str(os.path.getmtime(_SRC))
    if os.path.exists(so) and os.path.exists(stamp):
        with open(stamp) as f:
            if f.read() == src_mtime:
                return so
    tmp = so + f".tmp.{os.getpid()}"
    # -lrt: shm_open/shm_unlink live in librt on glibc < 2.34 (a no-op
    # link on newer glibc where they merged into libc). Without it the
    # .so only loads when some earlier import already mapped librt into
    # the process — load-order-dependent dlopen failures.
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp,
           "-lrt"]
    logger.info("building shm ring: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so)  # atomic vs concurrent builders
    with open(stamp + ".tmp", "w") as f:
        f.write(src_mtime)
    os.replace(stamp + ".tmp", stamp)
    return so


def _get_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_compile_lib())
        lib.shm_ring_create.restype = ctypes.c_void_p
        lib.shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                        ctypes.c_uint64]
        lib.shm_ring_open.restype = ctypes.c_void_p
        lib.shm_ring_open.argtypes = [ctypes.c_char_p, ctypes.c_double]
        lib.shm_ring_register_reader.restype = ctypes.c_int64
        lib.shm_ring_register_reader.argtypes = [ctypes.c_void_p]
        lib.shm_ring_chunk_size.restype = ctypes.c_uint64
        lib.shm_ring_chunk_size.argtypes = [ctypes.c_void_p]
        lib.shm_ring_num_chunks.restype = ctypes.c_uint64
        lib.shm_ring_num_chunks.argtypes = [ctypes.c_void_p]
        lib.shm_ring_write.restype = ctypes.c_int64
        lib.shm_ring_write.argtypes = [ctypes.c_void_p,
                                       ctypes.c_char_p, ctypes.c_uint64,
                                       ctypes.c_double]
        lib.shm_ring_writer_seq.restype = ctypes.c_uint64
        lib.shm_ring_writer_seq.argtypes = [ctypes.c_void_p]
        lib.shm_ring_reader_count.restype = ctypes.c_uint64
        lib.shm_ring_reader_count.argtypes = [ctypes.c_void_p]
        lib.shm_ring_read.restype = ctypes.c_int64
        lib.shm_ring_read.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                      ctypes.c_uint64, ctypes.c_char_p,
                                      ctypes.c_double]
        lib.shm_ring_close.restype = None
        lib.shm_ring_close.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        _lib = lib
        return _lib


class MessageQueue:
    """One-writer N-reader broadcast queue.

    Writer: ``MessageQueue.create(name, num_readers)`` then ``enqueue``;
    the first enqueue blocks until all declared readers joined (the
    reference's handshake in MessageQueue.wait_until_ready). Readers:
    ``MessageQueue.join(name)`` then ``dequeue`` in a loop. FIFO,
    every reader sees every message.
    """

    def __init__(self, handle, name: str, is_writer: bool,
                 num_readers: int = 0, rank: int = -1,
                 start_seq: Optional[int] = None):
        self._lib = _get_lib()
        self._h = handle
        self._name = name
        self._is_writer = is_writer
        self._num_readers = num_readers
        self._rank = rank
        self._seq = (start_seq if start_seq is not None else
                     self._lib.shm_ring_writer_seq(handle))
        self._chunk = self._lib.shm_ring_chunk_size(handle)
        self._ready = False
        self._broken = False
        self._buf = ctypes.create_string_buffer(self._chunk)
        # Ring telemetry (metrics/telemetry.py): per-message wall time
        # spent blocked in the native write/read calls, plus the reader
        # backlog (writer_seq - reader_seq). Captured at construction —
        # the engine core installs its recorder only for that window.
        from vllm_distributed_tpu.metrics import telemetry
        self._telemetry = telemetry.current_recorder()

    # ------------------------------------------------------------------
    @classmethod
    def create(cls, name: str, num_readers: int,
               chunk_size: int = DEFAULT_CHUNK,
               num_chunks: int = DEFAULT_CHUNKS) -> "MessageQueue":
        lib = _get_lib()
        h = lib.shm_ring_create(name.encode(), chunk_size, num_chunks)
        if not h:
            raise ShmRingError(f"shm_ring_create({name!r}) failed")
        return cls(h, name, is_writer=True, num_readers=num_readers)

    @classmethod
    def join(cls, name: str, timeout: float = 30.0) -> "MessageQueue":
        lib = _get_lib()
        h = lib.shm_ring_open(name.encode(), timeout)
        if not h:
            raise ShmRingError(f"shm_ring_open({name!r}) timed out")
        # Capture the start cursor BEFORE registering: the writer's join
        # handshake can release it the instant the last reader registers,
        # and a message sent between register and a later seq capture
        # would be skipped forever.
        start_seq = lib.shm_ring_writer_seq(h)
        rank = lib.shm_ring_register_reader(h)
        if rank < 0:
            lib.shm_ring_close(h, None)
            raise ShmRingError("shm ring reader table full")
        return cls(h, name, is_writer=False, rank=rank,
                   start_seq=start_seq)

    # ------------------------------------------------------------------
    def _wait_ready(self, timeout: float) -> None:
        """Writer-side: block until every declared reader registered, so
        lap-accounting covers them from message 0."""
        import time
        deadline = time.monotonic() + timeout
        while True:
            if self._reader_count() >= self._num_readers:
                self._ready = True
                return
            if time.monotonic() >= deadline:
                raise ShmRingError(
                    f"only {self._reader_count()} of {self._num_readers} "
                    f"readers joined {self._name!r} within {timeout}s")
            time.sleep(0.005)

    def _reader_count(self) -> int:
        return self._lib.shm_ring_reader_count(self._h)

    def enqueue_bytes(self, payload: bytes, timeout: float = 30.0) -> None:
        """Broadcast raw bytes (callers that already serialized — e.g.
        the multi-host executor pickles SchedulerOutput once for both
        transports — skip a second pickle round)."""
        assert self._is_writer
        if self._broken:
            raise ShmRingError(
                f"queue {self._name!r} is broken: an earlier enqueue "
                "timed out mid-message, readers are desynced")
        if not self._ready:
            self._wait_ready(timeout)
        import time
        t0 = time.perf_counter()
        stream = len(payload).to_bytes(8, "little") + payload
        for off in range(0, len(stream), self._chunk):
            piece = stream[off:off + self._chunk]
            rc = self._lib.shm_ring_write(self._h, piece, len(piece),
                                          timeout)
            if rc == 0:
                continue
            # A timeout after the first chunk leaves a truncated message
            # in the ring; later writes would be parsed as its tail.
            # There is no broadcast rollback — poison the queue instead
            # of silently corrupting every reader's framing.
            if off > 0:
                self._broken = True
            if rc == -2:
                raise ShmRingError(
                    f"enqueue timed out: a reader of {self._name!r} has "
                    f"not drained the ring in {timeout}s")
            raise ShmRingError(f"shm_ring_write failed rc={rc}")
        self._telemetry.record_shm("write", time.perf_counter() - t0)

    def enqueue(self, obj, timeout: float = 30.0) -> None:
        self.enqueue_bytes(
            pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL), timeout)

    def dequeue_bytes(self, timeout: float = 30.0) -> bytes:
        assert not self._is_writer
        import time
        t0 = time.perf_counter()
        first = self._read_chunk(timeout)
        total = int.from_bytes(first[:8], "little")
        data = first[8:8 + total]
        while len(data) < total:
            piece = self._read_chunk(timeout)
            data += piece[:total - len(data)]
        # Backlog AFTER consuming this message: chunks the writer has
        # published that this reader has not yet dequeued (a persistent
        # positive lag means this reader is the pod's straggler).
        lag = max(
            int(self._lib.shm_ring_writer_seq(self._h)) - self._seq, 0)
        self._telemetry.record_shm("read", time.perf_counter() - t0,
                                   lag=lag)
        return data

    def dequeue(self, timeout: float = 30.0):
        return pickle.loads(self.dequeue_bytes(timeout))

    def _read_chunk(self, timeout: float) -> bytes:
        rc = self._lib.shm_ring_read(self._h, self._rank, self._seq,
                                     self._buf, timeout)
        if rc == -2:
            raise TimeoutError(
                f"dequeue timed out after {timeout}s on {self._name!r}")
        if rc == -3:
            raise ShmRingOverrun(
                f"reader {self._rank} lapped on {self._name!r}: raise "
                "num_chunks or drain faster")
        if rc < 0:
            raise ShmRingError(f"shm_ring_read failed rc={rc}")
        self._seq += 1
        # rc is the payload length: only that many bytes were copied.
        return self._buf[:rc]

    def close(self) -> None:
        if self._h is not None:
            unlink = self._name.encode() if self._is_writer else None
            self._lib.shm_ring_close(self._h, unlink)
            self._h = None

    def __del__(self):  # pragma: no cover - GC-order best effort
        try:
            self.close()
        except Exception:
            pass
