"""Async KV-pull connector: disaggregated prefill over a network channel.

Reference: vllm/distributed/kv_transfer/kv_connector/v1/nixl_connector.py —
the decode engine PULLS finished-prefill KV pages from the prefill
engine's memory, asynchronously, with completion notifications on both
sides and deferred page free on the producer (nixl_connector.py:295,
823-894). The reference transport is RDMA (NIXL); TPUs have no NIXL, so
this connector is the DCN-equivalent: a socket side-channel between the
hosts, with pages read out of / written into the paged HBM cache at step
boundaries on each engine's main thread.

Lifecycle (mirrors nixl_connector.py):

1. Prefill (producer) engine finishes a request. ``request_finished``
   returns ``defer=True`` — the pages stay allocated — plus
   ``kv_transfer_params`` = {pull host/port, remote request id, token
   count}. The params ride the final RequestOutput to the proxy, which
   forwards them on the decode-side request.
2. Decode (consumer) engine admits the request:
   ``get_num_new_matched_tokens`` -> (page-aligned external span, True);
   the scheduler allocates pages, holds the request in
   WAITING_FOR_REMOTE_KVS, and ``build_connector_meta`` emits a pull
   instruction.
3. Consumer worker: ``start_load_kv`` hands the pull to a background
   thread (socket IO only — no device access off the main thread). The
   fetched pages are queued; the next ``get_finished`` applies them to
   ``runner.kv_caches`` and reports ``finished_recving`` -> the scheduler
   re-queues the request, which now skips prefill for the pulled span.
4. The pull thread sends DONE to the producer; the producer's server
   queues the notification, its ``get_finished`` reports
   ``finished_sending`` -> the scheduler frees the deferred pages.

Device-access discipline: the jitted step DONATES the KV cache buffers,
so only the engine's main thread ever holds the live array reference.
Background threads do socket work exclusively; every device read (serve
a peer's page request) and write (apply a finished pull) happens inside
``get_finished``, which the model runner calls every step — including
steps that schedule zero tokens (the engine core keeps stepping while
transfers are in flight).
"""

import os
import queue
import socket
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Optional

import msgpack
import numpy as np

from vllm_distributed_tpu.distributed.kv_transfer import page_io, quant
from vllm_distributed_tpu.distributed.kv_transfer.base import (
    KVConnectorBase, KVConnectorRole)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.request import Request
from vllm_distributed_tpu.utils import fault_injection
from vllm_distributed_tpu.utils.retry import RetryPolicy, call_with_retry

logger = init_logger(__name__)

_LEN = struct.Struct("!Q")


def _send_msg(sock: socket.socket, obj: dict) -> None:
    payload = msgpack.packb(obj, use_bin_type=True)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length, ) = _LEN.unpack(header)
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    return msgpack.unpackb(payload, raw=False, strict_map_key=False)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


@dataclass
class _PullInstruction:
    """One held request's pull order (scheduler -> worker, rides on
    SchedulerOutput.kv_connector_metadata)."""

    req_id: str
    local_page_ids: list[int]
    host: str
    port: int
    remote_req_id: str
    # Producer-side page ids to read (from kv_transfer_params; the NIXL
    # handshake's block-descriptor exchange, nixl_connector.py:695).
    remote_page_ids: list[int] = field(default_factory=list)


@dataclass
class _SendRegistration:
    """Producer-side: one finished request's deferred pages, valid for
    serving until ``deadline`` (unix seconds)."""

    req_id: str
    page_ids: list[int]
    deadline: float


@dataclass
class DCNPullConnectorMetadata:
    pulls: list[_PullInstruction] = field(default_factory=list)
    # Producer: deferred pages to (un)register for serving. The worker
    # serves ONLY registered pages — once a registration expires or a
    # DONE lands, a late pull gets an error instead of silently reading
    # pages the scheduler may have reallocated to another request.
    register: list[_SendRegistration] = field(default_factory=list)
    # Consumer: abandoned pulls (watchdog timeout / abort). The worker
    # discards — never applies — a transfer for these ids that lands
    # later: its target pages will eventually be reclaimed.
    cancels: list[str] = field(default_factory=list)


@dataclass
class _ServeJob:
    """A peer's page-read request, parked until the main thread can
    read HBM; the server thread waits on ``done``."""

    remote_req_id: str
    request_pages: Optional[list[int]] = None
    reply: dict = field(default_factory=dict)
    done: threading.Event = field(default_factory=threading.Event)
    # Quantized-payload negotiation: the consumer advertises its codec
    # version ("accept_qcomm"); 0 / absent (old consumers) always gets
    # the raw format. "want_raw" is a fallback re-request after a
    # failed quantized decode — it must be answered raw.
    accept_qcomm: int = 0
    want_raw: bool = False


@dataclass
class _FinishedPull:
    req_id: str
    page_ids: list[int]
    # Pulled pages, staged as DEVICE arrays by the transfer thread when
    # possible (host numpy fallback): [L, n_pages, KVH_cache, PS, D].
    k: Optional[object]  # jax.Array | np.ndarray; None on error
    v: Optional[object]
    error: Optional[str] = None
    # Chunked-apply progress (pages [0, applied) already scattered).
    applied: int = 0


class DCNPullConnector(KVConnectorBase):
    """NIXL-equivalent async pull connector (see module docstring)."""

    # Connector label on the vdt:kv_transfer_* telemetry families.
    telemetry_name = "dcn_pull"

    def __init__(self, config, role: KVConnectorRole) -> None:
        super().__init__(config, role)
        # Captured at construction: the engine core installs its own
        # recorder only for its construction window.
        from vllm_distributed_tpu.metrics import telemetry
        self._telemetry = telemetry.current_recorder()
        kv_cfg = config.kv_transfer_config
        extra = kv_cfg.kv_connector_extra_config or {}
        self.block_size = config.cache_config.block_size
        self.is_producer = kv_cfg.is_kv_producer
        self.is_consumer = kv_cfg.is_kv_consumer
        self.pull_host = extra.get("pull_host", "127.0.0.1")
        self.pull_port = int(extra.get("pull_port", 0))
        ft_cfg = config.fault_tolerance_config
        # Socket-level retry for one pull attempt (transient transport
        # errors only; protocol errors surface as a failed pull).
        self.retry_policy = RetryPolicy(
            max_attempts=ft_cfg.retry_max_attempts,
            base_delay_s=ft_cfg.retry_base_delay_s,
            max_delay_s=ft_cfg.retry_max_delay_s)
        # Stats: socket-level pull retries (tests/observability).
        self.num_pull_retries = 0

        if role == KVConnectorRole.SCHEDULER:
            # ---- scheduler-side state ----
            # Requests whose pull was staged but not yet shipped to the
            # worker, and requests already pulled (admission re-pass must
            # return 0).
            self._staged_pulls: list[_PullInstruction] = []
            self._pulled: set[str] = set()
            self._staged_registrations: list[_SendRegistration] = []
            self._staged_cancels: list[str] = []
            # Producer: finished requests' page counts (stats/tests).
            self.num_deferred_frees = 0
        else:
            # ---- worker-side state ----
            self._serve_queue: "queue.Queue[_ServeJob]" = queue.Queue()
            self._done_notifications: "queue.Queue[str]" = queue.Queue()
            self._finished_pulls: "queue.Queue[_FinishedPull]" = queue.Queue()
            # Pulls mid-way through the chunked apply (see get_finished).
            self._applying: list[_FinishedPull] = []
            # Abandoned pulls: completed transfers for these ids are
            # discarded instead of applied (their pages get reclaimed).
            # Dict req_id -> monotonic expiry so entries whose transfer
            # never reports (the watchdog's own trigger case) cannot
            # accumulate forever on a long-lived consumer.
            self._cancelled_pulls: dict[str, float] = {}
            # Pulls that never started (injected drop): a cancel for
            # one needs no discard entry — nothing will ever apply.
            self._never_started: set[str] = set()
            # Stats: pages applied on the largest single step (tests).
            self.max_pages_applied_per_step = 0
            # Producer: currently-serveable deferred pages.
            self._registrations: dict[str, _SendRegistration] = {}
            # Producer pages staged for serving: remote_req_id -> page ids
            # (registered when the scheduler defers the free — the worker
            # learns them from the pull request itself; the page list
            # travels in the wire request).
            self._server: Optional[socket.socket] = None
            self._server_thread: Optional[threading.Thread] = None
            self._shutdown = threading.Event()
            if self.is_producer:
                self._start_server()

    # ==================================================================
    # Scheduler side
    # ==================================================================
    def get_num_new_matched_tokens(
            self, request: Request,
            num_computed_tokens: int) -> tuple[int, bool]:
        if not self.is_consumer:
            return 0, False
        params = request.kv_transfer_params
        if not self._valid_params(params):
            return 0, False
        if request.request_id in self._pulled:
            return 0, False  # re-admission after the pull landed
        bs = self.block_size
        # Whole pages only, and the last prompt token always recomputes
        # locally so it produces the first logit (same cap as the local
        # prefix cache).
        usable = min(int(params["num_tokens"]), request.num_tokens - 1)
        n_pages = usable // bs - num_computed_tokens // bs
        if n_pages <= 0:
            return 0, False
        return n_pages * bs, True

    @staticmethod
    def _valid_params(params) -> bool:
        """Client-supplied kv_transfer_params must never crash the core:
        a malformed dict simply disables the pull (local prefill runs)."""
        if not isinstance(params, dict):
            return False
        try:
            return (bool(params.get("remote_req_id"))
                    and int(params["num_tokens"]) > 0
                    and int(params["pull_port"]) > 0)
        except (KeyError, TypeError, ValueError):
            return False

    def update_state_after_alloc(self, request: Request,
                                 block_ids: list[int],
                                 num_external_tokens: int) -> None:
        if not self.is_consumer or num_external_tokens == 0:
            return
        params = request.kv_transfer_params
        if not self._valid_params(params):
            return
        bs = self.block_size
        start = request.num_computed_tokens // bs
        n = num_external_tokens // bs
        self._staged_pulls.append(
            _PullInstruction(
                req_id=request.request_id,
                local_page_ids=block_ids[start:start + n],
                host=params.get("pull_host", "127.0.0.1"),
                port=int(params["pull_port"]),
                remote_req_id=params["remote_req_id"],
                remote_page_ids=list(params.get("remote_page_ids",
                                                ()))[start:start + n],
            ))
        self._pulled.add(request.request_id)

    def build_connector_meta(
            self, scheduler_output) -> Optional[DCNPullConnectorMetadata]:
        meta = DCNPullConnectorMetadata()
        if self._staged_pulls:
            meta.pulls = self._staged_pulls
            self._staged_pulls = []
        if self._staged_registrations:
            meta.register = self._staged_registrations
            self._staged_registrations = []
        if self._staged_cancels:
            meta.cancels = self._staged_cancels
            self._staged_cancels = []
        for req_id in scheduler_output.finished_req_ids:
            self._pulled.discard(req_id)
        return meta

    def cancel_pull(self, req_id: str) -> None:
        # A cancel for a pull still sitting in _staged_pulls (never
        # shipped) can drop the instruction outright; otherwise the
        # worker gets the discard order with the next metadata.
        before = len(self._staged_pulls)
        self._staged_pulls = [p for p in self._staged_pulls
                              if p.req_id != req_id]
        if len(self._staged_pulls) == before:
            self._staged_cancels.append(req_id)

    def reset_for_retry(self, request: Request,
                        pull_resolved: bool) -> bool:
        """A resolved pull (worker reported) can always be re-staged;
        an UNRESOLVED one (watchdog timeout) cannot — a second pull
        under the same wire id would alias the late worker report of
        the first, so the scheduler degrades to local recompute."""
        if not pull_resolved and request.request_id in self._pulled:
            return False
        self._pulled.discard(request.request_id)
        return True

    def request_finished(
            self, request: Request,
            block_ids: list[int]) -> tuple[bool, Optional[dict]]:
        if not self.is_producer or not block_ids:
            return False, None
        from vllm_distributed_tpu.request import RequestStatus
        if request.status == RequestStatus.FINISHED_ABORTED:
            # Nobody will ever receive these coordinates; deferring the
            # free would leak the pages until the send timeout.
            return False, None
        # Hand the decode side its pull coordinates; pages stay alive
        # until it reports the pull done (deferred free,
        # nixl_connector.py:295). Only full prompt pages are usable.
        n_full = request.num_computed_tokens // self.block_size
        if n_full == 0:
            return False, None
        self.num_deferred_frees += 1
        extra = self.config.kv_transfer_config.kv_connector_extra_config \
            or {}
        import time
        # Monotonic deadline: an NTP step must not expire (or immortalize)
        # a deferred-free registration.
        self._staged_registrations.append(
            _SendRegistration(
                req_id=request.request_id,
                page_ids=block_ids[:n_full],
                deadline=time.monotonic() +
                float(extra.get("send_timeout_s", 300.0))))
        return True, {
            "remote_req_id": request.request_id,
            "pull_host": self.pull_host,
            "pull_port": int(extra.get("pull_port", self.pull_port)),
            "num_tokens": n_full * self.block_size,
            "remote_page_ids": block_ids[:n_full],
        }

    # ==================================================================
    # Worker side: producer page server
    # ==================================================================
    def _start_server(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.pull_host, self.pull_port))
        self.pull_port = srv.getsockname()[1]
        srv.listen(16)
        # Publish the actual bound port (port 0 auto-assigns) through the
        # shared config so the scheduler-side half hands peers the right
        # coordinates (worker half is constructed first: executor init
        # precedes scheduler init in EngineCore.__init__).
        kv_cfg = self.config.kv_transfer_config
        if kv_cfg.kv_connector_extra_config is None:
            kv_cfg.kv_connector_extra_config = {}
        kv_cfg.kv_connector_extra_config["pull_port"] = \
            srv.getsockname()[1]
        self._server = srv
        self._server_thread = threading.Thread(
            target=self._serve_loop, name="dcn-pull-server", daemon=True)
        self._server_thread.start()
        logger.info("DCN pull server listening on %s:%d", self.pull_host,
                    self.pull_port)

    def _serve_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _addr = self._server.accept()
            except OSError:
                return  # socket closed
            threading.Thread(target=self._serve_conn, args=(conn, ),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                msg = _recv_msg(conn)
                if msg is None:
                    return
                if msg["op"] == "pull":
                    # Unregistered requests get a fast rejection from
                    # this thread (no device access needed) instead of a
                    # 120s queue-drain timeout — with a short grace poll
                    # for a registration still in flight from the
                    # scheduler to the worker (one step of latency).
                    if not self._await_registration(msg["req_id"]):
                        _send_msg(conn, {
                            "ok": False,
                            "error": f"{msg['req_id']} not registered "
                                     "(never deferred, already pulled, "
                                     "or expired)"})
                        continue
                    job = _ServeJob(
                        remote_req_id=msg["req_id"],
                        request_pages=msg["page_ids"],
                        accept_qcomm=int(msg.get("accept_qcomm", 0)),
                        want_raw=bool(msg.get("raw", False)))
                    self._serve_queue.put(job)
                    # Wait for the main thread to read HBM (bounded so a
                    # dead engine can't wedge the peer forever).
                    if not job.done.wait(timeout=120.0):
                        _send_msg(conn, {"ok": False,
                                         "error": "page read timed out"})
                        continue
                    _send_msg(conn, job.reply)
                elif msg["op"] == "done":
                    self._done_notifications.put(msg["req_id"])
                    _send_msg(conn, {"ok": True})
        except OSError:
            pass
        finally:
            conn.close()

    def _await_registration(self, req_id: str, grace_s: float = 5.0) -> bool:
        """Server-thread check that ``req_id``'s pages are serveable,
        polling briefly in case the registration is still riding the
        scheduler->worker metadata (dict reads are GIL-safe)."""
        import time
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            if req_id in self._registrations:
                return True
            if self._shutdown.is_set():
                return False
            time.sleep(0.02)
        return False

    # ==================================================================
    # Worker side: consumer pull
    # ==================================================================
    def start_load_kv(self, metadata, runner) -> None:
        if not isinstance(metadata, DCNPullConnectorMetadata):
            return
        import time
        for reg in metadata.register:
            self._registrations[reg.req_id] = reg
        for req_id in metadata.cancels:
            if req_id in self._never_started:
                self._never_started.discard(req_id)
                continue
            # Bounded retention: long past any plausible transfer
            # lifetime the entry only leaks memory (the scheduler's
            # abandon backstop reclaimed the pages far earlier).
            self._cancelled_pulls[req_id] = time.monotonic() + 3600.0
        if self._cancelled_pulls:
            now = time.monotonic()
            self._cancelled_pulls = {
                rid: exp for rid, exp in self._cancelled_pulls.items()
                if exp > now
            }
        for pull in metadata.pulls:
            if fault_injection.should_fire("kv_pull.drop"):
                # Silent drop: no thread, no report — only the
                # scheduler's watchdog sweep recovers the request.
                logger.error("fault injection dropped KV pull for %s",
                             pull.req_id)
                self._never_started.add(pull.req_id)
                continue
            threading.Thread(target=self._pull_worker,
                             args=(pull, runner),
                             name=f"dcn-pull-{pull.req_id}",
                             daemon=True).start()

    def _pull_worker(self, pull: _PullInstruction, runner) -> None:
        """Background thread: socket IO only. Fetch the remote pages
        (with transient-error retry/backoff), queue them for main-thread
        application, notify the producer."""
        fault_injection.maybe_delay("kv_pull.delay")

        def count_retry(attempt, delay, err) -> None:
            self.num_pull_retries += 1

        self._telemetry.adjust_inflight(self.telemetry_name, +1)
        try:
            k_s, v_s = call_with_retry(
                lambda: self._fetch_and_stage(pull, runner),
                policy=self.retry_policy,
                description=f"KV pull for {pull.req_id}",
                on_retry=count_retry)
        except Exception as e:  # noqa: BLE001 - surfaced via error pull
            logger.error("KV pull for %s failed: %s", pull.req_id, e)
            self._telemetry.record_failure(self.telemetry_name)
            self._finished_pulls.put(
                _FinishedPull(req_id=pull.req_id,
                              page_ids=pull.local_page_ids,
                              k=None, v=None, error=str(e)))
            return
        finally:
            self._telemetry.adjust_inflight(self.telemetry_name, -1)
        self._finished_pulls.put(
            _FinishedPull(req_id=pull.req_id,
                          page_ids=pull.local_page_ids,
                          k=k_s, v=v_s))
        # The pages landed; a failed DONE handshake is only a deferred
        # producer free (its registration expires on its own), never an
        # errored pull — a second, errored report for the same request
        # would double-handle it (resume AND local recompute).
        try:
            with socket.create_connection((pull.host, pull.port),
                                          timeout=120.0) as sock:
                _send_msg(sock, {"op": "done",
                                 "req_id": pull.remote_req_id})
                _recv_msg(sock)  # ack
        except Exception as e:  # noqa: BLE001 - deferred-free only
            logger.warning(
                "KV pull for %s: done-notification failed after a "
                "successful transfer: %s", pull.req_id, e)

    def _fetch_and_stage(self, pull: _PullInstruction, runner):
        """One pull attempt: fetch the remote pages and stage them for
        the main thread's donated scatter. Transient socket errors
        propagate as OSError (retried by the caller's policy); protocol
        rejections raise RuntimeError (fatal — e.g. the producer's
        registration expired, so retrying cannot help)."""
        from vllm_distributed_tpu.metrics import telemetry
        t0 = telemetry.now()
        with socket.create_connection((pull.host, pull.port),
                                      timeout=120.0) as sock:
            # Advertise the codec only when THIS side's plane is on:
            # a VDT_QCOMM=0 consumer must stay byte-identical to the
            # unquantized plane even against an enabled producer. The
            # advertised number is the NEWEST payload version this
            # decoder accepts (latent payloads stamp a higher one), so
            # a pre-TPLA consumer advertising 1 never receives a
            # latent-format codec payload it would have to reject.
            accept = (quant.MAX_DECODE_VERSION
                      if quant.payload_enabled(self.telemetry_name)
                      else 0)
            _send_msg(sock, {"op": "pull",
                             "req_id": pull.remote_req_id,
                             "page_ids": pull.remote_page_ids,
                             "accept_qcomm": accept})
            reply = _recv_msg(sock)
            if reply is None:
                raise ConnectionResetError("connection dropped mid-pull")
            if not reply.get("ok"):
                raise RuntimeError(reply.get("error", "pull rejected"))
            nbytes, k, v = self._decode_reply(reply)
            if k is None:
                # Quantized payload failed validation (corrupt scale
                # header / geometry): degrade to the raw-precision
                # format on the same connection. The failed payload's
                # bytes still moved — keep them in the rx accounting.
                self._telemetry.record_qcomm_fallback(
                    self.telemetry_name)
                _send_msg(sock, {"op": "pull",
                                 "req_id": pull.remote_req_id,
                                 "page_ids": pull.remote_page_ids,
                                 "raw": True})
                reply = _recv_msg(sock)
                if reply is None:
                    raise ConnectionResetError(
                        "connection dropped mid-fallback-pull")
                if not reply.get("ok"):
                    raise RuntimeError(reply.get("error",
                                                 "fallback pull rejected"))
                raw_bytes, k, v = self._decode_reply(reply,
                                                     allow_codec=False)
                nbytes += raw_bytes
            self._telemetry.record_transfer(
                self.telemetry_name, "rx", nbytes,
                seconds=telemetry.now() - t0)
            # Latent-aware wire format: cross-check the payload's
            # geometry (codec header or raw-reply meta) against this
            # engine's model BEFORE staging — a foreign store fails the
            # pull cleanly (local recompute), never corrupts pages.
            codec = reply.get("codec")
            meta = (quant.latent_meta(codec) if quant.is_encoded(codec)
                    else reply.get("latent"))
            page_io.check_latent_wire(runner, k, v, meta)
            n = len(pull.local_page_ids)
            if k.shape[1] < n:
                raise RuntimeError(
                    f"producer served {k.shape[1]} pages, "
                    f"consumer allocated {n}")
            # Stage host->device ON THIS THREAD: the PCIe copy overlaps
            # the main thread's compute, and the main thread's apply is
            # then just the donated scatter.
            try:
                return page_io.stage_pages(runner, k[:, :n], v[:, :n])
            except Exception as stage_err:  # noqa: BLE001
                logger.warning(
                    "KV pull for %s: device staging failed (%s); "
                    "host fallback", pull.req_id, stage_err)
                return page_io.stage_pages(runner, k[:, :n], v[:, :n],
                                           on_device=False)

    def _decode_reply(self, reply: dict, allow_codec: bool = True):
        """One pull reply -> (wire_bytes, k, v) host arrays in wire
        layout. A quantized payload that fails validation returns
        (wire_bytes, None, None) so the caller can degrade to a raw
        re-request; a raw (pre-codec / VDT_QCOMM=0 / fallback) reply
        decodes exactly as before the codec existed."""
        payload = reply.get("codec")
        if quant.is_encoded(payload):
            nbytes = quant.encoded_nbytes(payload)
            if not allow_codec:
                raise RuntimeError(
                    "producer answered a raw-format request with a "
                    "quantized payload")
            try:
                k, v = quant.decode_pages(payload)
            except quant.QuantCodecError as e:
                logger.warning(
                    "quantized KV payload failed validation (%s); "
                    "re-requesting raw precision", e)
                return nbytes, None, None
            # Savings are credited HERE, after a successful decode — a
            # payload that fails validation and degrades to a raw
            # re-request moved quantized+raw bytes (worse than raw
            # alone) and must never count as a saving.
            self._telemetry.record_qcomm(
                self.telemetry_name, quant.raw_nbytes(payload) - nbytes)
            return nbytes, k, v
        k = np.frombuffer(reply["k"], dtype=reply["dtype"]).reshape(
            reply["k_shape"])
        v = np.frombuffer(reply["v"], dtype=reply["dtype"]).reshape(
            reply["v_shape"])
        return len(reply["k"]) + len(reply["v"]), k, v

    # ==================================================================
    # Worker side: main-thread device access
    # ==================================================================
    def get_finished(self, runner) -> tuple[set[str], set[str], set[str]]:
        finished_sending: set[str] = set()
        finished_recving: set[str] = set()
        failed_recving: set[str] = set()

        # Producer: serve queued peer reads from HBM.
        while True:
            try:
                job = self._serve_queue.get_nowait()
            except queue.Empty:
                break
            job.reply = self._read_pages(job, runner)
            job.done.set()

        # Producer: drain DONE notifications and expire stale
        # registrations — either way the pages stop being serveable
        # BEFORE the scheduler frees them (finished_sending triggers the
        # free), so a late pull can never read reallocated pages.
        while True:
            try:
                req_id = self._done_notifications.get_nowait()
            except queue.Empty:
                break
            self._registrations.pop(req_id, None)
            finished_sending.add(req_id)
        if self._registrations:
            import time
            now = time.monotonic()
            for req_id in list(self._registrations):
                if now > self._registrations[req_id].deadline:
                    logger.warning(
                        "deferred pages for %s expired unpulled; "
                        "releasing", req_id)
                    del self._registrations[req_id]
                    finished_sending.add(req_id)

        # Consumer: apply finished pulls to the paged cache in bounded
        # page CHUNKS via the donated in-place scatter — a large pull
        # spreads over several steps instead of stalling one (the pages
        # were already staged on device by the transfer thread, so each
        # chunk is HBM work only; reference: the layerwise
        # wait_for_layer_load overlap contract of kv_connector/v1/base.py
        # + nixl_connector.py async completion). Errored pulls go back
        # as FAILED so the scheduler recomputes the span locally instead
        # of reading never-written pages.
        from vllm_distributed_tpu import envs
        chunk = envs.VDT_KV_APPLY_CHUNK_PAGES
        while True:
            try:
                self._applying.append(self._finished_pulls.get_nowait())
            except queue.Empty:
                break
        budget = chunk
        pages_this_step = 0
        still_applying: list[_FinishedPull] = []
        for done in self._applying:
            if done.req_id in self._cancelled_pulls:
                # Abandoned by the scheduler (watchdog timeout/abort):
                # the target pages will be reclaimed, so the transfer
                # must never touch them. Discard and report, so the
                # scheduler can free the parked pages promptly.
                self._cancelled_pulls.pop(done.req_id, None)
                logger.warning(
                    "discarding completed pull for cancelled request %s "
                    "(%d pages, applied %d before the cancel landed)",
                    done.req_id, len(done.page_ids), done.applied)
                finished_recving.add(done.req_id)
                continue
            if done.error is not None:
                logger.error(
                    "request %s: external KV unavailable (%s); span will "
                    "be recomputed locally", done.req_id, done.error)
                failed_recving.add(done.req_id)
                continue
            n = len(done.page_ids)
            while done.applied < n:
                take = min(chunk, n - done.applied)
                if take > budget:
                    break  # resume next step
                page_io.scatter_pages_chunk(runner, done.page_ids,
                                            done.k, done.v,
                                            done.applied, chunk)
                done.applied += take
                budget -= take
                pages_this_step += take
            if done.applied >= n:
                finished_recving.add(done.req_id)
                logger.info("applied %d pulled KV pages for %s",
                            n, done.req_id)
            else:
                still_applying.append(done)
        self._applying = still_applying
        self.max_pages_applied_per_step = max(
            self.max_pages_applied_per_step, pages_this_step)
        return finished_sending, finished_recving, failed_recving

    def _read_pages(self, job: _ServeJob, runner) -> dict:
        """Main-thread HBM read of one finished request's pages. Pages are
        de-replicated to checkpoint KV heads so the store is TP-invariant
        (a tp=16 producer serves a tp=8 consumer fine)."""
        page_ids = job.request_pages
        reg = self._registrations.get(job.remote_req_id)
        if reg is None:
            return {"ok": False,
                    "error": f"{job.remote_req_id} not registered "
                             "(never deferred, already pulled, or "
                             "expired)"}
        if not page_ids or not set(page_ids).issubset(reg.page_ids):
            return {"ok": False,
                    "error": f"pages {page_ids} not registered for "
                             f"{job.remote_req_id}"}
        from vllm_distributed_tpu.metrics import telemetry
        t0 = telemetry.now()
        k, v = page_io.gather_pages(runner, page_ids)
        # MLA latent pages ship the versioned latent wire format: full
        # unsharded rows + geometry meta, so a consumer mesh of any TP
        # degree re-slices on receipt. Latent codec payloads need the
        # consumer to accept LATENT_WIRE_VERSION (a pre-TPLA consumer
        # advertising 1 gets the raw form instead).
        latent = page_io.latent_wire_meta(runner)
        need = (quant.LATENT_WIRE_VERSION if latent is not None
                else quant.WIRE_VERSION)
        if (not job.want_raw and job.accept_qcomm >= need
                and quant.payload_enabled(self.telemetry_name, k.dtype)):
            # bytes_saved is credited by the CONSUMER after a
            # successful decode — crediting at encode would overstate
            # savings exactly when a corrupt payload degrades to a raw
            # re-request.
            payload = quant.encode_pages(k, v, latent=latent)
            nbytes = quant.encoded_nbytes(payload)
            reply = {"ok": True, "codec": payload}
        else:
            nbytes = k.nbytes + v.nbytes
            reply = {
                "ok": True,
                "k": k.tobytes(),
                "v": v.tobytes(),
                "k_shape": list(k.shape),
                "v_shape": list(v.shape),
                "dtype": str(k.dtype),
            }
            if latent is not None:
                reply["latent"] = latent
        self._telemetry.record_transfer(self.telemetry_name, "tx",
                                        nbytes,
                                        seconds=telemetry.now() - t0)
        return reply

    def shutdown(self) -> None:
        self._shutdown.set()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
