"""KV-transfer connector API: the seam for disaggregated prefill/decode.

Reference: vllm/distributed/kv_transfer/kv_connector/v1/base.py:1-288 —
the same scheduler-side / worker-side split:

* Scheduler side (runs in the engine-core process, no device access):
  ``get_num_new_matched_tokens`` (how much of a waiting prompt's KV can
  come from outside), ``update_state_after_alloc`` (pages granted for the
  external span), ``build_connector_meta`` (per-step instructions
  piggybacked on SchedulerOutput), ``request_finished`` (deferred-free /
  handoff params).
* Worker side (runs next to the model runner, owns device transfers):
  ``start_load_kv`` before the forward pass, ``save_kv`` after it,
  ``get_finished`` for async completion notifications.

TPU adaptation: the KV cache is a sharded jax array owned by the model
runner, so worker-side methods receive the runner and mutate
``runner.kv_caches`` with scatter/gather device ops instead of writing
GPU tensors layer-by-layer during the forward (XLA owns the forward; KV
moves happen at step boundaries).
"""

import enum
from typing import TYPE_CHECKING, Any, Optional

if TYPE_CHECKING:
    from vllm_distributed_tpu.request import Request


class KVConnectorRole(enum.Enum):
    SCHEDULER = "scheduler"
    WORKER = "worker"


class KVConnectorBase:
    """Both halves of the connector API; subclasses implement the side(s)
    they support (reference: base.py:53 role enum + split)."""

    def __init__(self, config, role: KVConnectorRole) -> None:
        self.config = config
        self.role = role
        # Scheduler side: set by the Scheduler so connectors can query
        # current block ids without threading them through every hook.
        self.kv_manager = None

    # ------------------------------------------------------------------
    # Scheduler side
    # ------------------------------------------------------------------
    def get_num_new_matched_tokens(
            self, request: "Request",
            num_computed_tokens: int) -> tuple[int, bool]:
        """Tokens beyond ``num_computed_tokens`` whose KV can be loaded
        externally (multiple of the page size; capped so at least one
        prompt token remains to compute). Second element: True when the
        load is asynchronous (the scheduler must hold the request until
        the worker reports the load finished)."""
        return 0, False

    def update_state_after_alloc(self, request: "Request",
                                 block_ids: list[int],
                                 num_external_tokens: int) -> None:
        """Called after pages were allocated for a request with external
        tokens; ``block_ids`` is the request's full page list."""

    def build_connector_meta(self, scheduler_output) -> Optional[Any]:
        """Per-step worker instructions; attached to
        ``SchedulerOutput.kv_connector_metadata`` (must be picklable for
        the multiprocess engine core)."""
        return None

    def request_finished(
            self, request: "Request",
            block_ids: list[int]) -> tuple[bool, Optional[dict]]:
        """Request teardown hook. Returns (defer_free, kv_transfer_params):
        defer_free=True keeps the pages alive until the peer pulled them
        (reference: nixl_connector.py:295)."""
        return False, None

    def take_alloc_failures(self) -> set[str]:
        """Drain request ids whose external load failed at/after
        admission WITHOUT a pull ever being staged (e.g. producer
        resolution failed after alloc). The scheduler's watchdog sweep
        routes them through the failed-pull requeue path instead of
        leaving them parked in WAITING_FOR_REMOTE_KVS forever."""
        return set()

    def reset_for_retry(self, request: "Request",
                        pull_resolved: bool) -> bool:
        """Scheduler asks whether a failed pull can be cleanly re-staged
        at the request's next admission. ``pull_resolved`` is True when
        the worker definitively reported the pull finished/failed (no
        transfer for this id can still be in flight). Return False to
        make the scheduler degrade to local prefill recompute."""
        return False

    def cancel_pull(self, req_id: str) -> None:
        """Scheduler abandoned this request's in-flight pull (watchdog
        timeout or abort): the worker side must DISCARD — never apply —
        a transfer for this id that completes later, because the pages
        it targeted will eventually be reclaimed. Async connectors ship
        the cancel to the worker in their next metadata."""

    # ------------------------------------------------------------------
    # Worker side
    # ------------------------------------------------------------------
    def start_load_kv(self, metadata, runner) -> None:
        """Load external KV into ``runner.kv_caches`` pages BEFORE the
        step's forward (reference: base.py start_load_kv +
        wait_for_layer_load, collapsed: XLA runs the whole forward as one
        program, so loads complete up front)."""

    def save_kv(self, metadata, runner) -> None:
        """Persist/send KV pages AFTER the step's forward wrote them
        (reference: save_kv_layer + wait_for_save, collapsed)."""

    def get_finished(self, runner) -> tuple[set[str], set[str], set[str]]:
        """(finished_sending, finished_recving, failed_recving) request
        ids for async transfers; synchronous connectors return empty
        sets. Failed pulls re-queue for local recompute of the span.

        Called on the runner's main thread EVERY step (including steps
        that schedule zero tokens) — this is where async connectors apply
        completed pulls to ``runner.kv_caches`` and service queued peer
        reads, keeping all device access off background threads (the
        jitted step donates the cache buffers, so only the main thread
        ever holds the live array reference)."""
        return set(), set(), set()
