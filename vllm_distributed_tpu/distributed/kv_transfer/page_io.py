"""Paged-cache <-> host page movement shared by KV connectors.

Every connector exchanges pages in a TP-invariant wire layout: checkpoint
KV heads only (replica heads added for tp > num_kv_heads are identical by
construction, models/llama.py kv-head replication). These helpers own the
de-replicate / re-replicate transform and the device gather/scatter so
the layout lives in exactly one place.
"""

import numpy as np


def _replication(runner) -> int:
    return getattr(runner.model.cfg, "num_kv_head_replicas", 1)


def _record(runner, direction: str, num_bytes: int, t0: float) -> None:
    """Device-side page movement telemetry, labeled connector="page_io"
    — distinct from the network/filesystem legs the connectors record,
    so HBM gather/scatter cost is attributable separately (sums per
    label stay exact). ``runner._telemetry`` is the owning engine
    core's recorder, captured at runner construction; standalone tools
    fall back to the process default."""
    rec = getattr(runner, "_telemetry", None)
    if rec is None:
        return
    from vllm_distributed_tpu.metrics import telemetry
    rec.record_transfer("page_io", direction, num_bytes,
                        seconds=telemetry.now() - t0)


def _stage_views(runner):
    """[(cache_dict, (layer_lo, layer_hi), store)] — one entry for the
    flat runner, one per stage for the pipeline-parallel runner (whose
    kv_caches is a LIST of per-stage slices; the wire layout is always
    the full [L_total, ...] stack, so connectors stay PP-agnostic)."""
    kv = runner.kv_caches
    if isinstance(kv, list):
        ranges = runner.layer_ranges

        def store(idx):
            def put(new):
                runner.kv_caches[idx] = new
            return put

        return [(kv[p], ranges[p], store(p)) for p in range(len(kv))]

    def put(new):
        runner.kv_caches = new

    return [(kv, (0, kv["k"].shape[0]), put)]


def gather_pages(runner, page_ids) -> tuple[np.ndarray, np.ndarray]:
    """Read pages out of the device cache as host numpy in wire layout:
    [L, n_pages, KVH_checkpoint, page_size, head_dim] (stages
    concatenated on the layer dim under pipeline parallelism)."""
    import jax

    from vllm_distributed_tpu.metrics import telemetry
    t0 = telemetry.now()
    pages = np.asarray(page_ids, np.int32)
    r = _replication(runner)
    # Dispatch every stage's gather before fetching any: the N
    # device->host copies are independent and overlap.
    slices = [(cache["k"][:, pages], cache["v"][:, pages])
              for cache, _, _ in _stage_views(runner)]
    ks = [np.asarray(jax.device_get(k))[:, :, ::r] for k, _ in slices]
    vs = [np.asarray(jax.device_get(v))[:, :, ::r] for _, v in slices]
    k_out = np.concatenate(ks, axis=0)
    v_out = np.concatenate(vs, axis=0)
    _record(runner, "tx", k_out.nbytes + v_out.nbytes, t0)
    return k_out, v_out


def scatter_pages(runner, page_ids, k: np.ndarray, v: np.ndarray) -> None:
    """Write wire-layout pages into the device cache, re-expanding KV
    heads for this deployment's replication factor. Updates
    ``runner.kv_caches`` in place (new arrays; the old buffers are
    donated away by the next jitted step)."""
    from vllm_distributed_tpu.metrics import telemetry
    t0 = telemetry.now()
    pages = np.asarray(page_ids, np.int32)
    k, v = stage_pages(runner, k, v, on_device=False)
    for cache, (lo, hi), put in _stage_views(runner):
        k_all, v_all = cache["k"], cache["v"]
        put({
            "k": k_all.at[:, pages].set(k[lo:hi].astype(k_all.dtype)),
            "v": v_all.at[:, pages].set(v[lo:hi].astype(v_all.dtype)),
        })
    _record(runner, "rx", k.nbytes + v.nbytes, t0)


_scatter_donated_fn = None  # built lazily (module import stays jax-free)


def _scatter_donated():
    """In-place (donated) page write — no full-cache copy, unlike a bare
    .at[].set on a live array. Padding slots carry an out-of-range page
    id and drop."""
    global _scatter_donated_fn
    if _scatter_donated_fn is None:
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def fn(k_all, v_all, pages, k, v):
            return (k_all.at[:, pages].set(k.astype(k_all.dtype),
                                           mode="drop"),
                    v_all.at[:, pages].set(v.astype(v_all.dtype),
                                           mode="drop"))

        _scatter_donated_fn = fn
    return _scatter_donated_fn


def stage_pages(runner, k: np.ndarray, v: np.ndarray,
                on_device: bool = True):
    """Wire-layout pages -> CACHE layout (replication re-applied) — the
    single home of that transform for the staging path. With
    ``on_device`` the result is device arrays; safe from a transfer
    thread (only dispatches an async host->device copy, overlapping
    PCIe with the main thread's compute). ``on_device=False`` keeps
    host numpy (fallback when a thread cannot touch the device)."""
    r = _replication(runner)
    if r > 1:
        k = np.repeat(k, r, axis=2)
        v = np.repeat(v, r, axis=2)
    if not on_device:
        return k, v
    import jax.numpy as jnp
    return jnp.asarray(k), jnp.asarray(v)


def scatter_pages_chunk(runner, page_ids, k_dev, v_dev, lo: int,
                        chunk: int) -> None:
    """Apply pages [lo, lo+chunk) of a staged pull via the donated
    scatter; page id padding (for the fixed chunk shape) drops."""
    import jax.numpy as jnp

    from vllm_distributed_tpu.metrics import telemetry
    t0 = telemetry.now()
    nbytes = 0
    n = len(page_ids)
    take = min(chunk, n - lo)
    views = _stage_views(runner)
    # Every stage shares the pool geometry; build the padded id vector
    # (out-of-range sentinel drops) and upload it once.
    num_pages = views[0][0]["k"].shape[1]
    ids = np.full((chunk, ), num_pages, np.int32)
    ids[:take] = np.asarray(page_ids[lo:lo + take], np.int32)
    ids_dev = jnp.asarray(ids)
    pad = [(0, 0), (0, chunk - take)] + [(0, 0)] * (k_dev.ndim - 2)
    for cache, (llo, lhi), put in views:
        k_all, v_all = cache["k"], cache["v"]
        k_c = jnp.pad(k_dev[llo:lhi, lo:lo + take], pad)
        v_c = jnp.pad(v_dev[llo:lhi, lo:lo + take], pad)
        nbytes += k_c.nbytes + v_c.nbytes
        k_new, v_new = _scatter_donated()(k_all, v_all, ids_dev,
                                          k_c, v_c)
        put({"k": k_new, "v": v_new})
    _record(runner, "rx", nbytes, t0)
