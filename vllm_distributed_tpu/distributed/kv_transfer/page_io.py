"""Paged-cache <-> host page movement shared by KV connectors.

Every connector exchanges pages in a TP-invariant wire layout: checkpoint
KV heads only (replica heads added for tp > num_kv_heads are identical by
construction, models/llama.py kv-head replication). These helpers own the
de-replicate / re-replicate transform and the device gather/scatter so
the layout lives in exactly one place.
"""

import numpy as np


def _replication(runner) -> int:
    return getattr(runner.model.cfg, "num_kv_head_replicas", 1)


def gather_pages(runner, page_ids) -> tuple[np.ndarray, np.ndarray]:
    """Read pages out of the device cache as host numpy in wire layout:
    [L, n_pages, KVH_checkpoint, page_size, head_dim]."""
    import jax
    pages = np.asarray(page_ids, np.int32)
    r = _replication(runner)
    k = np.asarray(jax.device_get(runner.kv_caches["k"][:, pages]))[:, :, ::r]
    v = np.asarray(jax.device_get(runner.kv_caches["v"][:, pages]))[:, :, ::r]
    return k, v


def scatter_pages(runner, page_ids, k: np.ndarray, v: np.ndarray) -> None:
    """Write wire-layout pages into the device cache, re-expanding KV
    heads for this deployment's replication factor. Updates
    ``runner.kv_caches`` in place (new arrays; the old buffers are
    donated away by the next jitted step)."""
    pages = np.asarray(page_ids, np.int32)
    k, v = stage_pages(runner, k, v, on_device=False)
    k_all = runner.kv_caches["k"]
    v_all = runner.kv_caches["v"]
    runner.kv_caches = {
        "k": k_all.at[:, pages].set(k.astype(k_all.dtype)),
        "v": v_all.at[:, pages].set(v.astype(v_all.dtype)),
    }


_scatter_donated_fn = None  # built lazily (module import stays jax-free)


def _scatter_donated():
    """In-place (donated) page write — no full-cache copy, unlike a bare
    .at[].set on a live array. Padding slots carry an out-of-range page
    id and drop."""
    global _scatter_donated_fn
    if _scatter_donated_fn is None:
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def fn(k_all, v_all, pages, k, v):
            return (k_all.at[:, pages].set(k.astype(k_all.dtype),
                                           mode="drop"),
                    v_all.at[:, pages].set(v.astype(v_all.dtype),
                                           mode="drop"))

        _scatter_donated_fn = fn
    return _scatter_donated_fn


def stage_pages(runner, k: np.ndarray, v: np.ndarray,
                on_device: bool = True):
    """Wire-layout pages -> CACHE layout (replication re-applied) — the
    single home of that transform for the staging path. With
    ``on_device`` the result is device arrays; safe from a transfer
    thread (only dispatches an async host->device copy, overlapping
    PCIe with the main thread's compute). ``on_device=False`` keeps
    host numpy (fallback when a thread cannot touch the device)."""
    r = _replication(runner)
    if r > 1:
        k = np.repeat(k, r, axis=2)
        v = np.repeat(v, r, axis=2)
    if not on_device:
        return k, v
    import jax.numpy as jnp
    return jnp.asarray(k), jnp.asarray(v)


def scatter_pages_chunk(runner, page_ids, k_dev, v_dev, lo: int,
                        chunk: int) -> None:
    """Apply pages [lo, lo+chunk) of a staged pull via the donated
    scatter; page id padding (for the fixed chunk shape) drops."""
    import jax.numpy as jnp
    n = len(page_ids)
    num_pages = runner.kv_caches["k"].shape[1]
    ids = np.full((chunk, ), num_pages, np.int32)
    take = min(chunk, n - lo)
    ids[:take] = np.asarray(page_ids[lo:lo + take], np.int32)
    k_all, v_all = runner.kv_caches["k"], runner.kv_caches["v"]
    pad = [(0, 0), (0, chunk - take)] + [(0, 0)] * (k_dev.ndim - 2)
    k_c = jnp.pad(k_dev[:, lo:lo + take], pad)
    v_c = jnp.pad(v_dev[:, lo:lo + take], pad)
    k_new, v_new = _scatter_donated()(k_all, v_all, jnp.asarray(ids),
                                      k_c, v_c)
    runner.kv_caches = {"k": k_new, "v": v_new}
