"""Paged-cache <-> host page movement shared by KV connectors.

Every connector exchanges pages in a TP-invariant wire layout:

* Standard K/V caches: checkpoint KV heads only (replica heads added
  for tp > num_kv_heads are identical by construction, models/llama.py
  kv-head replication).
* MLA latent caches (models/deepseek.py): FULL UNSHARDED latent rows —
  the "k" slot of every (k, v) pair carries the kv_c latent stack
  [L, n_pages, page_size, kv_lora_rank] and the "v" slot the rope
  sidecar [L, n_pages, page_size, rope_dim], both unpadded. A producer
  serving the TPLA-sharded layout (ops/mla.py, kv_lora_rank/TP lanes
  per rank) re-assembles full rows on gather and a consumer of ANY TP
  degree re-slices them into its own layout on scatter — that
  prefill/decode asymmetry is what lets a TP=1 prefill engine feed a
  TP=8 TPLA decode engine (and vice versa) bit-exactly. Payload
  geometry (kv_lora_rank, rope_dim, tp_shard) rides the versioned wire
  format (quant.py latent headers, raw-reply "latent" meta) and is
  cross-checked by check_latent_wire before any scatter: a mismatched
  store is a clean rejection, never silent corruption.

These helpers own the de-replicate / re-replicate and shard / unshard
transforms and the device gather/scatter so the layout lives in exactly
one place.
"""

import numpy as np


def _replication(runner) -> int:
    return getattr(runner.model.cfg, "num_kv_head_replicas", 1)


def _latent_geometry(runner):
    """(kv_lora_rank, rope_dim, shards) when the runner serves an MLA
    latent cache, else None."""
    cfg = getattr(runner.model, "cfg", None)
    if cfg is None or not getattr(cfg, "mla", False):
        return None
    return (int(cfg.kv_lora_rank), int(cfg.qk_rope_head_dim),
            max(1, int(getattr(cfg, "tpla_shards", 1) or 1)))


def latent_wire_meta(runner):
    """Latent wire-format geometry dict for payload headers (None for
    standard K/V models)."""
    geo = _latent_geometry(runner)
    if geo is None:
        return None
    lkv, rope, shards = geo
    return {"kv_lora_rank": lkv, "rope_dim": rope, "tp_shard": shards}


def check_latent_wire(runner, k: np.ndarray, v: np.ndarray,
                      meta=None) -> None:
    """Reject a wire payload whose layout does not fit this runner's
    cache BEFORE any scatter: a latent payload into a standard-KV
    engine (or vice versa), or latent geometry from a different model.
    Raises RuntimeError — connectors surface it as a failed pull, so
    the span recomputes locally instead of reading corrupt pages."""
    geo = _latent_geometry(runner)
    if geo is None:
        if meta is not None or k.ndim == 4:
            raise RuntimeError(
                "latent-format KV payload offered to a standard-KV "
                "engine; rejecting (producer/consumer models disagree)")
        return
    lkv, rope, _ = geo
    if meta is not None and (int(meta.get("kv_lora_rank", -1)) != lkv
                             or int(meta.get("rope_dim", -1)) != rope):
        raise RuntimeError(
            f"latent payload geometry (kv_lora_rank="
            f"{meta.get('kv_lora_rank')}, rope_dim={meta.get('rope_dim')}"
            f") does not match this model ({lkv}, {rope}); rejecting")
    # The layer count must match EXACTLY: scatter's k[lo:hi] stage
    # slicing would silently truncate a same-geometry-but-deeper
    # model's stack into this cache (wrong-model KV, no error).
    layers = int(runner.model.cfg.num_layers)
    if (k.ndim != 4 or k.shape[-1] != lkv or v.shape[-1] != rope
            or k.shape[0] != layers or v.shape[0] != layers):
        raise RuntimeError(
            f"KV payload shapes {k.shape}/{v.shape} are not this "
            f"model's latent wire layout [{layers}, n, page, {lkv}]/"
            f"[{layers}, n, page, {rope}]; rejecting")


def _record(runner, direction: str, num_bytes: int, t0: float) -> None:
    """Device-side page movement telemetry, labeled connector="page_io"
    — distinct from the network/filesystem legs the connectors record,
    so HBM gather/scatter cost is attributable separately (sums per
    label stay exact). ``runner._telemetry`` is the owning engine
    core's recorder, captured at runner construction; standalone tools
    fall back to the process default."""
    rec = getattr(runner, "_telemetry", None)
    if rec is None:
        return
    from vllm_distributed_tpu.metrics import telemetry
    rec.record_transfer("page_io", direction, num_bytes,
                        seconds=telemetry.now() - t0)


def _cache_keys(cache: dict) -> tuple:
    """Cache-dict keys a connector moves, in wire (k, v) slot order:
    ("k", "v") for the standard layout, ("c", "pe") for the TPLA latent
    layout, ("c", ) for the replicated latent layout (the rope key
    lives inside the "c" row)."""
    if "k" in cache:
        return ("k", "v")
    return ("c", "pe") if "pe" in cache else ("c", )


def _stage_views(runner):
    """[(cache_dict, (layer_lo, layer_hi), store)] — one entry for the
    flat runner, one per stage for the pipeline-parallel runner (whose
    kv_caches is a LIST of per-stage slices; the wire layout is always
    the full [L_total, ...] stack, so connectors stay PP-agnostic)."""
    kv = runner.kv_caches
    if isinstance(kv, list):
        ranges = runner.layer_ranges

        def store(idx):
            def put(new):
                runner.kv_caches[idx] = new
            return put

        return [(kv[p], ranges[p], store(p)) for p in range(len(kv))]

    def put(new):
        runner.kv_caches = new

    return [(kv, (0, kv[_cache_keys(kv)[0]].shape[0]), put)]


def _latent_to_wire(c_np: np.ndarray, pe_np, lkv: int, rope: int,
                    shards: int) -> tuple[np.ndarray, np.ndarray]:
    """CACHE-layout latent pages -> wire layout (full unsharded rows):
    strips per-shard lane padding and re-interleaves the TPLA shard
    slices back into contiguous kv_lora_rank rows."""
    if pe_np is None:
        # Replicated layout: one concatenated (kv_c ++ k_pe) row.
        return (np.ascontiguousarray(c_np[..., :lkv]),
                np.ascontiguousarray(c_np[..., lkv:lkv + rope]))
    L, P, PS = c_np.shape[:3]
    shard_pad = c_np.shape[-1] // shards
    lkv_local = lkv // shards
    kv = c_np.reshape(L, P, PS, shards, shard_pad)[..., :lkv_local]
    return (np.ascontiguousarray(kv.reshape(L, P, PS, lkv)),
            np.ascontiguousarray(pe_np[..., :rope]))


def _wire_to_latent(k: np.ndarray, v: np.ndarray, lkv: int, rope: int,
                    shards: int, c_lanes: int, pe_lanes):
    """Wire-layout latent pages -> this deployment's CACHE layout
    (re-slice for the local TPLA shard count — the producer's TP degree
    is irrelevant, wire rows are always full)."""
    L, P, PS = k.shape[:3]
    if pe_lanes is None:
        row = np.concatenate([k, v], axis=-1)
        if c_lanes > row.shape[-1]:
            row = np.pad(row, [(0, 0)] * 3 + [(0, c_lanes - row.shape[-1])])
        return row, None
    shard_pad = c_lanes // shards
    lkv_local = lkv // shards
    kv = k.reshape(L, P, PS, shards, lkv_local)
    if shard_pad > lkv_local:
        kv = np.pad(kv, [(0, 0)] * 4 + [(0, shard_pad - lkv_local)])
    pe = v
    if pe_lanes > pe.shape[-1]:
        pe = np.pad(pe, [(0, 0)] * 3 + [(0, pe_lanes - pe.shape[-1])])
    return kv.reshape(L, P, PS, c_lanes), pe


def wire_page_shapes(runner) -> tuple[tuple, tuple]:
    """Per-page wire-layout shapes (the page axis removed): one page's
    k slice is [L, KVH_checkpoint, PS, D] for standard caches or
    [L, PS, kv_lora_rank] for MLA latent stores. The KV tier
    (core/kv_tier.py) validates disk spill files against these BEFORE
    admitting a tier hit, so a shape-foreign artifact in a shared
    spill directory is a clean miss, never a corrupt scatter."""
    geo = _latent_geometry(runner)
    views = _stage_views(runner)
    L = sum(hi - lo for _, (lo, hi), _ in views)
    cache = views[0][0]
    if geo is not None:
        lkv, rope, _ = geo
        ps = cache["c"].shape[2]
        return (L, ps, lkv), (L, ps, rope)
    r = _replication(runner)
    _, _, kvh, ps, d = cache["k"].shape
    return (L, kvh // r, ps, d), (L, kvh // r, ps, d)


def gather_pages_start(runner, page_ids) -> dict:
    """Non-blocking half of a page gather: slice the pages out of the
    device cache and START the device->host copies, returning a handle
    for ``gather_pages_finish``. The slices enqueue in device program
    order BEFORE any dispatch issued after this call, so a forward that
    immediately overwrites the pages (a demotion's evicted pages are
    handed straight to their new owner) still reads the pre-forward
    contents — while the DMA itself overlaps that forward's compute."""
    from vllm_distributed_tpu.metrics import telemetry
    t0 = telemetry.now()
    pages = np.asarray(page_ids, np.int32)
    geo = _latent_geometry(runner)
    views = _stage_views(runner)
    if geo is not None:
        slices = [(cache["c"][:, pages],
                   cache["pe"][:, pages] if "pe" in cache else None)
                  for cache, _, _ in views]
    else:
        slices = [(cache["k"][:, pages], cache["v"][:, pages])
                  for cache, _, _ in views]
    for a, b in slices:
        for x in (a, b):
            if x is None:
                continue
            try:
                x.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass  # backend without async host copies: finish blocks
    return {"slices": slices, "geo": geo, "t0": t0}


def gather_pages_finish(runner, handle) -> tuple[np.ndarray, np.ndarray]:
    """Blocking half of a page gather: fetch the (already in-flight)
    copies and apply the cache->wire layout transform."""
    import jax
    geo = handle["geo"]
    slices = handle["slices"]
    if geo is not None:
        lkv, rope, shards = geo
        parts = [_latent_to_wire(
            np.asarray(jax.device_get(c)),
            None if pe is None else np.asarray(jax.device_get(pe)),
            lkv, rope, shards) for c, pe in slices]
        k_out = np.concatenate([p[0] for p in parts], axis=0)
        v_out = np.concatenate([p[1] for p in parts], axis=0)
    else:
        r = _replication(runner)
        ks = [np.asarray(jax.device_get(k))[:, :, ::r]
              for k, _ in slices]
        vs = [np.asarray(jax.device_get(v))[:, :, ::r]
              for _, v in slices]
        k_out = np.concatenate(ks, axis=0)
        v_out = np.concatenate(vs, axis=0)
    _record(runner, "tx", k_out.nbytes + v_out.nbytes, handle["t0"])
    return k_out, v_out


def gather_pages(runner, page_ids) -> tuple[np.ndarray, np.ndarray]:
    """Read pages out of the device cache as host numpy in wire layout
    (stages concatenated on the layer dim under pipeline parallelism):
    [L, n_pages, KVH_checkpoint, page_size, head_dim] K/V stacks for
    standard caches, or [L, n_pages, page_size, kv_lora_rank] latent +
    [L, n_pages, page_size, rope_dim] rope stacks for MLA. All stage
    copies dispatch before any fetch, so the device->host legs
    overlap."""
    return gather_pages_finish(runner,
                               gather_pages_start(runner, page_ids))


def scatter_pages(runner, page_ids, k: np.ndarray, v: np.ndarray) -> None:
    """Write wire-layout pages into the device cache, re-expanding KV
    heads (standard) or re-slicing latent rows for the local TPLA shard
    count (MLA). Updates ``runner.kv_caches`` in place (new arrays; the
    old buffers are donated away by the next jitted step)."""
    from vllm_distributed_tpu.metrics import telemetry
    t0 = telemetry.now()
    pages = np.asarray(page_ids, np.int32)
    check_latent_wire(runner, k, v)
    k, v = stage_pages(runner, k, v, on_device=False)
    nbytes = k.nbytes + (0 if v is None else v.nbytes)
    for cache, (lo, hi), put in _stage_views(runner):
        keys = _cache_keys(cache)
        a_all = cache[keys[0]]
        new = {keys[0]: a_all.at[:, pages].set(
            k[lo:hi].astype(a_all.dtype))}
        if v is not None:
            b_all = cache[keys[1]]
            new[keys[1]] = b_all.at[:, pages].set(
                v[lo:hi].astype(b_all.dtype))
        put(new)
    _record(runner, "rx", nbytes, t0)


_scatter_donated_fn = None  # built lazily (module import stays jax-free)


def _scatter_donated():
    """In-place (donated) page write — no full-cache copy, unlike a bare
    .at[].set on a live array. Padding slots carry an out-of-range page
    id and drop."""
    global _scatter_donated_fn
    if _scatter_donated_fn is None:
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def fn(k_all, v_all, pages, k, v):
            return (k_all.at[:, pages].set(k.astype(k_all.dtype),
                                           mode="drop"),
                    v_all.at[:, pages].set(v.astype(v_all.dtype),
                                           mode="drop"))

        _scatter_donated_fn = fn
    return _scatter_donated_fn


_scatter_donated_one_fn = None


def _scatter_donated_one():
    """Single-array donated page scatter (the replicated latent layout
    moves one "c" array instead of a k/v pair)."""
    global _scatter_donated_one_fn
    if _scatter_donated_one_fn is None:
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(0, ))
        def fn(c_all, pages, c):
            return c_all.at[:, pages].set(c.astype(c_all.dtype),
                                          mode="drop")

        _scatter_donated_one_fn = fn
    return _scatter_donated_one_fn


def stage_pages(runner, k: np.ndarray, v: np.ndarray,
                on_device: bool = True):
    """Wire-layout pages -> CACHE layout (replication re-applied for
    standard K/V; latent rows re-sliced/padded for the local TPLA shard
    count, second element None for the replicated latent layout whose
    single "c" row carries the rope key too) — the single home of that
    transform for the staging path. With ``on_device`` the result is
    device arrays; safe from a transfer thread (only dispatches an
    async host->device copy, overlapping PCIe with the main thread's
    compute). ``on_device=False`` keeps host numpy (fallback when a
    thread cannot touch the device)."""
    geo = _latent_geometry(runner)
    if geo is not None:
        lkv, rope, shards = geo
        cache, _, _ = _stage_views(runner)[0]
        pe_lanes = (cache["pe"].shape[-1] if "pe" in cache else None)
        k, v = _wire_to_latent(k, v, lkv, rope, shards,
                               cache["c"].shape[-1], pe_lanes)
    else:
        r = _replication(runner)
        if r > 1:
            k = np.repeat(k, r, axis=2)
            v = np.repeat(v, r, axis=2)
    if not on_device:
        return k, v
    import jax.numpy as jnp
    return jnp.asarray(k), (None if v is None else jnp.asarray(v))


def scatter_pages_chunk(runner, page_ids, k_dev, v_dev, lo: int,
                        chunk: int) -> None:
    """Apply pages [lo, lo+chunk) of a staged pull via the donated
    scatter; page id padding (for the fixed chunk shape) drops. The
    staged arrays are already in CACHE layout (stage_pages), so the
    same donated scatter serves the standard ("k"/"v"), TPLA latent
    ("c"/"pe") and replicated latent ("c" only, v_dev None) layouts."""
    import jax.numpy as jnp

    from vllm_distributed_tpu.metrics import telemetry
    t0 = telemetry.now()
    nbytes = 0
    n = len(page_ids)
    take = min(chunk, n - lo)
    views = _stage_views(runner)
    keys = _cache_keys(views[0][0])
    # Every stage shares the pool geometry; build the padded id vector
    # (out-of-range sentinel drops) and upload it once.
    num_pages = views[0][0][keys[0]].shape[1]
    ids = np.full((chunk, ), num_pages, np.int32)
    ids[:take] = np.asarray(page_ids[lo:lo + take], np.int32)
    ids_dev = jnp.asarray(ids)
    pad = [(0, 0), (0, chunk - take)] + [(0, 0)] * (k_dev.ndim - 2)
    for cache, (llo, lhi), put in views:
        k_all = cache[keys[0]]
        k_c = jnp.pad(k_dev[llo:lhi, lo:lo + take], pad)
        nbytes += k_c.nbytes
        if v_dev is None:
            put({keys[0]: _scatter_donated_one()(k_all, ids_dev, k_c)})
            continue
        v_all = cache[keys[1]]
        v_c = jnp.pad(v_dev[llo:lhi, lo:lo + take], pad)
        nbytes += v_c.nbytes
        k_new, v_new = _scatter_donated()(k_all, v_all, ids_dev,
                                          k_c, v_c)
        put({keys[0]: k_new, keys[1]: v_new})
    _record(runner, "rx", nbytes, t0)
