"""Paged-cache <-> host page movement shared by KV connectors.

Every connector exchanges pages in a TP-invariant wire layout: checkpoint
KV heads only (replica heads added for tp > num_kv_heads are identical by
construction, models/llama.py kv-head replication). These helpers own the
de-replicate / re-replicate transform and the device gather/scatter so
the layout lives in exactly one place.
"""

import numpy as np


def _replication(runner) -> int:
    return getattr(runner.model.cfg, "num_kv_head_replicas", 1)


def gather_pages(runner, page_ids) -> tuple[np.ndarray, np.ndarray]:
    """Read pages out of the device cache as host numpy in wire layout:
    [L, n_pages, KVH_checkpoint, page_size, head_dim]."""
    import jax
    pages = np.asarray(page_ids, np.int32)
    r = _replication(runner)
    k = np.asarray(jax.device_get(runner.kv_caches["k"][:, pages]))[:, :, ::r]
    v = np.asarray(jax.device_get(runner.kv_caches["v"][:, pages]))[:, :, ::r]
    return k, v


def scatter_pages(runner, page_ids, k: np.ndarray, v: np.ndarray) -> None:
    """Write wire-layout pages into the device cache, re-expanding KV
    heads for this deployment's replication factor. Updates
    ``runner.kv_caches`` in place (new arrays; the old buffers are
    donated away by the next jitted step)."""
    pages = np.asarray(page_ids, np.int32)
    r = _replication(runner)
    if r > 1:
        k = np.repeat(k, r, axis=2)
        v = np.repeat(v, r, axis=2)
    k_all = runner.kv_caches["k"]
    v_all = runner.kv_caches["v"]
    runner.kv_caches = {
        "k": k_all.at[:, pages].set(k.astype(k_all.dtype)),
        "v": v_all.at[:, pages].set(v.astype(v_all.dtype)),
    }
