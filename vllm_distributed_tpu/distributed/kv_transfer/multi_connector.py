"""Compose several KV connectors into one (reference:
vllm/distributed/kv_transfer/kv_connector/v1/multi_connector.py — e.g. a
fast local SharedStorage cache in front of the cross-host DCN pull).

Semantics follow the reference: lookups take the FIRST child reporting
external tokens (that child then owns the request's load lifecycle);
saves/teardown fan out to every child; async completion sets union."""

from typing import Optional

from vllm_distributed_tpu.distributed.kv_transfer.base import (
    KVConnectorBase, KVConnectorRole)
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


class MultiConnector(KVConnectorBase):

    def __init__(self, config, role: KVConnectorRole) -> None:
        super().__init__(config, role)
        from vllm_distributed_tpu.distributed.kv_transfer import \
            create_kv_connector
        extra = config.kv_transfer_config.kv_connector_extra_config or {}
        names = extra.get("connectors")
        if not names:
            raise ValueError(
                "MultiConnector needs kv_connector_extra_config"
                "['connectors'] = [connector name, ...]")
        self.children: list[KVConnectorBase] = []
        for name in names:
            child = create_kv_connector(config, role, name=name)
            assert child is not None
            self.children.append(child)
        # Scheduler side: which child claimed each request's load.
        self._owner: dict[str, KVConnectorBase] = {}

    # -- scheduler side -------------------------------------------------
    @property
    def kv_manager(self):
        return getattr(self, "_kv_manager", None)

    @kv_manager.setter
    def kv_manager(self, mgr) -> None:
        self._kv_manager = mgr
        # The base __init__ assigns kv_manager=None before the children
        # list exists.
        for child in getattr(self, "children", ()):
            child.kv_manager = mgr

    def get_num_new_matched_tokens(self, request, num_computed_tokens):
        for child in self.children:
            n, load_async = child.get_num_new_matched_tokens(
                request, num_computed_tokens)
            if n > 0:
                self._owner[request.request_id] = child
                return n, load_async
        return 0, False

    def update_state_after_alloc(self, request, block_ids,
                                 num_external_tokens) -> None:
        owner = self._owner.get(request.request_id)
        if owner is not None:
            owner.update_state_after_alloc(request, block_ids,
                                           num_external_tokens)

    def build_connector_meta(self, scheduler_output):
        metas = [child.build_connector_meta(scheduler_output)
                 for child in self.children]
        for req_id in scheduler_output.finished_req_ids:
            self._owner.pop(req_id, None)
        return metas

    def request_finished(self, request, block_ids):
        defer = False
        params: Optional[dict] = None
        for child in self.children:
            child_defer, child_params = child.request_finished(
                request, block_ids)
            defer = defer or child_defer
            if child_params and params is None:
                params = child_params
        return defer, params

    def take_alloc_failures(self) -> set[str]:
        failed: set[str] = set()
        for child in self.children:
            failed |= child.take_alloc_failures()
        return failed

    def reset_for_retry(self, request, pull_resolved: bool) -> bool:
        owner = self._owner.pop(request.request_id, None)
        if owner is None:
            return False
        return owner.reset_for_retry(request, pull_resolved)

    def cancel_pull(self, req_id: str) -> None:
        for child in self.children:
            child.cancel_pull(req_id)

    # -- worker side ----------------------------------------------------
    def start_load_kv(self, metadata, runner) -> None:
        for child, meta in zip(self.children, metadata or []):
            if meta is not None:
                child.start_load_kv(meta, runner)

    def save_kv(self, metadata, runner) -> None:
        for child, meta in zip(self.children, metadata or []):
            if meta is not None:
                child.save_kv(meta, runner)

    def get_finished(self, runner):
        sending: set[str] = set()
        recving: set[str] = set()
        failed: set[str] = set()
        for child in self.children:
            s, r, x = child.get_finished(runner)
            sending |= s
            recving |= r
            failed |= x
        # A request another child already completed must not be failed
        # by a child that never owned it.
        failed -= recving
        return sending, recving, failed

    def shutdown(self) -> None:
        for child in self.children:
            if hasattr(child, "shutdown"):
                child.shutdown()
