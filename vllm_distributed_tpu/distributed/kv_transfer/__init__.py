"""KV-transfer connector factory (reference:
vllm/distributed/kv_transfer/kv_connector/factory.py)."""

from typing import Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.distributed.kv_transfer.base import (
    KVConnectorBase, KVConnectorRole)

__all__ = ["KVConnectorBase", "KVConnectorRole", "create_kv_connector"]


def create_kv_connector(config: EngineConfig, role: KVConnectorRole,
                        name: Optional[str] = None,
                        ) -> Optional[KVConnectorBase]:
    """Build the configured connector for one side (scheduler or worker);
    None when KV transfer is disabled. ``name`` overrides the configured
    connector (MultiConnector building its children)."""
    name = name or config.kv_transfer_config.kv_connector
    if not name:
        return None
    if name == "SharedStorageConnector":
        from vllm_distributed_tpu.distributed.kv_transfer.shared_storage \
            import SharedStorageConnector
        return SharedStorageConnector(config, role)
    if name == "DCNPullConnector":
        from vllm_distributed_tpu.distributed.kv_transfer.dcn_pull \
            import DCNPullConnector
        return DCNPullConnector(config, role)
    if name == "P2PDcnConnector":
        from vllm_distributed_tpu.distributed.kv_transfer.p2p_registry \
            import P2PDcnConnector
        return P2PDcnConnector(config, role)
    if name == "MultiConnector":
        from vllm_distributed_tpu.distributed.kv_transfer \
            .multi_connector import MultiConnector
        return MultiConnector(config, role)
    raise ValueError(f"unknown kv connector {name!r}")
