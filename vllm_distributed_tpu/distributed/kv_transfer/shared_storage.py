"""Filesystem KV connector: disaggregated prefill via a shared directory.

Reference: vllm/distributed/kv_transfer/kv_connector/v1/
shared_storage_connector.py — the simple/testing connector proving the
producer -> consumer lifecycle. A prefill engine (kv_role=kv_producer)
saves each full prompt page's K/V under the page's CHAINED CONTENT HASH
(the same hashing the prefix cache uses, core/kv_cache_utils.py), and a
decode engine (kv_role=kv_consumer) looks prompt pages up by hash and
loads hits directly into its paged cache, skipping prefill compute for
the matched prefix.

Content-hash keying makes the store position-independent and
prefix-granular: a consumer prompt that extends a producer prompt hits
on the shared page prefix. Files are one .npz per page, written
atomically (tmp + rename) so concurrent engines never read torn pages.
"""

import json
import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from vllm_distributed_tpu.core.kv_cache_utils import hash_request_tokens
from vllm_distributed_tpu.distributed.kv_transfer import page_io, quant
from vllm_distributed_tpu.distributed.kv_transfer.base import (
    KVConnectorBase, KVConnectorRole)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.request import Request
from vllm_distributed_tpu.utils.retry import RetryPolicy, call_with_retry

logger = init_logger(__name__)

DEFAULT_STORAGE_PATH = "/tmp/vdt_kv_storage"


_NATIVE_NPZ_DTYPES = frozenset(
    "float16 float32 float64 int8 uint8 int16 uint16 int32 uint32 "
    "int64 uint64 bool".split())


def _needs_bytes_codec(dtype) -> bool:
    """True for dtypes numpy cannot round-trip through .npy entries
    (ml_dtypes bfloat16 et al.: savez succeeds but np.load explodes
    parsing the descr). Those arrays ride as raw bytes + (shape,
    dtype-name) sidecars — the state-cache journal's discipline."""
    try:
        return np.dtype(dtype).name not in _NATIVE_NPZ_DTYPES
    except TypeError:
        return True


def _decode_bytes_entry(f, slot: str) -> np.ndarray:
    data = f[f"{slot}_raw"].tobytes()
    shape = tuple(int(x) for x in f[f"{slot}_shape"])
    dtype_name = bytes(f[f"{slot}_dtype"]).decode()
    try:
        dtype = np.dtype(dtype_name)
    except TypeError:
        import ml_dtypes  # registers bfloat16 et al.
        dtype = np.dtype(getattr(ml_dtypes, dtype_name))
    return np.frombuffer(data, dtype).reshape(shape)


def read_page_file(path: str):
    """One page file -> (k, v, latent_meta) — arrays [L, KVH, PS, D]
    (or the latent wire slices for MLA stores) plus the latent
    geometry dict when the file carries one (None for standard
    pages / legacy artifacts). Three formats coexist in a store:
    quantized codec files (kv_transfer/quant.py fields under npz
    keys), zlib-compressed raw (VDT_QCOMM=0 writers), and the
    legacy uncompressed raw — old artifacts keep decoding forever.
    A quantized file that fails validation raises QuantCodecError
    (fatal for the caller's retry policy, like any other corrupt
    artifact). Module-level: the hierarchical KV tier's disk spill
    files (core/kv_tier.py) share this exact format + namespace, so
    a tier restore and a disagg handoff read the same artifacts."""
    with np.load(path) as f:
        if "qcomm_meta" in f:
            meta = json.loads(f["qcomm_meta"].tobytes().decode())
            payload = {**meta,
                       "qk": f["qk"].tobytes(),
                       "qv": f["qv"].tobytes(),
                       "ks": f["ks"].tobytes(),
                       "vs": f["vs"].tobytes()}
            k, v = quant.decode_pages(payload)
            return k, v, quant.latent_meta(payload)
        latent = None
        if "latent_meta" in f:
            latent = json.loads(f["latent_meta"].tobytes().decode())
        if "k_raw" in f:
            # Non-native dtype (bfloat16): raw bytes + sidecars.
            return (_decode_bytes_entry(f, "k"),
                    _decode_bytes_entry(f, "v"), latent)
        return f["k"], f["v"], latent


def write_page_file(path: str, k_np, v_np, latent=None,
                    connector: str = "shared_storage") -> tuple[int, int]:
    """Atomic (tmp + rename) page-file write -> (disk_bytes,
    bytes_saved vs the raw uncompressed artifact). Quantized codec
    payload when the plane is on for ``connector``; zlib-compressed
    raw otherwise — either way on-disk KV artifacts shrink.
    ``latent`` (page_io.latent_wire_meta) stamps MLA latent pages
    with the versioned latent geometry so a pre-TPLA engine REJECTS
    the file at decode instead of misreading it."""
    tmp = path + f".tmp{os.getpid()}"
    raw_bytes = k_np.nbytes + v_np.nbytes
    quantized = quant.payload_enabled(connector, k_np.dtype)
    if quantized:
        payload = quant.encode_pages(k_np, v_np, latent=latent)
        meta = {f: payload[f]
                for f in quant.header_fields(payload["version"])
                + ("scale_crc", )}
        # Meta rides as raw JSON bytes — a unicode npy entry costs
        # 4 bytes/char, which matters at small page geometries.
        with open(tmp, "wb") as f:
            np.savez(f, qcomm_meta=np.frombuffer(
                         json.dumps(meta).encode(), np.uint8),
                     qk=np.frombuffer(payload["qk"], np.int8),
                     qv=np.frombuffer(payload["qv"], np.int8),
                     ks=np.frombuffer(payload["ks"], np.float32),
                     vs=np.frombuffer(payload["vs"], np.float32))
    else:
        entries: dict = {}
        if _needs_bytes_codec(k_np.dtype):
            # bfloat16 (ml_dtypes) arrays do not survive a .npy
            # round-trip; store raw bytes + (shape, dtype) sidecars so
            # they come back bit-exact.
            for slot, a in (("k", k_np), ("v", v_np)):
                a = np.ascontiguousarray(a)
                entries[f"{slot}_raw"] = np.frombuffer(
                    a.tobytes(), np.uint8)
                entries[f"{slot}_shape"] = np.asarray(a.shape, np.int64)
                entries[f"{slot}_dtype"] = np.frombuffer(
                    a.dtype.name.encode(), np.uint8)
        else:
            entries["k"], entries["v"] = k_np, v_np
        if latent is not None:
            entries["latent_meta"] = np.frombuffer(
                json.dumps(latent).encode(), np.uint8)
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **entries)
    disk_bytes = os.path.getsize(tmp)
    os.replace(tmp, path)
    # Savings attribute to the quantized plane only — zlib shrink
    # with the plane off is real but is not a qcomm counter.
    saved = max(raw_bytes - disk_bytes, 0) if quantized else 0
    return disk_bytes, saved


@dataclass
class _ReqLoad:
    """One request's pending external load."""

    req_id: str
    page_ids: list[int]
    hashes: list[str]  # hex file keys, aligned with page_ids


@dataclass
class _ReqSave:
    req_id: str
    page_ids: list[int]
    hashes: list[str]


@dataclass
class SharedStorageConnectorMetadata:
    """Per-step worker instructions (picklable; rides on
    SchedulerOutput.kv_connector_metadata)."""

    loads: list[_ReqLoad] = field(default_factory=list)
    saves: list[_ReqSave] = field(default_factory=list)


class SharedStorageConnector(KVConnectorBase):

    # Connector label on the vdt:kv_transfer_* telemetry families.
    telemetry_name = "shared_storage"

    def __init__(self, config, role: KVConnectorRole) -> None:
        super().__init__(config, role)
        # Captured at construction (the engine core's recorder install
        # window only spans construction).
        from vllm_distributed_tpu.metrics import telemetry
        self._telemetry = telemetry.current_recorder()
        extra = config.kv_transfer_config.kv_connector_extra_config or {}
        self.path = extra.get("shared_storage_path", DEFAULT_STORAGE_PATH)
        os.makedirs(self.path, exist_ok=True)
        self.block_size = config.cache_config.block_size
        self.is_producer = config.kv_transfer_config.is_kv_producer
        self.is_consumer = config.kv_transfer_config.is_kv_consumer
        # Transient filesystem errors (NFS hiccups on a genuinely shared
        # directory) retry briefly; persistent failures surface.
        ft_cfg = config.fault_tolerance_config
        self.retry_policy = RetryPolicy(
            max_attempts=ft_cfg.retry_max_attempts,
            base_delay_s=ft_cfg.retry_base_delay_s,
            max_delay_s=ft_cfg.retry_max_delay_s)

        # Scheduler-side state.
        self._reqs: dict[str, Request] = {}
        self._pending_loads: dict[str, _ReqLoad] = {}
        self._saved: set[str] = set()
        # req_id -> (num_computed_tokens, hit hashes): admission-retry memo.
        self._lookup_memo: dict[str, tuple[int, list[str]]] = {}
        # Stats (tests + observability).
        self.num_pages_loaded = 0
        self.num_pages_saved = 0
        self.num_lookup_hits = 0

    # ------------------------------------------------------------------
    def _file(self, hash_hex: str) -> str:
        return os.path.join(self.path, f"{hash_hex}.npz")

    def _read_page_file(self, key: str):
        """See module-level ``read_page_file`` (shared with the KV
        tier's disk spill so both read one page-file format)."""
        return read_page_file(self._file(key))

    def _write_page_file(self, key: str, k_np, v_np,
                         latent=None) -> tuple[int, int]:
        """See module-level ``write_page_file``."""
        return write_page_file(self._file(key), k_np, v_np,
                               latent=latent,
                               connector=self.telemetry_name)

    # ------------------------------------------------------------------
    # Scheduler side
    # ------------------------------------------------------------------
    def get_num_new_matched_tokens(
            self, request: Request,
            num_computed_tokens: int) -> tuple[int, bool]:
        self._reqs[request.request_id] = request
        if not self.is_consumer:
            return 0, False
        # A failed admission retries the same queue head every step;
        # memoize so retries cost no re-hash / filesystem stats. (Hit
        # stats count staged loads in build_connector_meta, so retries
        # are not double-counted.)
        memo = self._lookup_memo.get(request.request_id)
        if memo is not None and memo[0] == num_computed_tokens:
            hit_hashes = memo[1]
        else:
            bs = self.block_size
            hashes = hash_request_tokens(bs, request)
            # Cap so at least one prompt token remains to be computed
            # (the last token must produce a logit — same rule as the
            # local prefix cache, kv_cache_manager.py
            # get_computed_blocks).
            max_hit_pages = (request.num_tokens - 1) // bs
            start = num_computed_tokens // bs
            hit_hashes = []
            for i in range(start, min(len(hashes), max_hit_pages)):
                key = hashes[i].hash_value.hex()
                if not os.path.exists(self._file(key)):
                    break
                hit_hashes.append(key)
            self._lookup_memo[request.request_id] = (num_computed_tokens,
                                                     hit_hashes)
        if not hit_hashes:
            return 0, False
        self._pending_loads[request.request_id] = _ReqLoad(
            req_id=request.request_id, page_ids=[], hashes=list(hit_hashes))
        logger.info("external KV hit: %s pages for request %s",
                    len(hit_hashes), request.request_id)
        return len(hit_hashes) * self.block_size, False  # synchronous

    def update_state_after_alloc(self, request: Request,
                                 block_ids: list[int],
                                 num_external_tokens: int) -> None:
        load = self._pending_loads.get(request.request_id)
        if load is None or num_external_tokens == 0:
            return
        bs = self.block_size
        start = (request.num_computed_tokens // bs)
        n = num_external_tokens // bs
        load.page_ids = block_ids[start:start + n]
        load.hashes = load.hashes[:n]

    def build_connector_meta(self,
                             scheduler_output
                             ) -> SharedStorageConnectorMetadata:
        meta = SharedStorageConnectorMetadata()
        # Loads staged by the waiting-queue admissions this step.
        for req_id in list(self._pending_loads):
            if req_id in scheduler_output.num_scheduled_tokens:
                load = self._pending_loads.pop(req_id)
                if load.page_ids:
                    meta.loads.append(load)
                    self.num_lookup_hits += 1
                    self._lookup_memo.pop(req_id, None)
        # Saves: producer requests whose prompt prefill completes this
        # step (their full prompt pages' KV exists after the forward).
        if self.is_producer:
            for req_id, n_sched in \
                    scheduler_output.num_scheduled_tokens.items():
                request = self._reqs.get(req_id)
                if request is None or req_id in self._saved:
                    continue
                done = request.num_computed_tokens + n_sched
                if done < request.num_prompt_tokens:
                    continue  # still prefilling
                bs = self.block_size
                n_full = request.num_prompt_tokens // bs
                if n_full == 0:
                    self._saved.add(req_id)
                    continue
                hashes = [
                    bh.hash_value.hex()
                    for bh in hash_request_tokens(bs, request)[:n_full]
                ]
                page_ids = self.kv_manager.get_block_ids(req_id)[:n_full]
                meta.saves.append(
                    _ReqSave(req_id=req_id, page_ids=page_ids,
                             hashes=hashes))
                self._saved.add(req_id)
        # Teardown bookkeeping.
        for req_id in scheduler_output.finished_req_ids:
            self._reqs.pop(req_id, None)
            self._pending_loads.pop(req_id, None)
            self._lookup_memo.pop(req_id, None)
            self._saved.discard(req_id)
        return meta

    # ------------------------------------------------------------------
    # Worker side
    # ------------------------------------------------------------------
    def start_load_kv(self, metadata, runner) -> None:
        if not metadata or not metadata.loads:
            return
        # Stored pages always hold CHECKPOINT kv heads (wire layout,
        # page_io): the store stays TP-invariant, so a tp=16 producer
        # and a tp=8 consumer exchange pages fine.
        from vllm_distributed_tpu.metrics import telemetry
        for load in metadata.loads:
            t0 = telemetry.now()
            ks, vs = [], []
            latent = None
            disk_bytes = 0
            try:
                for key in load.hashes:
                    k_arr, v_arr, meta = call_with_retry(
                        lambda key=key: self._read_page_file(key),
                        policy=self.retry_policy,
                        description=f"KV page load {key[:12]}")
                    ks.append(k_arr)
                    vs.append(v_arr)
                    latent = latent or meta
                    disk_bytes += os.path.getsize(self._file(key))
            except Exception:
                self._telemetry.record_failure(self.telemetry_name)
                raise
            # Files hold one page's wire slice ([L, KVH, PS, D], or
            # [L, PS, kv_lora_rank]/[L, PS, rope_dim] for MLA latent
            # stores); stack to wire layout on the page axis. Transfer
            # bytes are the ARTIFACT bytes actually read (quantized/
            # compressed files count what they cost the shared
            # filesystem, not their decoded size).
            k_np, v_np = np.stack(ks, axis=1), np.stack(vs, axis=1)
            self._telemetry.record_transfer(
                self.telemetry_name, "rx", disk_bytes,
                seconds=telemetry.now() - t0)
            # Cross-check the store's stamped latent geometry (when any
            # file carried one) against THIS model before the scatter's
            # own shape check — a foreign store fails the load cleanly.
            page_io.check_latent_wire(runner, k_np, v_np, latent)
            page_io.scatter_pages(runner, load.page_ids, k_np, v_np)
            self.num_pages_loaded += len(load.page_ids)
            logger.info("loaded %d external KV pages for %s",
                        len(load.page_ids), load.req_id)

    def save_kv(self, metadata, runner) -> None:
        if not metadata or not metadata.saves:
            return
        from vllm_distributed_tpu.metrics import telemetry
        for save in metadata.saves:
            todo = [(pid, key)
                    for pid, key in zip(save.page_ids, save.hashes)
                    if not os.path.exists(self._file(key))]
            if not todo:
                continue
            t0 = telemetry.now()
            k_np, v_np = page_io.gather_pages(
                runner, [pid for pid, _ in todo])
            latent = page_io.latent_wire_meta(runner)
            disk_bytes = saved_bytes = 0
            try:
                for i, (_, key) in enumerate(todo):
                    nbytes, saved = call_with_retry(
                        lambda i=i, key=key: self._write_page_file(
                            key, k_np[:, i], v_np[:, i], latent=latent),
                        policy=self.retry_policy,
                        description=f"KV page save {key[:12]}")
                    disk_bytes += nbytes
                    saved_bytes += saved
            except Exception:
                self._telemetry.record_failure(self.telemetry_name)
                raise
            self._telemetry.record_transfer(
                self.telemetry_name, "tx", disk_bytes,
                seconds=telemetry.now() - t0)
            if saved_bytes:
                self._telemetry.record_qcomm(self.telemetry_name,
                                             saved_bytes)
            self.num_pages_saved += len(todo)
            logger.info("saved %d KV pages for %s", len(todo),
                        save.req_id)
