"""Quantized KV-transfer payload codec (block-scaled int8, versioned).

Every KV connector exchanges pages in the TP-invariant wire layout
``[L, n_pages, KVH, page_size, head_dim]`` (page_io.py). This module is
the single home of the quantized form of that payload: symmetric
per-block int8 values with fp32 scales, where the block size is clipped
to a divisor of the per-page-per-head span (``page_size * head_dim``)
so no scale ever crosses a page or head boundary — a consumer can
dequantize any page subset independently.

Wire format (a flat msgpack-friendly dict; np.savez stores the same
fields for the shared_storage on-disk form):

* header — ``version`` (standard K/V payloads stamp ``WIRE_VERSION``;
  MLA latent payloads stamp ``LATENT_WIRE_VERSION``; decoders reject
  versions newer than ``MAX_DECODE_VERSION`` so old engines degrade to
  the raw format / a clean rejection instead of misreading),
  ``dtype``/``k_shape``/``v_shape`` (original geometry, restored
  bit-exactly), ``block`` (elements per scale). Latent payloads
  additionally carry ``kind="latent"`` plus the latent geometry
  ``kv_lora_rank``/``rope_dim``/``tp_shard`` (the PRODUCER's TPLA shard
  count — informational: the wire rows are always full unsharded
  latent rows, so a consumer mesh of ANY TP degree re-slices on
  receipt; the geometry fields let it reject a shape-foreign store
  before touching values).
* payload — ``qk``/``qv`` int8 bytes, ``ks``/``vs`` fp32 scale bytes.
* integrity — ``scale_crc``: CRC32 over the canonical header plus both
  scale buffers. A corrupted scale (or geometry) header turns 1-byte
  wire damage into full-page garbage after dequantization, so decode
  verifies BEFORE touching the values and raises
  :class:`QuantCodecError`; connectors degrade to re-requesting the
  raw-precision payload (fault drill: ``qcomm.scale_corrupt``).

The raw format (``k``/``v`` bytes + dtype/shape, dcn_pull.py) remains
valid — ``VDT_QCOMM=0`` producers, pre-codec producers and fallback
replies all decode unchanged.
"""

import json
import math
import zlib

import ml_dtypes  # noqa: F401 - registers bfloat16 etc. with np.dtype
import numpy as np

from vllm_distributed_tpu.utils import fault_injection

WIRE_VERSION = 1
# MLA latent-page payloads (wire rows = kv_c latent in "k", rope k_pe
# sidecar in "v") stamp a HIGHER version: a pre-TPLA decoder rejects
# them outright (QuantCodecError -> raw re-request / failed pull) —
# rejection, never silent corruption. Standard payloads keep stamping
# WIRE_VERSION so old consumers interop unchanged.
LATENT_WIRE_VERSION = 2
MAX_DECODE_VERSION = 2

_HEADER_FIELDS = ("version", "dtype", "k_shape", "v_shape", "block")
_LATENT_FIELDS = ("kind", "kv_lora_rank", "rope_dim", "tp_shard")


def header_fields(version: int) -> tuple:
    """CRC-covered header fields for a payload version (the canonical
    set both encode and decode hash — and the set shared_storage
    persists into its npz meta)."""
    if version >= LATENT_WIRE_VERSION:
        return _HEADER_FIELDS + _LATENT_FIELDS
    return _HEADER_FIELDS


class QuantCodecError(RuntimeError):
    """Quantized payload failed validation (version, geometry or scale
    checksum). Deliberately NOT an OSError: retrying the same bytes
    cannot help — the caller degrades to the raw-precision payload."""


def payload_enabled(connector: str, dtype=None) -> bool:
    """Should ``connector`` ship quantized payloads? Gated per connector
    (or the "kv" group token) via VDT_QCOMM_PATHS; sub-byte caches
    (fp8) are already as small as the codec output and stay raw."""
    from vllm_distributed_tpu.parallel import collectives
    if not collectives.enabled(connector):
        return False
    return dtype is None or np.dtype(dtype).itemsize > 1


def _span(shape: tuple) -> int:
    """Per-page-per-head element span: the last two dims (page_size *
    head_dim) of the wire layout; trailing dim for anything flatter."""
    if len(shape) >= 2:
        return int(shape[-1]) * int(shape[-2])
    return int(shape[-1]) if shape else 1


def _crc(header: dict, ks: bytes, vs: bytes) -> int:
    fields = header_fields(int(header["version"]))
    canon = json.dumps({f: header[f] for f in fields},
                       sort_keys=True).encode()
    return zlib.crc32(vs, zlib.crc32(ks, zlib.crc32(canon)))


def _quantize(a: np.ndarray, block: int):
    flat = np.ascontiguousarray(a, dtype=np.float32).reshape(-1, block)
    amax = np.max(np.abs(flat), axis=1, keepdims=True)
    scale = np.maximum(amax / 127.0, 1e-30).astype(np.float32)
    q = np.clip(np.rint(flat / scale), -127, 127).astype(np.int8)
    return q, scale


def encode_pages(k: np.ndarray, v: np.ndarray, block: int = None,
                 latent: dict = None) -> dict:
    """Wire-layout page stacks -> quantized payload dict. ``latent``
    (page_io.latent_wire_meta) marks an MLA latent payload: the header
    gains the latent geometry and stamps LATENT_WIRE_VERSION. The scale
    block is clipped to a divisor of the SMALLER per-page span of the
    two stacks (for latent payloads the rope sidecar span is narrower
    than the latent span), so no scale crosses a page boundary in
    either stack."""
    from vllm_distributed_tpu.parallel import collectives
    k = np.asarray(k)
    v = np.asarray(v)
    assert k.dtype == v.dtype, (k.dtype, v.dtype)
    block = collectives.divisor_block(
        math.gcd(_span(k.shape), _span(v.shape)), block)
    qk, ks = _quantize(k, block)
    qv, vs = _quantize(v, block)
    ks_b, vs_b = ks.tobytes(), vs.tobytes()
    header = {
        "version": WIRE_VERSION,
        "dtype": str(k.dtype),
        "k_shape": [int(d) for d in k.shape],
        "v_shape": [int(d) for d in v.shape],
        "block": int(block),
    }
    if latent is not None:
        header.update({
            "version": LATENT_WIRE_VERSION,
            "kind": "latent",
            "kv_lora_rank": int(latent["kv_lora_rank"]),
            "rope_dim": int(latent["rope_dim"]),
            "tp_shard": int(latent.get("tp_shard", 1)),
        })
    crc = _crc(header, ks_b, vs_b)
    if fault_injection.should_fire("qcomm.scale_corrupt"):
        # Flip one scale byte AFTER the checksum: the consumer's decode
        # must detect it and degrade to the raw payload.
        ks_b = bytes([ks_b[0] ^ 0xFF]) + ks_b[1:]
    return {**header, "qk": qk.tobytes(), "qv": qv.tobytes(),
            "ks": ks_b, "vs": vs_b, "scale_crc": crc}


def is_encoded(payload) -> bool:
    return isinstance(payload, dict) and "qk" in payload \
        and "version" in payload


def latent_meta(payload: dict) -> "dict | None":
    """Latent geometry of an encoded payload (None for standard K/V
    payloads) — the consumer cross-checks it against its own model
    before scattering (page_io.check_latent_wire)."""
    if int(payload.get("version", 0)) < LATENT_WIRE_VERSION:
        return None
    if payload.get("kind") != "latent":
        return None
    return {"kv_lora_rank": int(payload["kv_lora_rank"]),
            "rope_dim": int(payload["rope_dim"]),
            "tp_shard": int(payload.get("tp_shard", 1))}


def encoded_nbytes(payload: dict) -> int:
    return sum(len(payload[f]) for f in ("qk", "qv", "ks", "vs"))


def raw_nbytes(payload: dict) -> int:
    itemsize = np.dtype(payload["dtype"]).itemsize
    return itemsize * (math.prod(payload["k_shape"])
                       + math.prod(payload["v_shape"]))


def _dequantize(q_bytes: bytes, s_bytes: bytes, shape: list,
                block: int, dtype) -> np.ndarray:
    n = math.prod(shape)
    if len(q_bytes) != n or len(s_bytes) != (n // block) * 4:
        raise QuantCodecError(
            f"payload geometry mismatch: {len(q_bytes)} value bytes / "
            f"{len(s_bytes)} scale bytes for shape {shape} block {block}")
    q = np.frombuffer(q_bytes, np.int8).reshape(-1, block)
    s = np.frombuffer(s_bytes, np.float32).reshape(-1, 1)
    return (q.astype(np.float32) * s).reshape(shape).astype(dtype)


def decode_pages(payload: dict) -> tuple[np.ndarray, np.ndarray]:
    """Quantized payload dict -> (k, v) numpy stacks in the original
    geometry and dtype. Raises :class:`QuantCodecError` on any
    version / geometry / checksum mismatch."""
    try:
        version = int(payload["version"])
        block = int(payload["block"])
        k_shape = [int(d) for d in payload["k_shape"]]
        v_shape = [int(d) for d in payload["v_shape"]]
        dtype = np.dtype(payload["dtype"])
    except (KeyError, TypeError, ValueError) as e:
        raise QuantCodecError(f"malformed quantized payload: {e}") from e
    if version > MAX_DECODE_VERSION:
        raise QuantCodecError(
            f"payload version {version} is newer than this decoder "
            f"({MAX_DECODE_VERSION})")
    if block <= 0 or _span(tuple(k_shape)) % block \
            or _span(tuple(v_shape)) % block:
        raise QuantCodecError(
            f"block {block} does not divide the page span of "
            f"{k_shape}/{v_shape}")
    header = {"version": version, "dtype": payload["dtype"],
              "k_shape": k_shape, "v_shape": v_shape, "block": block}
    if version >= LATENT_WIRE_VERSION:
        try:
            header.update({
                "kind": str(payload["kind"]),
                "kv_lora_rank": int(payload["kv_lora_rank"]),
                "rope_dim": int(payload["rope_dim"]),
                "tp_shard": int(payload["tp_shard"]),
            })
        except (KeyError, TypeError, ValueError) as e:
            raise QuantCodecError(
                f"latent payload missing geometry: {e}") from e
    if _crc(header, payload["ks"], payload["vs"]) != \
            int(payload.get("scale_crc", -1)):
        raise QuantCodecError("scale/geometry checksum mismatch")
    k = _dequantize(payload["qk"], payload["ks"], k_shape, block, dtype)
    v = _dequantize(payload["qv"], payload["vs"], v_shape, block, dtype)
    return k, v
