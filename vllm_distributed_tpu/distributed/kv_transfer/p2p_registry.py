"""Dynamic-membership registry + P2P connector for disaggregated serving.

Reference: vllm/distributed/kv_transfer/kv_connector/v1/p2p/
p2p_nccl_connector.py (+ its proxy discovery): prefill and decode
instances JOIN and LEAVE a running deployment dynamically — a decode
instance spun up mid-run discovers live prefill instances, pulls KV from
them, and its registration expires when it dies. The reference moves
pages over per-pair NCCL channels brokered by an HTTP proxy; the
TPU-native equivalent keeps the DCN-socket page transport of
``dcn_pull.py`` and adds the membership layer:

* ``P2PRegistryServer`` — a tiny msgpack/TCP service holding
  {instance_id -> role, address, expiry}. Registrations carry a TTL and
  must be heartbeat-renewed; a dead instance vanishes on expiry (the
  reference's proxy tracks liveness the same way).
* ``P2PRegistryClient`` — register/heartbeat/list/deregister.
* ``P2PDcnConnector`` — DCNPullConnector subclass. Producers register
  their page-server address under their instance id and stamp
  ``remote_instance`` into each finished request's kv_transfer_params;
  consumers register as members and RESOLVE the producer's current
  address through the registry at pull-admission time, so requests
  routed by instance id keep working across producer restarts and new
  decode instances need zero static peer configuration.
"""

import socket
import threading
import time
from typing import Optional

import msgpack

from vllm_distributed_tpu.distributed.kv_transfer.base import (
    KVConnectorRole)
from vllm_distributed_tpu.distributed.kv_transfer.dcn_pull import (
    _LEN, DCNPullConnector, _recv_msg, _send_msg)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.utils import fault_injection
from vllm_distributed_tpu.utils.retry import (RetryBudgetExceeded,
                                              RetryPolicy, call_with_retry)

logger = init_logger(__name__)


class P2PRegistryServer:
    """Membership table with TTL expiry (run one per deployment)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._lock = threading.Lock()
        # instance_id -> (role, (host, port), expires_at)
        self._members: dict[str, tuple[str, tuple[str, int], float]] = {}
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(32)
        self.host, self.port = srv.getsockname()
        self._srv = srv
        self._shutdown = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="p2p-registry", daemon=True)
        self._thread.start()
        logger.info("P2P registry listening on %s:%d", self.host,
                    self.port)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def members(self, role: Optional[str] = None) -> dict[str, dict]:
        # Monotonic TTL arithmetic: a wall-clock (NTP) step must neither
        # mass-expire healthy members nor immortalize dead ones. The
        # "expires" value shipped in list replies is server-relative.
        now = time.monotonic()
        with self._lock:
            self._members = {k: v for k, v in self._members.items()
                             if v[2] > now}
            return {
                k: {"role": r, "addr": list(a), "expires": e}
                for k, (r, a, e) in self._members.items()
                if role is None or r == role
            }

    def _loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn, ),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                msg = _recv_msg(conn)
                if msg is None:
                    return
                op = msg.get("op")
                if fault_injection.should_fire("registry.truncate"):
                    # Malformed response: a correct length prefix whose
                    # payload is not msgpack (0xc1 is reserved), so the
                    # client's decoder raises — the failure mode a
                    # half-written proxy response produces.
                    conn.sendall(_LEN.pack(4) + b"\xc1\xc1\xc1\xc1")
                    continue
                if op == "register":
                    ttl = float(msg.get("ttl", 10.0))
                    with self._lock:
                        self._members[msg["instance"]] = (
                            msg.get("role", "producer"),
                            (msg["addr"][0], int(msg["addr"][1])),
                            time.monotonic() + ttl)
                    _send_msg(conn, {"ok": True})
                elif op == "deregister":
                    with self._lock:
                        self._members.pop(msg["instance"], None)
                    _send_msg(conn, {"ok": True})
                elif op == "list":
                    _send_msg(conn, {
                        "ok": True,
                        "instances": self.members(msg.get("role")),
                    })
                else:
                    _send_msg(conn, {"ok": False,
                                     "error": f"unknown op {op!r}"})
        except (OSError, msgpack.UnpackException,
                msgpack.exceptions.ExtraData):
            pass
        finally:
            conn.close()

    def shutdown(self) -> None:
        self._shutdown.set()
        try:
            self._srv.close()
        except OSError:
            pass


class P2PRegistryClient:
    """One instance's view of the registry (fresh socket per call —
    calls are rare and short; liveness rides the heartbeat TTL)."""

    def __init__(self, registry_addr: str, instance_id: str,
                 role: str, ttl: float = 10.0,
                 retry_policy: Optional[RetryPolicy] = None) -> None:
        host, port = registry_addr.rsplit(":", 1)
        self._addr = (host, int(port))
        self.instance_id = instance_id
        self.role = role
        self.ttl = ttl
        # Registry calls are control-plane: retry transient socket
        # errors briefly, then let the caller's fallback (TTL expiry,
        # local prefill) decide.
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=3, base_delay_s=0.05, max_delay_s=0.5)
        self._stop = threading.Event()
        self._hb: Optional[threading.Thread] = None
        self._my_addr: Optional[tuple[str, int]] = None

    def _call_once(self, msg: dict, timeout: float = 5.0) -> dict:
        with socket.create_connection(self._addr, timeout=timeout) as s:
            _send_msg(s, msg)
            resp = _recv_msg(s)
            return resp or {"ok": False, "error": "closed"}

    def _call(self, msg: dict, timeout: float = 5.0) -> dict:
        return call_with_retry(
            lambda: self._call_once(msg, timeout),
            policy=self.retry_policy,
            description=f"registry {msg.get('op')}")

    def register(self, addr: tuple[str, int],
                 heartbeat: bool = True) -> None:
        self._my_addr = addr
        self._call({"op": "register", "instance": self.instance_id,
                    "role": self.role, "addr": list(addr),
                    "ttl": self.ttl})
        if heartbeat and self._hb is None:
            self._hb = threading.Thread(target=self._heartbeat_loop,
                                        name="p2p-heartbeat",
                                        daemon=True)
            self._hb.start()

    def _heartbeat_loop(self) -> None:
        # Catch EVERYTHING except the stop signal: one malformed
        # response (msgpack decode error on a truncated payload) must
        # not permanently end heartbeating — the instance would expire
        # from the registry while still alive and consumers would stop
        # routing to it (ADVICE r5).
        while not self._stop.wait(self.ttl / 3.0):
            if fault_injection.should_fire("heartbeat.stall"):
                continue  # injected stall: skip this beat
            try:
                self._call({"op": "register",
                            "instance": self.instance_id,
                            "role": self.role,
                            "addr": list(self._my_addr),
                            "ttl": self.ttl})
            except Exception as e:  # noqa: BLE001 - keep beating
                logger.warning(
                    "registry heartbeat for %s failed (%s); retrying "
                    "next interval", self.instance_id, e)

    def list(self, role: Optional[str] = None) -> dict[str, dict]:
        try:
            resp = self._call({"op": "list", "role": role})
        except Exception as e:  # noqa: BLE001 - degrade to "nobody home"
            logger.warning("registry list failed (%s); treating as empty",
                           e)
            return {}
        return resp.get("instances", {})

    def resolve(self, instance_id: str) -> Optional[tuple[str, int]]:
        info = self.list().get(instance_id)
        if info is None:
            return None
        return info["addr"][0], int(info["addr"][1])

    def leave(self) -> None:
        self._stop.set()
        try:
            self._call({"op": "deregister",
                        "instance": self.instance_id})
        except Exception as e:  # noqa: BLE001 - best-effort teardown;
            # a malformed response must not abort engine shutdown (the
            # TTL expires the registration anyway).
            logger.warning("registry deregister for %s failed (%s)",
                           self.instance_id, e)


class P2PDcnConnector(DCNPullConnector):
    """DCN pull with dynamic membership (see module docstring).

    Extra config: ``registry_addr`` ("host:port", required),
    ``instance_id`` (defaults to role-pid), ``registry_ttl``.
    """

    # Inherited page transfers report under this connector's own label
    # so a p2p deployment's bytes are attributable to the dynamic path.
    telemetry_name = "p2p"

    def __init__(self, config, role: KVConnectorRole) -> None:
        super().__init__(config, role)
        import os
        extra = config.kv_transfer_config.kv_connector_extra_config or {}
        registry_addr = extra.get("registry_addr")
        if not registry_addr:
            raise ValueError(
                "P2PDcnConnector needs kv_connector_extra_config."
                "registry_addr (host:port of the membership registry)")
        my_role = "producer" if self.is_producer else "consumer"
        self.instance_id = str(
            extra.get("instance_id", f"{my_role}-{os.getpid()}"))
        self.registry = P2PRegistryClient(
            registry_addr, self.instance_id, my_role,
            ttl=float(extra.get("registry_ttl", 10.0)),
            retry_policy=self.retry_policy)
        # Scheduler side: requests whose producer resolution failed
        # AFTER pages were allocated (drained by the scheduler's
        # watchdog sweep into the failed-pull requeue path).
        self._alloc_failed: set[str] = set()
        if role == KVConnectorRole.WORKER and self.is_producer:
            # _start_server (super().__init__) bound the page server;
            # join under its address and keep the membership alive.
            self.registry.register((self.pull_host, self.pull_port))
        elif role == KVConnectorRole.SCHEDULER and not self.is_producer:
            # Consumers are members too (the deployment can see them
            # join/leave); they serve no pages, so any address works.
            self.registry.register(("0.0.0.0", 0))

    # ---- scheduler side -------------------------------------------------
    @staticmethod
    def _valid_params(params) -> bool:
        if not isinstance(params, dict):
            return False
        try:
            if not (bool(params.get("remote_req_id"))
                    and int(params["num_tokens"]) > 0):
                return False
        except (KeyError, TypeError, ValueError):
            return False
        # Either explicit coordinates or a resolvable instance id.
        if params.get("remote_instance"):
            return True
        try:
            return int(params.get("pull_port", 0)) > 0
        except (TypeError, ValueError):
            return False

    def update_state_after_alloc(self, request, block_ids,
                                 num_external_tokens) -> None:
        params = request.kv_transfer_params
        if (self.is_consumer and num_external_tokens
                and isinstance(params, dict)
                and params.get("remote_instance")
                and not params.get("pull_port")):
            addr = self.registry.resolve(str(params["remote_instance"]))
            if addr is None:
                # Producer left between finish and pull: fall back to
                # local prefill. The scheduler has already parked the
                # request in WAITING_FOR_REMOTE_KVS and no worker
                # report will ever arrive, so SURFACE the failure
                # (take_alloc_failures) instead of only nulling the
                # params — silent nulling left the request parked
                # forever (ADVICE r5).
                logger.warning(
                    "producer instance %r not in registry; request %s "
                    "recomputes locally", params["remote_instance"],
                    request.request_id)
                self._telemetry.record_failure(self.telemetry_name)
                request.kv_transfer_params = None
                self._alloc_failed.add(request.request_id)
                return
            params["pull_host"], params["pull_port"] = addr[0], addr[1]
        super().update_state_after_alloc(request, block_ids,
                                         num_external_tokens)

    def request_finished(self, request, block_ids):
        defer, params = super().request_finished(request, block_ids)
        if params is not None:
            # Route by instance id: consumers resolve the CURRENT
            # address at pull time (survives producer restarts; new
            # consumers need no static peer config).
            params["remote_instance"] = self.instance_id
        return defer, params

    def take_alloc_failures(self) -> set[str]:
        failed, self._alloc_failed = self._alloc_failed, set()
        return failed

    def get_num_new_matched_tokens(self, request, num_computed_tokens):
        params = request.kv_transfer_params
        if (self.is_consumer and isinstance(params, dict)
                and params.get("remote_instance")
                and not params.get("pull_port")
                and self.registry.resolve(
                    str(params["remote_instance"])) is None):
            # Unknown producer: admit as a plain local-prefill request.
            return 0, False
        return super().get_num_new_matched_tokens(request,
                                                  num_computed_tokens)

    def shutdown(self) -> None:
        self.registry.leave()
        if hasattr(self, "_shutdown"):  # worker role owns the server
            super().shutdown()
