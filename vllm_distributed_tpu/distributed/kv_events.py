"""KV cache block events: ZMQ pub/sub for external prefix-cache routers.

Reference: vllm/distributed/kv_events.py:104 ``ZmqEventPublisher`` —
the scheduler's block pool reports BlockStored / BlockRemoved /
AllBlocksCleared; an external router subscribes and steers requests to
the engine already holding their prefix. Wire shape kept compatible in
spirit: msgpack batches tagged with a monotonically increasing sequence
number, plus a bounded replay buffer served over a side ROUTER socket so
a late subscriber can backfill missed batches.
"""

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


@dataclass
class BlockStored:
    block_hashes: list[bytes]
    parent_block_hash: Optional[bytes]
    token_ids: list[int]
    block_size: int
    lora_id: Optional[int] = None

    def to_wire(self) -> list:
        return ["stored", self.block_hashes, self.parent_block_hash,
                self.token_ids, self.block_size, self.lora_id]


@dataclass
class BlockRemoved:
    block_hashes: list[bytes]

    def to_wire(self) -> list:
        return ["removed", self.block_hashes]


@dataclass
class AllBlocksCleared:
    def to_wire(self) -> list:
        return ["cleared"]


@dataclass
class EventBatch:
    ts: float
    events: list = field(default_factory=list)


class KVEventPublisher:
    """Batches block events and publishes them on a ZMQ PUB socket from
    a background thread (the scheduler's hot loop only appends to an
    in-memory queue). A bounded replay buffer answers REQ backfills for
    sequence gaps (reference: kv_events.py replay mechanism)."""

    def __init__(self, endpoint: str, replay_endpoint: Optional[str] = None,
                 buffer_steps: int = 1000,
                 topic: bytes = b"kv-events") -> None:
        import zmq
        self.topic = topic
        self.ctx = zmq.Context.instance()
        self.pub = self.ctx.socket(zmq.PUB)
        self.pub.bind(endpoint)
        self.endpoint = endpoint
        self.replay = None
        if replay_endpoint:
            self.replay = self.ctx.socket(zmq.ROUTER)
            self.replay.bind(replay_endpoint)
        self._queue: "queue.Queue[EventBatch]" = queue.Queue()
        self._buffer: dict[int, bytes] = {}
        self._buffer_steps = buffer_steps
        self._seq = 0
        self._shutdown = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="kv-event-publisher")
        self._thread.start()

    # -- producer side (scheduler thread) ------------------------------
    def publish(self, events: list) -> None:
        if events:
            self._queue.put(EventBatch(ts=time.time(),  # wallclock-ok
                                       events=list(events)))

    # -- background IO --------------------------------------------------
    def _run(self) -> None:
        import zmq

        from vllm_distributed_tpu.engine import serial
        poller = zmq.Poller()
        if self.replay is not None:
            poller.register(self.replay, zmq.POLLIN)
        while not self._shutdown.is_set():
            try:
                batch = self._queue.get(timeout=0.05)
            except queue.Empty:
                batch = None
            if batch is not None:
                payload = serial.pack({
                    "seq": self._seq,
                    "ts": batch.ts,
                    "events": [e.to_wire() for e in batch.events],
                })
                self.pub.send_multipart(
                    [self.topic, str(self._seq).encode(), payload])
                self._buffer[self._seq] = payload
                self._seq += 1
                if len(self._buffer) > self._buffer_steps:
                    del self._buffer[min(self._buffer)]
            if self.replay is not None and poller.poll(0):
                ident, _, want = self.replay.recv_multipart()
                start = int(want.decode())
                for seq in sorted(self._buffer):
                    if seq >= start:
                        self.replay.send_multipart(
                            [ident, b"", str(seq).encode(),
                             self._buffer[seq]])
                self.replay.send_multipart([ident, b"", b"-1", b""])

    def shutdown(self) -> None:
        self._shutdown.set()
        self._thread.join(timeout=5)
        self.pub.close(linger=0)
        if self.replay is not None:
            self.replay.close(linger=0)
