"""Per-module logging, mirroring the reference's vllm/logger.py.

Behavior is controlled by env vars (see envs.py): VDT_LOGGING_LEVEL,
VDT_LOGGING_PREFIX.
"""

import logging
import sys

_FORMAT = "%(levelname)s %(asctime)s [%(name)s:%(lineno)d] %(message)s"
_DATE_FORMAT = "%m-%d %H:%M:%S"

_root_configured = False


def _configure_root() -> None:
    global _root_configured
    if _root_configured:
        return
    from vllm_distributed_tpu import envs

    root = logging.getLogger("vllm_distributed_tpu")
    root.setLevel(envs.VDT_LOGGING_LEVEL)
    handler = logging.StreamHandler(sys.stdout)
    prefix = envs.VDT_LOGGING_PREFIX
    handler.setFormatter(
        logging.Formatter(prefix + _FORMAT, datefmt=_DATE_FORMAT))
    root.addHandler(handler)
    root.propagate = False
    _root_configured = True


def init_logger(name: str) -> logging.Logger:
    """Return a logger under the framework's logging tree.

    Mirrors vllm/logger.py:init_logger in the reference.
    """
    _configure_root()
    if not name.startswith("vllm_distributed_tpu"):
        name = f"vllm_distributed_tpu.{name}"
    return logging.getLogger(name)
