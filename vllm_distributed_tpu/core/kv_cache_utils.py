"""KV-cache block hashing and page-geometry helpers.

Reference: vllm/v1/core/kv_cache_utils.py (block hashing incl. chained
parent hashes used by the prefix cache) — re-implemented with deterministic
sha256 hashes so prefix-cache behavior is reproducible across processes
(the reference uses Python hash() with PYTHONHASHSEED pinning; sha256 avoids
the pinning requirement entirely).
"""

import hashlib
import struct
from dataclasses import dataclass
from typing import NamedTuple, Optional

from vllm_distributed_tpu.request import Request


class BlockHash(NamedTuple):
    """Hash of one full KV page: chained over the parent page so equal
    hashes imply equal full prefixes."""

    hash_value: bytes
    # Kept for collision resistance checks / debugging.
    token_ids: tuple[int, ...]


NONE_HASH = b"\x00" * 16


def hash_block_tokens(
    parent_hash: Optional[bytes],
    token_ids: tuple[int, ...],
    extra_keys: Optional[tuple] = None,
) -> BlockHash:
    """Chained hash of a full block of tokens.

    ``extra_keys`` carries things that change KV content beyond token ids
    (LoRA id, multimodal content hashes) — reference:
    v1/core/kv_cache_utils.py generate_block_hash_extra_keys.
    """
    h = hashlib.sha256()
    h.update(parent_hash or NONE_HASH)
    h.update(struct.pack(f"<{len(token_ids)}q", *token_ids))
    if extra_keys:
        h.update(repr(extra_keys).encode())
    return BlockHash(h.digest()[:16], token_ids)


def request_hash_seed(request: Request) -> Optional[bytes]:
    """Chain seed for a request's block hashes: multimodal requests
    salt with the image content hash — same token ids + different
    images must never collide (reference: the mm hash keys folded into
    block hashing, v1/core/kv_cache_utils). EVERY place that (re)starts
    a hash chain must seed from here, or an unsalted chain could hand
    one user's image-conditioned KV to another."""
    return getattr(request, "mm_hash", None)


def hash_request_tokens(block_size: int,
                        request: Request) -> list[BlockHash]:
    """Hash all *full* blocks of the request's current tokens."""
    token_ids = request.all_token_ids
    hashes: list[BlockHash] = []
    parent: Optional[bytes] = request_hash_seed(request)
    for start in range(0, len(token_ids) - block_size + 1, block_size):
        chunk = tuple(token_ids[start:start + block_size])
        bh = hash_block_tokens(parent, chunk)
        hashes.append(bh)
        parent = bh.hash_value
    return hashes


@dataclass
class KVCacheSpec:
    """Geometry of one KV cache group (reference:
    v1/kv_cache_interface.py:20-208 FullAttentionSpec et al.).

    Round 1 supports full attention only; sliding-window/mamba groups slot in
    as additional specs later.
    """

    block_size: int
    num_kv_heads: int
    head_size: int
    dtype: str
    num_layers: int

    @property
    def page_size_bytes(self) -> int:
        itemsize = {"bfloat16": 2, "float16": 2, "float32": 4}[self.dtype]
        # K and V planes.
        return (2 * self.block_size * self.num_kv_heads * self.head_size *
                itemsize * self.num_layers)
