"""Budgeted cache of encoder (vision) outputs.

Reference: vllm/v1/core/encoder_cache_manager.py:254 — the scheduler
admits a multimodal request's encoder inputs only while their token
count fits the encoder-cache budget; entries free when the request no
longer needs them. Here the cached payloads (pre-computed embedding
rows) live worker-side per request; this manager owns the BUDGET
accounting on the scheduler side, so a flood of image-heavy requests
queues instead of overcommitting worker host memory.

Allocation lifetime: a request's inputs allocate at admission and free
when the request finishes or is preempted-and-freed (a preempted
request re-prefills, so its embeddings must survive preemption — they
re-allocate with the request's re-admission)."""

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


class EncoderCacheManager:

    def __init__(self, budget_tokens: int) -> None:
        self.budget = budget_tokens
        self._allocated: dict[str, int] = {}  # req_id -> encoder tokens

    @property
    def used(self) -> int:
        return sum(self._allocated.values())

    def has(self, req_id: str) -> bool:
        return req_id in self._allocated

    def can_allocate(self, req_id: str, num_tokens: int) -> bool:
        if req_id in self._allocated:
            return True
        return self.used + num_tokens <= self.budget

    def allocate(self, req_id: str, num_tokens: int) -> None:
        if req_id in self._allocated:
            return
        assert self.used + num_tokens <= self.budget
        self._allocated[req_id] = num_tokens

    def free(self, req_id: str) -> None:
        self._allocated.pop(req_id, None)
