"""Per-request KV page allocation on top of the BlockPool.

Reference: vllm/v1/core/kv_cache_manager.py (``KVCacheManager``:
get_computed_blocks:137 for prefix-cache hits, allocate_slots:195 — incl.
the fork's ``tknp_skip_allocation`` used when a token-parallel peer owns the
request's KV, which we express via ``skip_allocation``).
"""

from collections import defaultdict
from dataclasses import dataclass
from typing import Optional

from vllm_distributed_tpu.core.block_pool import BlockPool, KVCacheBlock
from vllm_distributed_tpu.core.kv_cache_utils import (BlockHash,
                                                      hash_block_tokens,
                                                      hash_request_tokens)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.request import Request
from vllm_distributed_tpu.utils import cdiv

logger = init_logger(__name__)


@dataclass
class KVCacheBlocks:
    """Opaque result of an allocation: the page ids newly visible to the
    worker for this request."""

    blocks: list[KVCacheBlock]

    def get_block_ids(self) -> list[int]:
        return [b.block_id for b in self.blocks]

    def __add__(self, other: "KVCacheBlocks") -> "KVCacheBlocks":
        return KVCacheBlocks(self.blocks + other.blocks)


class KVCacheManager:

    def __init__(
        self,
        block_size: int,
        num_blocks: int,
        enable_caching: bool = True,
    ) -> None:
        self.block_size = block_size
        self.enable_caching = enable_caching
        self.block_pool = BlockPool(num_blocks, enable_caching)

        # req_id -> pages owned (ordered by position in sequence).
        self.req_to_blocks: dict[str, list[KVCacheBlock]] = defaultdict(list)
        # req_id -> chained hashes of its full pages (grows lazily).
        self.req_to_block_hashes: dict[str, list[BlockHash]] = \
            defaultdict(list)
        # req_id -> number of pages already registered in the prefix cache.
        self.num_cached_block: dict[str, int] = {}

        # Stats (reference: PrefixCacheStats).
        self.prefix_cache_queries = 0
        self.prefix_cache_hits = 0

    @property
    def usage(self) -> float:
        return self.block_pool.usage

    def get_num_free_blocks(self) -> int:
        return self.block_pool.get_num_free_blocks()

    # ------------------------------------------------------------------
    def get_computed_blocks(
            self, request: Request) -> tuple[KVCacheBlocks, int]:
        """Longest cached-prefix lookup for a WAITING request.

        Returns (cached blocks, num_computed_tokens). Never returns the
        *entire* prompt as cached — the last token must be recomputed so a
        logit is produced for it (reference: kv_cache_manager.py:137).
        """
        if not self.enable_caching:
            return KVCacheBlocks([]), 0

        block_hashes = self.req_to_block_hashes[request.request_id]
        if not block_hashes:
            block_hashes = hash_request_tokens(self.block_size, request)
            self.req_to_block_hashes[request.request_id] = block_hashes

        self.prefix_cache_queries += 1
        computed: list[KVCacheBlock] = []
        # Cap so at least one prompt token remains to be computed.
        max_cache_hit_tokens = request.num_tokens - 1
        for i, bh in enumerate(block_hashes):
            if (i + 1) * self.block_size > max_cache_hit_tokens:
                break
            block = self.block_pool.get_cached_block(bh)
            if block is None:
                break
            computed.append(block)
        if computed:
            self.prefix_cache_hits += 1
        return KVCacheBlocks(computed), len(computed) * self.block_size

    def allocate_slots(
        self,
        request: Request,
        num_new_tokens: int,
        new_computed_blocks: Optional[KVCacheBlocks] = None,
        num_lookahead_tokens: int = 0,
        skip_allocation: bool = False,
    ) -> Optional[KVCacheBlocks]:
        """Ensure the request has pages for ``num_new_tokens`` more tokens.

        Returns the newly-allocated pages, or None if the pool cannot
        satisfy the allocation (caller preempts). ``skip_allocation``
        mirrors the fork's tknp_skip_allocation (scheduler.py:494-500):
        the tokens are scheduled but a token-parallel peer holds the KV.
        """
        assert num_new_tokens > 0
        if skip_allocation:
            return KVCacheBlocks([])

        computed_blocks = (new_computed_blocks.blocks
                           if new_computed_blocks else [])
        req_blocks = self.req_to_blocks[request.request_id]

        num_computed_tokens = (request.num_computed_tokens +
                               len(computed_blocks) * self.block_size)
        total_tokens = (num_computed_tokens + num_new_tokens +
                        num_lookahead_tokens)
        num_required_blocks = cdiv(total_tokens, self.block_size)
        num_new_blocks = (num_required_blocks - len(req_blocks) -
                          len(computed_blocks))

        # Cache-hit blocks with ref 0 still sit in the free queue; taking a
        # ref on them consumes free capacity, so discount them (reference:
        # kv_cache_manager.py:195 num_evictable_computed_blocks).
        num_evictable_computed = sum(1 for b in computed_blocks
                                     if b.ref_cnt == 0)
        if (num_new_blocks >
                self.block_pool.get_num_free_blocks() -
                num_evictable_computed):
            return None  # cannot allocate; caller decides to preempt

        # Commit: take refs on the cache-hit blocks, then allocate new ones.
        if computed_blocks:
            self.block_pool.touch(computed_blocks)
            req_blocks.extend(computed_blocks)

        new_blocks: list[KVCacheBlock] = []
        if num_new_blocks > 0:
            new_blocks = self.block_pool.get_new_blocks(num_new_blocks)
            req_blocks.extend(new_blocks)

        if self.enable_caching:
            self._cache_full_blocks(request, num_computed_tokens,
                                    num_new_tokens)

        return KVCacheBlocks(new_blocks)

    def _cache_full_blocks(self, request: Request,
                           num_computed_tokens: int,
                           num_new_tokens: int) -> None:
        """Register hashes for pages that become full once the scheduled
        tokens are computed. Hashes only cover tokens that *exist* now
        (prompt + already-sampled); a decode step filling a page registers
        it on the following step via the growing hash list."""
        req_blocks = self.req_to_blocks[request.request_id]
        block_hashes = self.req_to_block_hashes[request.request_id]
        # Extend hashes to cover any newly-complete full pages.
        num_full_after = min(num_computed_tokens + num_new_tokens,
                             request.num_tokens) // self.block_size
        parent = (block_hashes[-1].hash_value if block_hashes else None)
        while len(block_hashes) < num_full_after:
            start = len(block_hashes) * self.block_size
            chunk = tuple(request.all_token_ids[start:start +
                                                self.block_size])
            bh = hash_block_tokens(parent, chunk)
            block_hashes.append(bh)
            parent = bh.hash_value
        num_cached = self.num_cached_block.get(request.request_id, 0)
        if num_full_after > num_cached:
            self.block_pool.cache_full_blocks(req_blocks, block_hashes,
                                              num_cached, num_full_after)
            self.num_cached_block[request.request_id] = num_full_after

    # ------------------------------------------------------------------
    def free(self, request: Request) -> None:
        """Release all pages of a finished/preempted request. Pages are
        returned tail-first so prefixes are evicted last."""
        blocks = self.req_to_blocks.pop(request.request_id, [])
        self.num_cached_block.pop(request.request_id, None)
        self.block_pool.free_blocks(list(reversed(blocks)))

    def free_block_hashes(self, request: Request) -> None:
        """Forget the request's hash list (on finish — distinct from free()
        because preempted requests keep hashes for re-prefill)."""
        self.req_to_block_hashes.pop(request.request_id, None)

    def get_block_ids(self, request_id: str) -> list[int]:
        return [b.block_id for b in self.req_to_blocks[request_id]]

    def reset_prefix_cache(self) -> bool:
        return self.block_pool.reset_prefix_cache()

    def make_prefix_cache_stats(self) -> dict[str, float]:
        return {
            "queries": self.prefix_cache_queries,
            "hits": self.prefix_cache_hits,
        }
