"""Per-request KV page allocation on top of the BlockPool.

Reference: vllm/v1/core/kv_cache_manager.py (``KVCacheManager``:
get_computed_blocks:137 for prefix-cache hits, allocate_slots:195 — incl.
the fork's ``tknp_skip_allocation`` used when a token-parallel peer owns the
request's KV, which we express via ``skip_allocation``).
"""

from collections import defaultdict
from dataclasses import dataclass
from typing import Optional

from vllm_distributed_tpu.core.block_pool import BlockPool, KVCacheBlock
from vllm_distributed_tpu.core.kv_cache_utils import (BlockHash,
                                                      hash_block_tokens,
                                                      hash_request_tokens)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.request import Request
from vllm_distributed_tpu.utils import cdiv

logger = init_logger(__name__)


@dataclass
class KVCacheBlocks:
    """Opaque result of an allocation: the page ids newly visible to the
    worker for this request."""

    blocks: list[KVCacheBlock]

    def get_block_ids(self) -> list[int]:
        return [b.block_id for b in self.blocks]

    def __add__(self, other: "KVCacheBlocks") -> "KVCacheBlocks":
        return KVCacheBlocks(self.blocks + other.blocks)


class KVCacheManager:

    def __init__(
        self,
        block_size: int,
        num_blocks: int,
        enable_caching: bool = True,
        id_offset: int = 0,
        free_window: Optional[int] = None,
        tier=None,
    ) -> None:
        self.block_size = block_size
        self.enable_caching = enable_caching
        self.block_pool = BlockPool(num_blocks, enable_caching, id_offset)
        # Hierarchical KV tiering (core/kv_tier.py): evicted prefix
        # pages demote to host RAM / disk instead of vanishing, and
        # get_computed_blocks extends a device-cached prefix with
        # tier-resident continuation pages. None = untiered
        # (byte-identical pre-tiering behavior).
        self.tier = tier
        if tier is not None:
            self.block_pool.on_evict = tier.note_evicted
        # Sliding-window page freeing (reference: the SlidingWindowManager
        # of v1/core/single_type_kv_cache_manager.py:444 replacing
        # out-of-window blocks with the null block): when EVERY attention
        # layer is windowed, pages whose last position can never again
        # fall inside any future query's window are freed mid-request and
        # their req_to_blocks slot nulled. The attention mask already
        # excludes those positions, so a stale (possibly reused) page id
        # in the block table is never read into a live score. None =
        # some layer needs full history; no mid-request freeing.
        self.free_window = free_window
        # req_id -> count of leading slots already window-freed (loop
        # resume point; dropped with the request's block list).
        self._num_window_freed: dict[str, int] = {}

        # req_id -> pages owned (ordered by position in sequence).
        self.req_to_blocks: dict[str, list[KVCacheBlock]] = defaultdict(list)
        # req_id -> chained hashes of its full pages (grows lazily).
        self.req_to_block_hashes: dict[str, list[BlockHash]] = \
            defaultdict(list)
        # req_id -> number of pages already registered in the prefix cache.
        self.num_cached_block: dict[str, int] = {}

        # Stats (reference: PrefixCacheStats). Lifetime counters plus a
        # sliding window of recent lookup outcomes: the lifetime ratio
        # of a week-old server can't show that the cache stopped
        # hitting an hour ago, the window can.
        self.prefix_cache_queries = 0
        self.prefix_cache_hits = 0
        from collections import deque
        self._recent_queries: "deque[int]" = deque(maxlen=256)

    @property
    def usage(self) -> float:
        return self.block_pool.usage

    def get_num_free_blocks(self) -> int:
        return self.block_pool.get_num_free_blocks()

    # ------------------------------------------------------------------
    def get_computed_blocks(
            self, request: Request) -> tuple[KVCacheBlocks, int]:
        """Longest cached-prefix lookup for a WAITING request.

        Returns (cached blocks, num_computed_tokens). Never returns the
        *entire* prompt as cached — the last token must be recomputed so a
        logit is produced for it (reference: kv_cache_manager.py:137).
        """
        if not self.enable_caching:
            return KVCacheBlocks([]), 0

        block_hashes = self.req_to_block_hashes[request.request_id]
        if not block_hashes:
            block_hashes = hash_request_tokens(self.block_size, request)
            self.req_to_block_hashes[request.request_id] = block_hashes

        self.prefix_cache_queries += 1
        computed: list[KVCacheBlock] = []
        # Cap so at least one prompt token remains to be computed.
        max_cache_hit_tokens = request.num_tokens - 1
        for i, bh in enumerate(block_hashes):
            if (i + 1) * self.block_size > max_cache_hit_tokens:
                break
            block = self.block_pool.get_cached_block(bh)
            if block is None:
                break
            computed.append(block)
        # Tier continuation (core/kv_tier.py): extend the device-
        # resident prefix with pages whose content lives in host RAM /
        # disk. The hit arrays are staged on the tier manager under the
        # request id; the scheduler allocates device pages for the span
        # and ships a promote directive the runner executes before the
        # forward. The span counts as computed tokens — it is, the
        # bytes just live one tier down.
        num_tier = 0
        if self.tier is not None:
            num_tier = self.tier.match_prefix(
                request.request_id, block_hashes, len(computed),
                max_cache_hit_tokens, self.block_size)
        if computed or num_tier:
            self.prefix_cache_hits += 1
        self._recent_queries.append(1 if (computed or num_tier) else 0)
        return (KVCacheBlocks(computed),
                (len(computed) + num_tier) * self.block_size)

    def allocate_slots(
        self,
        request: Request,
        num_new_tokens: int,
        new_computed_blocks: Optional[KVCacheBlocks] = None,
        num_lookahead_tokens: int = 0,
        skip_allocation: bool = False,
        delay_caching: bool = False,
    ) -> Optional[KVCacheBlocks]:
        """Ensure the request has pages for ``num_new_tokens`` more tokens.

        Returns the newly-allocated pages, or None if the pool cannot
        satisfy the allocation (caller preempts). ``skip_allocation``
        mirrors the fork's tknp_skip_allocation (scheduler.py:494-500):
        the tokens are scheduled but a token-parallel peer holds the KV.
        """
        assert num_new_tokens > 0
        if skip_allocation:
            return KVCacheBlocks([])

        # Free the dead window prefix FIRST so the released pages can
        # satisfy this very allocation.
        self._free_out_of_window(request)

        computed_blocks = (new_computed_blocks.blocks
                           if new_computed_blocks else [])
        req_blocks = self.req_to_blocks[request.request_id]

        num_computed_tokens = (request.num_computed_tokens +
                               len(computed_blocks) * self.block_size)
        total_tokens = (num_computed_tokens + num_new_tokens +
                        num_lookahead_tokens)
        num_required_blocks = cdiv(total_tokens, self.block_size)
        num_new_blocks = (num_required_blocks - len(req_blocks) -
                          len(computed_blocks))

        # Cache-hit blocks with ref 0 still sit in the free queue; taking a
        # ref on them consumes free capacity, so discount them (reference:
        # kv_cache_manager.py:195 num_evictable_computed_blocks).
        num_evictable_computed = sum(1 for b in computed_blocks
                                     if b.ref_cnt == 0)
        if (num_new_blocks >
                self.block_pool.get_num_free_blocks() -
                num_evictable_computed):
            return None  # cannot allocate; caller decides to preempt

        # Commit: take refs on the cache-hit blocks, then allocate new ones.
        if computed_blocks:
            self.block_pool.touch(computed_blocks)
            req_blocks.extend(computed_blocks)

        new_blocks: list[KVCacheBlock] = []
        if num_new_blocks > 0:
            new_blocks = self.block_pool.get_new_blocks(num_new_blocks)
            req_blocks.extend(new_blocks)

        # delay_caching: pages allocated for an ASYNC external load must
        # not enter the prefix-cache index yet — the data isn't on device,
        # and a failed pull would otherwise poison every future lookup of
        # these hashes (reference: kv_cache_manager.py delay_cache_blocks
        # for the nixl path). They register later, when the request's
        # post-load allocations cover them.
        if self.enable_caching and not delay_caching:
            self._cache_full_blocks(request, num_computed_tokens,
                                    num_new_tokens)

        return KVCacheBlocks(new_blocks)

    def _cache_full_blocks(self, request: Request,
                           num_computed_tokens: int,
                           num_new_tokens: int) -> None:
        """Register hashes for pages that become full once the scheduled
        tokens are computed. Hashes only cover tokens that *exist* now
        (prompt + already-sampled); a decode step filling a page registers
        it on the following step via the growing hash list."""
        req_blocks = self.req_to_blocks[request.request_id]
        block_hashes = self.req_to_block_hashes[request.request_id]
        # Extend hashes to cover any newly-complete full pages.
        num_full_after = min(num_computed_tokens + num_new_tokens,
                             request.num_tokens) // self.block_size
        from vllm_distributed_tpu.core.kv_cache_utils import \
            request_hash_seed
        parent = (block_hashes[-1].hash_value if block_hashes
                  else request_hash_seed(request))
        while len(block_hashes) < num_full_after:
            start = len(block_hashes) * self.block_size
            chunk = tuple(request.all_token_ids[start:start +
                                                self.block_size])
            bh = hash_block_tokens(parent, chunk)
            block_hashes.append(bh)
            parent = bh.hash_value
        num_cached = self.num_cached_block.get(request.request_id, 0)
        if num_full_after > num_cached:
            self.block_pool.cache_full_blocks(req_blocks, block_hashes,
                                              num_cached, num_full_after)
            self.num_cached_block[request.request_id] = num_full_after

    def _free_out_of_window(self, request: Request) -> None:
        """Null + free every block whose last position precedes
        num_computed_tokens - window (no future query can attend it;
        the window mask in ops/attention guarantees it is never read)."""
        if self.free_window is None:
            return
        num_dead = max(
            0, request.num_computed_tokens - self.free_window + 1
        ) // self.block_size
        if num_dead <= 0:
            return
        blocks = self.req_to_blocks.get(request.request_id)
        if not blocks:
            return
        # Start at the first live slot (persisted) so steady-state decode
        # frees at most one new block in O(1), not O(dead prefix).
        start = self._num_window_freed.get(request.request_id, 0)
        end = min(num_dead, len(blocks))
        dead = []
        for i in range(start, end):
            if blocks[i] is not None:
                dead.append(blocks[i])
                blocks[i] = None
        if end > start:
            self._num_window_freed[request.request_id] = end
        if dead:
            self.block_pool.free_blocks(dead)

    # ------------------------------------------------------------------
    def free(self, request: Request) -> None:
        """Release all pages of a finished/preempted request. Pages are
        returned tail-first so prefixes are evicted last."""
        blocks = self.req_to_blocks.pop(request.request_id, [])
        self.num_cached_block.pop(request.request_id, None)
        self._num_window_freed.pop(request.request_id, None)
        self.block_pool.free_blocks(
            [b for b in reversed(blocks) if b is not None])

    def free_block_hashes(self, request: Request) -> None:
        """Forget the request's hash list (on finish — distinct from free()
        because preempted requests keep hashes for re-prefill)."""
        self.req_to_block_hashes.pop(request.request_id, None)
        if self.tier is not None:
            self.tier.drop_request(request.request_id)

    def transfer_ownership(self, old_id: str, new_id: str) -> None:
        """Re-key a request's page ownership (scheduler watchdog: pages
        of a timed-out-but-still-in-flight pull are parked under a
        tombstone id until the worker reports, so the request can be
        re-queued with fresh pages under its own id). Block hashes stay
        with the original id — they describe the request's content, not
        the parked pages."""
        if old_id in self.req_to_blocks:
            self.req_to_blocks[new_id] = self.req_to_blocks.pop(old_id)
        if old_id in self.num_cached_block:
            self.num_cached_block[new_id] = \
                self.num_cached_block.pop(old_id)
        if old_id in self._num_window_freed:
            self._num_window_freed[new_id] = \
                self._num_window_freed.pop(old_id)

    def get_block_ids(self, request_id: str) -> list[int]:
        # Window-freed slots keep a position-aligned placeholder id; the
        # attention window mask guarantees those positions are never
        # read (see _free_out_of_window).
        return [0 if b is None else b.block_id
                for b in self.req_to_blocks[request_id]]

    def reset_prefix_cache(self) -> bool:
        return self.block_pool.reset_prefix_cache()

    def make_prefix_cache_stats(self) -> dict[str, float]:
        return {
            "queries": self.prefix_cache_queries,
            "hits": self.prefix_cache_hits,
        }

    def kv_telemetry(self) -> dict:
        """Block-pool introspection for the telemetry plane: pool
        occupancy, the request-held block/token footprint the scheduler
        turns into a fragmentation figure, and the windowed hit rate.
        Runs on the stats-RPC caller's thread while the core thread
        allocates/frees — every container is list()-snapshotted
        (GIL-atomic) before Python-level iteration."""
        stats = dict(self.block_pool.get_stats())
        held = 0
        for blocks in list(self.req_to_blocks.values()):
            held += sum(1 for b in list(blocks) if b is not None)
        stats["held_blocks"] = held
        recent = list(self._recent_queries)
        stats["window_queries"] = len(recent)
        stats["window_hits"] = sum(recent)
        return stats


class TokenParallelKVCacheManager:
    """Partitioned KV management for token parallelism: the global page
    array is split into ``num_ranks`` contiguous per-rank pools, and every
    request's pages come exclusively from its assigned rank's pool — so a
    request's KV physically lives on one ``token``-axis shard of the
    sharded cache.

    TPU-native analogue of the fork's TokenParallelScheduler KV
    bookkeeping (vllm/v1/core/sched/scheduler.py:55-255 assign_ranks +
    per-rank free-block accounting, kv_cache_manager.py
    tknp_skip_allocation): instead of peer processes owning separate
    caches, one SPMD cache is sharded on the page axis and ownership is a
    page-range invariant maintained here. Page ids are GLOBAL (rank r owns
    [r*N/K, (r+1)*N/K)), so the worker's block tables and slot mappings
    need no translation — the runner derives each request's rank from its
    first page id.

    Requests must be assigned a rank (``request.tknp_rank``) before any
    call; the scheduler assigns ranks free-page-aware at admission.
    Prefix-cache lookups are per-rank: a prefix cached on rank 0 cannot
    serve a rank-1 request (its KV lives in rank 0's HBM shard), matching
    the reference's per-rank cache separation.
    """

    def __init__(
        self,
        block_size: int,
        num_blocks: int,
        num_ranks: int,
        enable_caching: bool = True,
    ) -> None:
        assert num_ranks > 1
        assert num_blocks % num_ranks == 0, \
            "page count must divide evenly across token-parallel ranks"
        self.block_size = block_size
        self.num_ranks = num_ranks
        self.blocks_per_rank = num_blocks // num_ranks
        self.managers = [
            KVCacheManager(block_size, self.blocks_per_rank,
                           enable_caching,
                           id_offset=r * self.blocks_per_rank)
            for r in range(num_ranks)
        ]
        # req_id -> rank, recorded at first allocation-path call.
        self.req_rank: dict[str, int] = {}

    def _mgr(self, request: Request) -> KVCacheManager:
        rank = getattr(request, "tknp_rank", None)
        assert rank is not None, \
            f"request {request.request_id} has no token-parallel rank"
        self.req_rank[request.request_id] = rank
        return self.managers[rank]

    def _maybe_mgr(self, request: Request) -> Optional[KVCacheManager]:
        """Manager for the request's rank, or None when no rank was ever
        assigned (a request aborted/rejected while still WAITING holds no
        pages and no hashes, so teardown is a no-op)."""
        if getattr(request, "tknp_rank", None) is None:
            return None
        return self._mgr(request)

    @property
    def usage(self) -> float:
        return sum(m.usage for m in self.managers) / self.num_ranks

    def get_num_free_blocks(self) -> int:
        return sum(m.get_num_free_blocks() for m in self.managers)

    def free_blocks_on_rank(self, rank: int) -> int:
        return self.managers[rank].get_num_free_blocks()

    def get_computed_blocks(self, request: Request):
        return self._mgr(request).get_computed_blocks(request)

    def allocate_slots(self, request: Request, num_new_tokens: int,
                       new_computed_blocks=None,
                       num_lookahead_tokens: int = 0,
                       skip_allocation: bool = False,
                       delay_caching: bool = False):
        return self._mgr(request).allocate_slots(
            request, num_new_tokens, new_computed_blocks,
            num_lookahead_tokens, skip_allocation, delay_caching)

    def free(self, request: Request) -> None:
        mgr = self._maybe_mgr(request)
        if mgr is not None:
            mgr.free(request)

    def free_block_hashes(self, request: Request) -> None:
        """Terminal teardown: also drops the rank record (it is only
        needed while block tables can still be queried)."""
        mgr = self._maybe_mgr(request)
        if mgr is not None:
            mgr.free_block_hashes(request)
        self.req_rank.pop(request.request_id, None)

    def release_rank(self, request: Request) -> None:
        """Un-assign a request that holds no pages so the next admission
        attempt re-picks the least-loaded rank (prevents a stalled queue
        head from pinning itself to a full rank)."""
        mgr = self._maybe_mgr(request)
        if mgr is not None:
            assert not mgr.req_to_blocks.get(request.request_id), \
                "cannot release the rank of a request holding pages"
            # A failed allocate_slots touches the defaultdict; drop the
            # empty entry or the old rank's manager leaks it forever.
            mgr.req_to_blocks.pop(request.request_id, None)
            mgr.free_block_hashes(request)
        self.req_rank.pop(request.request_id, None)
        request.tknp_rank = None

    def get_block_ids(self, request_id: str) -> list[int]:
        return self.managers[self.req_rank[request_id]].get_block_ids(
            request_id)

    def transfer_ownership(self, old_id: str, new_id: str) -> None:
        """Re-key page ownership within the owning rank's pool (see
        KVCacheManager.transfer_ownership)."""
        rank = self.req_rank.pop(old_id, None)
        if rank is None:
            return
        self.managers[rank].transfer_ownership(old_id, new_id)
        self.req_rank[new_id] = rank

    def reset_prefix_cache(self) -> bool:
        return all([m.reset_prefix_cache() for m in self.managers])

    def make_prefix_cache_stats(self) -> dict[str, float]:
        return {
            "queries": sum(m.prefix_cache_queries for m in self.managers),
            "hits": sum(m.prefix_cache_hits for m in self.managers),
        }

    def kv_telemetry(self) -> dict:
        """Per-rank pools summed — one fleet view of the partitioned
        page array (per-rank free counts already ride get_stats as
        tknp_free_blocks_rank*)."""
        merged: dict = {}
        for m in self.managers:
            for k, v in m.kv_telemetry().items():
                merged[k] = merged.get(k, 0) + v
        return merged
