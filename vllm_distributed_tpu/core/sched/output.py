"""Scheduler <-> worker wire types.

Reference: vllm/v1/core/sched/output.py (``SchedulerOutput`` carrying
NewRequestData/CachedRequestData, plus the fork's ``TokenParallelAllocation``
at output.py:84 carried on SchedulerOutput at output.py:168) and
vllm/v1/outputs.py (``ModelRunnerOutput``).
"""

from dataclasses import dataclass, field
from typing import Any, Optional

from vllm_distributed_tpu.sampling_params import SamplingParams


@dataclass
class NewRequestData:
    """First time a request is handed to the workers."""

    req_id: str
    prompt_token_ids: list[int]
    sampling_params: SamplingParams
    block_ids: list[int]
    num_computed_tokens: int
    # Multi-LoRA adapter selection ({"name", "path"}; see models/lora.py).
    lora_request: "dict | None" = None
    # Pooling/embedding request marker ({"type": "last"}).
    pooling_params: "dict | None" = None
    # Positioned pre-computed image embeddings (multimodal/
    # MultiModalInput list); the runner substitutes their rows at the
    # placeholder positions during prefill.
    mm_inputs: "list | None" = None


@dataclass
class CachedRequestData:
    """Incremental update for requests the workers already know."""

    req_ids: list[str] = field(default_factory=list)
    resumed_from_preemption: list[bool] = field(default_factory=list)
    # Tokens appended since last step (resumed requests carry all tokens).
    new_token_ids: list[list[int]] = field(default_factory=list)
    new_block_ids: list[list[int]] = field(default_factory=list)
    num_computed_tokens: list[int] = field(default_factory=list)


@dataclass
class TokenParallelAllocation:
    """Which token-parallel rank owns each scheduled request's KV.

    TPU analogue of the fork's TokenParallelAllocation
    (v1/core/sched/output.py:84): rank indexes the "token" mesh axis.
    Carried for observability/stats and wire parity — the runner itself
    derives ownership from each request's page range (every page of a
    request lives inside its rank's pool partition), which stays correct
    across preemption and needs no extra trust in the wire data.
    """

    req_to_rank: dict[str, int] = field(default_factory=dict)
    tokens_per_rank: list[int] = field(default_factory=list)


@dataclass
class SchedulerOutput:
    scheduled_new_reqs: list[NewRequestData] = field(default_factory=list)
    scheduled_cached_reqs: CachedRequestData = field(
        default_factory=CachedRequestData)
    # req_id -> tokens to run this step (new prompt chunk or 1 + spec len).
    num_scheduled_tokens: dict[str, int] = field(default_factory=dict)
    total_num_scheduled_tokens: int = 0
    # req_id -> speculative draft tokens being verified this step.
    scheduled_spec_decode_tokens: dict[str, list[int]] = \
        field(default_factory=dict)
    finished_req_ids: set[str] = field(default_factory=set)
    # Disaggregated-prefill metadata piggybacking on the step, consumed by
    # the worker-side connector (reference: base.py build_connector_meta).
    kv_connector_metadata: Optional[Any] = None
    # Structured output: req_id -> [V] bool numpy mask for the request's
    # next sampled token (reference: the grammar bitmask shipped with the
    # scheduler output and applied at gpu_model_runner.py:1433).
    structured_masks: Optional[dict[str, Any]] = None
    # Token-parallel ownership for this step (None when tknp disabled).
    token_parallel_allocation: Optional[TokenParallelAllocation] = None
    # >1: the worker runs this many fused decode steps device-side before
    # returning (TPU analogue of the reference's multi-step scheduling +
    # csrc/prepare_inputs/advance_step.cu — the host loop costs one
    # roundtrip per burst instead of per token). Slots for all steps are
    # pre-allocated via num_lookahead_tokens.
    multi_step: int = 1
    # SSM state cache (core/state_cache.py): snapshot copies the runner
    # executes AFTER this step's forward (each request's state rows ->
    # its assigned pool slot; preempt-parks ride here too — a parked
    # request runs no tokens, so pre/post makes no difference for it),
    # and restores it executes BEFORE the forward (pool slot or host
    # checkpoint file -> the request's state rows, so the segmented
    # scan re-enters mid-sequence via its has_init carry path). Only
    # attached to outputs with scheduled tokens (the zero-token
    # dispatch path does no device work by contract).
    state_saves: "list | None" = None
    state_restores: "list | None" = None
    # Hierarchical KV tiering (core/kv_tier.py), in-proc only like the
    # state directives. ``kv_demotes`` is ONE batched DemoteDirective:
    # pages evicted+reassigned this step whose contents the runner
    # gathers to the host tier BEFORE the forward overwrites them
    # (the gather's DMA overlaps the forward). ``kv_promotes`` are
    # per-request PromoteDirectives: staged tier-hit pages scattered
    # into freshly allocated device pages before the forward, also in
    # dispatch program order AFTER the demote gather (a promote target
    # may be the very page a demote is reading).
    kv_demotes: "object | None" = None
    kv_promotes: "list | None" = None
    # True when the scheduler granted this batch under async scheduling:
    # request.num_computed_tokens was already advanced AT SCHEDULE TIME
    # (so step N+1 could be granted while step N executes), and
    # update_from_output must not advance it again.
    async_scheduled: bool = False


EMPTY_MODEL_RUNNER_OUTPUT: "ModelRunnerOutput"


@dataclass
class ModelRunnerOutput:
    """Per-step result shipped from workers back to the scheduler
    (reference: vllm/v1/outputs.py ModelRunnerOutput)."""

    # Requests in batch order.
    req_ids: list[str] = field(default_factory=list)
    # Sampled token ids per request (len 0 for partial-prefill steps,
    # >1 with accepted speculative tokens).
    sampled_token_ids: list[list[int]] = field(default_factory=list)
    # Optional per-request, per-token logprobs: list aligned with
    # sampled_token_ids; each entry maps token_id -> logprob.
    logprobs: Optional[list[list[dict[int, float]]]] = None
    # Draft tokens proposed for the *next* step (spec decode).
    spec_token_ids: Optional[list[list[int]]] = None
    # KV-transfer completion notifications (disagg).
    finished_sending: Optional[set[str]] = None
    finished_recving: Optional[set[str]] = None
    # Pulls that errored (peer unreachable / timed out): the scheduler
    # re-queues these requests for LOCAL prefill of the span instead of
    # marking never-written pages computed.
    failed_recving: Optional[set[str]] = None
    # Pooled hidden states for embedding requests that completed their
    # prompt this step: req_id -> list[float].
    pooled: Optional[dict[str, list[float]]] = None
    # Prompt logprobs scored this step: req_id -> list of
    # (prompt_position, {token_id: logprob}) chunk entries; the
    # scheduler buffers them on the request until its first emitted
    # output (reference: prompt_logprobs_dict of v1/outputs.py).
    prompt_logprobs: Optional[dict[str, list]] = None


EMPTY_MODEL_RUNNER_OUTPUT = ModelRunnerOutput()
