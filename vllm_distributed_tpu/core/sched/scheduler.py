"""Continuous-batching scheduler.

Reference: vllm/v1/core/sched/scheduler.py (``Scheduler.schedule``:413,
``update_from_output``:1012). One token-budget loop unifies prefill, decode,
chunked prefill and speculative verification: each step, every scheduled
request contributes ``num_new_tokens`` (a prompt chunk, or 1 + draft length
for decode) against ``max_num_batched_tokens``. Preemption pops the
lowest-priority running request and returns it to the waiting queue with its
pages freed.

TPU note: the scheduler is pure control plane (no device code) and runs on
the host exactly as in the reference; static-shape discipline lives in the
worker, which pads this scheduler's ragged output to bucketed shapes.
"""

import time
from collections import Counter, deque
from typing import Iterable, Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.kv_cache_manager import (
    KVCacheBlocks, KVCacheManager, TokenParallelKVCacheManager)
from vllm_distributed_tpu.core.sched import qos as qos_mod
from vllm_distributed_tpu.core.sched.output import (CachedRequestData,
                                                    ModelRunnerOutput,
                                                    NewRequestData,
                                                    SchedulerOutput,
                                                    TokenParallelAllocation)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.request import Request, RequestStatus

logger = init_logger(__name__)


class EngineCoreOutput:
    """Per-request delta shipped to the engine front-end
    (reference: v1/engine/__init__.py EngineCoreOutput)."""

    __slots__ = ("req_id", "new_token_ids", "finish_reason", "stop_reason",
                 "num_cached_tokens", "logprobs", "kv_transfer_params",
                 "pooled", "prompt_logprobs", "events")

    def __init__(self, req_id: str, new_token_ids: list[int],
                 finish_reason: Optional[str] = None,
                 stop_reason: Optional[int | str] = None,
                 num_cached_tokens: int = 0,
                 logprobs: Optional[list[dict[int, float]]] = None,
                 kv_transfer_params: Optional[dict] = None,
                 pooled: Optional[list[float]] = None,
                 prompt_logprobs: Optional[list] = None,
                 events: Optional[list[tuple]] = None) -> None:
        self.req_id = req_id
        self.new_token_ids = new_token_ids
        self.finish_reason = finish_reason
        self.stop_reason = stop_reason
        self.num_cached_tokens = num_cached_tokens
        self.logprobs = logprobs
        # Producer handoff coordinates on the final output (disagg;
        # reference: v1/engine/__init__.py EngineCoreOutput).
        self.kv_transfer_params = kv_transfer_params
        # Embedding result for pooling requests (reference: pooling
        # outputs on the core output path, v1/outputs.py).
        self.pooled = pooled
        # Full prompt-logprob list (entry 0 = None), attached to the
        # request's FIRST emitted output once the prompt completes
        # (reference: prompt_logprobs on the engine-core output path).
        self.prompt_logprobs = prompt_logprobs
        # Core-side lifecycle events (metrics/events.py) accumulated on
        # the request since its previous output; the front-end stitches
        # them into the request's phase timeline.
        self.events = events

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None


class _PageTombstone:
    """Stand-in owner for pages of a timed-out remote-KV pull that may
    still be written by an in-flight transfer: the watchdog re-keys the
    pages to a tombstone so the request can re-queue with fresh pages
    while the old ones stay out of the pool until the worker reports
    (or the abandon backstop expires)."""

    __slots__ = ("request_id", "tknp_rank", "expires_at")

    def __init__(self, request_id: str, tknp_rank: Optional[int],
                 expires_at: float) -> None:
        self.request_id = request_id
        self.tknp_rank = tknp_rank
        self.expires_at = expires_at


class Scheduler:

    def __init__(
        self,
        config: EngineConfig,
        num_blocks: Optional[int] = None,
        kv_connector=None,
    ) -> None:
        self.config = config
        sched_cfg = config.scheduler_config
        self.max_num_batched_tokens = sched_cfg.max_num_batched_tokens
        self.max_num_seqs = sched_cfg.max_num_seqs
        self.max_model_len = sched_cfg.max_model_len
        self.enable_chunked_prefill = sched_cfg.enable_chunked_prefill
        self.long_prefill_token_threshold = \
            sched_cfg.long_prefill_token_threshold
        self.policy = sched_cfg.policy
        self.num_scheduler_steps = getattr(sched_cfg,
                                           "num_scheduler_steps", 1)

        if num_blocks is None:
            num_blocks = config.cache_config.num_gpu_blocks
        assert num_blocks is not None and num_blocks > 0, \
            "scheduler needs the page count (set cache_config.num_gpu_blocks)"
        # Token parallelism (the fork's TKNP, re-expressed for SPMD): the
        # page pool is partitioned per token-axis rank and the scheduler
        # assigns each request to a rank at admission (reference:
        # v1/core/sched/scheduler.py:55 TokenParallelScheduler).
        self.tknp_size = config.parallel_config.token_parallel_size
        # Sliding-window page freeing: only when every layer is windowed
        # AND no KV connector is attached (a connector may still read a
        # request's prompt pages for a peer pull after they leave the
        # window; its deferred-free holds don't cover mid-request frees).
        from vllm_distributed_tpu.models.loader import (
            resolve_encoder_only, resolve_free_window, resolve_stateful)
        free_window = (None if kv_connector is not None
                       else resolve_free_window(config.model_config))
        enable_caching = config.cache_config.enable_prefix_caching
        if resolve_encoder_only(config.model_config):
            # Encoder-only (BERT-family) archs: a bidirectional layer
            # needs the full sequence in one step, and there is no
            # causal KV to re-enter — whole-prompt scheduling, no
            # prefix reuse (the processor bounds prompts to the token
            # budget at admission).
            if self.enable_chunked_prefill or enable_caching:
                logger.info("encoder-only model: chunked prefill and "
                            "prefix caching disabled")
            self.enable_chunked_prefill = False
            enable_caching = False
        # SSM state cache (core/state_cache.py): give fixed-size state
        # snapshots the same rights paged KV has. A snapshot at a token
        # boundary is a complete resume point, so "prefix caching" for
        # stateful models = restore the state at the shared boundary.
        self.state_cache = None
        if resolve_stateful(config.model_config):
            from vllm_distributed_tpu.core.state_cache import (
                StateCacheManager, resolve_ckpt_interval,
                resolve_state_slots, state_cache_enabled)
            from vllm_distributed_tpu.models.loader import (
                resolve_state_only, resolve_state_snapshotable)
            if (state_cache_enabled(config, True)
                    and kv_connector is None
                    and resolve_state_snapshotable(config.model_config)):
                from vllm_distributed_tpu import envs as _envs
                paged = not resolve_state_only(config.model_config)
                if paged and not enable_caching:
                    # Hybrid (Jamba/Bamba): a state restore must re-enter
                    # coherently with the attention KV of the same
                    # prefix, so the page prefix cache MUST index those
                    # pages.
                    logger.info("hybrid SSM model: prefix caching forced "
                                "on for the state cache")
                    enable_caching = True
                elif not paged:
                    # Pure SSM: pages carry no bytes; the state cache
                    # keys its own hash chains.
                    enable_caching = False
                # Hierarchical tiering (VDT_KV_TIERING): snapshot
                # eviction demotes to the journal instead of
                # discarding; without an explicit checkpoint dir the
                # journal homes under the KV tier's spill directory.
                journal_dir = _envs.VDT_SSM_CKPT_DIR
                tiering = _envs.VDT_KV_TIERING
                if tiering and not journal_dir and _envs.VDT_KV_TIER_DIR:
                    import os as _os
                    journal_dir = _os.path.join(_envs.VDT_KV_TIER_DIR,
                                                "ssm")
                self.state_cache = StateCacheManager(
                    num_slots=resolve_state_slots(config),
                    block_size=config.cache_config.block_size,
                    interval=resolve_ckpt_interval(config),
                    paged_kv=paged,
                    journal_dir=journal_dir,
                    demote_on_evict=tiering)
                logger.info(
                    "SSM state cache: %d slots, checkpoint every %d "
                    "tokens%s", self.state_cache.num_slots,
                    self.state_cache.interval,
                    f", journal {self.state_cache.journal_dir}"
                    if self.state_cache.journal_dir else "")
                if getattr(sched_cfg, "num_scheduler_steps", 1) > 1:
                    # Fused decode bursts advance state mid-burst past
                    # snapshot boundaries; keep the cadence exact.
                    logger.info("SSM state cache: multi-step decode "
                                "bursts disabled")
                    self.num_scheduler_steps = 1
            else:
                # SSM state cannot re-enter at a cached page boundary;
                # without the state cache the reference behavior stands
                # (prefix caching disabled for mamba models).
                logger.info("stateful (SSM) model: prefix caching "
                            "disabled (state cache off)")
                enable_caching = False
        # Save directives not yet attached to an output (a preempt-park
        # on a step whose grant came up empty defers to the next
        # non-empty output — the zero-token dispatch path does no
        # device work by contract).
        self._deferred_state_saves: list = []
        # Hierarchical KV tiering (core/kv_tier.py): host-RAM + disk
        # spill tiers behind the device pool. Gated to the plain paged
        # path — stateful models' second tier is the state-cache
        # journal (their admission bypasses get_computed_blocks), and
        # sliding-window models free pages the mask forbids ever
        # reading again (demoting dead-window pages would resurrect
        # unreadable content). None = untiered, byte-identical.
        self.kv_tier = None
        if (self.tknp_size == 1 and enable_caching
                and self.state_cache is None and free_window is None):
            from vllm_distributed_tpu.core.kv_tier import maybe_kv_tier
            self.kv_tier = maybe_kv_tier(config, kv_connector)
        if self.tknp_size > 1:
            self.kv_cache_manager = TokenParallelKVCacheManager(
                block_size=config.cache_config.block_size,
                num_blocks=num_blocks,
                num_ranks=self.tknp_size,
                enable_caching=enable_caching,
            )
            # Per-rank scheduled-token counts (load-balance signal).
            self.tknp_tokens_per_rank = [0] * self.tknp_size
        else:
            self.kv_cache_manager = KVCacheManager(
                block_size=config.cache_config.block_size,
                num_blocks=num_blocks,
                enable_caching=enable_caching,
                free_window=free_window,
                tier=self.kv_tier,
            )
        # Structured output (reference: the engine core's
        # StructuredOutputManager beside the scheduler,
        # v1/structured_output/__init__.py); set by EngineCore when the
        # first structured request arrives.
        self.structured_manager = None
        # KV cache events for external prefix-aware routers (reference:
        # distributed/kv_events.py ZmqEventPublisher).
        self.kv_event_publisher = None
        ev_cfg = config.kv_events_config
        if ev_cfg.enable_kv_cache_events:
            from vllm_distributed_tpu.distributed.kv_events import \
                KVEventPublisher
            self.kv_event_publisher = KVEventPublisher(
                ev_cfg.endpoint, ev_cfg.replay_endpoint,
                ev_cfg.buffer_steps)
            for pool in self._block_pools():
                pool.enable_events()
        # Disaggregated-prefill hook (reference: scheduler holds the
        # scheduler-side KVConnector, sched/scheduler.py KVConnector calls).
        self.kv_connector = kv_connector
        if kv_connector is not None:
            # Let the connector query current block ids directly instead
            # of threading them through every hook.
            kv_connector.kv_manager = self.kv_cache_manager

        # Encoder (vision) output budget (reference:
        # v1/core/encoder_cache_manager.py); payloads live worker-side,
        # the scheduler owns admission accounting.
        from vllm_distributed_tpu.core.encoder_cache_manager import \
            EncoderCacheManager
        self.encoder_cache = EncoderCacheManager(
            config.scheduler_config.encoder_cache_budget)

        self.requests: dict[str, Request] = {}
        self.waiting: deque[Request] = deque()
        self.running: list[Request] = []
        # Finished ids to tell the workers to drop state for.
        self.finished_req_ids: set[str] = set()
        # Async KV transfer state (reference: scheduler.py
        # WAITING_FOR_REMOTE_KVS handling + nixl_connector.py:295
        # deferred free). Requests held until their KV pull lands, and
        # finished producer requests whose pages stay alive until the
        # consumer pulled them.
        self.waiting_for_remote_kv: dict[str, Request] = {}
        self.reqs_pending_send: dict[str, Request] = {}
        # Aborted while a pull was in flight: pages stay allocated until
        # the worker reports the (now moot) pull finished, so a late
        # apply can never write into reallocated pages.
        self.cancelled_remote_kv: dict[str, Request] = {}
        # Engine-core batch queue (PP microbatches, or the async
        # depth-2 pipeline): requests inside a dispatched-but-unretired
        # batch, REFCOUNTED — under async scheduling one request can sit
        # in two in-flight batches at once. In-flight requests are
        # protected from preemption (device work is writing their
        # pages) and external finishes defer until every batch holding
        # them retires; under PP (sync) they are also skipped by
        # schedule(), while async scheduling re-grants them
        # speculatively (see the schedule() running loop).
        self.in_flight_req_ids: Counter = Counter()
        self._deferred_finishes: dict[str, RequestStatus] = {}
        # Async scheduling: overlap host scheduling with device
        # execution. schedule() advances num_computed_tokens at GRANT
        # time (so the next schedule() can run ahead), grants one
        # speculative position per running decode request whose sampled
        # token is still on device (the runner chains it
        # device-to-device), and update_from_output reconciles when the
        # token lands — stop/EOS detection lags one step, and a request
        # finishing with a batch still in flight parks here until that
        # batch retires (its pages are being written).
        self.async_scheduling = getattr(sched_cfg, "async_scheduling",
                                        False)
        self._finished_pending_retire: dict[str, Request] = {}
        # Speculative (run-ahead) decode grants issued (stats).
        self.num_async_spec_grants = 0

        # Remote-KV watchdog (fault-tolerance layer): requests held in
        # WAITING_FOR_REMOTE_KVS past this deadline are swept into the
        # failed-pull requeue path instead of hanging forever.
        ft_cfg = config.fault_tolerance_config
        self.kv_pull_timeout_s = ft_cfg.kv_pull_timeout_s
        self.kv_pull_max_retries = ft_cfg.kv_pull_max_retries
        self.kv_pull_abandon_timeout_s = ft_cfg.kv_pull_abandon_timeout_s

        # Per-tenant QoS (core/sched/qos.py): deficit-round-robin
        # weighted fair queueing over tenants, soft KV page quotas with
        # quota-aware preemption, and the per-tenant accounting behind
        # the vdt:tenant_* families. None when VDT_QOS=0 (the default)
        # — every hook below is then a short-circuited None check and
        # scheduling stays byte-identical to the pre-QoS behavior.
        self.qos = qos_mod.maybe_qos_state(self.max_num_batched_tokens,
                                           num_blocks)
        # Per-step {tenant: deque of waiting requests in queue order},
        # built lazily by _qos_pick_waiting, popped by _waiting_remove.
        self._qos_waiting_by_tenant: Optional[dict[str, deque]] = None

        # Stats for the metrics subsystem.
        self.num_scheduled_steps = 0
        self.num_preemptions = 0
        # Preemption attribution: "capacity" = a lower-priority victim
        # was evicted for another request's pages, "self" = the request
        # could find no victim (token-parallel rank exhausted, or every
        # candidate in flight) and preempted itself.
        self.preemption_causes: dict[str, int] = {}
        self.watchdog_timeouts = 0
        self.kv_pull_retries = 0
        self.kv_pull_failures = 0
        # Request-lifecycle timeline (metrics/events.py): the scheduler's
        # local ring buffer, drained over the stats RPC; the per-request
        # event lists additionally ride EngineCoreOutput to the
        # front-end. `events_enabled` is cached (the envs registry
        # re-reads os.environ per access).
        self.events = ev.EventRecorder()
        self.events_enabled = self.events.enabled
        # Distributed trace plane: when on, every recorded event detail
        # carries the request's trace id so the front-end assembler can
        # stitch this replica's spans into the fleet-wide causal trace.
        # Cached like events_enabled; off means zero stamping work and
        # byte-identical event details.
        self.trace_enabled = ev.trace_plane_enabled()
        # Batch composition of the most recent non-empty step (gauges).
        self.last_step_prefill_tokens = 0
        self.last_step_decode_tokens = 0

    def _record_event(self, request: Request, event: str,
                      detail: Optional[dict] = None, *,
                      force: bool = False) -> None:
        """One lifecycle transition: onto the request's own event list
        (ships with its next output) and the scheduler's ring buffer
        (ships with get_stats). ``force`` bypasses the timeline kill
        switch for the per-request list only — recovery-ladder events
        feed ACCOUNTING at the front end (disagg fallback counters),
        which must not ride a telemetry flag; the ring buffer stays
        gated."""
        if not self.events_enabled and not force:
            return
        ts = time.monotonic()
        if self.trace_enabled and request.trace_ctx is not None:
            detail = ev.stamp_trace(detail, request.trace_ctx)
        request.events.append((ts, event, detail))
        if self.events_enabled:
            self.events.record(request.request_id, event, detail, ts=ts)

    def _take_events(self, request: Request) -> Optional[list[tuple]]:
        if not request.events:
            return None
        taken = request.events
        request.events = []
        return taken

    # ------------------------------------------------------------------
    # Request intake / teardown
    # ------------------------------------------------------------------
    def add_request(self, request: Request) -> None:
        assert request.request_id not in self.requests
        self.requests[request.request_id] = request
        request.status = RequestStatus.WAITING
        self._record_event(request, ev.QUEUED,
                           {"prompt_tokens": request.num_prompt_tokens,
                            "priority": request.priority})
        if self.policy == "priority":
            self._insert_by_priority(request)
        else:
            self.waiting.append(request)

    def _insert_by_priority(self, request: Request) -> None:
        key = (request.priority, request.arrival_time)
        for i, r in enumerate(self.waiting):
            if key < (r.priority, r.arrival_time):
                self.waiting.insert(i, request)
                return
        self.waiting.append(request)

    def finish_requests(self, request_ids: str | Iterable[str],
                        status: RequestStatus) -> None:
        """External finish (abort, stop-string hit detected by the
        front-end detokenizer). Reference: scheduler.py finish_requests."""
        if isinstance(request_ids, str):
            request_ids = (request_ids, )
        for req_id in request_ids:
            request = self.requests.get(req_id)
            if request is None or request.is_finished:
                continue
            if req_id in self.in_flight_req_ids:
                # A dispatched batch is still writing this request's
                # pages; freeing them now would hand them to another
                # request mid-write. Finish when the batch retires.
                self._deferred_finishes[req_id] = status
                continue
            if request.status == RequestStatus.RUNNING:
                self.running.remove(request)
            elif request.status == RequestStatus.WAITING_FOR_REMOTE_KVS:
                # The worker's pull is still in flight; keep the pages
                # alive until it reports in, then free (see
                # _update_kv_transfer_state). The abandon backstop
                # covers this hold too — a silently-dropped pull for an
                # aborted request must not leak its pages forever.
                self.waiting_for_remote_kv.pop(req_id, None)
                request.status = status
                request.expires_at = (time.monotonic() +
                                      self.kv_pull_abandon_timeout_s)
                if self.kv_connector is not None:
                    self.kv_connector.cancel_pull(req_id)
                self.cancelled_remote_kv[req_id] = request
                if self.structured_manager is not None:
                    self.structured_manager.remove_request(req_id)
                self.finished_req_ids.add(req_id)
                del self.requests[req_id]
                continue
            else:
                try:
                    self.waiting.remove(request)
                except ValueError:
                    pass
            request.status = status
            self._free_request(request)

    def _commit_encoder_budget(self, request: Request) -> None:
        # offset < 0 marks cross-attention payloads (whisper audio):
        # they live in fixed state rows, not the encoder cache.
        budgeted = [m for m in (request.mm_inputs or ())
                    if m.offset >= 0]
        if budgeted and not self.encoder_cache.has(request.request_id):
            self.encoder_cache.allocate(
                request.request_id,
                sum(m.num_tokens for m in budgeted))

    def _free_request(self, request: Request) -> Optional[dict]:
        """Tear a finished request down. Returns the connector's
        kv_transfer_params to hand back to the client (a producer's
        pull coordinates), or None."""
        assert request.is_finished
        self.encoder_cache.free(request.request_id)
        params = None
        defer = False
        if self.kv_connector is not None:
            # Teardown hook (reference: base.py request_finished).
            # Synchronous connectors never defer the free; async
            # (pull-based) connectors return defer=True and the free
            # then waits on the worker's finished_sending notice
            # (reference: nixl_connector.py:295 deferred block free).
            defer, params = self.kv_connector.request_finished(
                request,
                self.kv_cache_manager.get_block_ids(request.request_id)
                if request.request_id in getattr(
                    self.kv_cache_manager, "req_to_blocks", {}) else [])
        if defer:
            self.reqs_pending_send[request.request_id] = request
        else:
            self.kv_cache_manager.free(request)
            self.kv_cache_manager.free_block_hashes(request)
        if self.structured_manager is not None:
            self.structured_manager.remove_request(request.request_id)
        if self.state_cache is not None:
            # Uncommitted saves die with the request (their row is about
            # to be recycled); committed snapshots outlive it — they ARE
            # the multi-turn prefix cache.
            self.state_cache.abort_pending(request.request_id)
            self.state_cache.drop_request(request.request_id)
        self.finished_req_ids.add(request.request_id)
        del self.requests[request.request_id]
        return params

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------
    def has_requests(self) -> bool:
        return bool(self.waiting or self.running
                    or self.waiting_for_remote_kv)

    def has_schedulable_requests(self) -> bool:
        """Work the next schedule() call could actually grant tokens to
        — gates dispatching another batch in the engine core's batch
        queue. Under PP (sync) in-flight requests are excluded; under
        async scheduling a request with known-token backlog or exactly
        one in-flight sample is speculatively re-grantable."""
        if self.waiting:
            return True
        if self.async_scheduling:
            return any(self._async_schedulable(r) for r in self.running)
        return any(r.request_id not in self.in_flight_req_ids
                   for r in self.running)

    # ------------------------------------------------------------------
    # In-flight batch bookkeeping (engine-core batch queue)
    # ------------------------------------------------------------------
    def mark_in_flight(self, req_ids: Iterable[str]) -> None:
        for req_id in req_ids:
            self.in_flight_req_ids[req_id] += 1

    def unmark_in_flight(self, req_ids: Iterable[str]) -> None:
        for req_id in req_ids:
            n = self.in_flight_req_ids[req_id] - 1
            if n > 0:
                self.in_flight_req_ids[req_id] = n
            else:
                del self.in_flight_req_ids[req_id]

    # ------------------------------------------------------------------
    # Async-scheduling predicates
    # ------------------------------------------------------------------
    @staticmethod
    def _needs_sync_step(request: Request) -> bool:
        """Requests whose next grant depends on host-side state from the
        previous step's sampled token: grammar masks (structured output)
        advance on the emitted tokens, penalty/bias sampling reads the
        host token history, prompt_logprobs and pooling key off exact
        prompt accounting. These fall back to PP-style one-batch-at-a-
        time scheduling (skip while in flight, never run ahead)."""
        sp = request.sampling_params
        return (request.pooling_params is not None
                or sp.structured is not None
                or sp.prompt_logprobs is not None
                or sp.needs_extended_static
                or sp.min_tokens > 0)

    def _can_speculate(self, request: Request) -> bool:
        """One speculative run-ahead position may be granted iff every
        known token is computed (== exactly one sample is owed by an
        in-flight batch; the runner chains it device-to-device), the
        context window has room, and the owed sample won't already cap
        max_tokens (the extra position would be guaranteed waste)."""
        return (request.num_computed_tokens == request.num_tokens
                and not request.spec_token_ids
                and request.num_computed_tokens < self.max_model_len
                and (request.num_output_tokens + 1
                     < request.sampling_params.max_tokens))

    def _async_schedulable(self, request: Request) -> bool:
        if self._needs_sync_step(request):
            return request.request_id not in self.in_flight_req_ids
        if request.num_tokens_with_spec > request.num_computed_tokens:
            return True  # known-token backlog (prefill chunks)
        return self._can_speculate(request)

    def has_kv_transfer_work(self) -> bool:
        """True while async KV transfers are in flight: held consumer
        requests or producer pages awaiting a peer's pull. The engine
        core keeps stepping (with possibly-empty scheduler outputs) so
        the worker's get_finished() poll services them."""
        return bool(self.waiting_for_remote_kv or self.reqs_pending_send
                    or self.cancelled_remote_kv)

    def has_unfinished_requests(self) -> bool:
        return self.has_requests()

    def get_num_unfinished_requests(self) -> int:
        return len(self.waiting) + len(self.running)

    # ------------------------------------------------------------------
    # The hot loop
    # ------------------------------------------------------------------
    def schedule(self) -> SchedulerOutput:
        if self.qos is not None:
            # Replenish per-tenant deficits and snapshot who competes
            # for prefill bandwidth / holds pages this step. The
            # per-tenant waiting-queue view is rebuilt lazily by the
            # first _qos_pick_waiting of the step (the queue may gain
            # requests between steps).
            self.qos.begin_step(self.waiting, self.running,
                                self._qos_held_by_tenant())
            self._qos_waiting_by_tenant = None
        scheduled_new_reqs: list[NewRequestData] = []
        cached_reqs = CachedRequestData()
        num_scheduled_tokens: dict[str, int] = {}
        scheduled_spec_tokens: dict[str, list[int]] = {}
        token_budget = self.max_num_batched_tokens
        preempted: list[Request] = []
        # Batch composition (prefill vs decode tokens) of this step.
        prefill_tokens = 0
        decode_tokens = 0
        # SSM state-cache directives accumulated this step (plus any
        # deferred from empty outputs).
        state_saves: list = []
        state_restores: list = []
        # KV-tier promote directives staged by this step's admissions.
        kv_promotes: list = []

        # Multi-step decode burst: when every running request is in plain
        # decode and nothing is waiting, the worker can run N fused decode
        # steps on-device per host roundtrip. All N slots are allocated up
        # front via num_lookahead_tokens; the burst is disabled for any
        # request that could finish or hit the context window mid-burst.
        # (Token parallelism forces num_scheduler_steps=1 at config
        # normalization: the fused burst cannot refresh per-rank metadata.)
        multi_step = self.num_scheduler_steps
        if multi_step > 1:
            if self.waiting or not self.running:
                multi_step = 1
            else:
                for request in self.running:
                    sp = request.sampling_params
                    if (request.num_tokens_with_spec -
                            request.num_computed_tokens != 1
                            or request.pooling_params is not None
                            or request.spec_token_ids
                            or sp.needs_extended_static
                            or request.num_output_tokens < sp.min_tokens
                            or sp.max_tokens - request.num_output_tokens <
                            multi_step
                            or self.max_model_len -
                            request.num_computed_tokens < multi_step):
                        multi_step = 1
                        break

        # ---- 1. Running requests (decode / ongoing chunked prefill) ----
        req_index = 0
        while req_index < len(self.running) and token_budget > 0:
            request = self.running[req_index]
            if (request.request_id in self.in_flight_req_ids
                    and (not self.async_scheduling
                         or self._needs_sync_step(request))):
                # Another dispatched batch owns this request's next
                # token (PP batch queue, or an async request that needs
                # host-synchronous sampling state); it becomes
                # schedulable when that batch retires.
                req_index += 1
                continue
            num_new_tokens = (request.num_tokens_with_spec -
                              request.num_computed_tokens)
            speculative = False
            if (num_new_tokens <= 0 and self.async_scheduling
                    and not self._needs_sync_step(request)
                    and self._can_speculate(request)):
                # Async run-ahead: every known token is computed, so the
                # only thing missing is the sample an in-flight batch
                # owes. Grant the NEXT position now; the runner feeds it
                # the on-device sampled token (device-to-device chain).
                num_new_tokens = 1
                speculative = True
            if self.long_prefill_token_threshold > 0:
                num_new_tokens = min(num_new_tokens,
                                     self.long_prefill_token_threshold)
            num_new_tokens = min(num_new_tokens, token_budget)
            # Never run past the context window.
            num_new_tokens = min(
                num_new_tokens,
                self.max_model_len - request.num_computed_tokens)
            in_prefill = (request.num_computed_tokens
                          < request.num_prompt_tokens)
            if (self.qos is not None and num_new_tokens > 0
                    and in_prefill):
                # DRR: an ongoing chunked-prefill grant clips to the
                # tenant's remaining deficit while another tenant with
                # credit competes, and always leaves one-token headroom
                # per OTHER tenant's unserved running decode (decode
                # grants themselves are never clipped — stalling a
                # running decode moves everyone's TPOT).
                num_new_tokens = self.qos.prefill_allowance(
                    self.qos.key_of(request), num_new_tokens,
                    token_budget)
            if self.state_cache is not None and num_new_tokens > 0:
                # Land prefill chunks exactly on snapshot boundaries so
                # the state rows hold boundary state when the copy runs.
                num_new_tokens = self.state_cache.clip_grant(
                    request.num_computed_tokens, num_new_tokens)
            if num_new_tokens <= 0:
                req_index += 1
                continue

            scheduled = True
            skipped = False
            while True:
                new_blocks = self.kv_cache_manager.allocate_slots(
                    request, num_new_tokens,
                    num_lookahead_tokens=multi_step - 1)
                if new_blocks is not None:
                    break
                if multi_step > 1:
                    # Not enough pages for the whole burst: degrade to
                    # single-step before resorting to preemption. (Earlier
                    # requests keep their lookahead pages — they will be
                    # used by the following decode steps anyway.)
                    multi_step = 1
                    continue
                # Out of pages: preempt the lowest-priority running request
                # that has NOT been scheduled this step (evicting a
                # scheduled one would leave SchedulerOutput entries
                # pointing at freed pages).
                victim, cause = self._select_preemption_victim(
                    req_index, request)
                if (victim is request
                        and request.request_id in self.in_flight_req_ids):
                    # Async: the only preemptable candidate is this
                    # request itself, but an in-flight batch is writing
                    # its pages — evicting it would corrupt them. Skip
                    # the grant; pressure resolves once batches retire
                    # (an empty queue restores normal preemption).
                    skipped = True
                    break
                # "self" overrides only the capacity pick (the pre-QoS
                # no-eligible-victim semantics); a quota eviction keeps
                # its cause even when the over-quota tenant's lowest-
                # priority request IS the requester.
                self._preempt(victim,
                              cause=("self" if victim is request
                                     and cause == "capacity" else cause))
                preempted.append(victim)
                if victim is request:
                    scheduled = False
                    break
            if skipped:
                req_index += 1
                continue
            if not scheduled:
                # The current request itself was preempted; its slot in
                # self.running is gone — do not advance req_index.
                continue

            num_scheduled_tokens[request.request_id] = num_new_tokens
            token_budget -= num_new_tokens
            if self.qos is not None:
                self.qos.charge(self.qos.key_of(request), num_new_tokens,
                                decode=not in_prefill)
            if in_prefill:
                # Ongoing chunked prefill (num_computed is pre-advance
                # here even under async scheduling).
                prefill_tokens += num_new_tokens
                self._record_event(
                    request, ev.PREFILL_CHUNK,
                    {"computed": request.num_computed_tokens,
                     "granted": num_new_tokens})
            else:
                decode_tokens += num_new_tokens
            if request.spec_token_ids:
                # Trim drafts to the granted token count (1 committed token
                # + at most num_new_tokens-1 drafts); publishing untrimmed
                # drafts would desync num_computed_tokens accounting when
                # the budget caps num_new_tokens.
                num_drafts = max(num_new_tokens - 1, 0)
                request.spec_token_ids = request.spec_token_ids[:num_drafts]
                if request.spec_token_ids:
                    scheduled_spec_tokens[request.request_id] = \
                        list(request.spec_token_ids)
            cached_reqs.req_ids.append(request.request_id)
            cached_reqs.resumed_from_preemption.append(False)
            cached_reqs.new_token_ids.append(
                request.all_token_ids[request.num_computed_tokens:
                                      request.num_computed_tokens +
                                      num_new_tokens])
            cached_reqs.new_block_ids.append(new_blocks.get_block_ids())
            cached_reqs.num_computed_tokens.append(
                request.num_computed_tokens)
            if self.state_cache is not None:
                # Snapshot when this grant lands exactly on a boundary
                # (committed in update_from_output once the step's
                # tokens reconcile — an async run-ahead that stops
                # short never enters the index).
                directive = self.state_cache.maybe_save(
                    request,
                    request.num_computed_tokens + num_new_tokens)
                if directive is not None:
                    state_saves.append(directive)
            if self.async_scheduling:
                # Advance AT GRANT TIME so the next schedule() call can
                # run ahead of this batch; update_from_output skips the
                # advance for async_scheduled batches.
                request.num_computed_tokens += num_new_tokens
                if speculative:
                    self.num_async_spec_grants += 1
                    if not request.async_spec_granted:
                        # Timeline transition: entered run-ahead mode
                        # (once per request; grants recur per step).
                        request.async_spec_granted = True
                        self._record_event(request, ev.SPEC_GRANT, None)
            req_index += 1

        # ---- 2. Waiting requests (new or resumed-from-preemption) ----
        # Don't admit new work in a step where we had to preempt.
        if not preempted:
            while (self.waiting and token_budget > 0
                   and len(self.running) < self.max_num_seqs):
                request = (self.waiting[0] if self.qos is None
                           else self._qos_pick_waiting())

                if not self._lora_admittable(request):
                    # Admitting would need more distinct adapters than
                    # the runner has slots (reference: the scheduler's
                    # lora constraint); wait for a lora request to
                    # finish rather than crash the runner's slot pool.
                    break

                if request.num_prompt_tokens >= self.max_model_len:
                    # The prompt alone fills (or overflows) the context
                    # window: it could never produce a token. Reject it
                    # instead of admitting a request that can never finish.
                    logger.warning(
                        "Request %s prompt (%d tokens) exceeds "
                        "max_model_len (%d); ignoring.",
                        request.request_id, request.num_prompt_tokens,
                        self.max_model_len)
                    self._waiting_remove(request)
                    request.status = RequestStatus.FINISHED_IGNORED
                    self._free_request(request)
                    continue

                budgeted_mm = [m for m in (request.mm_inputs or ())
                               if m.offset >= 0]
                if budgeted_mm and not self.encoder_cache.has(
                        request.request_id):
                    n_enc = sum(m.num_tokens for m in budgeted_mm)
                    if n_enc > self.encoder_cache.budget:
                        logger.warning(
                            "Request %s needs %d encoder tokens; the "
                            "budget is %d; ignoring.",
                            request.request_id, n_enc,
                            self.encoder_cache.budget)
                        self._waiting_remove(request)
                        request.status = RequestStatus.FINISHED_IGNORED
                        self._free_request(request)
                        continue
                    if not self.encoder_cache.can_allocate(
                            request.request_id, n_enc):
                        break  # encoder budget full; wait
                    # NOTE: allocation is COMMITTED only at the popleft
                    # points below — a later admission failure (e.g. no
                    # KV pages) must not leave a still-waiting request
                    # holding budget, or a higher-priority arrival could
                    # deadlock the queue head against it.

                if self.tknp_size > 1 and request.tknp_rank is None:
                    self._assign_tknp_rank(request)

                num_computed_tokens = request.num_computed_tokens
                new_computed_blocks: Optional[KVCacheBlocks] = None
                state_restore = None
                state_only_admit = False
                num_tier_pages = 0
                if (num_computed_tokens == 0
                        and request.sampling_params.prompt_logprobs
                        is None):
                    # Fresh request: prefix-cache lookup. Skipped for
                    # prompt_logprobs requests — cached positions never
                    # run a forward, so their entries could not be
                    # scored (the reference likewise recomputes).
                    if self.state_cache is not None:
                        # Stateful models: the longest prefix with a
                        # live state snapshot (and, for hybrid models,
                        # its attention pages still cached) is a
                        # complete resume point — admit as a
                        # continuation at the boundary.
                        blocks, boundary, state_restore = \
                            self.state_cache.get_computed_state(
                                request, self._block_pools()[0])
                        if boundary:
                            num_computed_tokens = boundary
                            if blocks:
                                new_computed_blocks = KVCacheBlocks(
                                    blocks)
                            else:
                                # Pure-SSM models need no prefix pages;
                                # the boundary is marked computed just
                                # before allocation so allocate_slots
                                # covers the whole token range with
                                # fresh (content-free) pages.
                                state_only_admit = True
                    else:
                        new_computed_blocks, num_computed_tokens = \
                            self.kv_cache_manager.get_computed_blocks(
                                request)
                        if self.kv_tier is not None:
                            # Trailing pages of the hit live in a spill
                            # tier: their span counts as computed, but
                            # device pages must still be ALLOCATED for
                            # them (below) and a promote directive
                            # scatters the content back pre-forward.
                            num_tier_pages = \
                                self.kv_tier.pending_hit_count(
                                    request.request_id)
                    if request.num_cached_tokens < 0:
                        request.num_cached_tokens = num_computed_tokens

                # Disaggregated prefill: KV for part of the prompt may be
                # loadable from outside (reference: scheduler.py waiting
                # loop KVConnector.get_num_new_matched_tokens). External
                # pages are allocated now and filled by the worker-side
                # connector before the forward pass.
                num_external = 0
                load_async = False
                if (self.kv_connector is not None
                        and request.sampling_params.prompt_logprobs
                        is None):
                    # Externally-loaded positions never run a forward,
                    # so prompt_logprobs requests recompute locally
                    # (same reason as the prefix-cache bypass above).
                    num_external, load_async = \
                        self.kv_connector.get_num_new_matched_tokens(
                            request, num_computed_tokens)

                if load_async and num_external > 0:
                    # Async pull: allocate the external span now, then
                    # hold the request out of the queue until the worker
                    # reports the transfer landed (reference: scheduler
                    # WAITING_FOR_REMOTE_KVS + nixl start_load_kv). The
                    # local prefix hit is committed first so the pull
                    # only covers the missing pages.
                    new_blocks = self.kv_cache_manager.allocate_slots(
                        request, num_external, new_computed_blocks,
                        delay_caching=True)
                    if new_blocks is None:
                        break  # no room; retry next step
                    self._waiting_remove(request)
                    self._commit_encoder_budget(request)
                    request.status = RequestStatus.WAITING_FOR_REMOTE_KVS
                    self._record_event(request, ev.KV_PULL_WAIT,
                                       {"external_tokens": num_external})
                    request.num_computed_tokens = num_computed_tokens
                    request.num_external_computed_tokens = num_external
                    self.kv_connector.update_state_after_alloc(
                        request,
                        self.kv_cache_manager.get_block_ids(
                            request.request_id),
                        num_external)
                    # Monotonic: a wall-clock step (NTP, VM resume) must
                    # not mass-fire the sweep or the abandon backstop.
                    request.remote_kv_deadline = (
                        time.monotonic() + self.kv_pull_timeout_s
                        if self.kv_pull_timeout_s > 0 else None)
                    self.waiting_for_remote_kv[request.request_id] = request
                    continue

                num_new_tokens = (request.num_tokens - num_computed_tokens -
                                  num_external)
                if self.long_prefill_token_threshold > 0:
                    num_new_tokens = min(num_new_tokens,
                                         self.long_prefill_token_threshold)
                if num_new_tokens > token_budget:
                    if not self.enable_chunked_prefill:
                        break  # must fit in one step
                    num_new_tokens = token_budget
                if self.qos is not None and self.enable_chunked_prefill:
                    # DRR: the first chunk of the picked (max-deficit)
                    # tenant clips to its deficit — never below one
                    # token, so the selected tenant always progresses.
                    num_new_tokens = self.qos.admission_allowance(
                        self.qos.key_of(request), num_new_tokens)
                if (self.state_cache is not None
                        and self.enable_chunked_prefill):
                    num_new_tokens = self.state_cache.clip_grant(
                        num_computed_tokens, num_new_tokens)
                assert num_new_tokens > 0

                if state_only_admit:
                    request.num_computed_tokens = num_computed_tokens
                # Tier-hit pages need device pages allocated even
                # though their tokens count as computed (the content
                # scatters back pre-forward); the span rides the
                # allocation but never the token grant.
                tier_span = (num_tier_pages *
                             self.kv_cache_manager.block_size)
                new_blocks = self.kv_cache_manager.allocate_slots(
                    request, num_external + tier_span + num_new_tokens,
                    new_computed_blocks)
                if new_blocks is None:
                    if state_only_admit:
                        # Still WAITING: the next attempt re-runs the
                        # lookup (the snapshot may have been evicted by
                        # then, so the hit must not be sticky).
                        request.num_computed_tokens = 0
                    # Out of pages; retry next step. A fresh token-parallel
                    # request holding nothing un-pins from its rank so the
                    # next attempt re-picks by load (a full rank must not
                    # stall the queue head while others have room).
                    if (self.tknp_size > 1
                            and request.num_computed_tokens == 0
                            and not (new_computed_blocks
                                     and new_computed_blocks.blocks)):
                        self.kv_cache_manager.release_rank(request)
                    break

                self._waiting_remove(request)
                self._commit_encoder_budget(request)
                resumed = request.status == RequestStatus.PREEMPTED
                request.status = RequestStatus.RUNNING
                request.num_computed_tokens = num_computed_tokens
                if num_external:
                    # Externally-loaded tokens count as computed; the
                    # worker-side connector fills their pages before the
                    # step's forward.
                    self.kv_connector.update_state_after_alloc(
                        request,
                        self.kv_cache_manager.get_block_ids(
                            request.request_id),
                        num_external)
                    num_computed_tokens += num_external
                    request.num_computed_tokens = num_computed_tokens
                self.running.append(request)
                self._record_event(request,
                                   ev.RESUMED if resumed else ev.SCHEDULED,
                                   {"computed": num_computed_tokens,
                                    "granted": num_new_tokens})
                if self.kv_tier is not None and num_tier_pages:
                    # Commit the staged tier hit: the runner scatters
                    # the (already-verified, already-pinned) arrays
                    # into the first tier-span pages of this
                    # allocation before the forward.
                    hits = self.kv_tier.take_hits(request.request_id)
                    if hits:
                        from vllm_distributed_tpu.core.kv_tier import \
                            PromoteDirective
                        kv_promotes.append(PromoteDirective(
                            req_id=request.request_id,
                            page_ids=new_blocks.get_block_ids()
                            [:len(hits)],
                            keys=[h[0] for h in hits],
                            tiers=[h[1] for h in hits],
                            arrays=[(h[2], h[3]) for h in hits]))
                        self._record_event(
                            request, ev.KV_TIER_PROMOTE,
                            {"pages": len(hits),
                             "tiers": sorted({h[1] for h in hits})})
                if self.state_cache is not None:
                    # This grant rewrites the recurrence from
                    # `num_computed_tokens`; any uncommitted park of an
                    # older boundary no longer describes the row.
                    self.state_cache.abort_pending(request.request_id)
                    if state_restore is not None:
                        state_restores.append(state_restore)
                        # Hit accounting lives HERE (not in the lookup):
                        # a blocked queue head re-runs the lookup every
                        # step and must not inflate the hit rate.
                        self.state_cache.hits += 1
                        self.state_cache.resume_tokens_saved += \
                            num_computed_tokens

                num_scheduled_tokens[request.request_id] = num_new_tokens
                token_budget -= num_new_tokens
                if self.qos is not None:
                    self.qos.charge(self.qos.key_of(request),
                                    num_new_tokens)
                if self.state_cache is not None:
                    directive = self.state_cache.maybe_save(
                        request, num_computed_tokens + num_new_tokens)
                    if directive is not None:
                        state_saves.append(directive)
                if num_computed_tokens < request.num_prompt_tokens:
                    prefill_tokens += num_new_tokens
                else:
                    # Whole prompt already computed (e.g. remote-KV
                    # pull landed everything): this grant is decode.
                    decode_tokens += num_new_tokens

                all_block_ids = self.kv_cache_manager.get_block_ids(
                    request.request_id)
                if resumed:
                    cached_reqs.req_ids.append(request.request_id)
                    cached_reqs.resumed_from_preemption.append(True)
                    cached_reqs.new_token_ids.append(
                        list(request.all_token_ids))
                    cached_reqs.new_block_ids.append(all_block_ids)
                    cached_reqs.num_computed_tokens.append(
                        num_computed_tokens)
                else:
                    scheduled_new_reqs.append(
                        NewRequestData(
                            req_id=request.request_id,
                            prompt_token_ids=list(request.prompt_token_ids),
                            sampling_params=request.sampling_params,
                            block_ids=all_block_ids,
                            num_computed_tokens=num_computed_tokens,
                            lora_request=request.lora_request,
                            pooling_params=request.pooling_params,
                            mm_inputs=request.mm_inputs,
                        ))
                if self.async_scheduling:
                    # Grant-time advance (see the running loop): the
                    # wire data above carries the pre-advance count.
                    request.num_computed_tokens += num_new_tokens

        self.num_scheduled_steps += 1
        total = sum(num_scheduled_tokens.values())
        if num_scheduled_tokens:
            self.last_step_prefill_tokens = prefill_tokens
            self.last_step_decode_tokens = decode_tokens
        tknp_alloc = None
        if self.tknp_size > 1:
            req_to_rank = {
                req_id: self.requests[req_id].tknp_rank
                for req_id in num_scheduled_tokens
            }
            tokens_per_rank = [0] * self.tknp_size
            for req_id, n in num_scheduled_tokens.items():
                tokens_per_rank[req_to_rank[req_id]] += n
            self.tknp_tokens_per_rank = tokens_per_rank
            tknp_alloc = TokenParallelAllocation(
                req_to_rank=req_to_rank,
                tokens_per_rank=tokens_per_rank)
        structured_masks = None
        if self.structured_manager is not None:
            masks = {
                req_id: self.structured_manager.mask_for(req_id)
                for req_id in num_scheduled_tokens
                if self.structured_manager.has(req_id)
            }
            structured_masks = masks or None
        output = SchedulerOutput(
            scheduled_new_reqs=scheduled_new_reqs,
            scheduled_cached_reqs=cached_reqs,
            num_scheduled_tokens=num_scheduled_tokens,
            total_num_scheduled_tokens=total,
            scheduled_spec_decode_tokens=scheduled_spec_tokens,
            finished_req_ids=self.finished_req_ids,
            multi_step=multi_step if num_scheduled_tokens else 1,
            token_parallel_allocation=tknp_alloc,
            structured_masks=structured_masks,
            async_scheduled=self.async_scheduling,
        )
        if self.state_cache is not None:
            saves = self._deferred_state_saves + state_saves
            if num_scheduled_tokens:
                # Aborted parks (their request restarted from scratch
                # or finished) must not reach the runner — the row no
                # longer holds the boundary's state. Owed journal
                # writes of already-committed async saves ride along
                # as persist_only directives.
                output.state_saves = ([
                    d for d in saves if self.state_cache.is_pending(d)
                ] + self.state_cache.take_persists()) or None
                output.state_restores = state_restores or None
                self._deferred_state_saves = []
            else:
                # The zero-token dispatch path does no device work by
                # contract; park copies wait for the next real batch.
                self._deferred_state_saves = saves
        if self.kv_tier is not None:
            # Demotes drain every step (evictions only happen inside
            # successful allocations, so a step carrying them always
            # dispatched work; the guard is defensive). Promotes were
            # staged by this step's admissions.
            output.kv_demotes = self.kv_tier.take_demotes(
                bool(num_scheduled_tokens))
            output.kv_promotes = kv_promotes or None
            if self.events_enabled and output.kv_demotes:
                # Page-level batch (no single owner request): rid="".
                self.events.record(
                    "", ev.KV_TIER_DEMOTE,
                    {"pages": len(output.kv_demotes.page_ids)})
        self.finished_req_ids = set()
        if self.kv_connector is not None:
            output.kv_connector_metadata = \
                self.kv_connector.build_connector_meta(output)
        if self.kv_event_publisher is not None:
            events = []
            for pool in self._block_pools():
                events.extend(pool.take_events())
            self.kv_event_publisher.publish(events)
        return output

    def _block_pools(self):
        mgr = self.kv_cache_manager
        if hasattr(mgr, "block_pool"):
            return [mgr.block_pool]
        return [m.block_pool for m in mgr.managers]

    def shutdown(self) -> None:
        if self.kv_event_publisher is not None:
            self.kv_event_publisher.shutdown()

    def _lora_admittable(self, request: Request) -> bool:
        """Distinct adapters among live requests + this one must fit the
        runner's slot count. ALL unfinished lora requests count —
        preempted ones still hold their worker slot until they finish
        (the runner releases at removal, not preemption)."""
        if request.lora_request is None:
            return True
        max_loras = self.config.lora_config.max_loras
        names = {r.lora_request["name"] for r in self.requests.values()
                 if r.lora_request is not None}
        names.add(request.lora_request["name"])
        return len(names) <= max_loras

    def _assign_tknp_rank(self, request: Request) -> None:
        """Assign a token-parallel rank: most free pages first, then
        lightest current token load (reference: TokenParallelScheduler
        .assign_ranks, scheduler.py:88 — round-robin made free-block and
        load aware)."""
        mgr: TokenParallelKVCacheManager = self.kv_cache_manager
        request.tknp_rank = max(
            range(self.tknp_size),
            key=lambda r: (mgr.free_blocks_on_rank(r),
                           -self.tknp_tokens_per_rank[r], -r))
        logger.debug("request %s -> token-parallel rank %d",
                     request.request_id, request.tknp_rank)

    def _select_preemption_victim(
            self, req_index: int,
            request: Request) -> tuple[Request, str]:
        """Pick a victim among requests not yet scheduled this step
        (self.running[req_index:]) and the preemption cause it will be
        attributed. Under the priority policy the lowest-priority
        *unscheduled* request is chosen — a request already granted
        tokens this step is never evicted mid-step. With QoS on, the
        quota policy is consulted first: the most-over-quota tenant's
        lowest-priority request goes before any in-quota victim
        (cause "quota"; cooldown hysteresis inside quota_victim keeps
        an oscillating tenant from livelocking in evict/resume cycles).

        Token parallelism: only same-rank victims free pages in the
        exhausted rank's pool partition, so other ranks' requests are
        never evicted for this allocation; with no same-rank candidate
        the request preempts itself."""
        candidates = [r for r in self.running[req_index:]
                      if r.request_id not in self.in_flight_req_ids]
        if not candidates:
            return request, "self"
        if self.tknp_size > 1:
            candidates = [r for r in candidates
                          if r.tknp_rank == request.tknp_rank]
            if not candidates:
                return request, "self"
        if self.qos is not None:
            victim = self.qos.quota_victim(candidates, self.qos.key_of,
                                           self.num_scheduled_steps)
            if victim is not None:
                return victim, "quota"
        if self.policy == "priority":
            return max(candidates,
                       key=lambda r: (r.priority, r.arrival_time)), \
                "capacity"
        return candidates[-1], "capacity"

    # ------------------------------------------------------------------
    # Per-tenant QoS hooks (no-ops when VDT_QOS=0: self.qos is None)
    # ------------------------------------------------------------------
    def _qos_held_by_tenant(self) -> dict[str, int]:
        """KV pages currently held per tenant bucket, across every live
        request (running, waiting-with-pages, remote-KV holds)."""
        held: dict[str, int] = {}
        for r in list(self.requests.values()):
            n = self._num_blocks_of(r.request_id)
            if n:
                k = self.qos.key_of(r)
                if k == qos_mod.CANARY_TENANT:
                    continue  # correctness probes hold no tenant quota
                held[k] = held.get(k, 0) + n
        return held

    def _qos_pick_waiting(self) -> Request:
        """The waiting request QoS admits next: the earliest queued
        request of the tenant pick_waiting_tenant chooses (largest
        deficit; over-quota tenants passed over under pool pressure).
        Queue order within a tenant is untouched, so priority/arrival
        still decide among a tenant's own requests. The per-tenant
        queue view is built ONCE per step and popped incrementally by
        _waiting_remove — rescanning the whole deque on every
        admission iteration would make the loop O(waiting^2)."""
        if self._qos_waiting_by_tenant is None:
            by_tenant: dict[str, deque] = {}
            for r in self.waiting:
                by_tenant.setdefault(self.qos.key_of(r),
                                     deque()).append(r)
            self._qos_waiting_by_tenant = by_tenant
        keys = [k for k, q in self._qos_waiting_by_tenant.items() if q]
        best = self.qos.pick_waiting_tenant(keys,
                                            self.kv_cache_manager.usage)
        return self._qos_waiting_by_tenant[best][0]

    def _waiting_remove(self, request: Request) -> None:
        """Remove an admitted/rejected request from the waiting queue.
        QoS off always operates on the queue head (the pre-QoS popleft);
        QoS may have picked a mid-queue request of another tenant and
        also owes its per-tenant queue view the matching pop."""
        if self.waiting and self.waiting[0] is request:
            self.waiting.popleft()
        else:
            self.waiting.remove(request)
        if self.qos is not None and self._qos_waiting_by_tenant:
            q = self._qos_waiting_by_tenant.get(self.qos.key_of(request))
            if q and q[0] is request:
                q.popleft()
            elif q is not None:
                try:
                    q.remove(request)
                except ValueError:
                    pass

    def _preempt(self, request: Request, cause: str = "capacity") -> None:
        self.running.remove(request)
        if self.state_cache is not None:
            # Park the state instead of discarding: when the eviction
            # boundary is snapshot-aligned the resume restores it and
            # re-prefills nothing; otherwise the latest periodic
            # snapshot bounds the re-prefill to the tail since the
            # last checkpoint. (The copy rides the next non-empty
            # output; the parked request runs no tokens until resume,
            # so its rows stay exactly at the parked state.)
            directive = self.state_cache.maybe_save(
                request, request.num_computed_tokens)
            if directive is not None:
                self._deferred_state_saves.append(directive)
        self.kv_cache_manager.free(request)
        request.status = RequestStatus.PREEMPTED
        request.num_computed_tokens = 0
        request.spec_token_ids = []
        request.num_preemptions += 1
        self.num_preemptions += 1
        self.preemption_causes[cause] = \
            self.preemption_causes.get(cause, 0) + 1
        if self.qos is not None:
            # vdt:tenant_preemptions_total counts EVERY eviction the
            # tenant suffered, whatever the cause — operators read it
            # next to kv_blocks to see who is being squeezed.
            self.qos.note_preemption(self.qos.key_of(request))
        self._record_event(request, ev.PREEMPTED,
                           {"num_preemptions": request.num_preemptions,
                            "cause": cause})
        if self.policy == "priority":
            self._insert_by_priority(request)
        else:
            self.waiting.appendleft(request)

    # ------------------------------------------------------------------
    # Post-step accounting
    # ------------------------------------------------------------------
    def update_from_output(
        self,
        scheduler_output: SchedulerOutput,
        runner_output: ModelRunnerOutput,
    ) -> list[EngineCoreOutput]:
        """Fold sampled tokens back into request state; detect token-level
        stops; free finished requests. Reference: scheduler.py:1012."""
        num_scheduled = scheduler_output.num_scheduled_tokens
        sampled_by_req: dict[str, list[int]] = {
            req_id: tokens
            for req_id, tokens in zip(runner_output.req_ids,
                                      runner_output.sampled_token_ids)
        }
        logprobs_by_req: dict[str, list[dict[int, float]]] = {}
        if runner_output.logprobs is not None:
            logprobs_by_req = {
                req_id: lps
                for req_id, lps in zip(runner_output.req_ids,
                                       runner_output.logprobs)
            }
        spec_by_req: dict[str, list[int]] = {}
        if runner_output.spec_token_ids is not None:
            spec_by_req = {
                req_id: spec
                for req_id, spec in zip(runner_output.req_ids,
                                        runner_output.spec_token_ids)
            }

        self._update_kv_transfer_state(runner_output)

        # External finishes (aborts, stop strings) that arrived while
        # the request sat in a dispatched batch: the batch has retired
        # (the engine core clears in_flight before calling here), so the
        # normal finish path is safe now.
        if self._deferred_finishes:
            ready = [req_id for req_id in self._deferred_finishes
                     if req_id not in self.in_flight_req_ids]
            for req_id in ready:
                self.finish_requests(req_id,
                                     self._deferred_finishes.pop(req_id))

        # Async scheduling: requests that FINISHED at reconcile time
        # while a later speculative batch was still writing their pages.
        # That batch has now retired (the engine core unmarks before
        # calling here), so the parked pages can finally be freed — the
        # free also queues the worker-side row cleanup.
        if self._finished_pending_retire:
            for req_id in [r for r in self._finished_pending_retire
                           if r not in self.in_flight_req_ids]:
                self._free_request(
                    self._finished_pending_retire.pop(req_id))

        pooled_map = runner_output.pooled or {}
        plp_map = runner_output.prompt_logprobs or {}
        outputs: list[EngineCoreOutput] = []
        finished: list[Request] = []
        for request in self.running:
            req_id = request.request_id
            if req_id not in num_scheduled:
                continue
            scheduled = num_scheduled[req_id]
            if not request.prompt_lp_delivered:
                # Buffered until the first emitted output (mid-prompt
                # chunks produce no EngineCoreOutput); dict-keyed so a
                # preemption re-run overwrites, not duplicates. Entries
                # scored by a preempt-resume AFTER delivery are dropped
                # (the runner also stops scoring completed prompts).
                for entry, d in plp_map.get(req_id, ()):
                    request.prompt_lp_entries[entry] = d
            if req_id in pooled_map:
                # Embedding request: the prompt finished this step; the
                # pooled hidden state IS the result (no sampling).
                if not scheduler_output.async_scheduled:
                    request.num_computed_tokens += scheduled
                request.status = RequestStatus.FINISHED_STOPPED
                finished.append(request)
                outputs.append(EngineCoreOutput(
                    req_id=req_id, new_token_ids=[],
                    finish_reason=request.get_finished_reason(),
                    num_cached_tokens=max(request.num_cached_tokens, 0),
                    pooled=pooled_map[req_id],
                    events=self._take_events(request)))
                continue
            if scheduler_output.multi_step > 1:
                # The worker computed KV for one token per fused step.
                scheduled = scheduler_output.multi_step
            generated = sampled_by_req.get(req_id, [])

            # Speculative verification: some scheduled draft tokens may
            # have been rejected (reference: scheduler.py:1012 spec path).
            num_spec = len(
                scheduler_output.scheduled_spec_decode_tokens.get(req_id, []))
            if num_spec > 0:
                num_rejected = num_spec + 1 - len(generated)
                scheduled -= max(num_rejected, 0)
            if not scheduler_output.async_scheduled:
                # Async batches advanced num_computed at grant time
                # (spec decode is config-gated off there, so the
                # rejection adjustment never applies to them).
                request.num_computed_tokens += scheduled
            request.spec_token_ids = spec_by_req.get(req_id, [])

            if not generated:
                continue  # partial prefill chunk; nothing sampled yet

            new_token_ids: list[int] = []
            stop_reason: Optional[int | str] = None
            for token_id in generated:
                request.append_output_token_ids(token_id)
                new_token_ids.append(token_id)
                stopped, stop_reason = self._check_stop(request, token_id)
                if stopped:
                    # Discard any extra accepted spec tokens past the stop.
                    request.spec_token_ids = []
                    break

            if self.structured_manager is not None and new_token_ids:
                # Advance the grammar with exactly the kept tokens (a
                # stop may have trimmed trailing accepted drafts).
                self.structured_manager.advance(req_id, new_token_ids)

            if request.is_finished:
                finished.append(request)
            # Logprobs trimmed to the tokens actually kept after stop
            # handling (a stop may discard trailing accepted spec tokens).
            logprobs = logprobs_by_req.get(req_id)
            if logprobs is not None:
                logprobs = logprobs[:len(new_token_ids)]
            prompt_lps = None
            if (request.sampling_params.prompt_logprobs is not None
                    and not request.prompt_lp_delivered):
                n_prompt = len(request.prompt_token_ids)
                prompt_lps = [None] + [
                    request.prompt_lp_entries.get(i)
                    for i in range(1, n_prompt)
                ]
                request.prompt_lp_delivered = True
                request.prompt_lp_entries.clear()
            outputs.append(
                EngineCoreOutput(
                    req_id=req_id,
                    new_token_ids=new_token_ids,
                    finish_reason=request.get_finished_reason(),
                    stop_reason=stop_reason,
                    num_cached_tokens=max(request.num_cached_tokens, 0),
                    logprobs=logprobs,
                    prompt_logprobs=prompt_lps,
                    events=self._take_events(request),
                ))

        # Commit this step's state snapshots now that its tokens have
        # reconciled: a snapshot enters the lookup index only when the
        # request really committed tokens through its boundary (an
        # async run-ahead that stopped short is discarded). Runs before
        # the finished frees below so a request that finished AT the
        # boundary still commits — its snapshot is the next turn's
        # resume point.
        if self.state_cache is not None and scheduler_output.state_saves:
            for directive in scheduler_output.state_saves:
                self.state_cache.commit_save(
                    directive, self.requests.get(directive.req_id))

        for request in finished:
            self.running.remove(request)
            if request.request_id in self.in_flight_req_ids:
                # A later (speculative) batch is still writing this
                # request's pages: the finish is emitted to the client
                # now, but the free waits until that batch retires (see
                # the pending-retire sweep above). Its discarded sample
                # is dropped there because the request left `running`.
                self._finished_pending_retire[request.request_id] = request
                continue
            params = self._free_request(request)
            if params is not None:
                # Producer handoff coordinates ride on the final output
                # (reference: EngineCoreOutput.kv_transfer_params) so the
                # client/proxy can route the decode-side request.
                for out in outputs:
                    if out.req_id == request.request_id:
                        out.kv_transfer_params = params
                        break
        return outputs

    def _update_kv_transfer_state(
            self, runner_output: ModelRunnerOutput) -> None:
        """Fold the worker's async-transfer notifications back in:
        pulled-in requests rejoin the waiting queue with their external
        span marked computed; pulled-from producer pages are freed
        (reference: scheduler.py update_from_output finished_recving/
        finished_sending handling)."""
        for req_id in (runner_output.finished_recving or ()):
            cancelled = self.cancelled_remote_kv.pop(req_id, None)
            if cancelled is not None:
                self.kv_cache_manager.free(cancelled)
                self.kv_cache_manager.free_block_hashes(cancelled)
                continue
            request = self.waiting_for_remote_kv.pop(req_id, None)
            if request is None:
                continue
            request.num_computed_tokens += \
                request.num_external_computed_tokens
            # Externally-loaded tokens were never computed locally:
            # count them as cached for stats/billing parity.
            request.num_cached_tokens = (
                max(request.num_cached_tokens, 0) +
                request.num_external_computed_tokens)
            request.num_external_computed_tokens = 0
            self._record_event(request, ev.KV_PULL_DONE, None)
            self._requeue_after_hold(request)
        for req_id in (runner_output.failed_recving or ()):
            cancelled = self.cancelled_remote_kv.pop(req_id, None)
            if cancelled is not None:
                self.kv_cache_manager.free(cancelled)
                self.kv_cache_manager.free_block_hashes(cancelled)
                continue
            request = self.waiting_for_remote_kv.pop(req_id, None)
            if request is None:
                continue
            # The span's pages were allocated but never written. Free
            # everything and rejoin the queue as a fresh request
            # (retrying the pull or recomputing locally — see
            # _handle_failed_pull). Freeing matters for ordering —
            # keeping the unwritten span pages while re-running the
            # prefix lookup could append later-cached prefix blocks
            # AFTER them, corrupting the request's page order.
            self.kv_cache_manager.free(request)
            self._handle_failed_pull(request, pull_resolved=True,
                                     reason="worker reported pull failure")
        for req_id in (runner_output.finished_sending or ()):
            request = self.reqs_pending_send.pop(req_id, None)
            if request is not None:
                self.kv_cache_manager.free(request)
                self.kv_cache_manager.free_block_hashes(request)
        # Backstop expiry for deferred frees nobody pulled: the worker's
        # serve registration expires first (send_timeout_s) and reports
        # finished_sending; this 2x backstop only fires if the worker
        # poll itself is wedged, so pages still can't leak forever.
        if self.reqs_pending_send:
            now = time.monotonic()
            timeout = 2 * (self.config.kv_transfer_config
                           .kv_connector_extra_config
                           .get("send_timeout_s", 300.0)
                           if self.config.kv_transfer_config else 300.0)
            for req_id in list(self.reqs_pending_send):
                request = self.reqs_pending_send[req_id]
                deadline = getattr(request, "_send_deadline", None)
                if deadline is None:
                    request._send_deadline = now + float(timeout)
                elif now > deadline:
                    logger.warning(
                        "deferred KV pages for %s expired unpulled after "
                        "%.0fs; freeing", req_id, float(timeout))
                    del self.reqs_pending_send[req_id]
                    self.kv_cache_manager.free(request)
                    self.kv_cache_manager.free_block_hashes(request)
        self._sweep_remote_kv_holds()

    # ------------------------------------------------------------------
    # Remote-KV watchdog (fault-tolerance layer)
    # ------------------------------------------------------------------
    def _sweep_remote_kv_holds(self) -> None:
        """Per-step deadline sweep over WAITING_FOR_REMOTE_KVS: the
        reference scheduler trusts the worker to eventually report every
        pull, so a dropped transfer (or a connector whose admission-time
        producer resolution failed after alloc) parks the request
        forever. The sweep fails such holds through the same requeue
        path as a worker-reported pull failure."""
        # Connector-reported admission failures (e.g. P2P producer
        # resolution failed after alloc): no pull was ever staged, so
        # freeing the pages immediately is unconditionally safe.
        if self.kv_connector is not None and self.waiting_for_remote_kv:
            for req_id in self.kv_connector.take_alloc_failures():
                request = self.waiting_for_remote_kv.pop(req_id, None)
                if request is None:
                    continue
                self.kv_cache_manager.free(request)
                self._handle_failed_pull(
                    request, pull_resolved=True,
                    reason="connector admission failure")
        # Deadline sweep. A swept hold's pull may still be in flight at
        # the worker, so its pages are parked, not freed.
        if self.waiting_for_remote_kv and self.kv_pull_timeout_s > 0:
            now = time.monotonic()
            for req_id in list(self.waiting_for_remote_kv):
                request = self.waiting_for_remote_kv[req_id]
                deadline = request.remote_kv_deadline
                if deadline is None or now <= deadline:
                    continue
                del self.waiting_for_remote_kv[req_id]
                self.watchdog_timeouts += 1
                self._record_event(request, ev.KV_PULL_TIMEOUT,
                                   {"timeout_s": self.kv_pull_timeout_s})
                self._park_timed_out_pages(request)
                self._handle_failed_pull(
                    request, pull_resolved=False,
                    reason=f"no pull completion within "
                           f"{self.kv_pull_timeout_s:.1f}s")
        # Backstop: parked pages whose worker report never arrived are
        # reclaimed once the abandon window expires. Safe against a
        # late-but-live transfer because the sweep/abort issued a
        # cancel_pull: the worker discards (never applies) a completed
        # pull for a cancelled id, so after the cancel lands no write
        # into these pages can happen (see DCNPullConnector.cancel_pull).
        if self.cancelled_remote_kv:
            now = time.monotonic()
            for req_id in list(self.cancelled_remote_kv):
                holder = self.cancelled_remote_kv[req_id]
                expires = getattr(holder, "expires_at", None)
                if expires is not None and now > expires:
                    logger.warning(
                        "parked pages for timed-out pull %s expired "
                        "unreported; reclaiming", req_id)
                    del self.cancelled_remote_kv[req_id]
                    self.kv_cache_manager.free(holder)
                    self.kv_cache_manager.free_block_hashes(holder)

    def _park_timed_out_pages(self, request: Request) -> None:
        """The timed-out hold's pull may still be in flight; a late
        apply writes the pages allocated at admission, so they must stay
        out of the pool until the worker reports. Ownership moves to a
        tombstone registered in cancelled_remote_kv — the same
        late-report protocol aborted holds use."""
        tomb = _PageTombstone(
            request_id=f"{request.request_id}#wd{self.watchdog_timeouts}",
            tknp_rank=request.tknp_rank,
            expires_at=time.monotonic() + self.kv_pull_abandon_timeout_s)
        self.kv_cache_manager.transfer_ownership(request.request_id,
                                                 tomb.request_id)
        if self.kv_connector is not None:
            # Tell the worker to DISCARD (never apply) this pull if it
            # completes later: after the cancel lands, nothing can write
            # the parked pages, so the abandon backstop's free is safe.
            self.kv_connector.cancel_pull(request.request_id)
        self.cancelled_remote_kv[request.request_id] = tomb

    def _handle_failed_pull(self, request: Request, *, pull_resolved: bool,
                            reason: str) -> None:
        """Requeue after a failed/timed-out pull. Degradation order:
        retry the remote pull (bounded, and only when the connector can
        cleanly re-stage one — ``pull_resolved`` says no transfer for
        this id can still be in flight), then local prefill recompute."""
        self.kv_pull_failures += 1
        request.num_computed_tokens = 0
        request.num_external_computed_tokens = 0
        request.remote_kv_deadline = None
        retry = (request.kv_transfer_params is not None
                 and request.num_kv_pull_retries < self.kv_pull_max_retries
                 and self.kv_connector is not None
                 and self.kv_connector.reset_for_retry(request,
                                                       pull_resolved))
        if retry:
            request.num_kv_pull_retries += 1
            self.kv_pull_retries += 1
            self._record_event(request, ev.KV_PULL_RETRY,
                               {"attempt": request.num_kv_pull_retries,
                                "reason": reason}, force=True)
            logger.warning(
                "KV pull for %s failed (%s); retrying pull %d/%d",
                request.request_id, reason, request.num_kv_pull_retries,
                self.kv_pull_max_retries)
        else:
            logger.warning(
                "KV pull for %s failed (%s); degrading to local prefill "
                "recompute", request.request_id, reason)
            request.kv_transfer_params = None
            self._record_event(request, ev.KV_PULL_LOCAL,
                               {"reason": reason}, force=True)
        self._requeue_after_hold(request)

    def _requeue_after_hold(self, request: Request) -> None:
        request.status = RequestStatus.WAITING
        if self.policy == "priority":
            self._insert_by_priority(request)
        else:
            self.waiting.appendleft(request)

    def _check_stop(
            self, request: Request,
            last_token_id: int) -> tuple[bool, Optional[int | str]]:
        sp = request.sampling_params
        if (request.num_tokens >= self.max_model_len
                or request.num_output_tokens >= sp.max_tokens):
            request.status = RequestStatus.FINISHED_LENGTH_CAPPED
            return True, None
        if request.num_output_tokens < sp.min_tokens:
            return False, None
        if last_token_id in sp.all_stop_token_ids:
            request.status = RequestStatus.FINISHED_STOPPED
            if last_token_id != request.eos_token_id or sp.ignore_eos:
                request.stop_reason = last_token_id
            return True, request.stop_reason
        return False, None

    # ------------------------------------------------------------------
    def _kv_cache_telemetry(self) -> dict:
        """Paged-KV introspection (get_stats / /debug/kv_cache /
        SIGUSR1): pool occupancy, tombstone-parked pages, internal
        fragmentation and the windowed prefix-cache hit rate. All reads
        of GIL-atomic containers — safe from the stats RPC while the
        core thread mutates."""
        kv = self.kv_cache_manager.kv_telemetry()
        total = kv.get("total_blocks", 0) or 1
        free = kv.get("free_blocks", 0)
        # Pages parked under watchdog/abort tombstones: allocated, but
        # owned by no live request until the worker reports (or the
        # abandon backstop reclaims them).
        tombstoned = 0
        for holder in list(self.cancelled_remote_kv.values()):
            tombstoned += self._num_blocks_of(holder.request_id) or 0
        # Internal fragmentation: the fraction of request-held page
        # slots not covered by computed tokens (partially-filled tail
        # pages + lookahead). High steady-state fragmentation says the
        # page size is too coarse for the traffic.
        held = kv.get("held_blocks", 0)
        live_tokens = sum(r.num_computed_tokens
                          for r in list(self.requests.values()))
        frag = 0.0
        if held > 0:
            page = self.config.cache_config.block_size
            covered = min(live_tokens / (held * page), 1.0)
            frag = 1.0 - covered
        wq = kv.get("window_queries", 0)
        return {
            "total_blocks": total,
            "free_blocks": free,
            "used_blocks": total - free,
            "held_blocks": held,
            "tombstoned_blocks": tombstoned,
            "cached_blocks": kv.get("cached_blocks", 0),
            "cached_free_blocks": kv.get("cached_free_blocks", 0),
            "fragmentation_frac": round(frag, 6),
            # Raw window tallies ship alongside the ratio so the DP
            # merge can compute the EXACT fleet hit rate from sums
            # instead of diluting it with idle replicas' zeros.
            "window_queries": wq,
            "window_hits": kv.get("window_hits", 0),
            "window_hit_rate": (kv.get("window_hits", 0) / wq
                                if wq else 0.0),
            "preemption_causes": dict(self.preemption_causes),
        }

    def get_stats(self) -> dict[str, float]:
        stats = {
            "num_running_reqs": len(self.running),
            "num_waiting_reqs": len(self.waiting),
            "kv_cache_usage": self.kv_cache_manager.usage,
            "kv_cache": self._kv_cache_telemetry(),
            "num_preemptions": self.num_preemptions,
            "num_async_spec_grants": self.num_async_spec_grants,
            "watchdog_timeouts": self.watchdog_timeouts,
            "kv_pull_retries": self.kv_pull_retries,
            "kv_pull_failures": self.kv_pull_failures,
            "last_step_prefill_tokens": self.last_step_prefill_tokens,
            "last_step_decode_tokens": self.last_step_decode_tokens,
            **self.kv_cache_manager.make_prefix_cache_stats(),
        }
        if self.state_cache is not None:
            stats.update(self.state_cache.stats())
        if self.kv_tier is not None:
            # Nested tier dict ({pages,bytes,demotions,promotions,
            # misses} by tier + promotion histogram + the router's
            # transition feed) — merged per leaf in dp_client, never
            # by the flat numeric-sum loop.
            stats["kv_tier"] = self.kv_tier.stats()
        if self.qos is not None:
            # {tenant: {granted_tokens, kv_blocks, preemptions}} — flat
            # numeric leaves per tenant so the DP aggregation can sum
            # them per label (vdt:tenant_* families).
            stats["tenants"] = self.qos.stats(self._qos_held_by_tenant())
        if self.tknp_size > 1:
            for r, n in enumerate(self.tknp_tokens_per_rank):
                stats[f"tknp_tokens_rank{r}"] = n
                stats[f"tknp_free_blocks_rank{r}"] = \
                    self.kv_cache_manager.free_blocks_on_rank(r)
        return stats

    def _num_blocks_of(self, req_id: str) -> Optional[int]:
        try:
            if req_id in getattr(self.kv_cache_manager,
                                 "req_to_blocks", {}):
                return len(self.kv_cache_manager.get_block_ids(req_id))
            mgrs = getattr(self.kv_cache_manager, "managers", None)
            if mgrs is not None:  # token-parallel: per-rank managers
                for m in mgrs:
                    if req_id in getattr(m, "req_to_blocks", {}):
                        return len(m.get_block_ids(req_id))
        except Exception:  # noqa: BLE001 - debug surface, never raise
            pass
        return None

    def get_debug_state(self) -> dict:
        """Live scheduler introspection for the /debug endpoints and the
        SIGUSR1 dump: every tracked request with its status, progress,
        page footprint and in-flight refcount, plus queue/hold summary.
        Read-only and cheap — safe to call while requests are in
        flight. On the in-proc/background-thread paths this runs on the
        CALLER's thread while the core thread mutates the containers,
        so take C-level (GIL-atomic) list/dict snapshots before any
        Python-level iteration — iterating the live dict/deque raises
        "changed size during iteration" mid-step."""
        waiting = list(self.waiting)
        running = list(self.running)
        reqs = []
        for request in list(self.requests.values()):
            reqs.append({
                "request_id": request.request_id,
                "status": request.status.name,
                "priority": request.priority,
                "tenant": request.tenant,
                "num_prompt_tokens": request.num_prompt_tokens,
                "num_output_tokens": request.num_output_tokens,
                "num_computed_tokens": request.num_computed_tokens,
                "num_cached_tokens": max(request.num_cached_tokens, 0),
                "num_preemptions": request.num_preemptions,
                "num_kv_pull_retries": request.num_kv_pull_retries,
                "inflight_refcount":
                    self.in_flight_req_ids.get(request.request_id, 0),
                "kv_blocks": self._num_blocks_of(request.request_id),
                "tknp_rank": request.tknp_rank,
            })
        return {
            "requests": reqs,
            "qos": (self.qos.debug() if self.qos is not None else None),
            "num_waiting": len(waiting),
            "num_running": len(running),
            "waiting_req_ids": [r.request_id for r in waiting],
            "running_req_ids": [r.request_id for r in running],
            "waiting_for_remote_kvs":
                list(self.waiting_for_remote_kv),
            "reqs_pending_send": list(self.reqs_pending_send),
            "cancelled_remote_kv": list(self.cancelled_remote_kv),
            "finished_pending_retire":
                list(self._finished_pending_retire),
            "deferred_finishes": list(self._deferred_finishes),
            "kv_cache": self._kv_cache_telemetry(),
            "kv_cache_usage": self.kv_cache_manager.usage,
            "num_preemptions": self.num_preemptions,
            "last_step_prefill_tokens": self.last_step_prefill_tokens,
            "last_step_decode_tokens": self.last_step_decode_tokens,
        }
