"""Per-tenant QoS state for the scheduler (``VDT_QOS``).

Execution-time fairness — the third rung of the multi-tenant ladder
after fair *placement* (the routing tier's per-class weighted shedding)
and fair *admission* (the API gate's watermarks): once requests are in
the scheduler, a single tenant with long prompts and greedy
``max_tokens`` could previously monopolize the token budget, the KV
page pool and the batch slots, moving every other tenant's p99 TPOT.

Three mechanisms, all scoped to this module so the scheduler's hooks
stay one-line ``if self.qos is not None`` guards:

* **Weighted fair queueing** via deficit round robin on *granted
  tokens*: each scheduler step replenishes every active tenant's
  deficit counter in proportion to its weight (``VDT_QOS_WEIGHTS``,
  default equal; the routing tier's interactive/best_effort classes map
  through the ``interactive``/``best_effort`` spec keys), every granted
  token is charged against the counter, and chunked-prefill grants clip
  to the remaining deficit while another tenant competes for prefill
  bandwidth. Decode grants are never clipped (stalling a running decode
  moves everyone's TPOT) — instead each prefill grant leaves headroom
  for the other tenants' running decodes (``_decode_need``), so a flood
  tenant's prompt chunks can no longer starve an interactive tenant's
  decode tokens. Work-conserving: with no competitor the clips are
  waived and a sole tenant still gets the whole budget; unused deficit
  carries over (bounded by ``DEFICIT_CARRY_STEPS`` step budgets).

* **Soft KV page quotas** (``VDT_QOS_KV_QUOTA_FRAC`` of the pool per
  tenant): free until the pool pressures, then (a) a tenant over its
  quota waits at admission while an under-quota tenant has waiting
  work, and (b) when pages run out, preemption evicts the
  most-over-quota tenant's lowest-priority request first (preemption
  cause ``quota``, riding the existing preemption machinery — SSM state
  parks, tombstoned pages and cause attribution all apply). A
  per-tenant cooldown (``QUOTA_COOLDOWN_STEPS``) is the hysteresis: a
  tenant oscillating around its quota falls back to ordinary capacity
  preemption between quota evictions instead of livelocking the
  scheduler in evict/resume cycles (drill: fault point
  ``sched.quota_thrash``).

* **Per-tenant accounting** for the ``vdt:tenant_*`` metric families.
  Label cardinality is bounded by ``VDT_QOS_MAX_TRACKED_TENANTS``:
  tenants beyond the cap hash into a fixed set of overflow buckets
  (``~<n>``), tenantless requests share the ``_anon`` bucket.

``VDT_QOS=0`` (the default) constructs no state at all — the scheduler
keeps its pre-QoS behavior byte-identical.
"""

import zlib
from typing import Iterable, Optional

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

# Tenantless requests share one deficit/quota bucket.
DEFAULT_KEY = "_anon"
# Reserved tenant of correctness-sentinel canary probes
# (correctness_plane.py). Canaries ride the real serving path but are
# QoS-exempt end to end: never bucketed, never charged, never clipped,
# never quota-victimized, dropped from the vdt:tenant_* families — a
# probe must measure the fleet, not perturb (or be perturbed by) any
# tenant's fairness accounting.
CANARY_TENANT = "_canary"
# Tenants past VDT_QOS_MAX_TRACKED_TENANTS hash into this many shared
# overflow buckets, bounding metric-label cardinality at cap + this.
OVERFLOW_BUCKETS = 8
# Deficit bounds, in step budgets: unused credit carries over up to
# this many steps' worth; work-conserving over-grants may run the
# counter the same amount into debt before it saturates.
DEFICIT_CARRY_STEPS = 4
# Pool usage at/above which the soft quota gates *admission* of
# over-quota tenants (eviction-side quota enforcement needs no
# threshold — it only ever runs on an allocation failure).
QUOTA_PRESSURE = 0.9
# Quota-preemption hysteresis: a tenant is not quota-victimized again
# within this many scheduler steps of its last quota eviction — the
# gap falls back to ordinary capacity preemption, so an oscillating
# tenant cannot livelock the scheduler in evict/resume cycles.
QUOTA_COOLDOWN_STEPS = 8


def parse_weights(spec: str) -> dict[str, float]:
    """``VDT_QOS_WEIGHTS`` parser: comma list of ``name:weight``.
    ``name`` is a tenant id, or one of the class keys ``interactive`` /
    ``best_effort`` (PR 7's priority classes) / ``default``. Malformed
    or non-positive entries are dropped with a log, never raised — a
    bad operator spec must not take the scheduler down."""
    out: dict[str, float] = {}
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        name, sep, raw = entry.rpartition(":")
        try:
            weight = float(raw) if sep else float("nan")
        except ValueError:
            weight = float("nan")
        if not sep or not name.strip() or not weight > 0:
            logger.warning("ignoring malformed VDT_QOS_WEIGHTS entry %r",
                           entry)
            continue
        out[name.strip()] = weight
    return out


def bucket_tenant(tenant: Optional[str], tracked: set,
                  max_tracked: int) -> str:
    """Bounded-cardinality tenant key: the tenant id itself while the
    tracked set has room (first come, first tracked), a stable hash
    bucket ``~<n>`` past the cap, ``_anon`` for tenantless requests.
    Shared by the scheduler's QosState and the front end's per-tenant
    goodput accounting so both label spaces stay bounded and agree."""
    if not tenant:
        return DEFAULT_KEY
    if tenant == CANARY_TENANT:
        return CANARY_TENANT  # reserved; never counts against the cap
    if tenant in tracked:
        return tenant
    if len(tracked) < max_tracked:
        tracked.add(tenant)
        return tenant
    return "~%d" % (zlib.crc32(tenant.encode("utf-8", "replace"))
                    % OVERFLOW_BUCKETS)


class QosState:
    """Per-tenant DRR deficits, soft KV quotas and accounting. One
    instance per scheduler; every method is called with the scheduler's
    own thread discipline (the stats RPC reads GIL-atomic dicts)."""

    def __init__(self, token_budget: int, total_blocks: int, *,
                 weights_spec: Optional[str] = None,
                 quota_frac: Optional[float] = None,
                 max_tracked: Optional[int] = None) -> None:
        from vllm_distributed_tpu import envs
        if weights_spec is None:
            weights_spec = envs.VDT_QOS_WEIGHTS
        if quota_frac is None:
            quota_frac = envs.VDT_QOS_KV_QUOTA_FRAC
        if max_tracked is None:
            max_tracked = envs.VDT_QOS_MAX_TRACKED_TENANTS
        self.token_budget = max(1, int(token_budget))
        self.total_blocks = int(total_blocks)
        self.weights = parse_weights(weights_spec)
        # Soft per-tenant page quota; 0 disables quota enforcement
        # (DRR still applies). frac == 1 is a vacuous quota and is
        # treated as disabled too.
        self.quota_blocks = (int(quota_frac * total_blocks)
                             if 0 < quota_frac < 1 else 0)
        self.max_tracked = max(1, int(max_tracked))

        self._tracked: set[str] = set()
        self._bucket_weight: dict[str, float] = {}
        self.deficit: dict[str, float] = {}
        # Cumulative accounting (vdt:tenant_* families).
        self.granted_tokens: dict[str, int] = {}
        self.preemptions: dict[str, int] = {}
        # Per-step working state (begin_step).
        self._competing: set[str] = set()
        self._decode_need: dict[str, int] = {}
        self.held: dict[str, int] = {}
        # key -> num_scheduled_steps of its last quota eviction.
        self._last_quota_preempt: dict[str, int] = {}

    # ------------------------------------------------------------------
    def weight_of(self, key: str, priority: int) -> float:
        """Explicit tenant entry first, then the request's priority
        class (interactive <= 0 < best_effort), then ``default``."""
        w = self.weights.get(key)
        if w is None:
            cls = "best_effort" if priority > 0 else "interactive"
            w = self.weights.get(cls, self.weights.get("default", 1.0))
        return w

    def key_of(self, request) -> str:
        key = bucket_tenant(request.tenant, self._tracked,
                            self.max_tracked)
        if key == CANARY_TENANT:
            return key  # QoS-exempt: no weight memo, no DRR state
        # Memo the bucket's weight from the traffic actually seen (a
        # bucket mixing classes takes the latest request's class).
        self._bucket_weight[key] = self.weight_of(key, request.priority)
        return key

    # ------------------------------------------------------------------
    # Per-step DRR bookkeeping
    # ------------------------------------------------------------------
    def begin_step(self, waiting: Iterable, running: Iterable,
                   held_by_tenant: Optional[dict[str, int]]) -> None:
        """Replenish deficits for every tenant with live work, snapshot
        who competes for prefill bandwidth and how many decode tokens
        each tenant's running requests will want this step."""
        active: set[str] = set()
        competing: set[str] = set()
        decode_need: dict[str, int] = {}
        for r in waiting:
            k = self.key_of(r)
            if k == CANARY_TENANT:
                continue  # canaries neither earn nor contest deficit
            active.add(k)
            competing.add(k)
        for r in running:
            k = self.key_of(r)
            if k == CANARY_TENANT:
                continue
            active.add(k)
            if r.num_computed_tokens < r.num_prompt_tokens:
                competing.add(k)
            else:
                decode_need[k] = decode_need.get(k, 0) + 1
        self._competing = competing
        self._decode_need = decode_need
        self.held = held_by_tenant or {}
        if not active:
            return
        total_w = sum(self._bucket_weight.get(k, 1.0) for k in active)
        cap = DEFICIT_CARRY_STEPS * self.token_budget
        for k in active:
            quantum = (self.token_budget
                       * self._bucket_weight.get(k, 1.0) / total_w)
            self.deficit[k] = min(self.deficit.get(k, 0.0) + quantum, cap)

    def charge(self, key: str, tokens: int, decode: bool = False) -> None:
        """Every granted token draws down the tenant's deficit (floored
        so work-conserving over-grants can't build unbounded debt)."""
        if key == CANARY_TENANT:
            return
        self.granted_tokens[key] = (self.granted_tokens.get(key, 0)
                                    + int(tokens))
        floor = -DEFICIT_CARRY_STEPS * self.token_budget
        self.deficit[key] = max(self.deficit.get(key, 0.0) - tokens, floor)
        if decode and self._decode_need.get(key, 0) > 0:
            # This tenant's decode headroom was consumed; later prefill
            # grants this step no longer reserve for it.
            self._decode_need[key] -= 1

    def prefill_allowance(self, key: str, want: int,
                          budget_left: int) -> int:
        """Clip for a RUNNING chunked-prefill grant. Two caps, both
        waived when nobody needs the headroom: the DRR deficit while
        another tenant with credit competes for prefill bandwidth, and
        a reservation of one decode token per OTHER tenant's running
        decode request still unserved this step (positional budget
        exhaustion must not starve decodes sitting later in the
        running list)."""
        if key == CANARY_TENANT:
            return want  # admission-exempt: a probe is never clipped
        allowed = want
        if any(k != key and self.deficit.get(k, 0.0) > 0.0
               for k in self._competing):
            allowed = min(allowed, max(0, int(self.deficit.get(key, 0.0))))
        reserve = sum(n for k, n in self._decode_need.items() if k != key)
        if reserve > 0:
            allowed = min(allowed, max(0, budget_left - reserve))
        return allowed

    def admission_allowance(self, key: str, want: int) -> int:
        """Clip for a WAITING-loop (first) chunked-prefill grant. The
        caller picked the max-deficit tenant, so deficit <= 0 means no
        waiting tenant holds credit — grant in full (work conserving);
        otherwise clip to the deficit, never below one token (the
        selected tenant must make progress)."""
        if key == CANARY_TENANT:
            return want
        d = self.deficit.get(key, 0.0)
        if d <= 0:
            return want
        return max(1, min(want, int(d)))

    def pick_waiting_tenant(self, keys_in_order: list[str],
                            usage: float) -> str:
        """The waiting tenant to admit next: largest deficit wins, ties
        go to the earliest queue position. Under pool pressure
        (``usage >= QUOTA_PRESSURE``) tenants over their soft KV quota
        are passed over while an under-quota tenant is waiting. A
        waiting canary probe always admits first: it is tiny, rare
        (one per VDT_CANARY_INTERVAL_S per replica) and its whole point
        is to measure the serving path, not to queue behind deficit
        arithmetic it is exempt from."""
        if CANARY_TENANT in keys_in_order:
            return CANARY_TENANT
        candidates = keys_in_order
        if self.quota_blocks > 0 and usage >= QUOTA_PRESSURE:
            under = [k for k in keys_in_order
                     if self.held.get(k, 0) <= self.quota_blocks]
            if under:
                candidates = under
        best = candidates[0]
        for k in candidates[1:]:
            if self.deficit.get(k, 0.0) > self.deficit.get(best, 0.0):
                best = k
        return best

    # ------------------------------------------------------------------
    # Quota-aware preemption
    # ------------------------------------------------------------------
    def quota_victim(self, candidates: list, key_of, step: int):
        """Among the preemption candidates, the lowest-priority request
        of the most-over-quota tenant — or None, handing victim choice
        back to the ordinary capacity policy. Only ever called on an
        allocation failure, so "soft until pressure" needs no extra
        threshold here. The ``sched.quota_thrash`` fault point forces
        an effective quota of zero (every page-holding tenant is
        over), driving a preemption storm the cooldown hysteresis must
        bound."""
        from vllm_distributed_tpu.utils import fault_injection
        quota = self.quota_blocks
        if fault_injection.should_fire("sched.quota_thrash"):
            quota = 0
        elif quota <= 0:
            return None
        groups: dict[str, list] = {}
        for r in candidates:
            groups.setdefault(key_of(r), []).append(r)
        best_key, best_over = None, 0
        for k in groups:
            over = self.held.get(k, 0) - quota
            if over <= 0:
                continue
            if step - self._last_quota_preempt.get(k, -(1 << 30)) \
                    < QUOTA_COOLDOWN_STEPS:
                continue  # hysteresis: recently quota-evicted
            if over > best_over:
                best_key, best_over = k, over
        if best_key is None:
            return None
        self._last_quota_preempt[best_key] = step
        return max(groups[best_key],
                   key=lambda r: (r.priority, r.arrival_time))

    def note_preemption(self, key: str) -> None:
        if key == CANARY_TENANT:
            return
        self.preemptions[key] = self.preemptions.get(key, 0) + 1

    # ------------------------------------------------------------------
    # Accounting surfaces
    # ------------------------------------------------------------------
    def stats(self, held_by_tenant: dict[str, int]) -> dict[str, dict]:
        """The per-tenant entry of scheduler.get_stats(): flat numeric
        leaves per tenant so the DP merge can sum them per label."""
        keys = (set(self.granted_tokens) | set(self.preemptions)
                | set(held_by_tenant))
        keys.discard(CANARY_TENANT)  # probes are not tenant traffic
        return {
            k: {
                "granted_tokens": int(self.granted_tokens.get(k, 0)),
                "kv_blocks": int(held_by_tenant.get(k, 0)),
                "preemptions": int(self.preemptions.get(k, 0)),
            }
            for k in keys
        }

    def debug(self) -> dict:
        """Live introspection for /debug/requests and the SIGUSR1 dump
        (GIL-atomic snapshots; safe from the stats thread)."""
        return {
            "quota_blocks": self.quota_blocks,
            "deficit": {k: round(v, 1) for k, v in dict(
                self.deficit).items()},
            "weights": dict(self._bucket_weight),
            "kv_blocks": dict(self.held),
        }


def maybe_qos_state(token_budget: int,
                    total_blocks: int) -> Optional[QosState]:
    """The scheduler's one read of ``VDT_QOS`` (at construction — the
    envs registry re-reads os.environ per access). None = QoS off and
    every scheduler hook short-circuits."""
    from vllm_distributed_tpu import envs
    if not envs.VDT_QOS:
        return None
    state = QosState(token_budget, total_blocks)
    logger.info(
        "per-tenant QoS on: DRR over %d-token steps, quota %d/%d pages"
        "%s, tracking <= %d tenants", state.token_budget,
        state.quota_blocks, state.total_blocks,
        " (quota off)" if state.quota_blocks == 0 else "",
        state.max_tracked)
    return state
