"""Free-list + prefix-cache index over KV pages.

Reference: vllm/v1/core/block_pool.py (``BlockPool``: get_new_blocks:202,
cache_full_blocks:96, LRU eviction via a doubly-linked free queue). The
logic is device-agnostic control plane and ports conceptually: a pool of
page ids, a ref-counted LRU free list, and a hash->page index that lets new
requests reuse pages holding an identical prefix.
"""

from typing import Optional

from vllm_distributed_tpu.core.kv_cache_utils import BlockHash
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


class KVCacheBlock:
    """One KV page's bookkeeping record."""

    __slots__ = ("block_id", "ref_cnt", "block_hash", "prev_free_block",
                 "next_free_block")

    def __init__(self, block_id: int) -> None:
        self.block_id = block_id
        self.ref_cnt = 0
        self.block_hash: Optional[BlockHash] = None
        # Doubly-linked free-list pointers (None when not free).
        self.prev_free_block: Optional[KVCacheBlock] = None
        self.next_free_block: Optional[KVCacheBlock] = None

    def __repr__(self) -> str:
        return (f"KVCacheBlock(id={self.block_id}, ref={self.ref_cnt}, "
                f"hashed={self.block_hash is not None})")


class FreeKVCacheBlockQueue:
    """LRU doubly-linked list of free blocks.

    popleft() evicts the least-recently-freed block; blocks reused via a
    prefix-cache hit are unlinked from the middle in O(1).
    Reference: v1/core/kv_cache_utils.py FreeKVCacheBlockQueue.
    """

    def __init__(self, blocks: list[KVCacheBlock]) -> None:
        self.num_free_blocks = 0
        # Sentinel head/tail simplify edge cases.
        self._head = KVCacheBlock(-1)
        self._tail = KVCacheBlock(-2)
        self._head.next_free_block = self._tail
        self._tail.prev_free_block = self._head
        for block in blocks:
            self.append(block)

    def popleft(self) -> KVCacheBlock:
        block = self._head.next_free_block
        assert block is not None and block is not self._tail, \
            "no free blocks"
        self.remove(block)
        return block

    def remove(self, block: KVCacheBlock) -> None:
        prev, nxt = block.prev_free_block, block.next_free_block
        assert prev is not None and nxt is not None, \
            f"{block} is not in the free queue"
        prev.next_free_block = nxt
        nxt.prev_free_block = prev
        block.prev_free_block = None
        block.next_free_block = None
        self.num_free_blocks -= 1

    def append(self, block: KVCacheBlock) -> None:
        last = self._tail.prev_free_block
        assert last is not None
        last.next_free_block = block
        block.prev_free_block = last
        block.next_free_block = self._tail
        self._tail.prev_free_block = block
        self.num_free_blocks += 1

    def get_all_free_blocks(self) -> list[KVCacheBlock]:
        out = []
        node = self._head.next_free_block
        while node is not None and node is not self._tail:
            out.append(node)
            node = node.next_free_block
        return out


class BlockPool:
    """Pool of KV pages shared by all requests.

    Reference semantics (v1/core/block_pool.py):
      - ref-counted pages; pages with ref 0 sit in an LRU free queue but
        keep their hash so they remain prefix-cache hits until evicted;
      - ``cache_full_blocks`` assigns chained hashes to newly-filled pages;
      - eviction (popping a hashed free page) removes it from the index.
    """

    def __init__(self, num_blocks: int, enable_caching: bool = True,
                 id_offset: int = 0) -> None:
        """``id_offset`` shifts this pool's page ids: token-parallel
        KV management partitions the global page array into per-rank
        pools whose ids index directly into the rank's slice (TPU
        analogue of the fork's per-rank KV allocation,
        vllm/v1/core/sched/scheduler.py:55 TokenParallelScheduler)."""
        assert num_blocks > 0
        self.num_blocks = num_blocks
        self.enable_caching = enable_caching
        self.blocks = [KVCacheBlock(id_offset + i)
                       for i in range(num_blocks)]
        self.free_block_queue = FreeKVCacheBlockQueue(self.blocks)
        # hash -> block holding that content (at most one per hash).
        self.cached_block_hash_to_block: dict[bytes, KVCacheBlock] = {}
        # When enabled, block cache mutations append events here; the
        # scheduler drains them each step into the KV event publisher
        # (reference: block_pool's kv_cache_events plumbing).
        self.pending_events: Optional[list] = None
        # Hierarchical KV tiering (core/kv_tier.py): called with
        # (block_id, block_hash) when a hashed free page is popped for
        # reuse, BEFORE the hash is dropped — the tier queues the
        # page's content for a pre-forward demotion gather instead of
        # letting the prefix vanish. None = pages evict silently
        # (pre-tiering behavior).
        self.on_evict = None

    def enable_events(self) -> None:
        self.pending_events = []

    def take_events(self) -> list:
        events, self.pending_events = self.pending_events or [], []
        return events

    def get_num_free_blocks(self) -> int:
        return self.free_block_queue.num_free_blocks

    @property
    def usage(self) -> float:
        return 1.0 - self.get_num_free_blocks() / self.num_blocks

    def get_stats(self) -> dict[str, int]:
        """Pool-occupancy telemetry for the stats poll / debug dump.
        ``cached_free_blocks`` are ref-0 pages still advertising their
        hash — reclaimable prefix cache, the pool's soft headroom.
        O(cached index) per call; runs at scrape cadence, never on the
        allocation path. The stats RPC runs on the CALLER's thread
        while the core thread mutates the index — take a GIL-atomic
        list() snapshot before iterating or a concurrent insert raises
        "dictionary changed size during iteration" mid-scrape."""
        cached_blocks = list(self.cached_block_hash_to_block.values())
        cached = len(cached_blocks)
        cached_free = sum(1 for b in cached_blocks if b.ref_cnt == 0)
        return {
            "total_blocks": self.num_blocks,
            "free_blocks": self.get_num_free_blocks(),
            "cached_blocks": cached,
            "cached_free_blocks": cached_free,
        }

    # ------------------------------------------------------------------
    def get_cached_block(self, block_hash: BlockHash) -> Optional[KVCacheBlock]:
        return self.cached_block_hash_to_block.get(block_hash.hash_value)

    def touch(self, blocks: list[KVCacheBlock]) -> None:
        """Take a reference on blocks (removing ref-0 ones from the free
        queue) — used when a new request reuses cached blocks."""
        for block in blocks:
            if block.ref_cnt == 0:
                self.free_block_queue.remove(block)
            block.ref_cnt += 1

    def get_new_blocks(self, num_blocks: int) -> list[KVCacheBlock]:
        """Pop ``num_blocks`` from the free queue (caller must have checked
        availability). Evicts any prefix-cache entries the popped blocks
        still carry."""
        if num_blocks > self.get_num_free_blocks():
            raise ValueError("cannot allocate more blocks than are free")
        out: list[KVCacheBlock] = []
        for _ in range(num_blocks):
            block = self.free_block_queue.popleft()
            self._maybe_evict_cached_block(block)
            block.ref_cnt = 1
            out.append(block)
        return out

    def _maybe_evict_cached_block(self, block: KVCacheBlock) -> None:
        if block.block_hash is not None:
            if self.on_evict is not None:
                # Demote instead of discard: the tier snapshots this
                # page's content pre-forward (the popped page is handed
                # to its new owner this very step, so the callback must
                # fire at the pop, not later).
                self.on_evict(block.block_id, block.block_hash)
            self.cached_block_hash_to_block.pop(
                block.block_hash.hash_value, None)
            if self.pending_events is not None:
                from vllm_distributed_tpu.distributed.kv_events import \
                    BlockRemoved
                self.pending_events.append(BlockRemoved(
                    block_hashes=[block.block_hash.hash_value]))
            block.block_hash = None

    def cache_full_blocks(
        self,
        blocks: list[KVCacheBlock],
        block_hashes: list[BlockHash],
        num_cached_blocks: int,
        num_full_blocks: int,
    ) -> None:
        """Register hashes for blocks [num_cached_blocks, num_full_blocks)
        that have just become full."""
        if not self.enable_caching:
            return
        assert num_full_blocks <= len(blocks)
        assert num_full_blocks <= len(block_hashes)
        for i in range(num_cached_blocks, num_full_blocks):
            block = blocks[i]
            block_hash = block_hashes[i]
            if block is None:
                # Sliding-window-freed slot (kv_cache_manager nulls the
                # dead prefix); nothing to register.
                continue
            if block.block_hash is not None:
                continue  # already cached (shared hit)
            existing = self.cached_block_hash_to_block.get(
                block_hash.hash_value)
            if existing is not None and existing is not block:
                # Another block already holds this content; keep the index
                # pointing at the existing one.
                continue
            block.block_hash = block_hash
            self.cached_block_hash_to_block[block_hash.hash_value] = block
            if self.pending_events is not None:
                from vllm_distributed_tpu.distributed.kv_events import \
                    BlockStored
                parent = (block_hashes[i - 1].hash_value
                          if i > 0 else None)
                self.pending_events.append(BlockStored(
                    block_hashes=[block_hash.hash_value],
                    parent_block_hash=parent,
                    token_ids=list(block_hash.token_ids),
                    block_size=len(block_hash.token_ids)))

    def free_blocks(self, ordered_blocks: list[KVCacheBlock]) -> None:
        """Drop one reference on each block; ref-0 blocks enter the free
        queue in the given order (callers pass tail-first so that the
        *front* of a sequence — the most reusable prefix — is evicted
        last)."""
        for block in ordered_blocks:
            block.ref_cnt -= 1
            assert block.ref_cnt >= 0, f"double free of {block}"
            if block.ref_cnt == 0:
                self.free_block_queue.append(block)

    def reset_prefix_cache(self) -> bool:
        """Drop all cached hashes (only valid when no request holds refs).
        Reference: block_pool.py reset_prefix_cache."""
        if self.get_num_free_blocks() != self.num_blocks:
            logger.warning("reset_prefix_cache failed: blocks are in use")
            return False
        for block in self.blocks:
            block.block_hash = None
        self.cached_block_hash_to_block.clear()
        if self.pending_events is not None:
            from vllm_distributed_tpu.distributed.kv_events import \
                AllBlocksCleared
            self.pending_events.append(AllBlocksCleared())
        return True
