"""Hierarchical KV memory: host-RAM and disk spill tiers behind the
device page pool.

HBM is the admission ceiling everywhere in the stack (ROADMAP item 3):
TPLA bought ~TP× latent-page capacity and then stopped, and an evicted
prefix page was simply discarded — the prefix cache was a per-replica
LRU caching minutes, not hours, of session history. This module gives
``BlockPool`` a spill hierarchy behind the device pool:

* **T1 — pinned host RAM** (budget ``VDT_KV_TIER_HOST_MB``): a prefix
  page evicted by ``BlockPool._maybe_evict_cached_block`` demotes its
  CONTENT to a bounded host pool instead of vanishing. The device->host
  copy rides ``page_io.gather_pages_start`` pre-forward (program order
  guarantees the pre-overwrite bytes) and completes off the hot path,
  overlapping the step's forward.
* **T2 — disk** (``VDT_KV_TIER_DIR``, budget ``VDT_KV_TIER_DISK_MB``):
  host-pool eviction demotes to one page file per page, reusing the
  shared_storage connector's page-file format + CRC + quantized-codec
  machinery (``distributed/kv_transfer/shared_storage.py`` /
  ``quant.py``) under the same content-addressed ``BlockHash`` keys —
  disagg handoffs, shared-storage stores and tier restores share ONE
  namespace, and a respawned engine warm-starts from whatever spill
  files survive.

Promotion is the reverse path: ``KVCacheManager.get_computed_blocks``
extends a WAITING request's device-cached prefix with tier-resident
pages; the scheduler allocates fresh device pages for the span and the
runner scatters the staged content back (batched host->device via the
existing ``page_io`` device leg) BEFORE the forward. A corrupt or
missing spill file (fault point ``kv_tier.spill_corrupt``) is detected
at the scheduler-side lookup — a clean miss that recomputes, never
wrong tokens.

Everything here is content-addressed: equal ``BlockHash`` chains imply
equal token prefixes, so a demoted page's bytes never go stale, and a
promotion back to the device re-registers the same hash in the prefix
index. The manager is pure host-side control+data plane (numpy only,
no jax): the scheduler owns the bookkeeping and ships
``kv_demotes``/``kv_promotes`` directives on ``SchedulerOutput``; the
runner executes the device legs.

``VDT_KV_TIERING=0`` (the default) constructs nothing — every hook is
a short-circuited None check and behavior is byte-identical.
"""

import os
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.metrics.stats import Histogram
from vllm_distributed_tpu.utils import fault_injection

logger = init_logger(__name__)

# Router-facing tier codes (engine/router.py residency tagging).
TIER_DEVICE = 0
TIER_HOST = 1
TIER_DISK = 2
TIER_GONE = -1

# Promotion-latency buckets: host promotions are sub-millisecond page
# scatters, disk promotions pay a file read + decode first.
_PROMOTE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5)


def maybe_kv_tier(config, kv_connector=None) -> "Optional[KVTierManager]":
    """Construct the tier manager when ``VDT_KV_TIERING=1`` and the
    deployment shape supports it; None otherwise (every scheduler/
    runner hook is then a short-circuited None check — byte-identical
    revert). The tier needs one scheduler driving one flat runner
    (no token-parallel page partitions whose ids live in per-rank
    pools, no PP stage split runner-side, single host) and no KV
    connector (a connector's delay_caching/deferred-free lifecycle
    would race the tier's eviction hook over the same pages), plus
    prefix caching on (no hashes, nothing to key spills by)."""
    from vllm_distributed_tpu import envs
    if not envs.VDT_KV_TIERING:
        return None
    pc = config.parallel_config
    if (pc.token_parallel_size > 1 or pc.pipeline_parallel_size > 1
            or pc.num_hosts > 1 or kv_connector is not None
            or not config.cache_config.enable_prefix_caching):
        logger.info("KV tiering requested but unsupported for this "
                    "deployment shape (tknp/pp/multi-host/connector/"
                    "caching-off); running untiered")
        return None
    page_tokens = 0
    try:
        page_tokens = int(config.cache_config.block_size)
    except (TypeError, ValueError):
        pass
    mgr = KVTierManager(
        host_budget_bytes=int(envs.VDT_KV_TIER_HOST_MB * 2**20),
        disk_dir=envs.VDT_KV_TIER_DIR,
        disk_budget_bytes=int(envs.VDT_KV_TIER_DISK_MB * 2**20),
        demote_pages_per_step=envs.VDT_KV_TIER_DEMOTE_PAGES)
    logger.info(
        "KV tiering on: host budget %g MiB%s (page size %d tokens)",
        envs.VDT_KV_TIER_HOST_MB,
        f", disk tier {mgr.disk_dir} ({envs.VDT_KV_TIER_DISK_MB:g} MiB)"
        if mgr.disk_dir else ", disk tier off", page_tokens)
    return mgr


@dataclass
class DemoteDirective:
    """One step's batched demotion: the runner gathers ``page_ids``
    (device pages just evicted+reassigned this step — their pre-forward
    contents are the evicted prefixes) and inserts each page's wire
    slice into the host tier under its content hash."""

    page_ids: list[int]
    keys: list[bytes]


@dataclass
class PromoteDirective:
    """One admitted request's tier restore: scatter ``arrays`` (wire-
    layout per-page (k, v) pairs, staged by the scheduler-side lookup
    so a host-pool eviction between admission and dispatch cannot
    invalidate the hit) into the freshly allocated ``page_ids`` BEFORE
    the forward. ``tiers`` records each page's source ("host"/"disk")
    for the promotion counters."""

    req_id: str
    page_ids: list[int]
    keys: list[bytes]
    tiers: list[str]
    arrays: list  # [(k_np, v_np)] aligned with page_ids


@dataclass
class _HostPage:
    k: np.ndarray
    v: np.ndarray

    @property
    def nbytes(self) -> int:
        return self.k.nbytes + self.v.nbytes


@dataclass
class KVTierManager:
    """Bookkeeping + host data plane for the two spill tiers. Lives on
    the scheduler; the runner holds the same (in-proc) reference for
    the device legs. All mutation happens on the engine-core thread
    (schedule() and dispatch run on one thread); ``stats()`` runs on
    the stats-RPC caller's thread and therefore snapshots containers
    GIL-atomically before iterating."""

    host_budget_bytes: int = 512 * 2**20
    disk_dir: str = ""
    disk_budget_bytes: int = 4096 * 2**20
    demote_pages_per_step: int = 64

    # T1: content hash -> host page, LRU order (oldest first).
    _host: "OrderedDict[bytes, _HostPage]" = field(
        default_factory=OrderedDict)
    _host_bytes: int = 0
    # T2 index: content hash -> file bytes, insertion order (oldest
    # first — the budget sweep's eviction order).
    _disk: "OrderedDict[bytes, int]" = field(default_factory=OrderedDict)
    _disk_bytes: int = 0
    # Wire-layout per-page shapes ((k, v), page axis removed), wired by
    # the engine core from the runner at init. Disk files (possibly
    # written by another engine sharing the directory) are validated
    # against these before a hit is admitted.
    wire_shapes: Optional[tuple] = None
    # Evictions observed this schedule() (BlockPool on_evict hook),
    # drained into one DemoteDirective per step.
    _pending_demotes: list = field(default_factory=list)
    # req_id -> [(key, tier, k, v)] staged tier hits (get_computed_
    # blocks lookup; consumed at admission, dropped on finish).
    _pending_hits: dict = field(default_factory=dict)
    # Tier transitions for the router's residency index ((hex, code)),
    # drained via get_stats -> router.observe_stats. Bounded: overflow
    # drops oldest — the router's hints degrade, nothing breaks.
    _transitions: deque = field(
        default_factory=lambda: deque(maxlen=1024))

    # Counters (stats()).
    demotions: dict = field(
        default_factory=lambda: {"host": 0, "disk": 0})
    demotion_bytes: dict = field(
        default_factory=lambda: {"host": 0, "disk": 0})
    promotions: dict = field(
        default_factory=lambda: {"host": 0, "disk": 0})
    misses: dict = field(default_factory=lambda: {"host": 0, "disk": 0})
    demotes_dropped: int = 0
    # Pages restored from a surviving (or shared) T2 namespace at
    # construction — the elastic fleet's warm-start signal: a scaled-out
    # or role-converted replica does not start cold (engine/fleet.py).
    warm_start_pages: int = 0
    promotion_hist: Histogram = field(
        default_factory=lambda: Histogram(_PROMOTE_BUCKETS))

    def __post_init__(self) -> None:
        if self.disk_dir:
            os.makedirs(self.disk_dir, exist_ok=True)
            self._scan_disk()

    # ------------------------------------------------------------------
    # T2 file namespace (the shared_storage page-file namespace: one
    # <hash hex>.npz per page, content-addressed).
    # ------------------------------------------------------------------
    def _file(self, key: bytes) -> str:
        return os.path.join(self.disk_dir, f"{key.hex()}.npz")

    def _scan_disk(self) -> None:
        """Warm-start the T2 index from surviving spill files (mtime
        order, so the budget sweep still evicts oldest-first). Files
        from a previous incarnation — or another replica sharing the
        directory — ARE the fleet-scale session memory; content
        addressing makes them safe to serve once their shape checks."""
        entries = []
        for name in os.listdir(self.disk_dir):
            if not name.endswith(".npz") or name.startswith("ssm_"):
                continue
            try:
                key = bytes.fromhex(name[:-4])
            except ValueError:
                continue
            path = os.path.join(self.disk_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            entries.append((st.st_mtime, key, st.st_size))
        entries.sort()
        for _, key, size in entries:
            self._disk[key] = size
            self._disk_bytes += size
        self.warm_start_pages = len(entries)
        if entries:
            logger.info("KV tier warm start: %d spill pages (%.1f MiB) "
                        "in %s", len(entries),
                        self._disk_bytes / 2**20, self.disk_dir)

    # ------------------------------------------------------------------
    # Demotion (BlockPool eviction hook -> directive -> runner insert)
    # ------------------------------------------------------------------
    def note_evicted(self, block_id: int, block_hash) -> None:
        """BlockPool._maybe_evict_cached_block callback: the page id is
        being reassigned this step; queue its content for a pre-forward
        gather unless the hash is already tiered (content-addressed
        dedupe — a re-demotion would buy nothing). A deduped eviction
        still emits the tier transition: the DEVICE copy is gone, and
        without the retag the router would keep scoring a promoted-
        then-re-evicted page at full HBM credit forever."""
        key = block_hash.hash_value
        if key in self._host:
            self._transitions.append((key.hex(), TIER_HOST))
            return
        if key in self._disk:
            self._transitions.append((key.hex(), TIER_DISK))
            return
        if len(self._pending_demotes) >= self.demote_pages_per_step:
            # Bound the pre-forward gather; pages past the cap cannot
            # defer (their device content is overwritten this step),
            # so the demotion opportunity is dropped and counted.
            self.demotes_dropped += 1
            return
        self._pending_demotes.append((block_id, key))

    def take_demotes(self, step_has_work: bool) -> \
            Optional[DemoteDirective]:
        """Drain this step's eviction queue into one batched directive.
        Evictions only happen inside successful allocations, so a step
        with demotes always dispatches — but if a zero-token step ever
        carries them (defensive), they are dropped: the directive is
        only valid against this step's pre-forward device state."""
        if not self._pending_demotes:
            return None
        pending, self._pending_demotes = self._pending_demotes, []
        if not step_has_work:
            self.demotes_dropped += len(pending)
            return None
        return DemoteDirective(page_ids=[p for p, _ in pending],
                               keys=[k for _, k in pending])

    def insert_host(self, key: bytes, k_np: np.ndarray,
                    v_np: np.ndarray) -> None:
        """Runner-side: land one demoted page in the host pool (most-
        recently-used position), spilling LRU pages to disk past the
        host budget. Arrays are wire layout (page axis removed)."""
        if key in self._host or key in self._disk:
            return
        if self.wire_shapes is None:
            self.wire_shapes = (tuple(k_np.shape), tuple(v_np.shape))
        page = _HostPage(k=np.ascontiguousarray(k_np),
                         v=np.ascontiguousarray(v_np))
        self._host[key] = page
        self._host_bytes += page.nbytes
        self.demotions["host"] += 1
        self.demotion_bytes["host"] += page.nbytes
        self._transitions.append((key.hex(), TIER_HOST))
        while self._host_bytes > self.host_budget_bytes \
                and len(self._host) > 1:
            old_key, old = self._host.popitem(last=False)
            self._host_bytes -= old.nbytes
            self._spill_to_disk(old_key, old)

    def _spill_to_disk(self, key: bytes, page: _HostPage) -> None:
        """T1 eviction: demote to a page file (shared_storage format)
        when the disk tier is configured, else the content is gone."""
        if not self.disk_dir:
            self._transitions.append((key.hex(), TIER_GONE))
            return
        from vllm_distributed_tpu.distributed.kv_transfer import \
            shared_storage
        try:
            nbytes, _ = shared_storage.write_page_file(
                self._file(key), page.k, page.v, connector="kv_tier")
        except OSError as e:
            logger.warning("KV tier disk spill failed for %s: %s",
                           key.hex()[:12], e)
            self._transitions.append((key.hex(), TIER_GONE))
            return
        self._disk[key] = nbytes
        self._disk_bytes += nbytes
        self.demotions["disk"] += 1
        self.demotion_bytes["disk"] += nbytes
        self._transitions.append((key.hex(), TIER_DISK))
        while self._disk_bytes > self.disk_budget_bytes \
                and len(self._disk) > 1:
            victim, size = self._disk.popitem(last=False)
            self._disk_bytes -= size
            try:
                os.remove(self._file(victim))
            except OSError:
                pass
            self._transitions.append((victim.hex(), TIER_GONE))

    # ------------------------------------------------------------------
    # Lookup / promotion (scheduler side)
    # ------------------------------------------------------------------
    def _read_disk(self, key: bytes):
        """Read+validate one spill file -> (k, v) or None (corrupt /
        missing / shape-foreign -> counted miss, file dropped when it
        exists but is bad). The CRC lives in the quantized codec or the
        zlib container; the deterministic ``kv_tier.spill_corrupt``
        fault point simulates a failed check so the degrade-to-
        recompute path can be drilled."""
        from vllm_distributed_tpu.distributed.kv_transfer import \
            shared_storage
        path = self._file(key)
        try:
            if fault_injection.should_fire("kv_tier.spill_corrupt"):
                raise OSError("injected spill corruption")
            k, v, _latent = shared_storage.read_page_file(path)
            k, v = np.asarray(k), np.asarray(v)
        except Exception as e:  # noqa: BLE001 - any decode failure
            logger.warning("KV tier spill %s unreadable (%s); "
                           "treating as a miss", key.hex()[:12], e)
            self._drop_disk(key, remove_file=True)
            self.misses["disk"] += 1
            return None
        if self.wire_shapes is not None and (
                tuple(k.shape) != self.wire_shapes[0]
                or tuple(v.shape) != self.wire_shapes[1]):
            # Shape-foreign artifact (another model's store sharing the
            # directory): miss WITHOUT deleting — it may be someone
            # else's valid page.
            logger.warning(
                "KV tier spill %s has foreign wire shapes %s/%s "
                "(want %s); ignoring", key.hex()[:12], k.shape, v.shape,
                self.wire_shapes)
            # De-index (with its bytes — a bare pop would leave
            # phantom bytes inflating the budget accounting forever)
            # but keep the file: it may be someone else's valid page.
            self._drop_disk(key, remove_file=False)
            self.misses["disk"] += 1
            return None
        return k, v

    def _drop_disk(self, key: bytes, remove_file: bool = False) -> None:
        size = self._disk.pop(key, None)
        if size is not None:
            self._disk_bytes -= size
        if remove_file:
            try:
                os.remove(self._file(key))
            except OSError:
                pass
        self._transitions.append((key.hex(), TIER_GONE))

    def lookup(self, block_hash):
        """(tier, k, v) for a content hash, or None. Host hits return
        the pooled arrays by reference; disk hits read+verify the spill
        file NOW (scheduler-side) so admission never gambles on a later
        runner-side read — the state-cache journal's verified-payload
        idiom."""
        key = block_hash.hash_value
        entry = self._host.get(key)
        if entry is not None:
            self._host.move_to_end(key)
            return "host", entry.k, entry.v
        if self.disk_dir and (key in self._disk
                              or os.path.exists(self._file(key))):
            got = self._read_disk(key)
            if got is None:
                return None
            if key not in self._disk:
                # Cross-replica file discovered by the exists() probe.
                try:
                    self._disk[key] = os.path.getsize(self._file(key))
                    self._disk_bytes += self._disk[key]
                except OSError:
                    pass
            return ("disk", ) + got
        return None

    def match_prefix(self, req_id: str, block_hashes, start: int,
                     max_tokens: int, block_size: int) -> int:
        """Extend a device-cached prefix of ``start`` pages with tier-
        resident continuation pages: walks ``block_hashes[start:]``
        while each hash resolves in T1/T2 and the page still leaves at
        least one prompt token to compute. Stages the hit arrays under
        ``req_id`` (pinned until admission or finish — a blocked queue
        head retries every step without re-reading disk, and a host
        eviction between lookup and dispatch cannot invalidate the
        admitted hit) and returns the number of tier pages matched."""
        stash = self._pending_hits.get(req_id)
        hits = []
        j = start
        while ((j + 1) * block_size <= max_tokens
               and j < len(block_hashes)):
            key = block_hashes[j].hash_value
            if stash is not None and len(hits) < len(stash) \
                    and stash[len(hits)][0] == key:
                hits.append(stash[len(hits)])  # memoized (content-
                j += 1                         # addressed: never stale)
                continue
            got = self.lookup(block_hashes[j])
            if got is None:
                break
            tier, k, v = got
            hits.append((key, tier, k, v))
            j += 1
        if hits:
            self._pending_hits[req_id] = hits
        else:
            self._pending_hits.pop(req_id, None)
        return len(hits)

    def pending_hit_count(self, req_id: str) -> int:
        return len(self._pending_hits.get(req_id, ()))

    def take_hits(self, req_id: str) -> Optional[list]:
        return self._pending_hits.pop(req_id, None)

    def drop_request(self, req_id: str) -> None:
        self._pending_hits.pop(req_id, None)

    def record_promotion(self, directive: PromoteDirective,
                         seconds: float) -> None:
        """Runner-side: account one executed promote directive."""
        for key, tier in zip(directive.keys, directive.tiers):
            self.promotions[tier] += 1
            self._transitions.append((key.hex(), TIER_DEVICE))
        self.promotion_hist.observe(seconds)

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        """Tier telemetry for the stats RPC ("kv_tier" entry →
        vdt:kv_tier_* families). Runs on the stats caller's thread:
        every container is snapshotted GIL-atomically. ``transitions``
        is a destructive drain feeding the router's residency index
        (engine/router.py observe_stats); non-router consumers ignore
        it."""
        transitions = []
        while True:
            try:
                transitions.append(self._transitions.popleft())
            except IndexError:
                break
        return {
            "pages": {"host": len(self._host), "disk": len(self._disk)},
            "bytes": {"host": self._host_bytes,
                      "disk": self._disk_bytes},
            "demotions": dict(self.demotions),
            "demotion_bytes": dict(self.demotion_bytes),
            "promotions": dict(self.promotions),
            "misses": dict(self.misses),
            "demotes_dropped": self.demotes_dropped,
            "warm_start_pages": self.warm_start_pages,
            "promotion_seconds": self.promotion_hist.to_dict(),
            "transitions": transitions,
        }
