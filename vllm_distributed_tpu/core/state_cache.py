"""First-class SSM state cache: O(1) checkpoint/restore for stateful
(Mamba/Jamba/Bamba) serving.

Paged KV can be re-derived at any page boundary by continuation prefill,
so prefix caching, preemption and crash recovery all come for free for
attention models. An SSM recurrence cannot re-enter at an arbitrary
boundary — but its state is CONSTANT-SIZE, so a snapshot of the
``(conv_state, ssm_state)`` rows at a token boundary is a complete
resume point (PAPERS.md "Compiler-First State Space Duality and Portable
O(1) Autoregressive Caching"). This module gives that snapshot the same
rights paged KV already has:

* **Prefix "caching"** — a bounded device-side pool of per-request state
  snapshots keyed by the chained ``BlockHash`` of the token prefix (the
  exact hashing the page prefix cache uses, ``core/kv_cache_utils``),
  with LRU eviction. A WAITING stateful request whose prompt prefix
  matches a snapshot is admitted as a continuation at the snapshot
  boundary instead of token 0 — shared system prompts and multi-turn
  sessions skip the re-prefill entirely.
* **Preemption parks state** — ``Scheduler._preempt`` snapshots the
  victim's state rows into the pool instead of discarding; resume
  restores the rows and continues, re-prefilling at most the tail since
  the last checkpoint boundary.
* **O(1) crash recovery** — snapshots optionally serialize to a host
  checkpoint journal (``VDT_SSM_CKPT_DIR``; one atomically-renamed file
  per snapshot, the shared_storage connector's tmp+rename .npz
  discipline). A respawned core's journal replay finds the last
  checkpoint by content hash and re-prefills only the tail — bounded by
  ``VDT_SSM_CKPT_INTERVAL`` tokens instead of O(prompt).

Boundaries are page-aligned multiples of the checkpoint interval: the
scheduler clips prefill chunks to land exactly on a boundary, so the
state rows hold exactly-the-boundary state when the snapshot copy runs.
Hybrid models (Jamba/Bamba) must restore state rows AND attention KV
pages coherently, so a hit additionally requires every prefix page to
still be resident in the block pool's prefix cache; pure-SSM models
(``STATE_ONLY``) carry no KV bytes and skip the page requirement.

This manager is pure host-side control plane (no jax): the scheduler
owns the bookkeeping and ships ``state_saves`` / ``state_restores``
directives on ``SchedulerOutput``; the model runner executes them as
jitted row<->pool copies (``worker/model_runner.py``) in dispatch
program order, which is what makes same-step restore-then-evict safe
(restores run before the forward, saves after it).
"""

import os
import zlib
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from vllm_distributed_tpu.core.kv_cache_utils import (hash_block_tokens,
                                                      request_hash_seed)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.request import Request

logger = init_logger(__name__)


def state_cache_enabled(config, stateful: bool) -> bool:
    """Single gate shared by the scheduler (bookkeeping) and the runner
    (device pool) so the two sides can never disagree about whether
    directives will be executed. The cache needs one runner driving one
    mesh (no token-parallel page partitions, no PP stage split, no
    follower hosts replaying broadcast outputs) and no KV connector
    (external-KV admission and state restore would race over
    num_computed_tokens)."""
    if not stateful:
        return False
    from vllm_distributed_tpu import envs
    if not envs.VDT_SSM_STATE_CACHE:
        return False
    pc = config.parallel_config
    kv_cfg = config.kv_transfer_config
    return (pc.token_parallel_size == 1
            and pc.pipeline_parallel_size == 1
            and pc.num_hosts <= 1
            and not (kv_cfg is not None and kv_cfg.kv_connector))


def resolve_state_slots(config) -> int:
    """Snapshot-pool slot count (device rows per state array). Shared by
    the scheduler and the runner — both must size identically."""
    from vllm_distributed_tpu import envs
    n = envs.VDT_SSM_STATE_CACHE_SLOTS
    if n > 0:
        return n
    return max(2 * config.scheduler_config.max_num_seqs, 8)


def resolve_ckpt_interval(config) -> int:
    """Checkpoint cadence in tokens, rounded UP to a page multiple so
    every snapshot boundary is also a block-hash boundary."""
    from vllm_distributed_tpu import envs
    bs = config.cache_config.block_size
    interval = max(envs.VDT_SSM_CKPT_INTERVAL, bs)
    return ((interval + bs - 1) // bs) * bs


# ---------------------------------------------------------------------------
# Host checkpoint journal (shared_storage connector file discipline:
# one file per snapshot, tmp + atomic rename, content-hash key).
# ---------------------------------------------------------------------------
def journal_path(journal_dir: str, key: bytes) -> str:
    return os.path.join(journal_dir, f"ssm_{key.hex()}.npz")


def _fs_now(dirpath: str) -> Optional[float]:
    """Current time on the FILESYSTEM's clock: the mtime of a
    just-written probe file. Journal TTLs compare against os.stat
    mtimes, so age arithmetic must read the clock that stamped them —
    never the process wall clock, whose view can skew from the
    filesystem's (remote mounts, clock steps between writer and
    sweeper)."""
    import tempfile
    try:
        fd, path = tempfile.mkstemp(prefix=".sweep_probe_", dir=dirpath)
    except OSError:
        return None
    try:
        os.close(fd)
        return os.stat(path).st_mtime
    except OSError:
        return None
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


def sweep_journal(journal_dir: str, *, max_bytes: int, ttl_s: float,
                  keep=frozenset(), now: float = None) -> tuple[int, int]:
    """Bounded-retention sweep of a checkpoint-journal directory:
    deletes ``ssm_*.npz`` files older than ``ttl_s`` seconds (0 = no
    TTL), then — if the survivors still exceed ``max_bytes`` (0 =
    unbounded) — the oldest first until the directory fits. Paths in
    ``keep`` (checkpoints an unshipped persist directive still owes, or
    the blocked-admission memo) are never reclaimed; neither is
    anything that is not a journal file. Returns (files_removed,
    bytes_removed).

    Content-addressed journal files deliberately outlive their requests
    (they ARE the crash-recovery tier), so this sweep — run at manager
    init and on sleep() — is the only thing bounding the directory."""
    if not journal_dir or not os.path.isdir(journal_dir):
        return 0, 0
    if now is None:
        # File ages only compare meaningfully on the clock that stamped
        # the mtimes. A probe write reads "filesystem now" from that
        # same clock — the monotonic-clock policy's answer for file
        # TTLs, where a process wall-clock read would re-introduce the
        # process-vs-filesystem skew the deadline lint bans. A failed
        # probe (read-only or FULL disk — exactly when reclamation
        # matters most) skips only the TTL pass below; the size prune
        # is mtime-ORDER only and needs no clock, so it still runs.
        now = _fs_now(journal_dir)
    entries = []
    for name in os.listdir(journal_dir):
        if not (name.startswith("ssm_") and name.endswith(".npz")):
            continue
        path = os.path.join(journal_dir, name)
        if path in keep:
            continue
        try:
            st = os.stat(path)
        except OSError:
            continue
        entries.append((st.st_mtime, st.st_size, path))
    entries.sort()  # oldest first
    removed = removed_bytes = 0

    def reclaim(mtime, size, path) -> bool:
        nonlocal removed, removed_bytes
        try:
            os.remove(path)
        except OSError:
            return False
        removed += 1
        removed_bytes += size
        return True

    survivors = []
    for mtime, size, path in entries:
        if ttl_s > 0 and now is not None and now - mtime > ttl_s:
            reclaim(mtime, size, path)
        else:
            survivors.append((mtime, size, path))
    if max_bytes > 0:
        total = sum(size for _, size, _ in survivors)
        for mtime, size, path in survivors:
            if total <= max_bytes:
                break
            if reclaim(mtime, size, path):
                total -= size
    if removed:
        logger.info(
            "SSM checkpoint journal sweep: reclaimed %d files "
            "(%.1f MiB) from %s", removed, removed_bytes / 2**20,
            journal_dir)
    return removed, removed_bytes


def state_fingerprint(shapes: dict) -> bytes:
    """Geometry fingerprint of a model's state arrays ({name: ((shape),
    dtype)}): stored in every journal file and checked at lookup so a
    VDT_SSM_CKPT_DIR shared across models/revisions can never serve a
    CRC-valid but shape-foreign checkpoint into the runner."""
    import hashlib
    desc = sorted((name, tuple(int(x) for x in shape), str(dtype))
                  for name, (shape, dtype) in shapes.items())
    return hashlib.sha256(repr(desc).encode()).digest()[:16]


def write_journal(path: str, arrays: dict[str, np.ndarray],
                  num_tokens: int, fingerprint: bytes = b"") -> None:
    """Serialize one snapshot's state arrays. Arrays are stored as raw
    bytes + (shape, dtype) metadata so bfloat16 (ml_dtypes) rows
    round-trip without numpy's native-dtype restrictions; a CRC32 over
    the payload guards restores against torn/corrupt files."""
    payload: dict[str, np.ndarray] = {
        "num_tokens": np.asarray([num_tokens], np.int64),
        "fingerprint": np.frombuffer(fingerprint, np.uint8),
    }
    crc = 0
    for name in sorted(arrays):
        a = np.ascontiguousarray(np.asarray(arrays[name]))
        data = a.tobytes()
        crc = zlib.crc32(data, crc)
        payload[f"{name}.data"] = np.frombuffer(data, np.uint8)
        payload[f"{name}.shape"] = np.asarray(a.shape, np.int64)
        payload[f"{name}.dtype"] = np.frombuffer(
            a.dtype.name.encode(), np.uint8)
    payload["checksum"] = np.asarray([crc & 0xFFFFFFFF], np.uint64)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)


def read_journal(path: str) -> Optional[dict[str, np.ndarray]]:
    """Load + checksum-verify one snapshot file. Returns the state
    arrays (keyed by state-cache name), or None on any corruption —
    including the deterministic ``ssm.restore_corrupt`` fault point,
    which simulates a checksum mismatch so the degrade-to-re-prefill
    path can be drilled."""
    from vllm_distributed_tpu.utils import fault_injection
    try:
        with np.load(path) as f:
            stored = int(f["checksum"][0])
            names = sorted(k[:-5] for k in f.files if k.endswith(".data"))
            fingerprint = (bytes(f["fingerprint"])
                           if "fingerprint" in f.files else b"")
            crc = 0
            out: dict[str, np.ndarray] = {}
            for name in names:
                data = f[f"{name}.data"].tobytes()
                crc = zlib.crc32(data, crc)
                shape = tuple(int(x) for x in f[f"{name}.shape"])
                dtype_name = bytes(f[f"{name}.dtype"]).decode()
                try:
                    dtype = np.dtype(dtype_name)
                except TypeError:
                    import ml_dtypes  # registers bfloat16 et al.
                    dtype = np.dtype(getattr(ml_dtypes, dtype_name))
                out[name] = np.frombuffer(data, dtype).reshape(shape)
    except Exception as e:  # noqa: BLE001 - torn/missing file
        logger.warning("unreadable SSM checkpoint %s: %s", path, e)
        return None
    if (crc & 0xFFFFFFFF) != stored or fault_injection.should_fire(
            "ssm.restore_corrupt"):
        logger.warning("SSM checkpoint %s failed its checksum; "
                       "degrading to full re-prefill", path)
        return None
    out["__fingerprint__"] = fingerprint
    return out


# ---------------------------------------------------------------------------
@dataclass
class StateSnapshot:
    """One committed (or pending) snapshot: pool slot ``slot`` holds the
    state after exactly ``num_tokens`` tokens whose chained page hash is
    ``key`` (None while a speculative save's tokens are unconfirmed)."""

    slot: int
    num_tokens: int
    key: Optional[bytes] = None
    journal: Optional[str] = None
    last_used: int = 0
    # A deferred journal write (async saves resolve their key only at
    # commit) is still owed for this slot: eviction must not reuse it
    # until the persist directive ships.
    journal_pending: bool = False


@dataclass
class SaveDirective:
    """Wire form of one pending snapshot copy (SchedulerOutput
    ``state_saves``): the runner copies input-batch row(req_id) into
    pool slot ``slot`` after the step's forward, and, when ``journal``
    is set, serializes the slot to that path. ``persist_only``
    directives skip the copy — they journal an already-committed slot
    whose key (and therefore path) only became known at commit time
    (async run-ahead saves)."""

    req_id: str
    slot: int
    num_tokens: int
    journal: Optional[str] = None
    persist_only: bool = False


@dataclass
class RestoreDirective:
    """Wire form of one restore (SchedulerOutput ``state_restores``):
    before the step's forward the runner fills input-batch row(req_id)
    from pool slot ``slot``, or — for a crash-recovery journal hit
    (slot < 0) — from the checkpoint at ``journal`` (``arrays`` carries
    the scheduler's already-verified payload; directives never cross a
    process boundary, so the runner reuses it instead of re-reading)."""

    req_id: str
    slot: int
    num_tokens: int
    journal: Optional[str] = None
    arrays: Optional[dict] = None


@dataclass
class StateCacheManager:
    """Scheduler-side bookkeeping for the snapshot pool. Pure python —
    device copies happen in the runner, driven by the directives this
    manager emits."""

    num_slots: int
    block_size: int
    interval: int
    paged_kv: bool
    journal_dir: str = ""
    # Per-slot device bytes (conv + ssm rows across layers) and the
    # journal geometry fingerprint; wired by the engine core from the
    # runner's pool geometry after construction (the scheduler never
    # touches device arrays).
    bytes_per_slot: int = 0
    journal_fingerprint: bytes = b""
    # Hierarchical KV/state tiering (VDT_KV_TIERING): LRU eviction
    # DEMOTES a committed snapshot to the checkpoint journal (one owed
    # persist_only directive; the slot stays pinned until it ships)
    # instead of discarding device-only state — the journal is the
    # snapshot pool's second tier, closing the "eviction discards"
    # gap. Restores of demoted snapshots ride the existing journal
    # fallback of get_computed_state.
    demote_on_evict: bool = False

    by_key: dict[bytes, StateSnapshot] = field(default_factory=dict)
    by_slot: dict[int, StateSnapshot] = field(default_factory=dict)
    free_slots: list[int] = field(default_factory=list)
    # (req_id, num_tokens) -> snapshot issued but not yet committed by
    # update_from_output (the copy may be in flight on device).
    pending: dict[tuple[str, int], StateSnapshot] = field(
        default_factory=dict)
    # Deferred journal writes for committed async saves (key resolved
    # at commit): drained into the next non-empty SchedulerOutput as
    # persist_only directives.
    pending_persists: list = field(default_factory=list)
    # Incremental per-request hash chains (same chaining as
    # hash_request_tokens; dropped on finish).
    _chains: dict[str, list] = field(default_factory=dict)
    # (path, verified arrays) of the most recent journal read: blocked
    # admissions retry the same lookup every step.
    _last_journal: Optional[tuple] = None
    _clock: int = 0

    # Stats (flat numeric keys so the DP aggregator's numeric-sum loop
    # merges them across replicas without special cases).
    hits: int = 0
    queries: int = 0
    evictions: int = 0
    checkpoints: int = 0
    resume_tokens_saved: int = 0
    restore_corruptions: int = 0
    journal_files_reclaimed: int = 0
    journal_demotions: int = 0

    def __post_init__(self) -> None:
        self.free_slots = list(range(self.num_slots - 1, -1, -1))
        if self.journal_dir:
            os.makedirs(self.journal_dir, exist_ok=True)
            # Retention sweep at init: expired / over-budget files from
            # prior runs are reclaimed BEFORE any of them could serve a
            # replay (recent checkpoints — the ones recovery actually
            # wants — sort last and survive).
            self._sweep_journal()

    def _sweep_journal(self) -> None:
        from vllm_distributed_tpu import envs
        keep = {s.journal for s in self.by_key.values() if s.journal}
        keep.update(d.journal for d in self.pending_persists
                    if getattr(d, "journal", None))
        if self._last_journal is not None:
            keep.add(self._last_journal[0])
        removed, _ = sweep_journal(
            self.journal_dir,
            max_bytes=envs.VDT_SSM_CKPT_MAX_MB * 2**20,
            ttl_s=envs.VDT_SSM_CKPT_TTL_S, keep=keep)
        self.journal_files_reclaimed += removed

    # ------------------------------------------------------------------
    # Hash chains
    # ------------------------------------------------------------------
    def _chain(self, request: Request, num_tokens: int) -> list:
        """Chained page hashes covering tokens[0:num_tokens] (page
        multiple), extended incrementally per request."""
        chain = self._chains.setdefault(request.request_id, [])
        want = num_tokens // self.block_size
        tokens = request.all_token_ids
        parent = (chain[-1].hash_value if chain
                  else request_hash_seed(request))
        while len(chain) < want:
            start = len(chain) * self.block_size
            bh = hash_block_tokens(
                parent, tuple(tokens[start:start + self.block_size]))
            chain.append(bh)
            parent = bh.hash_value
        return chain[:want]

    def _key_at(self, request: Request, num_tokens: int) -> bytes:
        return self._chain(request, num_tokens)[-1].hash_value

    def drop_request(self, req_id: str) -> None:
        """Forget per-request scratch on finish (snapshots themselves
        are content-addressed and deliberately outlive the request —
        they ARE the multi-turn prefix cache). The journal memo exists
        only to serve a BLOCKED admission's retries; once requests
        finish it must not pin a checkpoint's host arrays forever."""
        self._chains.pop(req_id, None)
        self._last_journal = None

    # ------------------------------------------------------------------
    # Grant shaping
    # ------------------------------------------------------------------
    def clip_grant(self, num_computed: int, granted: int) -> int:
        """Clip a prefill grant so it ENDS exactly on the LAST snapshot
        boundary it can reach — the state rows then hold
        exactly-the-boundary state when the save directive's copy runs.
        Clipping to the furthest (not the next) boundary keeps prefill
        chunks at the token budget, not the interval: a grant loses at
        most ``interval - 1`` tokens, never ``granted - interval``."""
        end = num_computed + granted
        boundary = (end // self.interval) * self.interval
        if boundary > num_computed and boundary < end:
            return boundary - num_computed
        return granted

    # ------------------------------------------------------------------
    # Saves
    # ------------------------------------------------------------------
    def maybe_save(self, request: Request,
                   num_tokens: int) -> Optional[SaveDirective]:
        """Snapshot directive for a request whose computed-token count
        reaches ``num_tokens`` this step, or None (off-boundary,
        already snapshotted, or the pool is fully pinned by pending
        copies). ``num_tokens`` may exceed the host-known tokens under
        async run-ahead — the key is then resolved at commit time, once
        the speculative token has reconciled."""
        if num_tokens <= 0 or num_tokens % self.interval != 0:
            return None
        if (request.request_id, num_tokens) in self.pending:
            return None
        key = None
        journal = None
        if num_tokens <= request.num_tokens:
            key = self._key_at(request, num_tokens)
            snap = self.by_key.get(key)
            if snap is not None:
                self._touch(snap)
                return None  # identical prefix already snapshotted
            if self.journal_dir:
                journal = journal_path(self.journal_dir, key)
        slot = self._take_slot()
        if slot is None:
            return None
        snap = StateSnapshot(slot=slot, num_tokens=num_tokens, key=key,
                             journal=journal)
        self.by_slot[slot] = snap
        self.pending[(request.request_id, num_tokens)] = snap
        return SaveDirective(req_id=request.request_id, slot=slot,
                             num_tokens=num_tokens, journal=journal)

    def commit_save(self, directive: SaveDirective,
                    request: Optional[Request]) -> None:
        """Finalize (or discard) a shipped save once its step
        reconciled: the snapshot enters the lookup index only if the
        request actually committed tokens through the boundary — an
        async run-ahead that stopped short must not advertise state
        containing a discarded token."""
        snap = self.pending.pop((directive.req_id, directive.num_tokens),
                                None)
        if snap is None:
            return  # aborted (restart-from-scratch / external finish)
        valid = (request is not None
                 and request.num_tokens >= directive.num_tokens)
        if valid and snap.key is None:
            snap.key = self._key_at(request, directive.num_tokens)
        if valid and self.by_key.get(snap.key) is not None:
            # Two requests with an identical prefix raced their pending
            # saves; the first committed copy wins (same content).
            valid = False
        if not valid:
            self._release(snap)
            return
        self.by_key[snap.key] = snap
        self._touch(snap)
        self.checkpoints += 1
        if (self.journal_dir and snap.journal is None):
            # Async save whose key only resolved now: the journal write
            # could not ride the original copy. Owe a persist_only
            # directive (next non-empty output); the slot is pinned
            # against eviction until it ships.
            snap.journal = journal_path(self.journal_dir, snap.key)
            if not os.path.exists(snap.journal):
                snap.journal_pending = True
                self.pending_persists.append(SaveDirective(
                    req_id=directive.req_id, slot=snap.slot,
                    num_tokens=snap.num_tokens, journal=snap.journal,
                    persist_only=True))

    def abort_pending(self, req_id: str) -> None:
        """Drop every uncommitted save of ``req_id`` — called when the
        request restarts its recurrence from an earlier point (resume
        from scratch or from an older snapshot) or finishes externally:
        a later copy of its row would capture state the pending
        boundary no longer describes."""
        for pkey in [k for k in self.pending if k[0] == req_id]:
            self._release(self.pending.pop(pkey))

    def is_pending(self, directive: SaveDirective) -> bool:
        return (directive.req_id, directive.num_tokens) in self.pending

    def take_persists(self) -> list:
        """Drain the owed journal writes. Un-pinning at drain time is
        safe: the directives dispatch within this very step, and any
        later eviction's overwriting copy is dispatched after them —
        device program order serializes the reads before the write."""
        if not self.pending_persists:
            return []
        out = []
        for d in self.pending_persists:
            snap = self.by_slot.get(d.slot)
            if snap is None or snap.journal != d.journal:
                continue  # snapshot reset/released meanwhile
            snap.journal_pending = False
            out.append(d)
        self.pending_persists = []
        return out

    # ------------------------------------------------------------------
    # Lookup / restore
    # ------------------------------------------------------------------
    def get_computed_state(self, request: Request, block_pool) -> tuple[
            list, int, Optional[RestoreDirective]]:
        """Longest-prefix snapshot lookup for a WAITING stateful
        request. Returns (cached prefix pages, boundary, restore
        directive) — ([], 0, None) on miss. Hybrid models additionally
        require every prefix page resident in ``block_pool`` (state
        rows and attention KV must re-enter coherently); pure-SSM
        models carry no KV bytes and skip the page check. Device-pool
        misses fall back to the host checkpoint journal (crash
        recovery), checksum-verified before admission. The HIT counter
        is incremented by the scheduler at successful admission, not
        here — a blocked queue head retries this lookup every step and
        must not inflate the hit rate."""
        self.queries += 1
        # At least one token must remain to be computed (same rule as
        # the page prefix cache: the last token must produce a logit).
        max_tokens = request.num_tokens - 1
        boundary = (max_tokens // self.interval) * self.interval
        resident: list = []
        if self.paged_kv and boundary > 0:
            # ONE forward walk of the page chain finds the longest
            # resident prefix; it caps the boundary scan so the lookup
            # is O(pages), not O(boundaries x pages). Residency must be
            # re-checked on every admission attempt — ref-0 cached
            # pages can be evicted between retries of a blocked queue
            # head, and a stale block handle would be page corruption.
            for bh in self._chain(request, boundary):
                block = block_pool.get_cached_block(bh)
                if block is None:
                    break
                resident.append(block)
            boundary = min(boundary,
                           (len(resident) * self.block_size
                            // self.interval) * self.interval)
        while boundary > 0:
            chain = self._chain(request, boundary)
            key = chain[-1].hash_value
            blocks = (resident[:boundary // self.block_size]
                      if self.paged_kv else [])
            snap = self.by_key.get(key)
            if snap is not None:
                self._touch(snap)
                return blocks, boundary, RestoreDirective(
                    req_id=request.request_id, slot=snap.slot,
                    num_tokens=boundary)
            if self.journal_dir:
                path = journal_path(self.journal_dir, key)
                if os.path.exists(path):
                    # One-entry memo: a blocked admission retries the
                    # same queue head every step, and the file content
                    # is immutable (content-addressed, atomic rename),
                    # so re-reading + re-CRC'ing multi-MB state per
                    # step would be pure waste.
                    if (self._last_journal is not None
                            and self._last_journal[0] == path):
                        arrays = self._last_journal[1]
                    else:
                        arrays = read_journal(path)
                    if arrays is None:
                        # Quarantine: a corrupt checkpoint must not be
                        # re-verified (and re-counted) on every later
                        # admission of the same prefix.
                        self.restore_corruptions += 1
                        try:
                            os.remove(path)
                        except OSError:
                            pass
                        boundary -= self.interval
                        continue
                    stored_fp = arrays.get("__fingerprint__", b"")
                    if (self.journal_fingerprint and stored_fp
                            and stored_fp != self.journal_fingerprint):
                        # A shared journal dir serving another model's
                        # geometry: miss (do NOT delete — the file is
                        # someone else's valid checkpoint).
                        logger.warning(
                            "SSM checkpoint %s has a foreign state "
                            "geometry; ignoring", path)
                        boundary -= self.interval
                        continue
                    self._last_journal = (path, arrays)
                    return blocks, boundary, RestoreDirective(
                        req_id=request.request_id, slot=-1,
                        num_tokens=boundary, journal=path,
                        arrays=arrays)
            boundary -= self.interval
        return [], 0, None

    # ------------------------------------------------------------------
    # Slots / LRU
    # ------------------------------------------------------------------
    def _touch(self, snap: StateSnapshot) -> None:
        self._clock += 1
        snap.last_used = self._clock

    def _release(self, snap: StateSnapshot) -> None:
        self.by_slot.pop(snap.slot, None)
        if snap.key is not None:
            existing = self.by_key.get(snap.key)
            if existing is snap:
                del self.by_key[snap.key]
        self.free_slots.append(snap.slot)

    def _take_slot(self) -> Optional[int]:
        if self.free_slots:
            return self.free_slots.pop()
        # Evict the least-recently-used COMMITTED snapshot. Pending
        # slots are skipped (their device copy may be in flight), but a
        # committed victim's slot can be reused immediately: the
        # overwriting copy is dispatched after any restore that still
        # references the old content, and device program order
        # serializes them (restores run pre-forward, saves
        # post-forward).
        while True:
            committed = [s for s in self.by_slot.values()
                         if s.key is not None and s.key in self.by_key
                         and self.by_key[s.key] is s
                         and not s.journal_pending]
            if not committed:
                return None
            victim = min(committed, key=lambda s: s.last_used)
            if (self.demote_on_evict and self.journal_dir
                    and victim.key is not None):
                # Journal-as-second-tier (VDT_KV_TIERING): a victim
                # whose checkpoint file is missing (journal written
                # lazily, or reclaimed by the sweep) is DEMOTED, not
                # discarded — owe its journal write as a persist_only
                # directive and pin the slot until it ships; the LRU
                # walk picks another victim this round. Once the file
                # exists the slot evicts normally and the journal
                # fallback of get_computed_state serves restores.
                if victim.journal is None:
                    victim.journal = journal_path(self.journal_dir,
                                                  victim.key)
                if not os.path.exists(victim.journal):
                    victim.journal_pending = True
                    self.pending_persists.append(SaveDirective(
                        req_id="", slot=victim.slot,
                        num_tokens=victim.num_tokens,
                        journal=victim.journal, persist_only=True))
                    self.journal_demotions += 1
                    continue
            self._release(victim)
            self.evictions += 1
            return self.free_slots.pop()

    def reset(self) -> None:
        """Forget every snapshot (sleep/wake released the pool's HBM).
        Counters survive — they are lifetime totals. The sleep boundary
        also runs the journal retention sweep: an idle engine is the
        cheapest moment to reclaim expired / over-budget checkpoint
        files. The sweep runs BEFORE the bookkeeping clears so a
        checkpoint an unshipped persist directive still references is
        protected at the moment of the sweep."""
        if self.journal_dir:
            self._sweep_journal()
        self.by_key.clear()
        self.by_slot.clear()
        self.pending.clear()
        self.pending_persists.clear()
        self._chains.clear()
        self._last_journal = None
        self.free_slots = list(range(self.num_slots - 1, -1, -1))

    # ------------------------------------------------------------------
    def stats(self) -> dict[str, int]:
        return {
            "ssm_state_cache_hits": self.hits,
            "ssm_state_cache_queries": self.queries,
            "ssm_state_cache_evictions": self.evictions,
            "ssm_checkpoints": self.checkpoints,
            "ssm_state_bytes_held": len(self.by_key) * self.bytes_per_slot,
            "ssm_resume_tokens_saved": self.resume_tokens_saved,
            "ssm_restore_corruptions": self.restore_corruptions,
            "ssm_journal_reclaimed": self.journal_files_reclaimed,
            "ssm_journal_demotions": self.journal_demotions,
        }
