"""N-gram draft proposer (prompt-lookup decoding).

Reference: vllm/v1/spec_decode/ngram_proposer.py:11 (``NgramProposer``:
match the longest recent suffix n-gram, n in [prompt_lookup_min,
prompt_lookup_max], against the token history; propose the k tokens that
followed the most recent earlier occurrence). Pure numpy — runs on the
host between steps, no device work.
"""

import numpy as np

from vllm_distributed_tpu.config import SpeculativeConfig


class NgramProposer:

    def __init__(self, config: SpeculativeConfig) -> None:
        self.k = config.num_speculative_tokens
        self.max_n = config.prompt_lookup_max
        self.min_n = config.prompt_lookup_min
        assert self.min_n >= 1 and self.max_n >= self.min_n and self.k >= 1

    def propose(self, token_ids: np.ndarray) -> list[int]:
        """Draft up to k continuation tokens for the given history
        (prompt + generated so far); [] when no n-gram matches."""
        total = len(token_ids)
        for n in range(self.max_n, self.min_n - 1, -1):
            if total < n + 1:
                continue
            suffix = token_ids[total - n:]
            # Candidate windows exclude the suffix itself; matching the
            # MOST RECENT earlier occurrence (reference behavior).
            windows = np.lib.stride_tricks.sliding_window_view(
                token_ids[:total - 1], n)
            matches = np.nonzero((windows == suffix).all(axis=1))[0]
            if len(matches) == 0:
                continue
            start = int(matches[-1]) + n
            cont = token_ids[start:start + self.k]
            if len(cont) > 0:
                return [int(t) for t in cont]
        return []
