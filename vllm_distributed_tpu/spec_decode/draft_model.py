"""Draft-model speculative proposer.

Reference: the learned-drafter speculative path (vllm/v1/spec_decode/
eagle.py:26 proposes with a small model and the rejection sampler
verifies; vllm's classic draft-model mode loads a separate small
checkpoint). TPU-first re-design:

* The draft is STATELESS: each proposal re-prefills the last
  ``draft_window`` tokens of the request and greedily decodes k more in
  one jitted ``lax.scan`` — no second paged-cache manager, no draft
  block tables threaded through the scheduler. RoPE attention scores
  depend only on relative distance, so anchoring the window at position
  0 is sound; the window bound trades a little acceptance on long
  contexts for zero persistent draft state (the reference instead runs
  its drafter against its own KV cache, eagle.py:120).
* Proposals are batched over all requests needing drafts ([R, W] in one
  jit keyed by the R bucket) and sampled greedily — verification by the
  existing S+1-position prefix-match sampler keeps the output
  distribution exactly the target's regardless of draft quality.
* The draft runs the XLA attention path against a throwaway in-jit
  cache (tiny shapes; the Pallas kernel would add nothing at window
  scale).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.config import SpeculativeConfig
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.models.common import AttentionBatch
from vllm_distributed_tpu.utils import cdiv, make_buckets, pad_to_bucket

logger = init_logger(__name__)

_PAGE = 8  # draft-cache page size (kernel-independent; XLA path)


class DraftModelProposer:
    """Batched greedy k-token proposals from a small causal LM."""

    def __init__(self, config: SpeculativeConfig, dtype,
                 max_num_reqs: int = 256) -> None:
        assert config.model, ("speculative method 'draft_model' needs "
                              "speculative_model (a local checkpoint)")
        self.k = config.num_speculative_tokens
        from transformers import AutoConfig

        from vllm_distributed_tpu.models.llama import LlamaArchConfig
        from vllm_distributed_tpu.models.loader import load_hf_state_dict
        from vllm_distributed_tpu.models.registry import \
            resolve_architecture
        hf = AutoConfig.from_pretrained(config.model)
        cls = resolve_architecture(hf)
        arch = LlamaArchConfig.from_hf_config(hf, dtype=dtype)
        cls.configure_arch(arch, hf)
        self.model = cls(arch)
        self.params = jax.tree.map(
            jnp.asarray,
            self.model.params_from_hf_state_dict(
                load_hf_state_dict(config.model)))
        self.window = min(config.draft_window,
                          getattr(hf, "max_position_embeddings", 2048)
                          - self.k - 1)
        assert self.window >= 1
        self.req_buckets = make_buckets(4, max_num_reqs)
        self._fn = jax.jit(self._build_fn(),
                           static_argnames=("R", ))
        logger.info("draft model %s loaded (window %d, k %d)",
                    config.model, self.window, self.k)

    def precompile(self) -> int:
        """Warm the proposal graph for every request bucket (called from
        the runner's precompile pass so no draft compile lands on the
        serving path). Returns graphs compiled."""
        for R in self.req_buckets:
            drafts = self._fn(self.params,
                              jnp.zeros((R, self.window), jnp.int32),
                              jnp.ones((R, ), jnp.int32), R=R)
            jax.block_until_ready(drafts)
        return len(self.req_buckets)

    # ------------------------------------------------------------------
    def _build_fn(self):
        model = self.model
        W, k = self.window, self.k
        ppr = cdiv(W + k, _PAGE)

        def propose(params, windows, lens, *, R):
            # [R, W] left-aligned token windows, lens in [1, W].
            caches = model.make_kv_caches(R * ppr, _PAGE)
            bt = (jnp.arange(R, dtype=jnp.int32)[:, None] * ppr +
                  jnp.arange(ppr, dtype=jnp.int32)[None, :])
            tok = windows.reshape(-1)                     # [R*W]
            pos_in_row = jnp.arange(W, dtype=jnp.int32)
            positions = jnp.tile(pos_in_row, R)
            req_idx = jnp.repeat(jnp.arange(R, dtype=jnp.int32), W)
            base_slot = req_idx * (ppr * _PAGE)
            # Padding rows (past each row's len) park on slot -1.
            valid = pos_in_row[None, :] < lens[:, None]
            slots = jnp.where(valid.reshape(-1),
                              base_slot + positions, -1)
            batch = AttentionBatch(
                req_idx=req_idx, positions=positions,
                slot_mapping=slots, block_tables=bt,
                seq_lens=lens)
            hidden, caches = model.forward(params, caches, tok, batch)
            last = (jnp.arange(R, dtype=jnp.int32) * W + lens - 1)
            logits = model.compute_logits(params, hidden[last])
            t0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def step(carry, _):
                caches, tok_r, pos_r = carry
                slot_r = jnp.arange(R, dtype=jnp.int32) * (ppr * _PAGE) \
                    + pos_r
                b = AttentionBatch(
                    req_idx=jnp.arange(R, dtype=jnp.int32),
                    positions=pos_r, slot_mapping=slot_r,
                    block_tables=bt, seq_lens=pos_r + 1)
                h, caches = model.forward(params, caches, tok_r, b)
                lg = model.compute_logits(params, h)
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                return (caches, nxt, pos_r + 1), nxt

            (_, _, _), rest = jax.lax.scan(
                step, (caches, t0, lens), None, length=k - 1)
            drafts = jnp.concatenate(
                [t0[None], rest], axis=0).T  # [R, k]
            return drafts

        return propose

    # ------------------------------------------------------------------
    def propose_batch(self, histories: list[np.ndarray]) -> list[list[int]]:
        """One window per request history -> k greedy draft tokens each."""
        if not histories:
            return []
        n = len(histories)
        R = pad_to_bucket(n, self.req_buckets)
        W = self.window
        windows = np.zeros((R, W), np.int32)
        lens = np.ones((R, ), np.int32)
        for i, h in enumerate(histories):
            w = h[-W:]
            windows[i, :len(w)] = w
            lens[i] = len(w)
        drafts = np.asarray(self._fn(self.params, jnp.asarray(windows),
                                     jnp.asarray(lens), R=R))
        return [[int(t) for t in drafts[i]] for i in range(n)]
