"""Draft-model speculative proposer.

Reference: the learned-drafter speculative path (vllm/v1/spec_decode/
eagle.py:26 proposes with a small model and the rejection sampler
verifies; vllm's classic draft-model mode loads a separate small
checkpoint). TPU-first re-design:

* The draft is STATELESS: each proposal re-prefills the last
  ``draft_window`` tokens of the request and greedily decodes k more in
  one jitted ``lax.scan`` — no second paged-cache manager, no draft
  block tables threaded through the scheduler. RoPE attention scores
  depend only on relative distance, so anchoring the window at position
  0 is sound; the window bound trades a little acceptance on long
  contexts for zero persistent draft state (the reference instead runs
  its drafter against its own KV cache, eagle.py:120).
* Proposals are batched over all requests needing drafts ([R, W] in one
  jit keyed by the R bucket). Rows with temperature > 0 SAMPLE their
  drafts from the top-``SUPPORT_K`` truncated tempered draft
  distribution and carry that support (token ids + probabilities) back
  as q-metadata, so verification can run true stochastic rejection
  sampling (accept-with-prob min(1, p/q) + exact residual resample —
  reference: v1/sample/rejection_sampler.py:23) instead of the
  strictly-lower-acceptance prefix match. Greedy rows draft greedily
  with a delta support; either way the emitted distribution is exactly
  the target's.
* The draft runs the XLA attention path against a throwaway in-jit
  cache (tiny shapes; the Pallas kernel would add nothing at window
  scale).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.config import SpeculativeConfig
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.models.common import AttentionBatch
from vllm_distributed_tpu.utils import cdiv, make_buckets, pad_to_bucket

logger = init_logger(__name__)

_PAGE = 8  # draft-cache page size (kernel-independent; XLA path)

# Truncated draft-distribution support width: the proposer samples from
# its top-SUPPORT_K renormalized distribution and reports (ids, probs)
# on that support. Rejection sampling is exact w.r.t. this truncated q
# regardless of the width — K only bounds how spread proposals can be.
SUPPORT_K = 8


def sample_draft_step(logits, temps, seeds, step):
    """One stochastic draft sample per row from the top-SUPPORT_K
    truncated tempered distribution. Returns (token [R], support ids
    [R, K], support probs [R, K]); greedy rows (temp < 1e-5) emit their
    argmax with a delta support."""
    R, V = logits.shape
    kcap = min(SUPPORT_K, V)
    temp = jnp.maximum(temps, 1e-6)[:, None]
    topv, topi = jax.lax.top_k(logits / temp, kcap)  # [R, K]
    probs = jax.nn.softmax(topv, axis=-1)  # renormalized on the support
    base = jax.random.PRNGKey(3)
    keys = jax.vmap(lambda s: jax.random.fold_in(base, s))(
        (seeds + 104729 * step).astype(jnp.uint32))
    g = jax.vmap(lambda k: jax.random.gumbel(k, (kcap, )))(keys)
    choice = jnp.argmax(jnp.log(jnp.maximum(probs, 1e-30)) + g, axis=-1)
    rows = jnp.arange(R, dtype=jnp.int32)
    tok = topi[rows, choice].astype(jnp.int32)
    greedy = temps < 1e-5
    tok = jnp.where(greedy, topi[:, 0].astype(jnp.int32), tok)
    delta = jnp.zeros((R, kcap),
                      probs.dtype).at[:, 0].set(1.0)
    probs = jnp.where(greedy[:, None], delta, probs)
    return tok, topi.astype(jnp.int32), probs


class DraftModelProposer:
    """Batched greedy k-token proposals from a small causal LM."""

    def __init__(self, config: SpeculativeConfig, dtype,
                 max_num_reqs: int = 256) -> None:
        assert config.model, ("speculative method 'draft_model' needs "
                              "speculative_model (a local checkpoint)")
        self.k = config.num_speculative_tokens
        from transformers import AutoConfig

        from vllm_distributed_tpu.models.llama import LlamaArchConfig
        from vllm_distributed_tpu.models.loader import load_hf_state_dict
        from vllm_distributed_tpu.models.registry import \
            resolve_architecture
        hf = AutoConfig.from_pretrained(config.model)
        cls = resolve_architecture(hf)
        arch = LlamaArchConfig.from_hf_config(hf, dtype=dtype)
        cls.configure_arch(arch, hf)
        self.model = cls(arch)
        self.params = jax.tree.map(
            jnp.asarray,
            self.model.params_from_hf_state_dict(
                load_hf_state_dict(config.model)))
        self.window = min(config.draft_window,
                          getattr(hf, "max_position_embeddings", 2048)
                          - self.k - 1)
        assert self.window >= 1
        self.req_buckets = make_buckets(4, max_num_reqs)
        self._fn = jax.jit(self._build_fn(),
                           static_argnames=("R", ))
        logger.info("draft model %s loaded (window %d, k %d)",
                    config.model, self.window, self.k)

    def precompile(self) -> int:
        """Warm the proposal graph for every request bucket (called from
        the runner's precompile pass so no draft compile lands on the
        serving path). Returns graphs compiled."""
        for R in self.req_buckets:
            drafts = self._fn(self.params,
                              jnp.zeros((R, self.window), jnp.int32),
                              jnp.ones((R, ), jnp.int32),
                              jnp.zeros((R, ), jnp.float32),
                              jnp.zeros((R, ), jnp.int64), R=R)
            jax.block_until_ready(drafts)
        return len(self.req_buckets)

    # ------------------------------------------------------------------
    def _build_fn(self):
        model = self.model
        W, k = self.window, self.k
        ppr = cdiv(W + k, _PAGE)

        def propose(params, windows, lens, temps, seeds, *, R):
            # [R, W] left-aligned token windows, lens in [1, W].
            caches = model.make_kv_caches(R * ppr, _PAGE)
            bt = (jnp.arange(R, dtype=jnp.int32)[:, None] * ppr +
                  jnp.arange(ppr, dtype=jnp.int32)[None, :])
            tok = windows.reshape(-1)                     # [R*W]
            pos_in_row = jnp.arange(W, dtype=jnp.int32)
            positions = jnp.tile(pos_in_row, R)
            req_idx = jnp.repeat(jnp.arange(R, dtype=jnp.int32), W)
            base_slot = req_idx * (ppr * _PAGE)
            # Padding rows (past each row's len) park on slot -1.
            valid = pos_in_row[None, :] < lens[:, None]
            slots = jnp.where(valid.reshape(-1),
                              base_slot + positions, -1)
            batch = AttentionBatch(
                req_idx=req_idx, positions=positions,
                slot_mapping=slots, block_tables=bt,
                seq_lens=lens)
            hidden, caches = model.forward(params, caches, tok, batch)
            last = (jnp.arange(R, dtype=jnp.int32) * W + lens - 1)
            logits = model.compute_logits(params, hidden[last])
            t0, ids0, p0 = sample_draft_step(logits, temps, seeds, 0)

            def step(carry, j):
                caches, tok_r, pos_r = carry
                slot_r = jnp.arange(R, dtype=jnp.int32) * (ppr * _PAGE) \
                    + pos_r
                b = AttentionBatch(
                    req_idx=jnp.arange(R, dtype=jnp.int32),
                    positions=pos_r, slot_mapping=slot_r,
                    block_tables=bt, seq_lens=pos_r + 1)
                h, caches = model.forward(params, caches, tok_r, b)
                lg = model.compute_logits(params, h)
                nxt, ids_j, p_j = sample_draft_step(lg, temps, seeds, j)
                return (caches, nxt, pos_r + 1), (nxt, ids_j, p_j)

            (_, _, _), (rest, ids_r, p_r) = jax.lax.scan(
                step, (caches, t0, lens),
                jnp.arange(1, k, dtype=jnp.int32))
            drafts = jnp.concatenate([t0[None], rest], axis=0).T  # [R,k]
            q_ids = jnp.concatenate(
                [ids0[None], ids_r], axis=0).transpose(1, 0, 2)
            q_probs = jnp.concatenate(
                [p0[None], p_r], axis=0).transpose(1, 0, 2)
            return drafts, q_ids, q_probs

        return propose

    # ------------------------------------------------------------------
    def propose_batch(self, histories: list[np.ndarray],
                      temps: Optional[np.ndarray] = None,
                      seeds: Optional[np.ndarray] = None):
        """One window per request history -> k draft tokens each, plus
        the truncated draft-support metadata ([k, K] ids and probs per
        request) rejection-sampling verification consumes."""
        if not histories:
            return [], []
        n = len(histories)
        R = pad_to_bucket(n, self.req_buckets)
        W = self.window
        windows = np.zeros((R, W), np.int32)
        lens = np.ones((R, ), np.int32)
        for i, h in enumerate(histories):
            w = h[-W:]
            windows[i, :len(w)] = w
            lens[i] = len(w)
        temps_a = np.zeros((R, ), np.float32)
        if temps is not None:
            temps_a[:n] = temps
        seeds_a = np.zeros((R, ), np.int64)
        if seeds is not None:
            seeds_a[:n] = seeds
        drafts, q_ids, q_probs = self._fn(
            self.params, jnp.asarray(windows), jnp.asarray(lens),
            jnp.asarray(temps_a), jnp.asarray(seeds_a), R=R)
        drafts = np.asarray(drafts)
        meta = list(zip(np.asarray(q_ids), np.asarray(q_probs)))
        return ([[int(t) for t in drafts[i]] for i in range(n)],
                meta[:n])
