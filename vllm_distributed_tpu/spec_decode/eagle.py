"""EAGLE-class learned drafter with persistent draft KV.

Reference: vllm/v1/spec_decode/eagle.py:26 (EagleProposer: a small
draft transformer fed the target's hidden states, advancing its own KV
cache in-step, proposing k tokens per decode step). TPU-first
re-design rather than a port:

* The draft KV lives as EXTRA LAYERS of the target's stacked paged
  cache ([L_target + L_eagle, pages, ...]) addressed through the same
  block tables and slot mapping — no second cache manager, no draft
  block tables in the scheduler. ``run_layers(cache_layer_offset=L)``
  makes the drafter's reads/writes land past the target's depth.
* The drafter ADVANCES inside the target's jitted forward: every
  scheduled token's (embedding, target hidden) pair runs through the
  eagle layers in the same XLA program (one fused step, no extra
  dispatch), writing draft KV for exactly the positions the target
  wrote — speculative positions are re-written next step when their
  tokens are actually processed, so stale draft KV can never be read.
* Proposal is a separate tiny jit after verification: k sequential
  draft-attention steps over the paged draft KV. Proposed positions
  beyond the request's allocated pages park on slot -1 (the write
  drops); their KV is simply absent for later propose steps — a
  quality (never correctness) trade at page boundaries.
* Drafts are sampled from the top-K truncated tempered draft
  distribution (spec_decode/draft_model.py sample_draft_step) and the
  support rides back as q-metadata for exact rejection-sampling
  verification (sample/sampler.py spec_verify_rejection).

Checkpoint format: a local HF Llama-style directory whose config
declares the (few) draft layers, with the same hidden/head geometry as
the target, plus an ``fc.weight`` ([H, 2H] torch layout) combining
[token embedding; target hidden] -> H. Missing embed/lm_head/final
norm tensors fall back to sharing the target's (the official EAGLE
weights share them).
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.config import SpeculativeConfig
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.models.common import AttentionBatch
from vllm_distributed_tpu.spec_decode.draft_model import sample_draft_step
from vllm_distributed_tpu.utils import make_buckets, pad_to_bucket

logger = init_logger(__name__)


class EagleDrafter:
    """Draft layers stacked onto the target's paged KV cache."""

    def __init__(self, config: SpeculativeConfig, target_model,
                 max_num_reqs: int, page_size: int) -> None:
        assert config.model, ("speculative method 'eagle' needs "
                              "speculative_model (a local checkpoint)")
        from transformers import AutoConfig

        from vllm_distributed_tpu.models.llama import (LlamaArchConfig,
                                                       LlamaForCausalLM)
        self.k = config.num_speculative_tokens
        self.page_size = page_size
        tcfg = target_model.cfg
        hf = AutoConfig.from_pretrained(config.model)
        arch = LlamaArchConfig.from_hf_config(hf, dtype=tcfg.dtype)
        if (arch.hidden_size != tcfg.hidden_size
                or arch.head_dim != tcfg.head_dim):
            raise ValueError(
                f"eagle drafter geometry ({arch.hidden_size}/"
                f"{arch.head_dim}) must match the target "
                f"({tcfg.hidden_size}/{tcfg.head_dim})")
        self.model = LlamaForCausalLM(arch)
        self.num_layers = arch.num_layers
        self.layer_offset = tcfg.num_layers
        self.ckpt = config.model
        self.req_buckets = make_buckets(4, max_num_reqs)
        self._propose_fn = jax.jit(self._build_propose(),
                                   donate_argnums=(1, ),
                                   static_argnames=("R", ))

    # ------------------------------------------------------------------
    def load_params(self, target_params: dict) -> dict:
        """Eagle param tree from the checkpoint; embed/lm_head/final_ln
        fall back to the target's arrays (shared, not copied)."""
        from vllm_distributed_tpu.models.loader import load_hf_state_dict
        tensors = load_hf_state_dict(self.ckpt)
        c = self.model.cfg
        have = set(tensors)
        if "model.embed_tokens.weight" not in have:
            tensors["model.embed_tokens.weight"] = np.zeros(
                (c.vocab_size, c.hidden_size), np.float32)
        if "lm_head.weight" not in have:
            tensors["lm_head.weight"] = np.zeros(
                (c.vocab_size, c.hidden_size), np.float32)
        if "model.norm.weight" not in have:
            tensors["model.norm.weight"] = np.ones(
                (c.hidden_size, ), np.float32)
        params = self.model.params_from_hf_state_dict(tensors)
        if "model.embed_tokens.weight" not in have:
            params["embed"] = target_params["embed"]
        if "lm_head.weight" not in have:
            params["lm_head"] = target_params["lm_head"]
        if "model.norm.weight" not in have:
            params["final_ln"] = target_params["final_ln"]
        fc = tensors.get("fc.weight")
        if fc is None:
            raise ValueError(
                "eagle checkpoint is missing fc.weight ([H, 2H]): the "
                "[embedding; hidden] combiner is what makes it EAGLE")
        params["fc"] = jnp.asarray(np.asarray(fc).T, c.dtype)
        if "fc.bias" in tensors:
            params["fc_b"] = jnp.asarray(tensors["fc.bias"], c.dtype)
        return params

    def param_specs(self) -> dict:
        specs = self.model.param_specs()
        from jax.sharding import PartitionSpec as P
        specs["fc"] = P(None, None)
        specs["fc_b"] = P(None)
        return specs

    # ------------------------------------------------------------------
    def combine(self, eparams: dict, token_ids: jax.Array,
                positions: jax.Array, hidden: jax.Array) -> jax.Array:
        """fc([embedding; target hidden]) -> drafter input rows."""
        emb = self.model.embed(eparams, token_ids, positions)
        x = jnp.concatenate([emb, hidden.astype(emb.dtype)], axis=-1)
        x = x @ eparams["fc"]
        if "fc_b" in eparams:
            x = x + eparams["fc_b"]
        return x

    def advance(self, eparams: dict, kv_caches: dict,
                token_ids: jax.Array, hidden: jax.Array,
                batch: AttentionBatch) -> dict:
        """In-jit piece of the target step: run every scheduled token
        through the eagle layers, writing draft KV at the same slots
        the target wrote (cache rows [layer_offset, +num_layers))."""
        x = self.combine(eparams, token_ids, batch.positions, hidden)
        _g, kv_caches = self.model.run_layers(
            eparams["layers"], kv_caches, x, batch,
            cache_layer_offset=self.layer_offset)
        return kv_caches

    # ------------------------------------------------------------------
    def _build_propose(self):
        model = self.model
        k = self.k
        ps = self.page_size
        L_off = self.layer_offset

        def propose(eparams, kv_caches, h_tgt, tok, pos, block_tables,
                    num_blocks, temps, seeds, num_active, *, R):
            """k sequential draft steps. ``tok``/``pos``: the last
            emitted token and its position (its draft KV is written by
            step j=0); ``h_tgt``: target hidden at pos-1 (the state
            that produced ``tok``)."""
            rows = jnp.arange(R, dtype=jnp.int32)
            ones = jnp.ones((R, ), jnp.int32)
            h = h_tgt
            drafts, ids_l, probs_l = [], [], []
            for j in range(k):
                active = rows < num_active[0]
                page_idx = pos // ps
                in_range = jnp.logical_and(active,
                                           page_idx < num_blocks)
                page = block_tables[rows, jnp.minimum(
                    page_idx, block_tables.shape[1] - 1)]
                slot = jnp.where(in_range, page * ps + pos % ps, -1)
                kv_runs = jnp.stack(
                    [page, pos % ps, rows - pos % ps + ps,
                     jnp.where(in_range, 1, 0)], axis=1)
                seq_info = jnp.stack([rows, ones, pos + 1, rows], axis=1)
                batch = AttentionBatch(
                    req_idx=rows, positions=pos, slot_mapping=slot,
                    block_tables=block_tables, seq_lens=pos + 1,
                    seq_info=seq_info, num_seqs=num_active,
                    kv_runs=kv_runs, num_kv_runs=num_active, max_q=1)
                x = self.combine(eparams, tok, pos, h)
                g, kv_caches = model.run_layers(
                    eparams["layers"], kv_caches, x, batch,
                    cache_layer_offset=L_off)
                logits = model.compute_logits(eparams, g)
                d, ids_j, p_j = sample_draft_step(logits, temps, seeds,
                                                  j + 17)
                drafts.append(d)
                ids_l.append(ids_j)
                probs_l.append(p_j)
                tok, h, pos = d, g, pos + 1
            return (kv_caches, jnp.stack(drafts, axis=1),
                    jnp.stack(ids_l, axis=1), jnp.stack(probs_l, axis=1))

        return propose

    # ------------------------------------------------------------------
    def propose_batch(self, kv_caches: dict, entries: list,
                      hidden_sel: jax.Array, temps: np.ndarray,
                      seeds: np.ndarray, block_table: np.ndarray,
                      num_blocks: np.ndarray):
        """entries: (req_id, flat_hidden_row, last_token, last_pos) per
        eligible request. Returns (updated caches, drafts per request,
        support metadata per request)."""
        n = len(entries)
        R = pad_to_bucket(n, self.req_buckets)
        idx = np.zeros((R, ), np.int32)
        tok = np.zeros((R, ), np.int32)
        pos = np.zeros((R, ), np.int32)
        temps_a = np.zeros((R, ), np.float32)
        seeds_a = np.zeros((R, ), np.int64)
        bt = np.zeros((R, block_table.shape[1]), np.int32)
        nb = np.zeros((R, ), np.int32)
        for i, (_rid, flat, t, p) in enumerate(entries):
            idx[i], tok[i], pos[i] = flat, t, p
        temps_a[:n] = temps
        seeds_a[:n] = seeds
        bt[:n] = block_table
        nb[:n] = num_blocks
        h_tgt = hidden_sel[jnp.asarray(idx)]
        kv_caches, drafts, q_ids, q_probs = self._propose_fn(
            self.eparams, kv_caches, h_tgt, jnp.asarray(tok),
            jnp.asarray(pos), jnp.asarray(bt), jnp.asarray(nb),
            jnp.asarray(temps_a), jnp.asarray(seeds_a),
            jnp.asarray([n], np.int32), R=R)
        drafts = np.asarray(drafts)
        meta = list(zip(np.asarray(q_ids), np.asarray(q_probs)))
        return (kv_caches,
                [[int(t) for t in drafts[i]] for i in range(n)],
                meta[:n])

    def precompile(self, kv_caches: dict, hidden_size, dtype,
                   pages_per_req: int) -> tuple:
        """Warm the propose graph per R bucket (with the serving block
        table width so no shape leaks); returns (kv_caches, n) — the
        caches are donated through each call."""
        n = 0
        for R in self.req_buckets:
            kv_caches, d, _, _ = self._propose_fn(
                self.eparams, kv_caches,
                jnp.zeros((R, hidden_size), dtype),
                jnp.zeros((R, ), jnp.int32),
                jnp.zeros((R, ), jnp.int32),
                jnp.zeros((R, pages_per_req), jnp.int32),
                jnp.zeros((R, ), jnp.int32),
                jnp.zeros((R, ), jnp.float32),
                jnp.zeros((R, ), jnp.int64),
                jnp.zeros((1, ), jnp.int32), R=R)
            jax.block_until_ready(d)
            n += 1
        return kv_caches, n

    eparams: Optional[dict] = None  # placed by the runner after load
