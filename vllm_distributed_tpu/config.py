"""Configuration system.

Mirrors the reference's single-aggregate design (vllm/config.py:4364
``VllmConfig`` holding ~15 sub-configs, each a validated dataclass) but is
TPU-native: parallelism is expressed as mesh axis sizes (data/pipe/model/
token/expert) that map onto a ``jax.sharding.Mesh``, and cache sizing speaks
HBM pages instead of CUDA blocks.

The aggregate ``EngineConfig`` is passed down through every layer as one
object, exactly like the reference's ``VllmConfig``.
"""

import hashlib
import os
from dataclasses import dataclass, field
from typing import Any, Optional

from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.utils import cdiv

logger = init_logger(__name__)

# ---------------------------------------------------------------------------
# ModelConfig (reference: vllm/config.py:230)
# ---------------------------------------------------------------------------


@dataclass
class ModelConfig:
    """Which model to run and how to interpret its checkpoint."""

    model: str = "meta-llama/Meta-Llama-3-8B"
    tokenizer: Optional[str] = None
    # Skip tokenizer loading; prompts/outputs are token ids only
    # (reference: vllm/config.py ModelConfig.skip_tokenizer_init).
    skip_tokenizer_init: bool = False
    trust_remote_code: bool = False
    dtype: str = "bfloat16"  # bfloat16 | float32 (TPU-native dtypes)
    # Quantization: None (full precision), weight-only "int4" / "int8" /
    # "fp8", "w8a8" (int8 weights + dynamic int8 activations), or
    # "int4g" (group-wise asymmetric uint4, group 128 — the scheme that
    # preserves GPTQ/AWQ checkpoints' group structure losslessly;
    # "gptq"/"awq" are accepted aliases) (reference:
    # quantization/tpu_int8.py + fp8 configs + gptq_marlin serving).
    quantization: Optional[str] = None
    seed: int = 0
    max_model_len: Optional[int] = None
    # Overrides applied on top of the HF config (tests use this to build tiny
    # models without a checkpoint on disk).
    hf_overrides: dict[str, Any] = field(default_factory=dict)
    # Populated lazily by maybe_load_hf_config().
    hf_config: Any = None

    def __post_init__(self) -> None:
        if self.tokenizer is None:
            self.tokenizer = self.model
        if self.dtype not in ("bfloat16", "float32", "float16"):
            raise ValueError(f"unsupported dtype {self.dtype!r}")
        if self.quantization in ("gptq", "awq"):
            self.quantization = "int4g"
        if self.quantization not in (None, "int4", "int8", "fp8",
                                     "w8a8", "int4g"):
            raise ValueError(
                f"unsupported quantization {self.quantization!r} "
                "(supported: int4, int4g/gptq/awq, int8, fp8, w8a8)")

    def maybe_load_hf_config(self) -> Any:
        """Load (and cache) the HF config for the model.

        Non-path model names normally resolve via the HF hub; when the hub
        is unreachable (air-gapped TPU pods, CI) and ``hf_overrides``
        describes the architecture, fall back to a LlamaConfig built purely
        from the overrides so dummy-weight runs never need the network.
        """
        if self.hf_config is None:
            if self.model.endswith(".gguf"):
                # Single-file GGUF: the architecture config lives in
                # the file's own metadata (reference: gguf_loader.py).
                from transformers import LlamaConfig

                from vllm_distributed_tpu.models.gguf import (
                    hf_config_dict_from_gguf, read_gguf)
                meta, tensors = read_gguf(self.model)
                cfg = hf_config_dict_from_gguf(meta, tensors)
                cfg.update(self.hf_overrides)
                self.hf_config = LlamaConfig(**cfg)
                return self.hf_config
            try:
                from transformers import AutoConfig
                try:
                    # Local path / populated cache first: skips minutes of
                    # hub-retry backoff on air-gapped hosts.
                    hf_config = AutoConfig.from_pretrained(
                        self.model, trust_remote_code=self.trust_remote_code,
                        local_files_only=True)
                except Exception:
                    hf_config = AutoConfig.from_pretrained(
                        self.model,
                        trust_remote_code=self.trust_remote_code)
            except Exception:
                # Only fall back when the overrides actually pin down the
                # architecture — a partial override on top of LlamaConfig
                # defaults would silently run a different model.
                required = {"vocab_size", "hidden_size",
                            "num_hidden_layers", "num_attention_heads"}
                if not required.issubset(self.hf_overrides):
                    raise
                from transformers import LlamaConfig
                logger.warning(
                    "could not resolve HF config for %r; building a "
                    "LlamaConfig from hf_overrides", self.model)
                hf_config = LlamaConfig()
            for k, v in self.hf_overrides.items():
                setattr(hf_config, k, v)
            self.hf_config = hf_config
        if self.max_model_len is None:
            derived = getattr(self.hf_config, "max_position_embeddings", 2048)
            self.max_model_len = int(derived)
        return self.hf_config

    # -- Introspection helpers used by the worker/scheduler ---------------
    def get_vocab_size(self) -> int:
        return int(self.maybe_load_hf_config().vocab_size)

    def get_hidden_size(self) -> int:
        return int(self.maybe_load_hf_config().hidden_size)

    def get_num_layers(self) -> int:
        return int(self.maybe_load_hf_config().num_hidden_layers)

    def get_num_attention_heads(self) -> int:
        return int(self.maybe_load_hf_config().num_attention_heads)

    def get_num_kv_heads(self) -> int:
        cfg = self.maybe_load_hf_config()
        return int(
            getattr(cfg, "num_key_value_heads", cfg.num_attention_heads))

    def get_head_size(self) -> int:
        cfg = self.maybe_load_hf_config()
        if getattr(cfg, "head_dim", None) is not None:
            return int(cfg.head_dim)
        return cfg.hidden_size // cfg.num_attention_heads


# ---------------------------------------------------------------------------
# CacheConfig (reference: vllm/config.py:1511)
# ---------------------------------------------------------------------------


@dataclass
class CacheConfig:
    """Paged-KV-cache geometry and sizing."""

    # Tokens per KV page. On TPU the page size interacts with the ragged
    # paged attention kernel's block shapes; multiples of 16 keep bf16 tiles
    # aligned (reference TPU backend pads similarly: v1/attention/backends/
    # pallas.py:71-76 derives min page size from SMEM budget).
    block_size: int = 16
    # Fraction of device HBM the engine may use (weights + KV + workspace).
    gpu_memory_utilization: float = 0.90
    # Explicit page count override (None -> profiled at startup).
    num_gpu_blocks_override: Optional[int] = None
    # Number of pages decided at init time (set by the engine after
    # profiling, like determine_available_memory in the reference).
    num_gpu_blocks: Optional[int] = None
    enable_prefix_caching: bool = True
    # KV cache dtype ("auto" follows model dtype).
    cache_dtype: str = "auto"

    def __post_init__(self) -> None:
        if self.block_size <= 0:
            raise ValueError("block_size must be positive")
        if not 0.0 < self.gpu_memory_utilization <= 1.0:
            raise ValueError("gpu_memory_utilization must be in (0, 1]")


# ---------------------------------------------------------------------------
# ParallelConfig (reference: vllm/config.py:1798, incl. the fork's
# token_parallel_size at :1899)
# ---------------------------------------------------------------------------

MESH_AXIS_DATA = "data"
MESH_AXIS_PIPE = "pipe"
MESH_AXIS_MODEL = "model"
# The fork's token-parallel (TKNP) axis: extra devices that hold only KV
# cache + attention state (reference: parallel_state.py:883-913).  On TPU we
# realize it as a mesh axis that shards requests' KV across devices while
# weights live on the "model" axis only.
MESH_AXIS_TOKEN = "token"
# Expert parallelism for MoE dispatch (reference: parallel_state.py:1189).
MESH_AXIS_EXPERT = "expert"


@dataclass
class ParallelConfig:
    """Mesh geometry.

    The reference builds process groups ExternalDP x (DP|TKNP) x PP x TP
    (parallel_state.py:1116-1126); here the same axes are sizes of a single
    ``jax.sharding.Mesh`` and XLA inserts the collectives.
    """

    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    data_parallel_size: int = 1
    token_parallel_size: int = 1
    enable_expert_parallel: bool = False
    # Megatron-style sequence parallelism over the TP group (reference:
    # CompilationConfig.pass_config.enable_sequence_parallelism + the
    # sequence_parallelism.py compile pass): the residual stream is
    # constrained token-sharded on the "model" axis between blocks, so
    # XLA turns each TP all-reduce into reduce-scatter + all-gather and
    # norms/elementwise run on 1/tp of the tokens. GSPMD does the
    # rewrite the reference implements as a custom torch.fx pass.
    enable_sequence_parallel: bool = False
    # EPLB: extra physical expert slots hosting replicas of hot experts
    # (reference: ParallelConfig num_redundant_experts + eplb config).
    num_redundant_experts: int = 0
    # How data parallelism is realized (reference: one DPEngineCoreProc
    # per DP rank behind a balancing coordinator, v1/engine/core.py:812 +
    # coordinator.py:21):
    #  - "engine": data_parallel_size full engine replicas (scheduler +
    #    KV pool + mesh slice each) behind a balancing front-end client.
    #    The serving path. Replicas share no collectives, so the
    #    reference's lockstep dummy batches / wave sync are unnecessary
    #    by construction (EP spans the model axis inside one replica,
    #    never the data axis across replicas).
    #  - "mesh": a single engine whose mesh carries a "data" axis and
    #    shards the batch SPMD (the dryrun/training-style layout).
    data_parallel_mode: str = "engine"
    # This replica's rank under "engine" mode (set by the DP front-end;
    # selects the replica's device slice).
    data_parallel_rank: int = 0
    # Explicit first-device index of this replica's slice (set by the
    # disagg pool planner when pools have asymmetric TP degrees, so
    # replica world sizes differ and rank * world_size no longer
    # addresses the right devices). None = legacy rank-based slicing.
    data_parallel_device_offset: Optional[int] = None
    # Route DP requests through a separate coordinator PROCESS (the
    # reference's DPCoordinator, v1/engine/coordinator.py) instead of
    # front-end-local accounting — the serving-plane scale-out hook.
    data_parallel_coordinator: bool = False
    # Run the engine core (scheduler + executor busy loop) in its own
    # process with ZMQ transport (reference: EngineCoreProc, core.py:362).
    multiprocess_engine_core: bool = False
    # Multi-host SPMD (reference boundary: one worker process per host,
    # v1/executor/multiproc_executor.py:42 + StatelessProcessGroup
    # bootstrap, distributed/utils.py:138; JAX analogue:
    # jax.distributed.initialize + one controller process per host whose
    # jax.devices() spans the whole pod). num_hosts > 1 makes the worker
    # initialize the distributed runtime before touching devices.
    num_hosts: int = 1
    host_rank: int = 0
    # coordinator "ip:port" (host 0); None lets JAX auto-detect on TPU
    # pods (GCE metadata).
    coordinator_address: Optional[str] = None
    # ZMQ endpoint host 0 binds for SchedulerOutput broadcast to
    # follower hosts (e.g. "tcp://0.0.0.0:5560"); required when
    # num_hosts > 1 with the MultiHostExecutor.
    broadcast_addr: Optional[str] = None
    # Multi-host: processes per pod slice (jax.distributed).
    distributed_init_method: Optional[str] = None

    def __post_init__(self) -> None:
        for name in ("tensor_parallel_size", "pipeline_parallel_size",
                     "data_parallel_size", "token_parallel_size"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1")
        if self.data_parallel_mode not in ("engine", "mesh"):
            raise ValueError(
                f"data_parallel_mode must be 'engine' or 'mesh', got "
                f"{self.data_parallel_mode!r}")
        if self.token_parallel_size > 1 and self.data_parallel_size > 1:
            # Mirrors the reference's DP|TKNP exclusivity
            # (parallel_state.py:1116-1126).
            raise ValueError(
                "token parallelism and data parallelism are mutually "
                "exclusive")

    @property
    def world_size(self) -> int:
        return (self.tensor_parallel_size * self.pipeline_parallel_size *
                self.data_parallel_size * self.token_parallel_size)

    @property
    def mesh_shape(self) -> dict[str, int]:
        """Axis-name -> size for the device mesh (order matters: outermost
        axes map to DCN, innermost to ICI)."""
        return {
            MESH_AXIS_DATA: self.data_parallel_size,
            MESH_AXIS_TOKEN: self.token_parallel_size,
            MESH_AXIS_PIPE: self.pipeline_parallel_size,
            MESH_AXIS_MODEL: self.tensor_parallel_size,
        }


# ---------------------------------------------------------------------------
# SchedulerConfig (reference: vllm/config.py:2139)
# ---------------------------------------------------------------------------


@dataclass
class SchedulerConfig:
    """Continuous-batching budget knobs."""

    max_num_batched_tokens: int = 8192
    max_num_seqs: int = 256
    max_model_len: int = 8192
    enable_chunked_prefill: bool = True
    # Requests with more than this many prompt tokens remaining are
    # considered "long" and capped per step (reference:
    # sched/scheduler.py:457 long_prefill_token_threshold).
    long_prefill_token_threshold: int = 0
    policy: str = "fcfs"  # fcfs | priority
    # Fused decode steps per host roundtrip (reference: V0 multi-step
    # scheduling / --num-scheduler-steps; on TPU the burst is one jitted
    # lax.scan, see worker/model_runner.py). 1 disables.
    num_scheduler_steps: int = 1
    # Total encoder (vision) output tokens admitted concurrently
    # (reference: encoder_cache_size / max_num_encoder_input_tokens,
    # v1/core/encoder_cache_manager.py); image requests past the budget
    # wait.
    encoder_cache_budget: int = 8192
    # Async scheduling (reference: the V1 --async-scheduling path):
    # overlap host scheduling/input-prep with device execution by
    # keeping a depth-2 batch pipeline in flight on the non-PP path.
    # The scheduler grants step N+1 (advancing each running request by
    # one speculative position) while step N executes; the runner
    # chains decode input tokens device-to-device so the host never
    # round-trips sampled tokens on the hot path. Stop/EOS detection
    # lags one step (the over-issued position's work is discarded).
    # Auto-disabled (see EngineConfig.__post_init__) with pipeline
    # parallelism, speculative decoding, multi-step bursts, KV-transfer
    # connectors, token parallelism, and multi-host execution; requests
    # needing host-synchronous sampling state (structured output,
    # prompt_logprobs, pooling, penalties/min-tokens) individually fall
    # back to synchronous one-step-lag scheduling.
    async_scheduling: bool = False

    def __post_init__(self) -> None:
        if self.policy not in ("fcfs", "priority"):
            raise ValueError(f"unknown scheduling policy {self.policy!r}")
        if not self.enable_chunked_prefill:
            # Without chunked prefill a whole prompt must fit in one step.
            self.max_num_batched_tokens = max(self.max_num_batched_tokens,
                                              self.max_model_len)


# ---------------------------------------------------------------------------
# Remaining sub-configs
# ---------------------------------------------------------------------------


@dataclass
class DeviceConfig:
    """Which JAX platform to run on ("auto" picks TPU when present)."""

    device: str = "auto"  # auto | tpu | cpu


@dataclass
class LoadConfig:
    """Weight loading (reference: vllm/config.py:1711 + model_loader/)."""

    # auto | safetensors | dummy | sharded_state (orbax tree saved by
    # save_sharded_state; model still names the HF dir for the config).
    load_format: str = "auto"
    download_dir: Optional[str] = None
    # Directory of the orbax tree for load_format="sharded_state"
    # (defaults to model_config.model).
    sharded_state_path: Optional[str] = None


@dataclass
class SpeculativeConfig:
    """Speculative decoding (reference: vllm/config.py:2502)."""

    method: Optional[str] = None  # ngram | draft_model | eagle | None
    num_speculative_tokens: int = 0
    # ngram proposer window (reference: v1/spec_decode/ngram_proposer.py).
    prompt_lookup_max: int = 4
    prompt_lookup_min: int = 1
    # draft_model proposer (reference: the draft-model speculative path,
    # vllm/v1/spec_decode/eagle.py + config.py SpeculativeConfig.model):
    # local checkpoint of a small causal LM proposing k greedy tokens,
    # verified in-step by the existing S+1-position sampler.
    model: Optional[str] = None
    # Context window the draft sees (stateless re-prefill of the last
    # W tokens each proposal — no second paged cache to manage; RoPE
    # scores depend on relative distance so the window offset is sound).
    draft_window: int = 32


@dataclass
class KVTransferConfig:
    """Disaggregated prefill/decode (reference: vllm/config.py:3826)."""

    kv_connector: Optional[str] = None
    kv_role: Optional[str] = None  # kv_producer | kv_consumer | kv_both
    kv_connector_extra_config: dict[str, Any] = field(default_factory=dict)
    # Disaggregated serving tier (engine/disagg.py): which pool this
    # engine replica belongs to — "prefill" | "decode" | None
    # (monolithic). Read by the model runner to prune the precompile
    # lattice per role (a prefill replica never warms decode-burst or
    # fused-block graph variants; a decode replica's token ladder is
    # capped by its pool config).
    pool_role: Optional[str] = None

    @property
    def is_kv_producer(self) -> bool:
        return self.kv_role in ("kv_producer", "kv_both")

    @property
    def is_kv_consumer(self) -> bool:
        return self.kv_role in ("kv_consumer", "kv_both")


@dataclass
class KVEventsConfig:
    """ZMQ publishing of prefix-cache block events for external routers
    (reference: vllm/config.py:3922 KVEventsConfig +
    distributed/kv_events.py)."""

    enable_kv_cache_events: bool = False
    endpoint: str = "tcp://127.0.0.1:5557"
    replay_endpoint: Optional[str] = None
    buffer_steps: int = 1000


@dataclass
class LoRAConfig:
    """Multi-LoRA serving (reference: vllm/config.py:2999 LoRAConfig).

    Static-shape discipline: ``max_loras`` adapter SLOTS of fixed
    ``max_lora_rank`` are baked into the compiled graphs (slot 0 is the
    always-zero "no adapter" slot); adapters hot-swap by writing slot
    buffers, never by recompiling."""

    enable_lora: bool = False
    max_loras: int = 4
    max_lora_rank: int = 16

    def __post_init__(self) -> None:
        if self.enable_lora:
            if self.max_loras < 1:
                raise ValueError("max_loras must be >= 1")
            if self.max_lora_rank < 1:
                raise ValueError("max_lora_rank must be >= 1")


@dataclass
class ObservabilityConfig:
    """Tracing/metrics switches (reference: vllm/config.py:3735)."""

    otlp_traces_endpoint: Optional[str] = None
    collect_detailed_traces: bool = False


@dataclass
class FaultToleranceConfig:
    """Timeouts and retry budgets for the fault-tolerance layer: the
    scheduler's remote-KV watchdog, the KV-transfer retry policy, and
    the engine-core health monitor. Degradation order for a failed
    remote pull: retry the pull (bounded) -> local prefill recompute ->
    request error; an unresponsive engine core fails pending requests
    with EngineDeadError instead of blocking forever."""

    # Watchdog: max seconds a request may sit in WAITING_FOR_REMOTE_KVS
    # before the sweep fails the pull (0 disables the sweep).
    kv_pull_timeout_s: float = 120.0
    # Request-level pull retries before degrading to local recompute.
    kv_pull_max_retries: int = 1
    # Socket-level retry policy for one pull / registry call.
    retry_max_attempts: int = 3
    retry_base_delay_s: float = 0.05
    retry_max_delay_s: float = 2.0
    # Backstop (seconds) after which pages parked for a timed-out,
    # still-in-flight pull are reclaimed even without a worker report.
    # Safe regardless of transfer duration: the sweep issues a
    # cancel_pull, and the worker discards (never applies) a transfer
    # whose id was cancelled — the backstop only covers connectors/
    # pulls that never report at all.
    kv_pull_abandon_timeout_s: float = 240.0
    # Engine-core liveness: heartbeat send period (0 disables the
    # beater) and the staleness window after which the client declares
    # the core dead. The window is deliberately generous by default —
    # first-compile stalls are legitimate; tests tighten it.
    heartbeat_interval_s: float = 1.0
    heartbeat_timeout_s: float = 300.0
    # Restart supervisor: when the health monitor declares a core dead,
    # respawn it with exponential backoff, at most restart_max_attempts
    # times within restart_window_s before circuit-breaking to the
    # terminal EngineDeadError (0 attempts disables recovery — death
    # stays terminal, the pre-supervisor behavior).
    restart_max_attempts: int = 3
    restart_window_s: float = 300.0
    restart_backoff_base_s: float = 0.5
    restart_backoff_max_s: float = 30.0
    # DP failover: how often the front-end probes a downed replica for
    # resurrection (0 disables probing — a failed-over replica stays
    # out of rotation for the process lifetime).
    replica_probe_interval_s: float = 10.0

    def __post_init__(self) -> None:
        if (self.kv_pull_timeout_s < 0 or self.heartbeat_interval_s < 0
                or self.heartbeat_timeout_s < 0
                or self.kv_pull_abandon_timeout_s < 0
                or self.retry_base_delay_s < 0
                or self.retry_max_delay_s < 0
                or self.restart_window_s < 0
                or self.restart_backoff_base_s < 0
                or self.restart_backoff_max_s < 0
                or self.replica_probe_interval_s < 0):
            raise ValueError("fault-tolerance timeouts must be >= 0")
        if self.kv_pull_max_retries < 0:
            raise ValueError("kv_pull_max_retries must be >= 0")
        if self.restart_max_attempts < 0:
            raise ValueError("restart_max_attempts must be >= 0")
        if self.retry_max_attempts < 1:
            # 0 would make every retried IO call fail without a single
            # attempt ("no retries" is retry_max_attempts=1).
            raise ValueError("retry_max_attempts must be >= 1")
        if self.heartbeat_interval_s == 0 and self.heartbeat_timeout_s > 0:
            # No beater -> the client-side staleness window would fire
            # on any quiet-but-healthy stretch; disable it together.
            logger.warning("heartbeat_interval_s=0 disables the beater; "
                           "disabling heartbeat_timeout_s with it")
            self.heartbeat_timeout_s = 0.0


# ---------------------------------------------------------------------------
# Aggregate
# ---------------------------------------------------------------------------


@dataclass
class EngineConfig:
    """The one object passed through every layer (reference: VllmConfig,
    vllm/config.py:4364)."""

    model_config: ModelConfig = field(default_factory=ModelConfig)
    cache_config: CacheConfig = field(default_factory=CacheConfig)
    parallel_config: ParallelConfig = field(default_factory=ParallelConfig)
    scheduler_config: SchedulerConfig = field(default_factory=SchedulerConfig)
    device_config: DeviceConfig = field(default_factory=DeviceConfig)
    load_config: LoadConfig = field(default_factory=LoadConfig)
    speculative_config: SpeculativeConfig = field(
        default_factory=SpeculativeConfig)
    kv_transfer_config: KVTransferConfig = field(
        default_factory=KVTransferConfig)
    lora_config: LoRAConfig = field(default_factory=LoRAConfig)
    kv_events_config: KVEventsConfig = field(
        default_factory=KVEventsConfig)
    observability_config: ObservabilityConfig = field(
        default_factory=ObservabilityConfig)
    fault_tolerance_config: FaultToleranceConfig = field(
        default_factory=FaultToleranceConfig)

    def __post_init__(self) -> None:
        # Clamp scheduler limits to the model context window once known,
        # re-applying the non-chunked-prefill invariant (a whole prompt
        # must fit in one step's budget) on the updated value.
        if self.model_config.max_model_len is not None:
            self.scheduler_config.max_model_len = \
                self.model_config.max_model_len
            if not self.scheduler_config.enable_chunked_prefill:
                self.scheduler_config.max_num_batched_tokens = max(
                    self.scheduler_config.max_num_batched_tokens,
                    self.scheduler_config.max_model_len)
        for reason, incompatible in (
                # The fused multi-step burst cannot refresh per-rank
                # token-parallel metadata on device.
                ("token parallelism",
                 self.parallel_config.token_parallel_size > 1),
                # The fused burst is a single-program graph; the staged
                # pipeline replaces it.
                ("pipeline parallelism",
                 self.parallel_config.pipeline_parallel_size > 1),
                # Connector load/save hooks run at step boundaries; the
                # fused burst would silently skip them.
                ("a KV-transfer connector",
                 bool(self.kv_transfer_config.kv_connector)),
                # The burst's scanned decode graph carries no per-token
                # adapter slots.
                ("LoRA", self.lora_config.enable_lora),
                # The burst calls the target forward alone — EAGLE's
                # in-step draft-KV advance would be skipped, starving
                # the drafter of context.
                ("EAGLE speculative decoding",
                 self.speculative_config is not None
                 and self.speculative_config.method == "eagle"),
        ):
            if incompatible and self.scheduler_config.num_scheduler_steps > 1:
                logger.warning(
                    "num_scheduler_steps=%d is incompatible with %s; "
                    "forcing single-step scheduling",
                    self.scheduler_config.num_scheduler_steps, reason)
                self.scheduler_config.num_scheduler_steps = 1
        if self.scheduler_config.async_scheduling:
            for reason, incompatible in (
                    # The PP batch queue already pipelines microbatches;
                    # layering speculative grants on top would re-grant
                    # stage-straddling requests.
                    ("pipeline parallelism (the PP batch queue already "
                     "overlaps)",
                     self.parallel_config.pipeline_parallel_size > 1),
                    # Draft tokens round-trip through the host between
                    # steps (propose -> schedule -> verify).
                    ("speculative decoding",
                     self.speculative_config is not None
                     and self.speculative_config.method is not None),
                    # The fused burst is the deeper device-side answer to
                    # the same host gap; both at once would double-grant.
                    ("multi-step decode bursts (num_scheduler_steps > 1)",
                     self.scheduler_config.num_scheduler_steps > 1),
                    # Connector load/save + deferred-free hooks assume
                    # step-synchronous page ownership.
                    ("a KV-transfer connector",
                     bool(self.kv_transfer_config.kv_connector)),
                    # Per-rank pool accounting under speculative grants is
                    # unvalidated; keep the TKNP path synchronous.
                    ("token parallelism",
                     self.parallel_config.token_parallel_size > 1),
                    # The broadcast executor has no async dispatch path.
                    ("multi-host execution",
                     self.parallel_config.num_hosts > 1),
            ):
                if incompatible:
                    logger.warning(
                        "async scheduling is incompatible with %s; "
                        "falling back to synchronous stepping", reason)
                    self.scheduler_config.async_scheduling = False
                    break
        override = self.cache_config.num_gpu_blocks_override
        tknp = self.parallel_config.token_parallel_size
        if override and tknp > 1 and (override % tknp or override < tknp):
            raise ValueError(
                f"num_gpu_blocks_override={override} must be a positive "
                f"multiple of token_parallel_size={tknp}")
        if (self.speculative_config is not None
                and self.speculative_config.method == "eagle"
                and self.parallel_config.pipeline_parallel_size > 1):
            raise ValueError(
                "EAGLE speculative decoding is not supported with "
                "pipeline parallelism (the draft layers stack onto the "
                "single-program cache; stage-sliced caches don't carry "
                "them)")
        if (self.speculative_config is not None
                and self.speculative_config.method == "eagle"
                and self.parallel_config.token_parallel_size > 1):
            raise ValueError(
                "EAGLE speculative decoding is not supported with "
                "token parallelism (the propose path reads the draft "
                "cache without the per-rank TKNP metadata)")

    def compute_hash(self) -> str:
        """Stable hash of the config for compilation-cache keys."""
        parts = repr((self.model_config, self.cache_config,
                      self.parallel_config, self.scheduler_config))
        return hashlib.sha256(parts.encode()).hexdigest()[:16]

    @property
    def max_pages_per_req(self) -> int:
        return cdiv(self.scheduler_config.max_model_len,
                    self.cache_config.block_size)


_current_engine_config: list[EngineConfig] = []


def get_current_engine_config() -> Optional[EngineConfig]:
    """Contextvar-style accessor so deep code can read the config without
    threading it (reference: get_current_vllm_config,
    parallel_state.py:1087-1093)."""
    return _current_engine_config[-1] if _current_engine_config else None


class set_current_engine_config:
    def __init__(self, config: EngineConfig) -> None:
        self.config = config

    def __enter__(self) -> EngineConfig:
        _current_engine_config.append(self.config)
        return self.config

    def __exit__(self, *args) -> None:
        _current_engine_config.pop()
