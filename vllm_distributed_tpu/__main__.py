"""`python -m vllm_distributed_tpu serve|bench ...` (reference: the
`vllm` console script -> entrypoints/cli/main.py:23)."""

import sys

from vllm_distributed_tpu.entrypoints.cli.main import main

if __name__ == "__main__":
    sys.exit(main())
