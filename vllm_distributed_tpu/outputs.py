"""User-facing request outputs (reference: vllm/outputs.py)."""

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class CompletionOutput:
    """One generated completion for a request."""

    index: int
    text: str
    token_ids: list[int]
    cumulative_logprob: Optional[float] = None
    logprobs: Optional[list[dict[int, float]]] = None
    finish_reason: Optional[str] = None  # "stop" | "length" | "abort"
    stop_reason: Optional[int | str] = None

    def finished(self) -> bool:
        return self.finish_reason is not None


@dataclass
class RequestOutput:
    """Aggregated output returned from LLMEngine.step() / AsyncLLM.generate."""

    request_id: str
    prompt: Optional[str]
    prompt_token_ids: list[int]
    outputs: list[CompletionOutput]
    finished: bool
    metrics: Optional[dict] = None
    num_cached_tokens: int = 0
    # Disaggregated prefill: a producer's final output carries the pull
    # coordinates the decode-side request needs (reference: vllm/outputs.py
    # RequestOutput.kv_transfer_params).
    kv_transfer_params: Optional[dict] = None
    # Per-prompt-token logprob dicts when SamplingParams.prompt_logprobs
    # was set: entry 0 is None, entry i maps token_id -> logprob of
    # prompt[i] given the prefix (reference: vllm/outputs.py
    # RequestOutput.prompt_logprobs).
    prompt_logprobs: Optional[list] = None

    @property
    def text(self) -> str:
        return self.outputs[0].text if self.outputs else ""


@dataclass
class PoolingOutput:
    """Embedding/pooling result (reference: vllm/outputs.py pooling path)."""

    request_id: str
    embedding: list[float] = field(default_factory=list)
    num_prompt_tokens: int = 0
    finished: bool = True
