"""Environment variable registry.

Mirrors the reference's vllm/envs.py (125 lazily-evaluated VLLM_* vars): every
framework knob that is not part of EngineArgs lives here, is lazily evaluated
at first access, and is documented in one place. Prefix is VDT_ (and we also
honor the corresponding VLLM_ spelling for drop-in compatibility where the
semantic matches).
"""

import os
from typing import Any, Callable

environment_variables: dict[str, Callable[[], Any]] = {
    # Logging level for the framework's logger tree (DEBUG/INFO/WARNING...).
    "VDT_LOGGING_LEVEL":
    lambda: os.getenv("VDT_LOGGING_LEVEL", os.getenv("VLLM_LOGGING_LEVEL", "INFO")).upper(),
    # Optional prefix prepended to every log line.
    "VDT_LOGGING_PREFIX":
    lambda: os.getenv("VDT_LOGGING_PREFIX", os.getenv("VLLM_LOGGING_PREFIX", "")),
    # Use the pure-XLA reference attention instead of the Pallas kernels
    # (debugging / CPU execution).
    "VDT_ATTENTION_BACKEND":
    lambda: os.getenv("VDT_ATTENTION_BACKEND", "auto"),  # auto|pallas|xla
    # MoE compute path: grouped ragged_dot dispatch (default) or the
    # all-expert einsum baseline (A/B + FLOP regression tests).
    "VDT_MOE_BACKEND":
    lambda: os.getenv("VDT_MOE_BACKEND", "ragged"),  # ragged|dense
    # Expert-parallel dispatch mechanism: "a2a" = token-sharded
    # all-to-all rows to expert-owner ranks (falls back automatically
    # when inapplicable, e.g. EPLB replicas or indivisible buckets);
    # "replicate" forces the replicate+psum path.
    "VDT_MOE_EP_MODE":
    lambda: os.getenv("VDT_MOE_EP_MODE", "a2a"),
    # Max KV pages a finished pull applies to the cache per engine step
    # (the donated scatter runs on the scheduling thread; chunking keeps
    # any single step's apply bounded so co-resident decode latency
    # doesn't spike while a large pull lands).
    "VDT_KV_APPLY_CHUNK_PAGES":
    lambda: max(1, int(os.getenv("VDT_KV_APPLY_CHUNK_PAGES", "64"))),
    # JAX platform to pin before backend init ("auto" = JAX default).
    # Setting "cpu" defeats a TPU plugin whose init can hang for minutes
    # on hosts where the chip is tunnelled (reference analogue: the
    # platforms/ device plumbing; see worker.init_device).
    # Platform pin applied via jax.config BEFORE backend init. Falls
    # back to a single-platform JAX_PLATFORMS value so SPAWNED engine
    # cores inherit the parent's pin through the environment: some
    # installed accelerator plugins ignore the JAX_PLATFORMS env var
    # itself, and an un-pinned child would hang initializing a tunnelled
    # TPU the parent deliberately avoided.
    "VDT_PLATFORM":
    lambda: os.getenv(
        "VDT_PLATFORM",
        os.getenv("JAX_PLATFORMS", "auto").split(",")[0] or "auto"),
    # Seconds the bench harness waits for TPU backend init in ONE probe
    # subprocess attempt. Kept short: bench.py additionally hard-caps the
    # total probe budget (VDT_BENCH_PROBE_BUDGET, default 300 s) so the
    # probe phase can never exceed the driver's wall clock — a dead
    # tunnel must still end with a parseable CPU-fallback record.
    "VDT_TPU_PROBE_TIMEOUT":
    lambda: float(os.getenv("VDT_TPU_PROBE_TIMEOUT", "120")),
    # Precompile the full shape lattice at startup: "auto" = on for
    # accelerator platforms, off on CPU; "1"/"0" force.
    "VDT_PRECOMPILE":
    lambda: os.getenv("VDT_PRECOMPILE", "auto"),
    # Raise (instead of warn) if a serving step compiles a new XLA graph
    # after precompile warm-up (recompile-storm guard; used in tests).
    "VDT_ASSERT_NO_RECOMPILE":
    lambda: os.getenv("VDT_ASSERT_NO_RECOMPILE", "0") == "1",
    # Force the engine core into a subprocess regardless of config
    # (reference: VLLM_ENABLE_V1_MULTIPROCESSING).
    "VDT_ENABLE_MP_ENGINE":
    lambda: os.getenv("VDT_ENABLE_MP_ENGINE", "0") == "1",
    # Run Pallas kernels in interpret mode (CPU tests).
    "VDT_PALLAS_INTERPRET":
    lambda: os.getenv("VDT_PALLAS_INTERPRET", "0") == "1",
    # Fuse the per-layer KV-page write into the attention mega-kernel
    # (one pass over the cache per mixed step) when the layout permits;
    # "0" keeps the separate write-then-attend pair for debugging.
    "VDT_FUSED_KV_WRITE":
    lambda: os.getenv("VDT_FUSED_KV_WRITE", "1") == "1",
    # Fused transformer-block decode (ops/pallas_block.py): decode-only
    # waves on an eligible dense model run each layer as ONE Pallas call
    # (RMSNorm -> fused QKV -> rope + KV-page write + attention ->
    # O-proj -> residual -> RMSNorm -> gated MLP -> residual), keeping
    # activations in VMEM across the layer. Default OFF until the parity
    # gates pin it; "0" reverts wholesale to the per-op mega-kernel
    # path. Eligibility is decided ONCE in models/loader.py (arch shape,
    # TP=1); read at model load.
    "VDT_BLOCK_FUSION":
    lambda: os.getenv("VDT_BLOCK_FUSION", "0") == "1",
    # Fraction of HBM usable for weights+KV (analogue of gpu_memory_utilization
    # default source).
    "VDT_MEMORY_FRACTION":
    lambda: float(os.getenv("VDT_MEMORY_FRACTION", "0.9")),
    # Directory for JAX persistent compilation cache ("" disables).
    "VDT_XLA_CACHE_DIR":
    lambda: os.getenv("VDT_XLA_CACHE_DIR",
                      os.path.expanduser("~/.cache/vdt_xla_cache")),
    # RPC timeout (seconds) for engine-core client handshakes.
    "VDT_RPC_TIMEOUT":
    lambda: float(os.getenv("VDT_RPC_TIMEOUT", "600")),
    # Port for the ZMQ engine-core transport (0 = auto).
    "VDT_ENGINE_CORE_PORT":
    lambda: int(os.getenv("VDT_ENGINE_CORE_PORT", "0")),
    # API key for the OpenAI server ("" disables auth).
    "VDT_API_KEY":
    lambda: os.getenv("VDT_API_KEY", os.getenv("VLLM_API_KEY", "")),
    # Host IP override used for distributed bootstrap.
    "VDT_HOST_IP":
    lambda: os.getenv("VDT_HOST_IP", os.getenv("VLLM_HOST_IP", "")),
    # jax.profiler trace output directory for the profile RPC
    # (reference: VLLM_TORCH_PROFILER_DIR).
    "VDT_PROFILER_DIR":
    lambda: os.getenv("VDT_PROFILER_DIR", "/tmp/vdt_profile"),
    # Hardened profiler capture window: seconds after which an
    # unstopped jax.profiler trace is force-stopped by the engine core
    # (a wedged xprof client must never wedge serving; the
    # perf.capture_stall fault drill pins this).
    "VDT_PROFILE_MAX_S":
    lambda: float(os.getenv("VDT_PROFILE_MAX_S", "120")),
    # Performance-attribution plane (metrics/costmodel.py): "1" builds
    # the analytic per-dispatch cost model at model load and charges
    # every runner dispatch with FLOPs/HBM bytes against measured
    # device time (vdt:mfu / vdt:mbu / vdt:hbm_bytes_total /
    # vdt:roofline_bound + GET /debug/perf). "0" reverts wholesale:
    # no cost model is constructed and the runner's per-step charge is
    # a single None check.
    "VDT_PERF_ATTRIB":
    lambda: os.getenv("VDT_PERF_ATTRIB", "1") == "1",
    # Row cap of the GET /debug/perf attribution table (rows ranked by
    # device-seconds; the response reports how many were dropped).
    "VDT_PERF_TOPN":
    lambda: max(1, int(os.getenv("VDT_PERF_TOPN", "20"))),
    # Directory where multi-host follower processes publish their
    # telemetry snapshots (shm-ring read side + device stats) for host
    # 0's stats plane to fold in; "" disables the export.
    "VDT_FOLLOWER_STATS_DIR":
    lambda: os.getenv("VDT_FOLLOWER_STATS_DIR", ""),
    # Request-lifecycle event timeline (metrics/events.py): per-request
    # phase attribution (queue/kv_pull/prefill/decode/stalls) recorded
    # at lifecycle transitions and stitched into child phase spans by
    # the tracer. "0" disables all recording (bench runs both legs to
    # bound the overhead). Read ONCE per component at construction.
    "VDT_REQUEST_TIMELINE":
    lambda: os.getenv("VDT_REQUEST_TIMELINE", "1") == "1",
    # Step-phase TPU timeline capture: "1" wraps every engine-core
    # dispatch in jax.profiler.StepTraceAnnotation so a trace captured
    # via the profile RPC (dump dir: VDT_PROFILER_DIR) shows per-step
    # boundaries on the device timeline. Opt-in: the annotation costs a
    # TraceMe on the hot path.
    "VDT_PROFILE_STEPS":
    lambda: os.getenv("VDT_PROFILE_STEPS", "0") == "1",
    # Persistent XLA compilation cache directory ("" disables). On the
    # tunnelled TPU, first compiles are the dominant bench cost and the
    # tunnel can drop mid-run; caching makes retried runs resume almost
    # instantly (reference analogue: VLLM_XLA_CACHE_PATH for torch_xla).
    "VDT_COMPILE_CACHE_DIR":
    lambda: os.getenv("VDT_COMPILE_CACHE_DIR",
                      os.getenv("VLLM_XLA_CACHE_PATH",
                                "/tmp/vdt_compile_cache")),
    # Cascade (shared-prefix) attention on the XLA path: "1" enables the
    # detection + split; opt-in because it adds a second compiled
    # forward variant per shape bucket.
    "VDT_CASCADE_ATTENTION":
    lambda: os.getenv("VDT_CASCADE_ATTENTION", "0") == "1",
    # Page count of the dense shared phase (cascade fires only when the
    # batch-wide common prefix covers at least this many pages).
    "VDT_CASCADE_SHARED_PAGES":
    lambda: int(os.getenv("VDT_CASCADE_SHARED_PAGES", "4")),
    # Disable the usage-stats style telemetry (always disabled by default;
    # kept for CLI parity).
    "VDT_NO_USAGE_STATS":
    lambda: os.getenv("VDT_NO_USAGE_STATS", "1") == "1",
    # --- Cluster routing tier (engine/router.py) ------------------------
    # Prefix-affinity + SLO-aware replica placement for the DP front
    # end. "0" reverts DPEngineClient to the pure live-count round-robin
    # balancer (byte-identical to the pre-router behavior).
    "VDT_ROUTER":
    lambda: os.getenv("VDT_ROUTER", "1") == "1",
    # Freshness budget (seconds) for the per-replica stats snapshots the
    # router scores with. In-process replicas refresh synchronously on
    # the admission path once the TTL expires; subprocess replicas are
    # fed passively by the server's existing get_stats polls (/metrics,
    # admission KV sampler) — never a new channel.
    "VDT_ROUTER_STATS_TTL_S":
    lambda: float(os.getenv("VDT_ROUTER_STATS_TTL_S", "1.0")),
    # Staleness horizon: when EVERY replica's load snapshot is older
    # than this, the router degrades to pure least-loaded balancing
    # (affinity with blind load signals would herd session traffic onto
    # one replica).
    "VDT_ROUTER_STALE_S":
    lambda: float(os.getenv("VDT_ROUTER_STALE_S", "5.0")),
    # Max leading prompt pages hashed for the affinity score (bounds
    # per-admission hashing cost for very long prompts).
    "VDT_ROUTER_PREFIX_PAGES":
    lambda: max(1, int(os.getenv("VDT_ROUTER_PREFIX_PAGES", "64"))),
    # Per-replica bound on the prefix-residency index (LRU entries).
    "VDT_ROUTER_PREFIX_CAPACITY":
    lambda: max(16, int(os.getenv("VDT_ROUTER_PREFIX_CAPACITY", "8192"))),
    # Seconds a residency entry stays credible without being touched
    # (a replica under steady traffic has almost certainly recycled the
    # pages by then).
    "VDT_ROUTER_PREFIX_TTL_S":
    lambda: float(os.getenv("VDT_ROUTER_PREFIX_TTL_S", "600")),
    # Pressure (blended KV usage / queue score, 0..1) above which the
    # affinity home is overridden and the request spills to the
    # least-cost healthy replica.
    "VDT_ROUTER_SPILL_PRESSURE":
    lambda: float(os.getenv("VDT_ROUTER_SPILL_PRESSURE", "0.85")),
    # --- Disaggregated prefill/decode serving tier (engine/disagg.py) ---
    # Master switch: "1" splits a DP fleet (data_parallel_size > 1) into
    # a prefill pool (chunked-prefill producers, big token buckets) and
    # a decode pool (deep decode batches, pull consumers) with routed KV
    # handoff between them. "0" (default) keeps the monolithic DP
    # balancer byte-identical to the pre-disagg behavior.
    "VDT_DISAGG":
    lambda: os.getenv("VDT_DISAGG", "0") == "1",
    # Replicas assigned to the prefill pool (the first k DP ranks).
    # 0 = auto: half the fleet, at least 1, always leaving >= 1 decode
    # replica.
    "VDT_DISAGG_PREFILL_REPLICAS":
    lambda: max(0, int(os.getenv("VDT_DISAGG_PREFILL_REPLICAS", "0"))),
    # Decode-pool scheduler token budget (max_num_batched_tokens of the
    # decode replicas). Bounds both the decode wave depth and the
    # chunk size of the local re-prefill fallback, and therefore the
    # decode pool's precompiled token-bucket ladder. 0 = auto:
    # max(max_num_seqs, 2 * block_size), clipped to the parent budget.
    "VDT_DISAGG_DECODE_TOKENS":
    lambda: max(0, int(os.getenv("VDT_DISAGG_DECODE_TOKENS", "0"))),
    # Per-pool tensor-parallel degree (0 = inherit the parent config).
    # Asymmetric meshes work because the KV handoff rides the versioned
    # standard/latent wire formats, which re-slice on receipt.
    "VDT_DISAGG_PREFILL_TP":
    lambda: max(0, int(os.getenv("VDT_DISAGG_PREFILL_TP", "0"))),
    "VDT_DISAGG_DECODE_TP":
    lambda: max(0, int(os.getenv("VDT_DISAGG_DECODE_TP", "0"))),
    # --- Hierarchical KV/state memory (core/kv_tier.py) -----------------
    # Master switch: "1" gives the page pool a spill hierarchy — prefix
    # pages evicted from HBM demote to a bounded pinned-host-RAM pool
    # (T1), host-pool eviction demotes to disk page files (T2, the
    # shared_storage format + CRC + quantized codec under the same
    # content-addressed BlockHash keys), and WAITING requests whose
    # prefix lives in a tier promote it back before the forward. SSM
    # state-cache eviction likewise demotes snapshots to the checkpoint
    # journal instead of discarding. "0" (default) constructs no tier
    # state anywhere — byte-identical revert. Read at engine build.
    "VDT_KV_TIERING":
    lambda: os.getenv("VDT_KV_TIERING", "0") == "1",
    # T1 budget: MiB of host RAM the demoted-page pool may hold before
    # spilling its LRU pages to the disk tier (fractions allowed —
    # tiny-geometry tests/bench force spills with sub-MiB budgets).
    "VDT_KV_TIER_HOST_MB":
    lambda: max(0.001, float(os.getenv("VDT_KV_TIER_HOST_MB", "512"))),
    # T2 spill directory ("" disables the disk tier; host-pool eviction
    # then discards). Content-addressed page files — safe to share with
    # a shared_storage store or across replicas of the SAME model
    # (namespace discipline is the operator's, as with shared_storage).
    "VDT_KV_TIER_DIR":
    lambda: os.getenv("VDT_KV_TIER_DIR", ""),
    # T2 budget: MiB of spill files kept on disk (oldest evicted past
    # the budget; fractions allowed like the host budget).
    "VDT_KV_TIER_DISK_MB":
    lambda: max(0.001, float(os.getenv("VDT_KV_TIER_DISK_MB", "4096"))),
    # Demotion cap: pages gathered device->host per engine step. The
    # gather is dispatched pre-forward (its DMA overlaps the step);
    # evictions past the cap lose their demotion (counted) because the
    # new page owner overwrites the content this very step.
    "VDT_KV_TIER_DEMOTE_PAGES":
    lambda: max(1, int(os.getenv("VDT_KV_TIER_DEMOTE_PAGES", "64"))),
    # --- Elastic fleet controller (engine/fleet.py) ---------------------
    # Master switch: "1" hosts a FleetController next to the DP balancer
    # — closed-loop scale-out/in over the replica set, live prefill <->
    # decode pool re-splits, wedge detection, and the folded resurrection
    # probe (one actuator, one budget). "0" (default) constructs no
    # controller: no extra thread, no new RPCs, and the legacy periodic
    # resurrection probe runs byte-identical to the pre-fleet behavior.
    "VDT_FLEET":
    lambda: os.getenv("VDT_FLEET", "0") == "1",
    # Fleet-size floor/ceiling for scale decisions. MIN bounds scale-in
    # (never retire below it). MAX bounds scale-out; 0 = auto: the boot
    # data_parallel_size (scale-out then only refills retired slots —
    # growing past boot needs devices the operator must provision).
    "VDT_FLEET_MIN_REPLICAS":
    lambda: max(1, int(os.getenv("VDT_FLEET_MIN_REPLICAS", "1"))),
    "VDT_FLEET_MAX_REPLICAS":
    lambda: max(0, int(os.getenv("VDT_FLEET_MAX_REPLICAS", "0"))),
    # Seconds between control-loop evaluations (ticks ride the output
    # path next to the resurrection probe; no dedicated thread).
    "VDT_FLEET_TICK_S":
    lambda: max(0.0, float(os.getenv("VDT_FLEET_TICK_S", "1.0"))),
    # Occupancy watermarks (fleet-wide live slots / (active replicas *
    # max_num_seqs)): sustained occupancy above HIGH scales out, below
    # LOW scales in. HIGH/LOW must straddle to hysterese.
    "VDT_FLEET_HIGH_WATERMARK":
    lambda: float(os.getenv("VDT_FLEET_HIGH_WATERMARK", "0.85")),
    "VDT_FLEET_LOW_WATERMARK":
    lambda: float(os.getenv("VDT_FLEET_LOW_WATERMARK", "0.25")),
    # Consecutive ticks a watermark (or pool-imbalance) signal must hold
    # before the controller actuates — the hysteresis half of the
    # anti-thrash story (the action budget is the other half).
    "VDT_FLEET_EVAL_TICKS":
    lambda: max(1, int(os.getenv("VDT_FLEET_EVAL_TICKS", "3"))),
    # Per-replica stats snapshots older than this freeze all actuation
    # (counted in vdt:fleet_freezes_total{reason="stale_stats"}) — the
    # router stale_stats idiom: never reshape the fleet on blind signals.
    "VDT_FLEET_STALE_S":
    lambda: max(0.0, float(os.getenv("VDT_FLEET_STALE_S", "10"))),
    # A replica with live requests whose steps_dispatched counter has
    # not advanced for this long is WEDGED (alive-but-not-stepping): its
    # journaled requests migrate off and it is force-cycled through the
    # PR-2 restart budget. 0 disables wedge detection.
    "VDT_FLEET_WEDGE_S":
    lambda: max(0.0, float(os.getenv("VDT_FLEET_WEDGE_S", "30"))),
    # Drain deadline for a retiring/converting replica: past it, still-
    # unfinished requests journal-migrate as continuations (token-
    # identical under greedy) and the drain completes anyway.
    "VDT_FLEET_DRAIN_S":
    lambda: max(0.0, float(os.getenv("VDT_FLEET_DRAIN_S", "30"))),
    # Supervisor-style action budget: at most ACTIONS fleet actions
    # (scale-out/in, re-split, wedge cycle) per rolling WINDOW seconds;
    # past it actuation freezes (reason="budget") until the window
    # slides — an oscillating signal cannot thrash the fleet.
    "VDT_FLEET_ACTIONS":
    lambda: max(1, int(os.getenv("VDT_FLEET_ACTIONS", "6"))),
    "VDT_FLEET_ACTION_WINDOW_S":
    lambda: max(1.0, float(os.getenv("VDT_FLEET_ACTION_WINDOW_S",
                                     "300"))),
    # Live pool re-split trigger (VDT_DISAGG fleets): convert one
    # replica toward the pressured pool when its per-replica occupancy
    # exceeds the other pool's by this factor. 0 disables re-splits.
    "VDT_FLEET_RESPLIT_RATIO":
    lambda: max(0.0, float(os.getenv("VDT_FLEET_RESPLIT_RATIO", "3"))),
    # --- HA fleet control plane (engine/control_plane.py) ---------------
    # Master switch: "1" hoists the FleetController behind the DP
    # coordinator's lease/fence plane — every front-end hosts a standby
    # controller, exactly one holds the TTL lease and actuates, every
    # actuation carries the lease epoch and the coordinator rejects
    # stale-epoch commands (counted, never raised into serving), and
    # multi-step actions journal intents so a successor leader can
    # finish them. "0" (default) keeps the PR-16 in-process controller
    # byte-identical: no lease RPCs, no journal, no fencing.
    "VDT_FLEET_CONTROLLER":
    lambda: os.getenv("VDT_FLEET_CONTROLLER", "0") == "1",
    # Lease TTL in seconds (monotonic server clock). The leader renews
    # each tick; a standby takes over within TTL of leader death. Ticks
    # must run faster than the TTL or leadership flaps.
    "VDT_FLEET_LEASE_TTL_S":
    lambda: max(0.001, float(os.getenv("VDT_FLEET_LEASE_TTL_S", "10"))),
    # Actuation-journal directory ("" = auto: <VDT_KV_TIER_DIR>/
    # fleet_journal when the T2 spill namespace is configured, else a
    # per-fleet tempdir). Intent records are one JSON file per in-flight
    # multi-step action, written atomically before each rung; a newly
    # elected leader replays or aborts whatever it finds here.
    "VDT_FLEET_JOURNAL_DIR":
    lambda: os.getenv("VDT_FLEET_JOURNAL_DIR", ""),
    # Richer scaling signals: "1" folds the roofline phase (memory- vs
    # compute-bound fraction, PR 14's cost model) and per-tenant goodput
    # (PR 13's SLO scoring) into the scale-out/in decision — a memory-
    # bound or goodput-starved fleet scales out earlier and resists
    # scale-in. "0" (default) decides on occupancy alone.
    "VDT_FLEET_SIGNALS":
    lambda: os.getenv("VDT_FLEET_SIGNALS", "0") == "1",
    # Signal weights: occupancy is inflated by (1 + WEIGHT *
    # memory_bound_fraction), and a min per-tenant goodput below FLOOR
    # counts as scale-out pressure / vetoes scale-in. FLOOR <= 0
    # disables the goodput term even with signals on.
    "VDT_FLEET_ROOFLINE_WEIGHT":
    lambda: max(0.0, float(os.getenv("VDT_FLEET_ROOFLINE_WEIGHT",
                                     "0.5"))),
    "VDT_FLEET_GOODPUT_FLOOR":
    lambda: float(os.getenv("VDT_FLEET_GOODPUT_FLOOR", "0.5")),
    # --- SSM state cache (core/state_cache.py) --------------------------
    # First-class state checkpoint/restore for stateful (Mamba/Jamba)
    # models: prefix-style admission at snapshot boundaries, preemption
    # that parks state instead of recomputing, and O(1) crash-recovery
    # resume. "0" reverts wholesale to the pre-cache behavior (prefix
    # caching disabled for stateful models, preemption recomputes from
    # token 0, journal replay re-prefills the whole prompt).
    "VDT_SSM_STATE_CACHE":
    lambda: os.getenv("VDT_SSM_STATE_CACHE", "1") == "1",
    # Snapshot-pool slots (device rows per state array). 0 = auto:
    # max(2 * max_num_seqs, 8).
    "VDT_SSM_STATE_CACHE_SLOTS":
    lambda: max(0, int(os.getenv("VDT_SSM_STATE_CACHE_SLOTS", "0"))),
    # Checkpoint cadence in tokens (rounded up to a page multiple so
    # every snapshot boundary is also a block-hash boundary). Crash
    # recovery re-prefills at most this many tokens.
    "VDT_SSM_CKPT_INTERVAL":
    lambda: max(1, int(os.getenv("VDT_SSM_CKPT_INTERVAL", "256"))),
    # Host checkpoint-journal directory for crash recovery ("" keeps
    # snapshots device-only). Files use the shared_storage connector's
    # atomic tmp+rename discipline, one .npz per snapshot boundary.
    "VDT_SSM_CKPT_DIR":
    lambda: os.getenv("VDT_SSM_CKPT_DIR", ""),
    # Checkpoint-journal retention: files are content-addressed and
    # deliberately outlive their requests (they ARE the crash-recovery
    # tier), so a sweep on manager init and on sleep() bounds the
    # directory instead of per-request deletes. Max total MiB (oldest
    # files reclaimed first past the budget; 0 = unbounded) and max file
    # age in seconds (0 = no TTL). Files still referenced by an
    # unshipped journal write are never reclaimed.
    "VDT_SSM_CKPT_MAX_MB":
    lambda: max(0, int(os.getenv("VDT_SSM_CKPT_MAX_MB", "1024"))),
    "VDT_SSM_CKPT_TTL_S":
    lambda: max(0.0, float(os.getenv("VDT_SSM_CKPT_TTL_S", "604800"))),
    # --- TPLA: tensor-parallel latent attention (ops/mla.py) ------------
    # Shard the MLA (DeepSeek) latent KV cache over the TP axis (PAPERS.md
    # "TPLA"): each rank stores kv_lora_rank/TP of every latent row (the
    # rope k_pe sidecar stays replicated), so the per-rank latent pool is
    # ~1/TP the bytes and MLA concurrency scales ~TP-fold. Default on for
    # TP>1 MLA models; "0" reverts wholesale to the replicated layout
    # (byte-identical cache, head-sharded attention). Read at model load.
    "VDT_TPLA":
    lambda: os.getenv("VDT_TPLA", "1") == "1",
    # --- API admission control / overload protection -------------------
    # High watermark: concurrent admitted HTTP generation requests above
    # which the server sheds load with 429 + Retry-After. 0 disables
    # admission control entirely.
    "VDT_ADMISSION_HIGH_WATERMARK":
    lambda: int(os.getenv("VDT_ADMISSION_HIGH_WATERMARK", "256")),
    # Low watermark (hysteresis): once shedding starts it continues
    # until depth falls to this level. 0 = derive as 3/4 of the high
    # watermark.
    "VDT_ADMISSION_LOW_WATERMARK":
    lambda: int(os.getenv("VDT_ADMISSION_LOW_WATERMARK", "0")),
    # Free-KV-page pressure shed: fraction of KV pages in use above
    # which admission sheds (sampled from engine stats at most twice a
    # second). 0.0 disables the KV-pressure trigger.
    "VDT_ADMISSION_KV_HIGH":
    lambda: float(os.getenv("VDT_ADMISSION_KV_HIGH", "0")),
    # Weighted per-class shedding: fraction of the high/low watermarks
    # at which BEST-EFFORT traffic (request priority > 0) sheds, so
    # overload evicts best-effort requests before interactive ones.
    # 1.0 disables the distinction (all classes share one watermark).
    "VDT_ADMISSION_BEST_EFFORT_FRAC":
    lambda: min(1.0, max(0.05, float(
        os.getenv("VDT_ADMISSION_BEST_EFFORT_FRAC", "0.75")))),
    # Retry-After seconds advertised on shed (429) and drain (503).
    "VDT_RETRY_AFTER_S":
    lambda: max(1, int(os.getenv("VDT_RETRY_AFTER_S", "1"))),
    # Per-request wall-clock deadline (seconds) for generation
    # endpoints; overdue requests abort through the engine's abort path
    # and answer 408. 0 disables; a request body's "timeout_s" field
    # overrides per call.
    "VDT_REQUEST_TIMEOUT_S":
    lambda: float(os.getenv("VDT_REQUEST_TIMEOUT_S", "0")),
    # SIGTERM drain deadline: seconds to let in-flight requests finish
    # after admission stops before the server exits anyway.
    "VDT_DRAIN_TIMEOUT_S":
    lambda: float(os.getenv("VDT_DRAIN_TIMEOUT_S", "30")),
    # --- Per-tenant QoS (core/sched/qos.py) ----------------------------
    # Scheduler-level execution fairness: "1" turns on deficit-round-
    # robin weighted fair queueing over tenants (granted tokens draw
    # down per-tenant deficit counters; chunked-prefill grants clip to
    # them), soft per-tenant KV page quotas with quota-aware preemption
    # (cause "quota"), and the vdt:tenant_* metric families. "0" (the
    # default) constructs no QoS state — scheduling is byte-identical
    # to the pre-QoS behavior. Read once at scheduler construction.
    "VDT_QOS":
    lambda: os.getenv("VDT_QOS", "0") == "1",
    # Weight spec: comma list of "name:weight" where name is a tenant
    # id or a class key ("interactive"/"best_effort"/"default").
    # Unlisted tenants take their priority class's weight, then
    # "default", then 1.0 (equal shares).
    "VDT_QOS_WEIGHTS":
    lambda: os.getenv("VDT_QOS_WEIGHTS", ""),
    # Soft per-tenant KV quota as a fraction of the page pool. Free
    # until pressure: enforced only when the pool is pressured
    # (admission gating at >= 0.9 usage; preemption victim choice on
    # allocation failure). Values outside (0, 1) disable quotas.
    "VDT_QOS_KV_QUOTA_FRAC":
    lambda: float(os.getenv("VDT_QOS_KV_QUOTA_FRAC", "0.5")),
    # Cardinality bound for the vdt:tenant_* label space: tenants past
    # this many distinct ids hash into 8 shared overflow buckets
    # ("~<n>"); tenantless requests share "_anon".
    "VDT_QOS_MAX_TRACKED_TENANTS":
    lambda: max(1, int(os.getenv("VDT_QOS_MAX_TRACKED_TENANTS", "64"))),
    # --- Quantized communication plane (parallel/collectives.py +
    # distributed/kv_transfer/quant.py) ----------------------------------
    # Master switch: "1" ships cross-device bytes block-scaled int8
    # (EQuARX-style in-graph collectives for the TKNP decode psum, the
    # MoE-EP all-to-alls and the dense-TP row-parallel reduce, plus the
    # quantized KV-transfer payload codec for dcn_pull / p2p /
    # shared_storage). "0" (default) keeps every path byte-identical to
    # the unquantized plane. In-graph gating is read at TRACE time —
    # flip it before building an engine, not mid-serving.
    "VDT_QCOMM":
    lambda: os.getenv("VDT_QCOMM", "0") == "1",
    # Per-path override: comma list of paths to quantize when VDT_QCOMM
    # is on ("" = all paths). Tokens: "tknp" (token-axis attention
    # psum), "ep" (MoE expert-parallel all-to-all + combine psum + the
    # re-replicate all-gather), "tp" (dense-model row-parallel output
    # reduce), "tpla" (TPLA latent-attention output combine), "tknp_kv"
    # (the TKNP KV-write shuffle: the step's new K/V rows crossing the
    # token-axis shard_map boundary to the page-owning rank), "kv"
    # (every KV-transfer connector payload) or an individual connector
    # name ("dcn_pull"/"p2p"/"shared_storage").
    "VDT_QCOMM_PATHS":
    lambda: os.getenv("VDT_QCOMM_PATHS", ""),
    # Quantization block (elements per fp32 scale). Payload codecs clip
    # it to the per-page-per-head span so no scale ever crosses a page
    # or head boundary; in-graph collectives use it as-is.
    "VDT_QCOMM_BLOCK":
    lambda: max(16, int(os.getenv("VDT_QCOMM_BLOCK", "256"))),
    # --- Telemetry plane ------------------------------------------------
    # SLO targets scored by the output processor over the request
    # timeline: time-to-first-token and time-per-output-token budgets in
    # milliseconds. 0 disables that target; both 0 disables goodput
    # accounting entirely (vdt:slo_* families are then not rendered).
    "VDT_SLO_TTFT_MS":
    lambda: float(os.getenv("VDT_SLO_TTFT_MS", "0")),
    "VDT_SLO_TPOT_MS":
    lambda: float(os.getenv("VDT_SLO_TPOT_MS", "0")),
    # Device/compilation telemetry (per-worker recompile counter,
    # device-wait timer, jax device-memory high-water mark). Read once
    # per worker at construction.
    "VDT_DEVICE_TELEMETRY":
    lambda: os.getenv("VDT_DEVICE_TELEMETRY", "1") == "1",
    # Transport telemetry (KV-transfer bytes/latency/inflight, shm-ring
    # wait/lag). Checked per record so the bench harness can flip it
    # between legs of one process.
    "VDT_TRANSPORT_TELEMETRY":
    lambda: os.getenv("VDT_TRANSPORT_TELEMETRY", "1") == "1",
    # --- Distributed trace plane (trace_plane.py) -----------------------
    # Master switch: "1" mints a trace_id + parent-span context at
    # admission, carries it on EngineCoreRequest over the msgpack wire
    # (old-wire tolerant), stamps it onto every EventRecorder event, and
    # hosts the front-end TraceAssembler + bounded flight recorder +
    # GET /debug/trace Perfetto export. "0" (default) mints nothing and
    # stamps nothing — the wire bytes and event details are
    # byte-identical to the pre-trace-plane behavior. Read ONCE per
    # component at construction.
    "VDT_TRACE_PLANE":
    lambda: os.getenv("VDT_TRACE_PLANE", "0") == "1",
    # Flight-recorder bound: max distinct traces the assembler retains
    # (oldest-admitted evicted past the bound) and max spans kept per
    # trace (earliest kept — a trace's causal root matters most).
    "VDT_TRACE_MAX_TRACES":
    lambda: max(8, int(os.getenv("VDT_TRACE_MAX_TRACES", "256"))),
    "VDT_TRACE_MAX_SPANS":
    lambda: max(16, int(os.getenv("VDT_TRACE_MAX_SPANS", "512"))),
    # --- SLO burn-rate watchdog (metrics/stats.py) ----------------------
    # Burn-rate threshold: a window burns when its miss rate exceeds
    # threshold * (1 - VDT_SLO_TARGET) (the error budget). The watchdog
    # runs whenever SLO targets are configured; DEGRADED (both the fast
    # and slow window burning) surfaces in /health + /debug/engine and
    # is offered to VDT_FLEET_SIGNALS as scale-out pressure. <= 0
    # disables the degraded flag while keeping the gauges.
    "VDT_SLO_BURN_THRESHOLD":
    lambda: float(os.getenv("VDT_SLO_BURN_THRESHOLD", "2.0")),
    # SLO availability target the error budget derives from (e.g. 0.99
    # = 1% of scored requests may miss their latency targets).
    "VDT_SLO_TARGET":
    lambda: min(0.9999, max(0.5, float(
        os.getenv("VDT_SLO_TARGET", "0.99")))),
    # --- Correctness sentinel (correctness_plane.py) --------------------
    # Master switch: "1" constructs the CorrectnessPlane on the DP
    # front-end (canary probe injector + reference journal +
    # cross-replica vote + numerics drift watch) and the model runner's
    # pre-sampling numerics tap. "0" (default) constructs NOTHING — no
    # injector, no extra jitted program, no new stats keys, old wire
    # bytes. Read ONCE per component at construction.
    "VDT_CORRECTNESS":
    lambda: os.getenv("VDT_CORRECTNESS", "0") == "1",
    # Seconds between canary probe rounds (each round fans one pinned
    # greedy golden prompt out to every in-rotation DP replica). <= 0
    # probes on every maintenance tick (tests/bench drills).
    "VDT_CANARY_INTERVAL_S":
    lambda: float(os.getenv("VDT_CANARY_INTERVAL_S", "30")),
    # Consecutive divergent canary rounds before a replica's suspicion
    # hardens into a fleet quarantine hint (and the vdt:replica_suspect
    # gauge latches). 2 keeps detection within <= 3 probes of a seeded
    # corruption while one transient mismatch never quarantines.
    "VDT_CANARY_QUARANTINE_N":
    lambda: max(1, int(os.getenv("VDT_CANARY_QUARANTINE_N", "2"))),
    # Numerics drift threshold: a replica whose rolling logits-entropy
    # mean deviates from the fleet mean by more than this fraction of
    # the fleet mean is drift-suspect. <= 0 disables the drift detector
    # while keeping the NaN watch and histograms.
    "VDT_NUMERICS_DRIFT_FRAC":
    lambda: float(os.getenv("VDT_NUMERICS_DRIFT_FRAC", "0.5")),
    # Deterministic fault injection: "name:rate[@delay_s],..." over the
    # named fault points of utils/fault_injection.py (kv_pull.drop,
    # kv_pull.delay, registry.truncate, engine_core.die,
    # heartbeat.stall, core_proc.spawn_fail, restart.storm,
    # admission.stall). Read at process start (spawned engine cores
    # inherit it); "" disables. Robustness drills/tests only.
    "VDT_FAULT_INJECT":
    lambda: os.getenv("VDT_FAULT_INJECT", ""),
}


def __getattr__(name: str) -> Any:
    if name in environment_variables:
        return environment_variables[name]()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__() -> list[str]:
    return list(environment_variables.keys())
